"""ValidatorHost: one HBBFT validator over the gRPC transport.

Bundles what an embedding application wires by hand in the reference
(its README's server/client/pool snippets): a GrpcServer accepting
peer streams, dialed client connections to every roster member, and
the HoneyBadger node — plus the piece the reference gets from Go's
runtime for free: a per-node *serial dispatcher*.  gRPC gives every
peer stream its own reader thread, but the protocol state machines are
single-threaded actors (the reference muxes everything through
reqChan loops, bba/bba.go:113-123); ``SerialDispatcher`` is that actor
loop at node level — every inbound message and every local command
funnels through one worker thread, so protocol code never needs locks.

Self-delivery bypasses the network: a node's own broadcasts are
enqueued straight onto its dispatcher (the in-proc transport routes
them through the scheduler instead; both count the node as a normal
quorum member).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from cleisthenes_tpu.config import Config
from cleisthenes_tpu.core.batch import Batch
from cleisthenes_tpu.protocol.honeybadger import HoneyBadger, NodeKeys
from cleisthenes_tpu.transport.base import (
    ConnectionPool,
    HmacAuthenticator,
    sign_wave_counted,
)
from cleisthenes_tpu.transport.grpc_net import (
    DialOpts,
    GrpcClient,
    GrpcConnection,
    GrpcServer,
)
from cleisthenes_tpu.transport.health import (
    Backoff,
    PeerHealthTracker,
    backoff_rng,
)
from cleisthenes_tpu.transport.message import (
    FrameEncodeMemo,
    Message,
    Payload,
    payload_body_count,
)
from cleisthenes_tpu.utils.determinism import guarded_by
from cleisthenes_tpu.utils.lockcheck import new_lock
from cleisthenes_tpu.utils.log import NodeLogger


class _Wave:
    """One delivery wave riding the dispatcher mailbox as a SINGLE
    actor message (Config.wave_routing): the gRPC verify loop hands a
    whole verified burst over in one queue entry instead of N."""

    __slots__ = ("msgs",)

    def __init__(self, msgs: List[Message]) -> None:
        self.msgs = msgs


class SerialDispatcher:
    """Node-level actor loop: serializes message dispatch and local
    commands onto one worker thread (the node's reqChan)."""

    def __init__(self, name: str = "dispatch") -> None:
        self._q: "queue.Queue" = queue.Queue()
        self._handler = None
        self._on_idle = None
        # flight recorder (utils/trace.py), set by the owning host
        # AFTER construction; only the worker thread records (the
        # producer-side serve_request never touches it).  None = off.
        self.trace = None
        self._thread = threading.Thread(
            target=self._loop, name=name, daemon=True
        )
        self._stopped = threading.Event()
        self._thread.start()

    def bind(self, handler) -> None:
        self._handler = handler
        # the dispatcher's empty-mailbox check is a real quiescence
        # point (all queued work processed), so handlers that batch
        # crypto/outbound by wave get their idle callback there
        from cleisthenes_tpu.transport.base import wire_idle_hooks

        _, self._on_idle = wire_idle_hooks(handler)

    # transport Handler interface: called from gRPC reader threads
    def serve_request(self, msg: Message) -> None:
        if not self._stopped.is_set():
            self._q.put(msg)

    def serve_wave(self, msgs: List[Message]) -> None:
        """Wave ingest (Config.wave_routing): enqueue one verified
        delivery wave as ONE mailbox entry — the worker hands it to
        the bound handler's serve_wave (the WaveRouter seam) in a
        single call."""
        if msgs and not self._stopped.is_set():
            self._q.put(_Wave(msgs))

    def call(self, fn: Callable[[], None]) -> None:
        """Run ``fn`` on the dispatch thread (local commands mutate
        protocol state, so they take the same door as messages)."""
        if not self._stopped.is_set():
            self._q.put(fn)

    def call_sync(self, fn: Callable[[], object], timeout: float = 30.0):
        """``call`` and wait for the result (for inspection APIs)."""
        if self._stopped.is_set():
            raise RuntimeError("dispatcher stopped")
        done = threading.Event()
        box: List[object] = []

        def run():
            try:
                box.append(fn())
            finally:
                done.set()

        self.call(run)
        if not done.wait(timeout):
            raise TimeoutError("dispatcher stalled")
        return box[0] if box else None

    def drain(self, timeout: float = 30.0) -> None:
        """Block until everything queued so far has been processed."""
        self.call_sync(lambda: None, timeout=timeout)

    def _loop(self) -> None:
        served = 0
        depth_peak = 0
        while not self._stopped.is_set():
            item = self._q.get()
            if item is None:
                return
            width = 1
            try:
                if callable(item):
                    item()
                elif isinstance(item, _Wave):
                    width = len(item.msgs)
                    handler = self._handler
                    if handler is not None:
                        serve_wave = getattr(handler, "serve_wave", None)
                        if serve_wave is not None:
                            serve_wave(item.msgs)
                        else:  # non-wave handler bound: per-frame
                            for m in item.msgs:
                                handler.serve_request(m)  # staticcheck: allow[DET004] fallback
                elif self._handler is not None:
                    self._handler.serve_request(item)  # staticcheck: allow[DET004] scalar arm
            except Exception:
                # a poisoned message must not kill the node's actor
                import traceback

                traceback.print_exc()
            tr = self.trace
            if tr is not None:
                served += width
                # backlog BEHIND the item just processed: the depth
                # signal (at the drain point itself it is 0 by
                # definition, so sample per item and report the peak)
                backlog = self._q.qsize()
                if backlog > depth_peak:
                    depth_peak = backlog
                if backlog == 0:
                    # mailbox drained: one wave's worth of items plus
                    # the deepest backlog observed during the wave
                    tr.instant(
                        "transport",
                        "queue_depth",
                        msgs=served,
                        depth=depth_peak,
                    )
                    served = 0
                    depth_peak = 0
            if self._on_idle is not None and self._q.empty():
                # mailbox drained: wave boundary (a racing producer
                # just means an extra flush later — never a lost one,
                # since its message re-triggers this check)
                try:
                    self._on_idle()
                except Exception:
                    import traceback

                    traceback.print_exc()

    def stop(self) -> None:
        self._stopped.set()
        self._q.put(None)


@guarded_by("_lock", "_ready", "_pending")
class GrpcPayloadBroadcaster:
    """PayloadBroadcaster over dialed peer connections + local
    short-circuit (transport.broadcast.ChannelBroadcaster's gRPC twin).

    Broadcasts sign+encode ONCE and fan the identical wire frame to
    every peer (signing_bytes is deterministic, so per-connection
    re-signing would produce the same bytes n-1 times)."""

    def __init__(
        self,
        node_id: str,
        pool: ConnectionPool,
        local: SerialDispatcher,
        auth,
        egress_columnar: bool = False,
    ) -> None:
        self._node_id = node_id
        self._pool = pool
        self._local = local
        self._auth = auth
        # until connect() finishes, the pool is incomplete: park
        # outbound traffic instead of silently dropping it for peers
        # not dialed yet (protocol messages are sent exactly once)
        self._ready = False
        self._pending: List = []
        self._lock = new_lock()
        # Columnar egress (Config.egress_columnar): the coalescer
        # hands each flush's whole wave to post_wave, which signs it
        # in ONE Authenticator.sign_wire_wave pass (payload bodies
        # encode once per distinct object via the encode memo, MACs
        # batched over the precomputed pair schedules) and makes one
        # stream write per peer per flush (the wave already folds to
        # one bundle per receiver).  Counters are the egress twins of
        # the connection-side delivery counters, folded into
        # Metrics.snapshot()["transport"] by the host.
        self._encode_memo = (
            FrameEncodeMemo() if egress_columnar else None
        )
        self.frames_encoded = 0
        self.encode_memo_hits = 0
        self.encode_memo_misses = 0
        self.mac_sign_batches = 0

    def mark_ready(self) -> None:
        with self._lock:
            self._ready = True
            pending, self._pending = self._pending, []
        for member_id, msg in pending:
            self._deliver(member_id, msg)

    def _wrap(self, payload: Payload) -> Message:
        return Message(
            sender_id=self._node_id, timestamp=time.time(), payload=payload
        )

    def _deliver(self, member_id: Optional[str], msg: Message) -> None:
        """member_id None = broadcast to all peers."""
        self.frames_encoded += payload_body_count(msg.payload)
        self.mac_sign_batches += 1
        if member_id is None:
            # pairwise MACs: each peer gets its own signed frame (one
            # key per peer — the sign-once/fan-out-identical-bytes path
            # would need a key every peer shares, exactly the forgeable
            # design ADVICE.md retired).  The envelope is encoded once;
            # only the 32-byte MAC differs per frame.
            conns = self._pool.get_all()
            frames = self._auth.sign_wire_many(  # staticcheck: allow[DET006] scalar arm
                msg, [c.id() for c in conns]
            )
            for conn in conns:
                conn.send_wire(frames[conn.id()])
        else:
            self._pool.send_to(member_id, msg)

    def post_wave(self, entries) -> None:
        """One egress wave (Config.egress_columnar): ``entries`` are
        ``(member_id | None, payload)`` pairs — one coalescer flush.
        The whole wave signs in ONE ``sign_wire_wave`` pass and ships
        as one stream write per peer per flush; local self-delivery
        short-circuits through the dispatcher exactly like the scalar
        arm, but only AFTER the fallible sign pass — a sign failure
        re-parks the wave in the coalescer, and serving local first
        would double-deliver the node's own payloads on the retry.
        Before the dial pool completes, the WHOLE wave parks per
        receiver in one pass and re-delivers scalar on mark_ready
        (boot-time traffic is a handful of frames; parking all-or-
        nothing keeps a mid-wave failure from re-parking entries the
        pending list already holds)."""
        msgs = [
            (member_id, self._wrap(payload))
            for member_id, payload in entries
        ]
        with self._lock:
            ready = self._ready
            if not ready:
                for member_id, msg in msgs:
                    if member_id != self._node_id:
                        self._pending.append((member_id, msg))
        if not ready:
            # scalar parity: local delivery never waits on the pool
            for member_id, msg in msgs:
                if member_id is None or member_id == self._node_id:
                    self._local.serve_request(msg)  # staticcheck: allow[DET004] self-delivery
            return
        wave: List = []  # (msg, receiver_ids, conns)
        local: List[Message] = []
        for member_id, msg in msgs:
            if member_id is None:
                conns = self._pool.get_all()
                wave.append((msg, [c.id() for c in conns], conns))
                local.append(msg)
            elif member_id == self._node_id:
                local.append(msg)
            else:
                conn = self._pool.get(member_id)
                if conn is not None:
                    wave.append((msg, [member_id], [conn]))
        if wave:
            tr = getattr(self._local, "trace", None)
            t0 = 0.0 if tr is None else tr.now()
            frames_list, hits, misses, bodies = sign_wave_counted(
                self._auth,
                [(msg, rids) for msg, rids, _conns in wave],
                self._encode_memo,
            )
            self.mac_sign_batches += 1
            self.encode_memo_hits += hits
            self.encode_memo_misses += misses
            self.frames_encoded += bodies
            if tr is not None:
                tr.complete(
                    "transport",
                    "frame_encode",
                    t0,
                    frames=len(wave),
                    memo_hits=hits,
                )
            for (_msg, _rids, conns), frames in zip(wave, frames_list):
                for conn in conns:
                    conn.send_wire(frames[conn.id()])
        for msg in local:
            self._local.serve_request(msg)  # staticcheck: allow[DET004] local self-delivery

    def _post(self, member_id: Optional[str], msg: Message) -> None:
        with self._lock:
            if not self._ready:
                self._pending.append((member_id, msg))
                return
        self._deliver(member_id, msg)

    def broadcast(self, payload: Payload) -> None:
        msg = self._wrap(payload)
        self._post(None, msg)
        self._local.serve_request(msg)  # staticcheck: allow[DET004] local self-delivery

    def send_to(self, member_id: str, payload: Payload) -> None:
        msg = self._wrap(payload)
        if member_id == self._node_id:
            self._local.serve_request(msg)  # staticcheck: allow[DET004] local self-delivery
        else:
            self._post(member_id, msg)


@guarded_by(
    "_closed_stats_lock",
    "_closed_delivered",
    "_closed_rejected",
    "_closed_decoded",
    "_closed_batches",
)
class ValidatorHost:
    """One validator process: server + peer dials + HoneyBadger node."""

    def __init__(
        self,
        config: Config,
        node_id: str,
        member_ids: Sequence[str],
        keys: NodeKeys,
        listen_addr: str = "127.0.0.1:0",
        auto_propose: bool = True,
        batch_log_path: Optional[str] = None,
        behavior=None,
        joining: bool = False,
        roster_version_base: int = 0,
    ) -> None:
        self.config = config
        self.node_id = node_id
        self.members = sorted(member_ids)
        self.keys = keys
        self._joining = joining
        self._addrs: Dict[str, str] = {}
        self._stopping = threading.Event()
        # per-member dial backoffs persist across redial loops so a
        # flapping link keeps its capped schedule instead of being
        # re-probed from base on every transient success (see
        # Backoff.note_lost); guarded by _backoffs_lock
        self._backoffs: Dict[str, Backoff] = {}
        self._backoffs_lock = new_lock()
        self.log = NodeLogger(node_id, "host")
        # inbound verification looks up the pair key by sender id, so
        # one authenticator verifies all peers; signing is bound to
        # (node_id, receiver) pairs
        if config.attested_log:
            from cleisthenes_tpu.protocol.attest import (
                AttestationDirectory,
                AttestingAuthenticator,
            )

            # each host holds its OWN simulated TEE NVRAM (one sealed
            # counter store per machine); fork evidence against peers
            # aggregates locally and surfaces through attest_stats
            self.attest_dir = AttestationDirectory()
            self._auth = AttestingAuthenticator(
                node_id, keys.mac_keys, self.attest_dir.attach(node_id)
            )
        else:
            self.attest_dir = None
            self._auth = HmacAuthenticator(node_id, keys.mac_keys)
        self.dispatcher = SerialDispatcher(name=f"dispatch-{node_id}")
        self.server = GrpcServer(
            listen_addr,
            self._auth,
            capacity=config.channel_capacity,
            delivery_columnar=config.delivery_columnar,
            wave_routing=config.wave_routing,
        )
        self.server.on_conn(self._accept)
        self.pool = ConnectionPool()
        self._client = GrpcClient(
            self._auth,
            delivery_columnar=config.delivery_columnar,
            wave_routing=config.wave_routing,
        )
        # frame counters of dialed streams that have since been lost:
        # folded in at loss time so the transport metric stays
        # cumulative across self-healing redials
        self._closed_stats_lock = new_lock()
        self._closed_delivered = 0
        self._closed_rejected = 0
        self._closed_decoded = 0
        self._closed_batches = 0
        # per-peer UP/DEGRADED/DOWN + reconnect counters + the recent
        # backoff schedule (proof the dial layer is not spinning)
        self.health = PeerHealthTracker(
            p for p in self.members if p != node_id
        )
        self.out = GrpcPayloadBroadcaster(
            node_id,
            self.pool,
            self.dispatcher,
            self._auth,
            egress_columnar=config.egress_columnar,
        )
        batch_log = None
        if batch_log_path is not None:
            from cleisthenes_tpu.core.ledger import BatchLog

            batch_log = BatchLog(batch_log_path, fsync=config.ledger_fsync)
        # peers retired by a RECONFIG: redial loops check the set and
        # cancel; guarded by the health tracker's own lock discipline
        # (writes happen on the dispatch thread, reads on dial threads
        # via PeerHealthTracker.is_retired)
        self.node = HoneyBadger(
            config=config,
            node_id=node_id,
            member_ids=self.members,
            keys=keys,
            out=self.out,
            auto_propose=auto_propose,
            batch_log=batch_log,
            # semantic-adversary seam (protocol.byzantine): the same
            # behavior objects the in-proc cluster mounts run over real
            # gRPC — a lie per receiver, each frame validly MAC'd
            behavior=behavior,
            authenticator=self._auth,
            joining=joining,
            roster_version_base=roster_version_base,
        )
        # dynamic-membership transport hooks: a discovered joiner gets
        # a dial lane (the redial loop completes its CATCHUP on
        # success); a torn-down retiree stops being dialed
        self.node.on_peer_added = self.add_peer
        self.node.on_peer_retired = self.retire_peer
        self.node.metrics.set_transport_health(self.health.snapshot)
        self.node.metrics.set_transport_stats(self._transport_stats)
        # SLO watchdogs (utils/watchdog.py) run on every host: alert
        # counters fold into Metrics.snapshot()["alerts"] whether or
        # not the scrape endpoints are enabled.  Peer states come from
        # the dial layer's health tracker.
        from cleisthenes_tpu.utils.watchdog import SloWatchdog

        self.watchdog = SloWatchdog(
            metrics=self.node.metrics,
            pending_fn=self.node.outstanding_tx_count,
            stall_factor=config.slo_stall_factor,
            stall_grace_s=config.slo_stall_grace_s,
            queue_depth_limit=config.slo_queue_depth,
            peer_lag_epochs=config.slo_peer_lag_epochs,
            peer_states_fn=self._peer_states,
            decrypt_lag_budget=config.decrypt_lag_max,
            trace=self.node.trace,
        )
        self.node.metrics.set_alerts(self.watchdog.alerts_block)
        # live telemetry endpoints (Config.obs_port): bounded-ring
        # sampler + localhost /metrics | /healthz | /vars.  Built here,
        # started by listen() next to the gRPC server.
        self.sampler = None
        self.obs = None
        if config.obs_port is not None:
            from cleisthenes_tpu.transport.obs_http import (
                ObsServer,
                ObsTarget,
            )
            from cleisthenes_tpu.utils.timeseries import TimeSeriesSampler

            self.sampler = TimeSeriesSampler(self.node.metrics.snapshot)
            self.sampler.on_tick(self.watchdog.check)
            self.obs = ObsServer(
                [
                    ObsTarget(
                        node_id,
                        self.node.metrics,
                        self.watchdog,
                        self.sampler,
                    )
                ],
                port=config.obs_port,
            )
        # client ingress plane (Config.ingress_port): the untrusted
        # submit/subscribe surface (transport/ingress.py), fronted by
        # the fee-priority mempool the node mounted above.  Built
        # here, bound by listen() next to the validator server.
        self.ingress = None
        self.ingress_server = None
        if config.ingress_port is not None:
            from cleisthenes_tpu.transport.ingress import (
                IngressGrpcServer,
                IngressPlane,
            )

            # post-admission nudge: an idle node starts an epoch for
            # fresh client work (start_epoch no-ops mid-epoch, so the
            # kick is an enqueue + cheap check, never a double propose)
            self.ingress = IngressPlane(
                self.node,
                on_admitted=lambda: self.dispatcher.call(
                    self.node.start_epoch
                ),
            )
            self.ingress_server = IngressGrpcServer(
                self.ingress, f"127.0.0.1:{config.ingress_port}"
            )
        # the dispatcher records queue-depth/wave events on the node's
        # own timeline (same worker thread as all protocol code)
        self.dispatcher.trace = self.node.trace
        self.dispatcher.bind(self.node)
        self._commits: "queue.Queue" = queue.Queue()
        self.node.on_commit = lambda epoch, batch: self._commits.put(
            (epoch, batch)
        )

    def _peer_states(self) -> Dict[str, str]:
        """Peer UP/DEGRADED/DOWN states for the SLO watchdog's peer
        detector (the dial layer's health snapshot, states only)."""
        return {
            peer: str(ph["state"])
            for peer, ph in self.health.snapshot().items()
        }

    def _transport_stats(self) -> Dict[str, int]:
        """Inbound frame counters across every stream this host EVER
        read (server-accepted + dialed, live + lost), for
        ``Metrics.snapshot()["transport"]`` — cumulative across
        redials, like GrpcServer.stats."""
        stats = self.server.stats()
        delivered = stats["delivered"]
        rejected = stats["rejected"]
        decoded = stats["frames_decoded"]
        batches = stats["mac_verify_batches"]
        with self._closed_stats_lock:  # see _on_conn_lost: atomic
            delivered += self._closed_delivered
            rejected += self._closed_rejected
            decoded += self._closed_decoded
            batches += self._closed_batches
            conns = self.pool.get_all()
        for conn in conns:
            delivered += getattr(conn, "delivered", 0)
            rejected += getattr(conn, "rejected", 0)
            decoded += getattr(conn, "frames_decoded", 0)
            batches += getattr(conn, "mac_verify_batches", 0)
        return {
            "delivered": delivered,
            "rejected": rejected,
            "frames_decoded": decoded,
            "mac_verify_batches": batches,
            # egress twins (Config.egress_columnar): the payload
            # broadcaster owns the outbound signer seam, so its
            # counters are already host-cumulative
            "frames_encoded": self.out.frames_encoded,
            "encode_memo_hits": self.out.encode_memo_hits,
            "encode_memo_misses": self.out.encode_memo_misses,
            "mac_sign_batches": self.out.mac_sign_batches,
        }

    # -- lifecycle ---------------------------------------------------------

    def _accept(self, conn: GrpcConnection) -> None:
        """Server-side stream accepted: route into the dispatcher
        (the reference's connHandler contract, comm.go:47-49)."""
        conn.handle(self.dispatcher)
        conn.start()

    def listen(self) -> str:
        self.server.listen()
        addr = f"127.0.0.1:{self.server.port}"
        self.log.info("listening", addr=addr)
        if self.obs is not None:
            port = self.obs.start()
            self.sampler.start(self.config.obs_sample_period_s)
            self.log.info("obs endpoints up", addr=f"127.0.0.1:{port}")
        if self.ingress_server is not None:
            self.ingress_server.listen()
            self.log.info(
                "ingress up",
                addr=f"127.0.0.1:{self.ingress_server.port}",
            )
        return addr

    def connect(
        self, addrs: Dict[str, str], deadline_s: float = 10.0
    ) -> None:
        """Dial every other roster member, retrying with capped
        exponential backoff until deadline (peers boot concurrently).
        Buffered outbound traffic flushes once the pool is complete."""
        missing = set(self.members) - {self.node_id} - set(addrs)
        if missing:  # config error: fail fast, don't spin the retry loop
            raise ValueError(f"no address for roster members {sorted(missing)}")
        self._addrs = dict(addrs)
        t0 = time.monotonic()
        for member in self.members:
            if member == self.node_id:
                continue
            backoff = self._backoff_for(member)
            while True:
                try:
                    self._dial_member(member)
                    break
                except Exception:
                    if time.monotonic() - t0 > deadline_s:
                        raise
                    delay = backoff.next_delay()
                    self.health.dial_scheduled(member, delay)
                    # interruptible like _redial_loop's wait: stop()
                    # must not block behind a capped-backoff sleep
                    if self._stopping.wait(delay):
                        raise
        self.out.mark_ready()
        self.log.info("connected", peers=len(self.pool))
        if self.node.epoch > 0 or self._joining:
            # restarted from a durable log — or a JOINER bootstrapping
            # into a running roster: peers may have committed epochs
            # we missed — catch up before proposing
            self.dispatcher.call(self.node.request_catchup)

    def _backoff_for(self, member: str) -> Backoff:
        """One dial lane's backoff: Config policy + seeded jitter (the
        jitter de-synchronizes a roster all redialing the same dead
        peer; the seed keeps fault tests replayable).

        The instance PERSISTS across redial loops: a flapping WAN link
        (dial lands, stream dies before ``stability_s``) continues the
        capped schedule rather than restarting from base on every
        transient success — re-arming is stability-gated in
        ``Backoff.note_lost``."""
        with self._backoffs_lock:
            b = self._backoffs.get(member)
            if b is None:
                b = self._backoffs[member] = Backoff(
                    self.config.dial_retry_base_s,
                    self.config.dial_retry_max_s,
                    rng=backoff_rng(
                        self.config.seed, self.node_id, member
                    ),
                )
            return b

    def _dial_member(self, member: str):
        """Single dial attempt; raises on failure (retry policy is the
        caller's — connect()'s deadline loop or the redial loop).
        Returns the pooled connection."""
        self.health.dial_started(member)
        try:
            conn = self._client.dial(
                DialOpts(
                    self._addrs[member],
                    timeout_s=self.config.dial_timeout_s,
                    capacity=self.config.channel_capacity,
                    conn_id=member,  # pool addressed by member
                )
            )
        except Exception:
            self.health.dial_failed(member)
            raise
        conn.handle(self.dispatcher)
        # a broken stream prunes itself from the pool and redials in
        # the background (messages sent while down are lost; HBBFT's
        # f-tolerance covers short outages, reconnection restores the
        # peer for later epochs).  Chain the dial-layer close hook
        # (it cancels the underlying gRPC call).
        cancel_call = conn._on_close
        conn._on_close = lambda c, m=member, cc=cancel_call: (
            cc(c) if cc else None,
            self._on_conn_lost(m, c),
        )
        conn.start()
        self.pool.add(conn)
        self.health.connected(member)
        self._backoff_for(member).note_connected()
        return conn

    def _on_conn_lost(self, member: str, conn) -> None:
        # fold the dying stream's frame counters into the cumulative
        # tally — the transport metric must stay monotonic across
        # self-healing redials (GrpcServer.stats does the same for
        # accepted conns).  Fold and pool-removal happen under ONE
        # lock, and _transport_stats reads under the same lock, so a
        # concurrent snapshot never sees the conn both folded and
        # live (lock order everywhere: _closed_stats_lock -> pool)
        with self._closed_stats_lock:
            self._closed_delivered += getattr(conn, "delivered", 0)
            self._closed_rejected += getattr(conn, "rejected", 0)
            self._closed_decoded += getattr(conn, "frames_decoded", 0)
            self._closed_batches += getattr(conn, "mac_verify_batches", 0)
            self.pool.remove(member)
        self.health.stream_lost(member)
        self._backoff_for(member).note_lost()
        self.log.warning("peer stream lost", peer=member)
        if self._stopping.is_set() or self.health.is_retired(member):
            return  # a retired peer's lost stream stays lost
        threading.Thread(
            target=self._redial_loop, args=(member,), daemon=True
        ).start()

    def _redial_loop(self, member: str) -> None:
        """Self-healing redial: capped exponential backoff with seeded
        jitter (Config.dial_retry_base_s/_max_s), waking early on
        stop().  Health transitions UP -> DEGRADED -> DOWN ride the
        dial attempts (transport/health.py)."""
        backoff = self._backoff_for(member)
        while not self._stopping.is_set():
            if self.health.is_retired(member):
                # peer left the roster while we were backing off:
                # cancel the loop — a retired host must not keep
                # absorbing this roster's redial storms
                return
            try:
                conn = self._dial_member(member)
            except Exception:
                delay = backoff.next_delay()
                self.health.dial_scheduled(member, delay)
                if self._stopping.wait(delay):
                    return
                continue
            if self._stopping.is_set() or self.health.is_retired(
                member
            ):  # stop()/retirement raced the dial
                self.pool.remove(member)
                conn.close()
                return
            # the path to this peer just healed: anything we served it
            # while the link was down is gone — complete its
            # interrupted catch-up (no-op if it never asked)
            self.dispatcher.call(
                lambda m=member: self.node.peer_reconnected(m)
            )
            return

    def add_peer(self, member: str, addr: str) -> None:
        """Dynamic membership: open a dial lane to a discovered
        JOINER.  The redial loop dials with the standard capped
        backoff until the joiner's server answers, then fires
        ``peer_reconnected`` — which serves the joiner's standing
        CATCHUP-from-0 request, completing its bootstrap."""
        if member == self.node_id or self._stopping.is_set():
            return
        # an id retired by an EARLIER reconfig may be re-admitted by
        # a later one: lift the retirement before the dial loop's
        # is_retired checks would cancel it
        self.health.readmit(member)
        if member not in self.members:
            self.members = sorted(set(self.members) | {member})
        self._addrs[member] = addr
        if self.pool.get(member) is not None:
            return  # already connected
        threading.Thread(
            target=self._redial_loop, args=(member,), daemon=True
        ).start()

    def retire_peer(self, member: str) -> None:
        """Dynamic membership: the peer left the roster and every
        pre-boundary epoch is settled.  Tear down its dial state —
        the backoff loop cancels, the pooled stream closes, and its
        health row drops from ``transport_health`` — so a retired
        host stops generating redial storms the moment its duties
        end."""
        self.health.retire(member)
        with self._backoffs_lock:
            self._backoffs.pop(member, None)
        self._addrs.pop(member, None)
        if member in self.members:
            self.members = sorted(set(self.members) - {member})
        conn = self.pool.get(member)
        if conn is not None:
            self.pool.remove(member)
            conn.close()
        self.log.info("peer retired", peer=member)

    def stop(self) -> None:
        self._stopping.set()
        if self.ingress_server is not None:
            self.ingress_server.stop()
        if self.sampler is not None:
            self.sampler.stop()
        if self.obs is not None:
            self.obs.stop()
        self.server.stop()
        self._client.close()
        self.dispatcher.stop()
        if self.node.batch_log is not None:
            self.node.batch_log.close()

    # -- application API ---------------------------------------------------

    def submit(self, tx: bytes) -> None:
        self.node.add_transaction(tx)  # queue is internally locked

    def propose(self) -> None:
        self.dispatcher.call(self.node.start_epoch)

    def wait_commit(self, timeout: float = 30.0):
        """Block for the next committed (epoch, Batch)."""
        return self._commits.get(timeout=timeout)

    def committed_batches(self) -> List[Batch]:
        return self.dispatcher.call_sync(
            lambda: list(self.node.committed_batches)
        )

    def pending_tx_count(self) -> int:
        return self.node.pending_tx_count()


__all__ = [
    "SerialDispatcher",
    "GrpcPayloadBroadcaster",
    "ValidatorHost",
]
