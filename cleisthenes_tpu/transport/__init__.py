"""Communication layer: wire format, connection seam, transports.

Mirrors the reference's L2/L1 (SURVEY.md §1): the ``Connection`` /
``Broadcaster`` / ``Handler`` seam from reference conn.go:27-38,182-184
that lets protocol instances run over a real network or an in-proc
channel transport (reference test/mock/stream.go) unchanged.
"""

from cleisthenes_tpu.transport.message import (
    BbaPayload,
    BbaType,
    CatchupReqPayload,
    CatchupRespPayload,
    CoinPayload,
    DecSharePayload,
    Message,
    RbcPayload,
    RbcType,
    decode_message,
    encode_message,
)
from cleisthenes_tpu.transport.base import (
    Authenticator,
    Broadcaster,
    ConnectionPool,
    Handler,
    HmacAuthenticator,
    NullAuthenticator,
)
from cleisthenes_tpu.transport.channel import ChannelNetwork, ChannelConnection

__all__ = [
    "Message",
    "RbcPayload",
    "BbaPayload",
    "CatchupReqPayload",
    "CatchupRespPayload",
    "CoinPayload",
    "DecSharePayload",
    "RbcType",
    "BbaType",
    "encode_message",
    "decode_message",
    "Handler",
    "Broadcaster",
    "ConnectionPool",
    "Authenticator",
    "HmacAuthenticator",
    "NullAuthenticator",
    "ChannelNetwork",
    "ChannelConnection",
]
