"""gRPC network transport: the reference's comm.go/stream.go, TPU-build.

Topology preserved from the reference: ONE full-duplex bidi stream per
peer pair (reference pb/message.proto:7-9 ``MessageStream``), a server
that wraps every inbound stream into a ``Connection`` and hands it to
an ``on_conn`` callback (comm.go:37-51), a client that dials with a
timeout and returns a ``Connection`` (comm.go:107-140), and per-
connection reader/writer actors with a bounded outbound mailbox
(conn.go:60-77,104-180 — goroutines become threads; the mailbox depth
is Config.channel_capacity, the reference's 200-deep chan).

Differences, both deliberate:
- Frames on the wire are the self-contained codec of
  transport.message (encode_message bytes) carried as raw gRPC
  messages via the generic-handler API — no generated protobuf stubs,
  byte-identical frames to the in-proc channel transport, same MACs.
- ``verify`` is real (Authenticator seam), completing the reference's
  TODO (conn.go:134-137); unverifiable frames are counted and dropped.
"""

from __future__ import annotations

import queue
import threading
import uuid
from typing import Callable, Dict, List, Optional, Sequence

import grpc

from cleisthenes_tpu.config import (
    DEFAULT_CHANNEL_CAPACITY,
    DEFAULT_DIAL_TIMEOUT_S,
)
from cleisthenes_tpu.transport.base import (
    Authenticator,
    Handler,
    NullAuthenticator,
)
from cleisthenes_tpu.transport.message import (
    Message,
    decode_frame,
    encode_message,
)
from cleisthenes_tpu.utils.determinism import guarded_by
from cleisthenes_tpu.utils.lockcheck import new_lock

SERVICE_NAME = "cleisthenes.StreamService"
METHOD_NAME = "MessageStream"
_FULL_METHOD = f"/{SERVICE_NAME}/{METHOD_NAME}"

_identity = lambda b: b  # raw-bytes (de)serializer  # noqa: E731

_CLOSE = object()  # outbound-queue sentinel


class GrpcConnection:
    """Per-peer actor (reference conn.go:40-180).

    ``send`` enqueues onto a bounded mailbox consumed by the stream's
    writer; ``start`` runs the reader loop that decodes, verifies and
    dispatches inbound frames to the registered Handler."""

    def __init__(
        self,
        inbound,  # iterator of wire bytes
        auth: Authenticator,
        capacity: int = DEFAULT_CHANNEL_CAPACITY,
        conn_id: Optional[str] = None,
        on_close: Optional[Callable[["GrpcConnection"], None]] = None,
        delivery_columnar: bool = False,
        wave_routing: bool = False,
    ) -> None:
        self._inbound = inbound
        self._auth = auth
        self._out: "queue.Queue" = queue.Queue(maxsize=capacity)
        self._conn_id = conn_id or str(uuid.uuid4())  # comm.go:46
        self._handler: Optional[Handler] = None
        self._closed = threading.Event()
        self._reader: Optional[threading.Thread] = None
        self._on_close = on_close
        # Config.delivery_columnar: the reader splits into an ingest
        # thread (stream -> queue) and a verify loop that drains the
        # queue's backlog per pass — one message wave — and MACs it
        # through ONE Authenticator.verify_wire_many call.
        self._columnar = delivery_columnar
        # Config.wave_routing: the verified wave dispatches as ONE
        # handler call (SerialDispatcher.serve_wave — one actor
        # mailbox entry per wave, not N) instead of one serve_request
        # per frame.  Rides the columnar verify loop.
        self._wave_routing = wave_routing and delivery_columnar
        self.delivered = 0
        self.rejected = 0
        # delivery-plane counters (Metrics.snapshot()["transport"])
        self.frames_decoded = 0
        self.mac_verify_batches = 0

    # -- Connection interface (conn.go:31-38) ------------------------------

    def id(self) -> str:
        return self._conn_id

    def handle(self, handler: Handler) -> None:
        self._handler = handler

    def send(
        self,
        msg: Message,
        on_success: Optional[Callable[[Message], None]] = None,
        on_err: Optional[Callable[[Exception], None]] = None,
    ) -> None:
        """conn.go:66-77: enqueue with callbacks; full mailbox or a
        closed connection surfaces through on_err."""
        try:
            # the pool addresses client connections by roster member id
            # (host.py DialOpts conn_id=member), so conn_id names the
            # receiver for the pairwise MAC
            signed = self._auth.sign(msg, self._conn_id)
            wire = encode_message(signed)  # staticcheck: allow[DET006] scalar arm / pre-pool path
        except Exception as exc:
            if on_err is not None:
                on_err(exc)
            return
        if self.send_wire(wire, on_err=on_err) and on_success is not None:
            on_success(msg)

    def send_wire(
        self,
        wire: bytes,
        on_err: Optional[Callable[[Exception], None]] = None,
    ) -> bool:
        """Enqueue pre-signed wire bytes (the broadcast fast path:
        sign+encode once, fan the identical frame to every peer)."""
        if self._closed.is_set():
            if on_err is not None:
                on_err(ConnectionError("connection closed"))
            return False
        try:
            self._out.put_nowait(wire)
            return True
        except queue.Full as exc:
            if on_err is not None:
                on_err(exc)
            return False

    def start(self) -> None:
        """conn.go:104-128: spawn the reader; the writer is the
        outbound iterator consumed by gRPC itself."""
        if self._reader is not None:
            return
        self._reader = threading.Thread(
            target=self._read_loop, name=f"conn-read-{self._conn_id[:8]}",
            daemon=True,
        )
        self._reader.start()

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        try:  # best-effort wakeup; outbound() also polls the flag
            self._out.put_nowait(_CLOSE)
        except queue.Full:
            pass
        if self._on_close is not None:
            self._on_close(self)

    # -- internals ---------------------------------------------------------

    def outbound(self):
        """The gRPC response/request iterator (writeStream,
        conn.go:143-162).  Polls the closed flag so termination never
        depends on a sentinel racing a full mailbox."""
        while True:
            try:
                item = self._out.get(timeout=0.25)
            except queue.Empty:
                if self._closed.is_set():
                    return
                continue
            if item is _CLOSE:
                return
            yield item

    def _read_loop(self) -> None:
        """readStream + dispatch (conn.go:110-128,164-180)."""
        if self._columnar:
            self._read_loop_columnar()
            return
        try:
            for wire in self._inbound:
                if self._closed.is_set():
                    break
                try:
                    msg, signing_prefix = decode_frame(wire)
                except ValueError:
                    self.rejected += 1
                    self._trace_rejected("undecodable")
                    continue
                self.frames_decoded += 1
                self.mac_verify_batches += 1
                if not self._auth.verify_wire(  # conn.go:134-137, real
                    msg, signing_prefix
                ):
                    self.rejected += 1
                    self._trace_rejected("bad_mac")
                    continue
                self.delivered += 1
                handler = self._handler
                if handler is not None:
                    handler.serve_request(msg)  # staticcheck: allow[DET004] scalar comparison arm
        except Exception:  # staticcheck: allow[ERR001] finally closes the conn
            pass  # stream broken: fall through to close
        finally:
            self.close()

    def _ingest_loop(self, q: "queue.Queue") -> None:
        """Stream -> local queue: the wave buffer's producer side.  The
        queue is BOUNDED (the scalar path's synchronous consumption
        exerted backpressure through gRPC flow control; an unbounded
        buffer here would re-open the flood-to-OOM hole), so a full
        buffer blocks ingest — and with it the gRPC window — until the
        verify loop drains.  The sentinel (stream end OR break)
        releases the verify loop."""
        try:
            for wire in self._inbound:
                if self._closed.is_set():
                    break
                while not self._closed.is_set():
                    try:
                        q.put(wire, timeout=0.25)
                        break
                    except queue.Full:
                        continue
        except Exception:  # staticcheck: allow[ERR001] sentinel closes the conn
            pass  # stream broken: the sentinel ends the verify loop
        finally:
            while True:  # the sentinel must land; the verify loop
                try:  # drains continuously, so this terminates
                    q.put(_CLOSE, timeout=0.25)
                    break
                except queue.Full:
                    if self._closed.is_set():
                        break  # verify loop already exiting on the flag

    def _read_loop_columnar(self) -> None:
        """Wave-batched inbound path (Config.delivery_columnar): drain
        the ingest queue's current backlog — one message wave, however
        many frames arrived since the last pass — decode them, and MAC
        the whole wave through ONE verify_wire_many call before
        dispatching in arrival order.  Width follows the actual burst
        shape: a peer's bundle fan-in lands together, so steady-state
        waves are much wider than 1."""
        q: "queue.Queue" = queue.Queue(maxsize=self._out.maxsize)
        threading.Thread(
            target=self._ingest_loop,
            args=(q,),
            name=f"conn-ingest-{self._conn_id[:8]}",
            daemon=True,
        ).start()
        try:
            ended = False
            while not ended and not self._closed.is_set():
                try:
                    first = q.get(timeout=0.25)
                except queue.Empty:
                    continue
                batch = [first]
                while True:  # the wave: everything already buffered
                    try:
                        batch.append(q.get_nowait())
                    except queue.Empty:
                        break
                msgs, prefixes = [], []
                for wire in batch:
                    if wire is _CLOSE:
                        ended = True
                        continue
                    try:
                        msg, prefix = decode_frame(wire)
                    except ValueError:
                        self.rejected += 1
                        self._trace_rejected("undecodable")
                        continue
                    self.frames_decoded += 1
                    msgs.append(msg)
                    prefixes.append(prefix)
                if not msgs:
                    continue
                self.mac_verify_batches += 1
                tr = getattr(self._handler, "trace", None)
                t0 = 0.0 if tr is None else tr.now()
                oks = self._auth.verify_wire_many(msgs, prefixes)
                if tr is not None:
                    tr.complete(
                        "transport",
                        "mac_verify_batch",
                        t0,
                        batch_width=len(msgs),
                    )
                handler = self._handler
                good: List[Message] = []
                for msg, ok in zip(msgs, oks):
                    if not ok:
                        self.rejected += 1
                        self._trace_rejected("bad_mac")
                        continue
                    self.delivered += 1
                    good.append(msg)
                if not good or handler is None:
                    continue
                serve_wave = (
                    getattr(handler, "serve_wave", None)
                    if self._wave_routing
                    else None
                )
                if serve_wave is not None:
                    # one actor message per wave: the dispatcher's
                    # mailbox carries the whole verified burst
                    serve_wave(good)
                else:
                    for msg in good:
                        handler.serve_request(msg)  # staticcheck: allow[DET004] scalar arm
        finally:
            self.close()

    def _trace_rejected(self, why: str) -> None:
        """Mirror of ChannelNetwork's rejected-frame instant: when the
        bound handler (the host's SerialDispatcher) carries a flight
        recorder, every rejected frame lands in the trace."""
        tr = getattr(self._handler, "trace", None)
        if tr is not None:
            tr.instant(
                "transport", "rejected", conn=self._conn_id, why=why
            )


ConnHandler = Callable[[GrpcConnection], None]  # comm.go:18
ErrHandler = Callable[[Exception], None]  # comm.go:19


@guarded_by(
    "_lock",
    "_conns",
    "_delivered_closed",
    "_rejected_closed",
    "_decoded_closed",
    "_batches_closed",
)
class GrpcServer:
    """Reference comm.go:21-99 GrpcServer.

    ``on_conn`` fires for every accepted stream with a started-but-
    unhandled Connection; the callback registers a Handler and calls
    ``start()`` (exactly the reference's app contract, comm.go:47-49).
    """

    def __init__(
        self,
        addr: str,
        auth: Optional[Authenticator] = None,
        capacity: int = DEFAULT_CHANNEL_CAPACITY,
        delivery_columnar: bool = False,
        wave_routing: bool = False,
    ) -> None:
        self.addr = addr
        self._auth = auth or NullAuthenticator()
        self._capacity = capacity
        self._delivery_columnar = delivery_columnar
        self._wave_routing = wave_routing
        self._on_conn: Optional[ConnHandler] = None
        self._on_err: Optional[ErrHandler] = None
        self._server: Optional[grpc.Server] = None
        self._conns: List[GrpcConnection] = []
        self._lock = new_lock()
        self.port: Optional[int] = None
        # counters folded in from closed connections, so stats() stays
        # cumulative across redials
        self._delivered_closed = 0
        self._rejected_closed = 0
        self._decoded_closed = 0
        self._batches_closed = 0

    def on_conn(self, handler: ConnHandler) -> None:
        """comm.go:65-70."""
        self._on_conn = handler

    def on_err(self, handler: ErrHandler) -> None:
        """comm.go:72-77."""
        self._on_err = handler

    def _remove_conn(self, conn: "GrpcConnection") -> None:
        with self._lock:
            try:
                self._conns.remove(conn)
            except ValueError:
                return  # already folded into the cumulative counters
            self._delivered_closed += conn.delivered
            self._rejected_closed += conn.rejected
            self._decoded_closed += conn.frames_decoded
            self._batches_closed += conn.mac_verify_batches

    def stats(self) -> dict:
        """Cumulative inbound frame counters across every stream this
        server ever accepted (live + closed), for
        ``Metrics.snapshot()["transport"]``."""
        with self._lock:
            delivered = self._delivered_closed
            rejected = self._rejected_closed
            decoded = self._decoded_closed
            batches = self._batches_closed
            for conn in self._conns:
                delivered += conn.delivered
                rejected += conn.rejected
                decoded += conn.frames_decoded
                batches += conn.mac_verify_batches
        return {
            "delivered": delivered,
            "rejected": rejected,
            "frames_decoded": decoded,
            "mac_verify_batches": batches,
        }

    def _stream_behavior(self, request_iterator, context):
        conn = GrpcConnection(
            request_iterator,
            self._auth,
            capacity=self._capacity,
            on_close=lambda c: (self._remove_conn(c), context.cancel()),
            delivery_columnar=self._delivery_columnar,
            wave_routing=self._wave_routing,
        )
        with self._lock:
            self._conns.append(conn)
        if self._on_conn is not None:
            self._on_conn(conn)
        return conn.outbound()

    def listen(self, max_workers: int = 32) -> None:
        """comm.go:79-99 — binds and serves in the background (gRPC
        owns the accept loop; no blocking call needed)."""
        handler = grpc.method_handlers_generic_handler(
            SERVICE_NAME,
            {
                METHOD_NAME: grpc.stream_stream_rpc_method_handler(
                    self._stream_behavior,
                    request_deserializer=_identity,
                    response_serializer=_identity,
                )
            },
        )
        from concurrent import futures as _futures

        self._server = grpc.server(
            _futures.ThreadPoolExecutor(max_workers=max_workers)
        )
        self._server.add_generic_rpc_handlers((handler,))
        self.port = self._server.add_insecure_port(self.addr)
        if self.port == 0:
            err = RuntimeError(f"could not bind {self.addr}")
            if self._on_err is not None:
                self._on_err(err)
            raise err  # never leave the caller with a dead server
        self._server.start()

    def stop(self, grace: float = 0.5) -> None:
        """comm.go:101-105."""
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            conn.close()
        if self._server is not None:
            self._server.stop(grace)


class DialOpts:
    """comm.go:111-117."""

    def __init__(
        self,
        addr: str,
        timeout_s: float = DEFAULT_DIAL_TIMEOUT_S,
        capacity: int = DEFAULT_CHANNEL_CAPACITY,
        conn_id: Optional[str] = None,
    ):
        self.addr = addr
        self.timeout_s = timeout_s
        self.capacity = capacity
        self.conn_id = conn_id


class GrpcClient:
    """Reference comm.go:119-140 GrpcClient."""

    def __init__(
        self,
        auth: Optional[Authenticator] = None,
        delivery_columnar: bool = False,
        wave_routing: bool = False,
    ):
        self._auth = auth or NullAuthenticator()
        self._delivery_columnar = delivery_columnar
        self._wave_routing = wave_routing
        self._channels: List[grpc.Channel] = []

    def dial(self, opts: DialOpts) -> GrpcConnection:
        """Insecure dial with timeout -> client stream wrapper ->
        Connection (comm.go:125-140)."""
        channel = grpc.insecure_channel(opts.addr)
        try:
            grpc.channel_ready_future(channel).result(timeout=opts.timeout_s)
        except Exception:
            channel.close()  # don't leak channels across dial retries
            raise
        self._channels.append(channel)
        multi = channel.stream_stream(
            _FULL_METHOD,
            request_serializer=_identity,
            response_deserializer=_identity,
        )
        # the connection exists first (gRPC starts consuming the
        # request iterator immediately); the call object then becomes
        # the connection's inbound stream
        conn = GrpcConnection(
            None,
            self._auth,
            capacity=opts.capacity,
            conn_id=opts.conn_id,
            delivery_columnar=self._delivery_columnar,
            wave_routing=self._wave_routing,
        )
        call = multi(conn.outbound())
        conn._inbound = call

        def cleanup(_c, ch=channel, call=call):
            # release the channel with its stream: redial cycles must
            # not accumulate live channels (sockets + threads)
            try:
                call.cancel()
            finally:
                try:
                    ch.close()
                except Exception:  # staticcheck: allow[ERR001] best-effort close
                    pass
                try:
                    self._channels.remove(ch)
                except ValueError:
                    pass

        conn._on_close = cleanup
        return conn

    def close(self) -> None:
        for ch in self._channels:
            ch.close()


__all__ = [
    "GrpcServer",
    "GrpcClient",
    "GrpcConnection",
    "DialOpts",
    "SERVICE_NAME",
    "METHOD_NAME",
]
