"""In-process channel transport with a deterministic scheduler.

The reference tests multi-node behavior without a cluster by wiring N
in-proc ``Connection``s over a channel-loopback fake transport
(``mock.StreamWrapper``, test/mock/stream.go:8-38; pattern described in
SURVEY.md §4.3).  This module is that idea promoted to a first-class
subsystem: a ``ChannelNetwork`` hosts any number of in-proc validators,
every message crosses the real wire codec (encode -> bytes -> decode)
and the real Authenticator, and delivery order is driven by a *seeded
deterministic scheduler* so Byzantine interleavings are replayable —
the asyncio-era answer to the reference's ``go test -race`` discipline
(SURVEY.md §5.2, §5.4: "seeded deterministic scheduler to test
Byzantine interleavings").

Fault injection (SURVEY.md §5.3 "the mock stream is the natural
injection point"): ``crash(node)``, ``partition(a, b)``, and an
arbitrary ``fault_filter`` for message-level drop/tamper/reorder
adversaries.
"""

from __future__ import annotations

import collections
import heapq
import random
import time
from typing import Callable, Dict, List, Optional, Set, Tuple, Union

from cleisthenes_tpu.transport.base import (
    Authenticator,
    Handler,
    NullAuthenticator,
    sign_wave_counted,
)
from cleisthenes_tpu.transport.message import (
    FrameDecodeMemo,
    FrameEncodeMemo,
    Message,
    decode_frame,
    decode_frame_shared,
    encode_message,
    payload_body_count,
)
from cleisthenes_tpu.transport.wan import WanEmulator, WanProfile

# A fault filter sees (sender_id, receiver_id, wire_bytes) and returns
# what to deliver: bytes (pass/tamper), None (drop), or a list of
# byte-strings (duplication / replay injection — the first delivers
# now, the rest re-enter the pending queue).  Tampering is modeled by
# returning different bytes — which the Authenticator then catches.
FaultFilter = Callable[[str, str, bytes], "Optional[bytes] | list"]


class ChannelEndpoint:
    """One validator's attachment to the network: its handler plus its
    authenticator (signing outbound, verifying inbound)."""

    def __init__(
        self,
        node_id: str,
        handler: Handler,
        auth: Authenticator,
        encode_memo: Optional[FrameEncodeMemo] = None,
    ) -> None:
        self.node_id = node_id
        self.auth = auth
        self.delivered = 0
        self.rejected = 0  # failed MAC verification
        # delivery-plane counters (Config.delivery_columnar; zeroed
        # keys of Metrics.snapshot()["transport"] via endpoint_stats):
        # payload decodes actually executed / shared-prefix memo
        # hits+misses / Authenticator verify invocations (one per
        # frame scalar, one per wave batch columnar)
        self.frames_decoded = 0
        self.decode_memo_hits = 0
        self.decode_memo_misses = 0
        self.mac_verify_batches = 0
        # egress-plane counters (Config.egress_columnar, the send-side
        # twins): payload bodies actually encoded / shared-prefix
        # encode-memo hits+misses / Authenticator sign invocations
        # (one per post scalar, one per wave columnar).  The memo is
        # THIS node's outbound encode memo (None on the scalar arm).
        self.frames_encoded = 0
        self.encode_memo_hits = 0
        self.encode_memo_misses = 0
        self.mac_sign_batches = 0
        self.encode_memo = encode_memo
        self.bind(handler)

    def bind(self, handler: Handler) -> None:
        """(Re)bind the handler.  ChannelNetwork.run() delivers the
        idle-callback promise (wire_idle_hooks) at every quiescence
        point; callers driving delivery manually with step() must pair
        it with idle_phase() — see step()."""
        self.handler = handler
        from cleisthenes_tpu.transport.base import wire_idle_hooks

        self.flush_outbound, self.on_idle = wire_idle_hooks(handler)


class ChannelConnection:
    """The in-proc ``Connection``: send = enqueue onto the network
    (reference conn.go:66-77 semantics, minus goroutines — delivery
    happens when the scheduler runs)."""

    def __init__(self, network: "ChannelNetwork", local_id: str, remote_id: str):
        self._network = network
        self._local_id = local_id
        self._remote_id = remote_id
        self._closed = False

    def id(self) -> str:
        return self._remote_id

    def send(self, msg, on_success=None, on_err=None) -> None:
        if self._closed:
            if on_err is not None:
                on_err(ConnectionError("connection closed"))
            return
        try:
            self._network.post(self._local_id, self._remote_id, msg)
        except Exception as exc:  # queue full / encode error
            if on_err is not None:
                on_err(exc)
            return
        if on_success is not None:
            on_success(msg)

    def close(self) -> None:
        self._closed = True

    def start(self) -> None:  # no reader loop needed in-proc
        pass

    def handle(self, handler) -> None:
        """Rebind where THIS node processes inbound traffic
        (reference conn.go:81-85: Handle sets the local dispatch target)."""
        self._network.rebind_handler(self._local_id, handler)


class ChannelNetwork:
    """N in-proc validators + a deterministic message scheduler."""

    def __init__(
        self,
        seed: Optional[int] = None,
        queue_capacity: int = 1_000_000,
        delivery_columnar: bool = False,
        wave_routing: bool = False,
        egress_columnar: bool = False,
        wan_profile: Optional[Union[str, WanProfile]] = None,
    ):
        # seed=None -> FIFO delivery; seed=int -> seeded random-order
        # delivery (the adversarial asynchronous scheduler from
        # docs/HONEYBADGER-EN.md:125-140's PBFT comparison).
        self._rng = random.Random(seed) if seed is not None else None
        self._endpoints: Dict[str, ChannelEndpoint] = {}
        # FIFO mode uses a deque (O(1) popleft); seeded mode uses a
        # list with swap-pop (O(1) uniform removal, order irrelevant).
        # Entries are 5-slot LISTS [sender, receiver, wire, prefiltered,
        # prepared] — slot 4 holds the columnar arm's pre-wave decode +
        # MAC verdict (None until a wave pass prepares it).
        self._pending = collections.deque() if seed is None else []
        self._queue_capacity = queue_capacity
        self._crashed: Set[str] = set()
        self._partitions: Set[Tuple[str, str]] = set()
        self.fault_filter: Optional[FaultFilter] = None
        self.messages_posted = 0
        self.bytes_posted = 0
        # (kind, body) -> payload: one broadcast's body parses once
        # for all local receivers (scalar arm; see message.decode_frame)
        self._payload_memo: dict = {}
        # Columnar delivery plane (Config.delivery_columnar): frames
        # decode through the shared-prefix memo and MAC-verify in ONE
        # Authenticator.verify_wire_many batch per receiver per wave
        # (_prepare_wave).  The scalar arm above stays byte-equivalent.
        self._columnar = delivery_columnar
        self._decode_memo = FrameDecodeMemo() if delivery_columnar else None
        self._unprepared = 0  # pending entries awaiting a wave pass
        # Wave-routed ingest (Config.wave_routing): one step() drains
        # the whole prepared wave, bucketing verified frames per
        # receiver, and hands each receiver its bundle in ONE
        # serve_wave call (protocol.router demuxes it into typed
        # columns) instead of one serve_request per frame.  Handlers
        # without serve_wave — and frames a mounted fault_filter must
        # see per-frame — fall back to the scalar chain.
        self._wave_routing = wave_routing and delivery_columnar
        # network-wide delivery counters (the per-epoch numbers
        # bench.py sections and perfgate gate on; per-endpoint twins
        # live on ChannelEndpoint for Metrics.snapshot)
        self.frames_decoded = 0
        self.mac_verify_calls = 0
        # Columnar egress plane (Config.egress_columnar): each flush's
        # whole wave of folded bundles arrives in ONE post_wave call,
        # signs through the sender endpoint's sign_wire_wave (payload
        # bodies encode once per distinct object via the per-endpoint
        # FrameEncodeMemo, MACs in one batched pass) and enqueues one
        # frame per peer per flush.  The scalar per-post path stays
        # byte-equivalent (tests/test_egress_equivalence.py).
        self._egress_columnar = egress_columnar
        # network-wide egress counters (the send-side twins of the
        # delivery counters above)
        self.frames_encoded = 0
        self.mac_sign_calls = 0
        # test hook (tests/test_egress_equivalence.py): when set,
        # called (sender_id, receiver_id, wire bytes) for every frame
        # at enqueue time — the frame-stream capture the egress
        # byte-equivalence proof compares across arms.  None in all
        # non-test use.
        self.frame_tap: Optional[Callable[[str, str, bytes], None]] = None
        # Seeded WAN emulation plane (ISSUE 16): when a profile is
        # mounted, every _enqueue prices the frame through a per-link
        # LinkModel (base RTT, jitter, retransmission delay, bandwidth
        # serialization, straggler episodes) into a VIRTUAL-clock
        # delivery deadline.  Undelivered frames wait in _wan_holding
        # — a (ready_at, seq, entry) min-heap invisible to
        # _prepare_wave/_step_wave — until _wan_release moves them to
        # _pending; when the visible queue drains the clock jumps to
        # the next deadline (quantum-coalesced).  The seq tiebreak
        # keeps heap order a pure function of admission order, so a
        # fixed (seed, profile) replays byte-identically.
        self.wan = (
            WanEmulator(wan_profile, seed)
            if wan_profile is not None
            else None
        )
        self._wan_holding: list = []
        self._wan_seq = 0

    # -- topology ----------------------------------------------------------

    def join(
        self,
        node_id: str,
        handler: Handler,
        auth: Optional[Authenticator] = None,
    ) -> None:
        self._endpoints[node_id] = ChannelEndpoint(
            node_id,
            handler,
            auth or NullAuthenticator(),
            encode_memo=(
                FrameEncodeMemo() if self._egress_columnar else None
            ),
        )
        if self.wan is not None:
            self.wan.register(node_id)

    def rebind_handler(self, node_id: str, handler: Handler) -> None:
        self._endpoints[node_id].bind(handler)

    def connect(self, local_id: str, remote_id: str) -> ChannelConnection:
        return ChannelConnection(self, local_id, remote_id)

    def node_ids(self) -> List[str]:
        return sorted(self._endpoints)

    def endpoint_stats(self, node_id: str) -> Dict[str, int]:
        """One endpoint's frame counters, for
        ``Metrics.snapshot()["transport"]`` (the public route to
        ``rejected`` — adversarial tests used to reach through the
        private ``_endpoints`` map for it)."""
        ep = self._endpoints[node_id]
        return {
            "delivered": ep.delivered,
            "rejected": ep.rejected,
            "frames_decoded": ep.frames_decoded,
            "decode_memo_hits": ep.decode_memo_hits,
            "decode_memo_misses": ep.decode_memo_misses,
            "mac_verify_batches": ep.mac_verify_batches,
            "frames_encoded": ep.frames_encoded,
            "encode_memo_hits": ep.encode_memo_hits,
            "encode_memo_misses": ep.encode_memo_misses,
            "mac_sign_batches": ep.mac_sign_batches,
        }

    def delivery_stats(self) -> Dict[str, int]:
        """Network-wide delivery-plane counters (deterministic for a
        seeded schedule): payload decodes executed, Authenticator
        verify invocations, and the shared-prefix memo's hit/miss
        tallies — the numbers bench.py's protocol sections and
        tools/perfgate.py gate on."""
        memo = self._decode_memo
        ehits = emisses = 0
        for ep in self._endpoints.values():
            em = ep.encode_memo
            if em is not None:
                ehits += em.hits
                emisses += em.misses
        return {
            "frames_decoded": self.frames_decoded,
            "mac_verifies": self.mac_verify_calls,
            "decode_memo_hits": 0 if memo is None else memo.hits,
            "decode_memo_misses": 0 if memo is None else memo.misses,
            # egress twins (Config.egress_columnar): payload bodies
            # actually encoded, Authenticator sign invocations, and
            # the per-endpoint encode memos' pooled hit/miss tallies
            "frames_encoded": self.frames_encoded,
            "mac_signs": self.mac_sign_calls,
            "encode_memo_hits": ehits,
            "encode_memo_misses": emisses,
        }

    def link_states(self, node_id: str) -> Dict[str, Dict[str, object]]:
        """``node_id``'s view of every peer link — the
        channel-transport analog of the gRPC dial layer's
        PeerHealthTracker, feeding the SLO watchdog's peer detector
        (the public route to fault state; /healthz must degrade under
        an injected partition on THIS transport too).

        Per peer: ``state`` ("down" when the peer crashed or a
        partition severs the pair; "straggling" when a mounted WAN
        profile has either endpoint inside a slow episode — alive but
        DEGRADED-grade, never DOWN; else "up"), plus the link model's
        ``rtt_ms`` / ``loss`` / ``straggling`` fields (zeroed without
        a WAN profile)."""
        wan = self.wan
        out: Dict[str, Dict[str, object]] = {}
        for peer in sorted(self._endpoints):
            if peer == node_id:
                continue
            down = (
                peer in self._crashed
                or node_id in self._crashed
                or (node_id, peer) in self._partitions
            )
            if wan is None:
                info: Dict[str, object] = {
                    "rtt_ms": 0.0,
                    "loss": 0.0,
                    "straggling": False,
                }
            else:
                info = wan.link_info(node_id, peer)
            state = "down" if down else (
                "straggling" if info["straggling"] else "up"
            )
            info["state"] = state
            out[peer] = info
        return out

    # -- fault injection ---------------------------------------------------

    def crash(self, node_id: str) -> None:
        """Fail-stop: node neither sends nor receives from now on, and
        its in-flight frames are lost NOW (a dead host's socket buffers
        die with it) — so a later restart() cannot resurrect pre-crash
        traffic as ghost deliveries."""
        self._crashed.add(node_id)
        kept = [
            it
            for it in self._pending
            if it[0] != node_id and it[1] != node_id
        ]
        if isinstance(self._pending, collections.deque):
            self._pending = collections.deque(kept)
        else:
            self._pending = kept
        self._unprepared = sum(1 for it in kept if it[4] is None)
        if self._wan_holding:
            # WAN-held frames die with the host's buffers too
            self._wan_holding = [
                (t, s, it)
                for (t, s, it) in self._wan_holding
                if it[0] != node_id and it[1] != node_id
            ]
            heapq.heapify(self._wan_holding)

    def recover(self, node_id: str) -> None:
        """Un-crash, keeping the node's old handler (a blip, not a
        process restart — use restart() for the latter)."""
        self._crashed.discard(node_id)

    def restart(
        self,
        node_id: str,
        handler: Handler,
        auth: Optional[Authenticator] = None,
    ) -> None:
        """Rejoin a crashed node as a restarted PROCESS: fresh handler
        (typically a HoneyBadger rebuilt from its durable batch log),
        same identity, empty inbox — pre-crash frames were dropped at
        crash time.  ``auth`` defaults to the endpoint's existing
        authenticator (key material survives restarts)."""
        self._crashed.discard(node_id)
        ep = self._endpoints.get(node_id)
        if ep is None:
            self.join(node_id, handler, auth)
            return
        if auth is not None:
            ep.auth = auth
        ep.bind(handler)

    def partition(self, a: str, b: str) -> None:
        """Drop all traffic between a and b (both directions)."""
        self._partitions.add((a, b))
        self._partitions.add((b, a))

    def heal(self, a: str, b: str) -> None:
        self._partitions.discard((a, b))
        self._partitions.discard((b, a))

    # -- message flow ------------------------------------------------------

    def _enqueue(self, sender_id: str, receiver_id: str, wire: bytes) -> None:
        self.messages_posted += 1
        self.bytes_posted += len(wire)
        if self.frame_tap is not None:
            self.frame_tap(sender_id, receiver_id, wire)
        entry = [sender_id, receiver_id, wire, False, None]
        if self.wan is not None:
            # WAN admission: the frame is priced into a virtual-clock
            # deadline and held invisible to the scheduler (and to the
            # wave passes) until _wan_release moves it over
            ready_at = self.wan.admit(sender_id, receiver_id, len(wire))
            heapq.heappush(
                self._wan_holding, (ready_at, self._wan_seq, entry)
            )
            self._wan_seq += 1
            return
        self._pending.append(entry)
        self._unprepared += 1

    def _wan_release(self) -> None:
        """Move every WAN-held frame whose deadline the virtual clock
        has passed into the visible pending queue.  When the visible
        queue is empty, the clock first jumps to the earliest held
        deadline plus one delivery quantum — co-deadline frames (an
        RBC echo wave, a broadcast fan-out) land in the same wave
        instead of one wave per float, keeping step counts bounded
        without changing which frames *can* be seen before others."""
        wan, holding = self.wan, self._wan_holding
        if wan is None or not holding:
            return
        if not self._pending and holding[0][0] > wan.now:
            wan.advance(
                holding[0][0] + wan.profile.delivery_quantum_ms / 1e3
            )
        now = wan.now
        while holding and holding[0][0] <= now:
            _, _, entry = heapq.heappop(holding)
            self._pending.append(entry)
            self._unprepared += 1

    def post(self, sender_id: str, receiver_id: str, msg: Message) -> None:
        """Sign, encode and enqueue one message."""
        if sender_id in self._crashed:
            return
        ep = self._endpoints.get(sender_id)
        if ep is not None and self._egress_columnar:
            # single-receiver sends take the SAME wave signer as flush
            # waves (ISSUE 13 satellite): a mid-wave re-send of a
            # payload object the encode memo already holds reuses its
            # encoded body instead of re-encoding the envelope
            self.post_wave(sender_id, (((receiver_id,), msg),))
            return
        if self.pending_count() >= self._queue_capacity:
            raise OverflowError("channel network queue full")
        if ep is None:
            wire = encode_message(msg)  # staticcheck: allow[DET006] non-endpoint test rig
        else:  # sign_wire_many encodes the envelope exactly once
            bodies = payload_body_count(msg.payload)
            ep.frames_encoded += bodies
            ep.mac_sign_batches += 1
            self.frames_encoded += bodies
            self.mac_sign_calls += 1
            frames = ep.auth.sign_wire_many(  # staticcheck: allow[DET006] scalar arm
                msg, [receiver_id]
            )
            wire = frames[receiver_id]
        self._enqueue(sender_id, receiver_id, wire)

    def post_many(
        self, sender_id: str, receiver_ids, msg: Message
    ) -> None:
        """Broadcast enqueue: ONE payload encode for the whole receiver
        set via the authenticator's sign_wire_many fast path (pairwise
        MACs differ per receiver; the envelope bytes do not)."""
        if sender_id in self._crashed:
            return
        ep = self._endpoints.get(sender_id)
        if ep is None:
            for rid in receiver_ids:
                self.post(sender_id, rid, msg)
            return
        if self._egress_columnar:
            self.post_wave(sender_id, ((tuple(receiver_ids), msg),))
            return
        bodies = payload_body_count(msg.payload)
        ep.frames_encoded += bodies
        ep.mac_sign_batches += 1
        self.frames_encoded += bodies
        self.mac_sign_calls += 1
        frames = ep.auth.sign_wire_many(  # staticcheck: allow[DET006] scalar arm
            msg, receiver_ids
        )
        for rid, wire in frames.items():
            if self.pending_count() >= self._queue_capacity:
                raise OverflowError("channel network queue full")
            self._enqueue(sender_id, rid, wire)

    def post_wave(self, sender_id: str, entries) -> None:
        """One egress wave (Config.egress_columnar): ``entries`` are
        ``(receiver_ids, msg)`` pairs — everything one coalescer flush
        ships.  The whole wave signs through the sender endpoint's
        ``Authenticator.sign_wire_wave`` (payload bodies encode once
        per distinct object via the per-endpoint FrameEncodeMemo, MACs
        in one batched pass over the precomputed pair-key schedules)
        and enqueues in one pass — one frame per peer per flush, since
        the coalescer already folded each receiver's wave into a
        single bundle.  Admission is atomic: the wave is rejected
        whole when it would overflow the queue, so a coalescer retry
        never double-posts a partially shipped wave."""
        if sender_id in self._crashed:
            return
        ep = self._endpoints.get(sender_id)
        if ep is None:
            for rids, msg in entries:
                for rid in rids:
                    self.post(sender_id, rid, msg)
            return
        need = sum(len(rids) for rids, _msg in entries)
        if self.pending_count() + need > self._queue_capacity:
            raise OverflowError("channel network queue full")
        tr = getattr(ep.handler, "trace", None)
        t0 = 0.0 if tr is None else tr.now()
        frames_list, hits, misses, bodies = sign_wave_counted(
            ep.auth,
            [(msg, rids) for rids, msg in entries],
            ep.encode_memo,
        )
        ep.mac_sign_batches += 1
        self.mac_sign_calls += 1
        ep.encode_memo_hits += hits
        ep.encode_memo_misses += misses
        ep.frames_encoded += bodies
        self.frames_encoded += bodies
        if tr is not None:
            # ONE span per egress wave (mirror of the ingest
            # frame_decode span): args carry the wave's bundle count
            # and the encode memo's hit tally, tools/tracetool.py
            # rolls them into the delivery summary
            tr.complete(
                "transport",
                "frame_encode",
                t0,
                frames=len(entries),
                memo_hits=hits,
            )
        for (rids, _msg), frames in zip(entries, frames_list):
            for rid in rids:
                self._enqueue(sender_id, rid, frames[rid])

    def pending_count(self) -> int:
        """In-flight frames: scheduler-visible plus WAN-held."""
        return len(self._pending) + len(self._wan_holding)

    def _prepare_wave(self) -> None:
        """Columnar arm: decode (shared-prefix memoized) and
        MAC-verify every not-yet-prepared pending frame — ONE
        ``verify_wire_many`` batch per receiver per wave.  A wave is
        whatever the previous handler turns posted since the last
        pass; the scheduler then delivers prepared frames in its usual
        (FIFO or seeded) order, so the interleaving semantics are
        untouched.  Skipped entirely while a fault_filter is mounted:
        tampering adversaries must see — and re-verify — the exact
        delivered bytes (the scalar per-frame path below)."""
        self._unprepared = 0
        todo: Dict[str, list] = {}
        crashed, partitions = self._crashed, self._partitions
        for it in self._pending:
            # frames the delivery checks would drop anyway (crashed
            # ends, severed pairs) must not burn digest+decode+MAC
            # work here or skew the delivery counters — the scalar arm
            # checks these before ever decoding.  A frame skipped now
            # that becomes deliverable later (heal/recover) falls to
            # the scalar per-frame path at pop time.
            if (
                it[4] is None
                and it[1] not in crashed
                and it[0] not in crashed
                and (it[0], it[1]) not in partitions
            ):
                todo.setdefault(it[1], []).append(it)
        memo = self._decode_memo
        for receiver in sorted(todo):  # deterministic endpoint order
            ep = self._endpoints.get(receiver)
            if ep is None:
                continue
            msgs, prefixes, good = [], [], []
            tr = getattr(ep.handler, "trace", None)
            t0 = 0.0 if tr is None else tr.now()
            wave_hits0 = memo.hits
            attempts = 0
            for it in todo[receiver]:
                attempts += 1
                h0 = memo.hits
                try:
                    msg, prefix = decode_frame_shared(it[2], memo)
                except ValueError:
                    it[4] = (None, "undecodable")
                    continue
                if memo.hits > h0:
                    ep.decode_memo_hits += 1
                else:
                    ep.decode_memo_misses += 1
                    ep.frames_decoded += 1
                    self.frames_decoded += 1
                msgs.append(msg)
                prefixes.append(prefix)
                good.append(it)
            if tr is not None and attempts:
                # ONE span per receiver per wave (a per-frame span at
                # N=64 is ~350k events/run — it would overflow the
                # trace ring and distort the attribution it feeds):
                # args carry the wave's decode-attempt and memo-hit
                # counts, tools/tracetool.py rolls them up
                tr.complete(
                    "transport",
                    "frame_decode",
                    t0,
                    frames=attempts,
                    memo_hits=memo.hits - wave_hits0,
                )
            if not msgs:
                continue
            self.mac_verify_calls += 1
            ep.mac_verify_batches += 1
            t0 = 0.0 if tr is None else tr.now()
            oks = ep.auth.verify_wire_many(msgs, prefixes)
            if tr is not None:
                tr.complete(
                    "transport",
                    "mac_verify_batch",
                    t0,
                    batch_width=len(msgs),
                )
            for it, msg, ok in zip(good, msgs, oks):
                it[4] = (msg, True) if ok else (None, "bad_mac")

    def _step_wave(self) -> bool:
        """Wave-routing delivery (Config.wave_routing): ONE step
        drains the entire pending queue — one message wave, everything
        the previous handler turns posted — bucketing verified frames
        per receiver in scheduler pop order, then hands each receiver
        its bundle in a single ``serve_wave`` call (the WaveRouter
        demuxes it into typed ingest columns; one batch handler
        dispatch per message kind).  Receivers fire in sorted-id order
        (the idle_phase discipline); messages their handlers post form
        the NEXT wave.  Frames a mounted fault_filter must see — and
        frames the wave pass skipped (crashed/severed at prepare time)
        — decode and verify through the per-frame scalar path, but
        still JOIN the receiver's wave, so the router seam stays
        exercised under wire-fault schedules."""
        if not self._pending:
            return False
        if self.fault_filter is None and self._unprepared:
            self._prepare_wave()
        waves: Dict[str, List[Message]] = {}
        while self._pending:
            if self._rng is None:
                item = self._pending.popleft()
            else:
                idx = self._rng.randrange(len(self._pending))
                item = self._pending[idx]
                self._pending[idx] = self._pending[-1]
                self._pending.pop()
            sender, receiver, wire, prefiltered, prepared = item
            if prepared is None and self._unprepared > 0:
                self._unprepared -= 1
            if receiver in self._crashed or sender in self._crashed:
                continue
            if (sender, receiver) in self._partitions:
                continue
            ep = self._endpoints.get(receiver)
            if ep is None:
                continue
            if prepared is not None and self.fault_filter is None:
                # cached pre-wave verdict — only usable while NO
                # filter is mounted: a filter mounted mid-run (with
                # prepared frames still in flight) must see and
                # re-verify the exact delivered bytes, exactly like
                # the scalar arm re-filters prepared entries
                msg, verdict = prepared
                if verdict is not True:
                    ep.rejected += 1
                    self._trace_rejected(ep, sender, verdict)
                    continue
            else:
                if self.fault_filter is not None and not prefiltered:
                    maybe = self.fault_filter(sender, receiver, wire)
                    if maybe is None:
                        continue
                    if isinstance(maybe, list):
                        if not maybe:
                            continue
                        wire = maybe[0]
                        # injected duplicates re-enter pending (never
                        # re-filtered); the drain loop folds them into
                        # this wave's tail — dedup absorbs them like
                        # any replay
                        for extra in maybe[1:]:
                            if len(self._pending) < self._queue_capacity:
                                self._pending.append(
                                    [sender, receiver, extra, True, None]
                                )
                                self._unprepared += 1
                    else:
                        wire = maybe
                try:
                    msg, signing_prefix = decode_frame(
                        wire, payload_memo=self._payload_memo
                    )
                except ValueError:
                    ep.rejected += 1
                    self._trace_rejected(ep, sender, "undecodable")
                    continue
                ep.frames_decoded += 1
                self.frames_decoded += 1
                ep.mac_verify_batches += 1
                self.mac_verify_calls += 1
                if not ep.auth.verify_wire(msg, signing_prefix):
                    ep.rejected += 1
                    self._trace_rejected(ep, sender, "bad_mac")
                    continue
            ep.delivered += 1
            wave = waves.get(receiver)
            if wave is None:
                waves[receiver] = [msg]
            else:
                wave.append(msg)
        for receiver in sorted(waves):
            ep = self._endpoints.get(receiver)
            serve_wave = getattr(ep.handler, "serve_wave", None)
            if serve_wave is not None:
                serve_wave(waves[receiver])
            else:
                for m in waves[receiver]:
                    # handler without wave ingest: per-frame fallback
                    ep.handler.serve_request(m)  # staticcheck: allow[DET004] non-wave fallback
        return True

    def step(self) -> bool:
        """Deliver one message (or, in wave-routing mode, one whole
        wave); returns False if none pending.

        Delivery order: FIFO without a seed, seeded-uniform-random with
        one — every run with the same seed replays the identical
        interleaving.

        Manual driving contract: handlers joined to this network defer
        outbound bundles and batched crypto to idle callbacks, so a
        caller looping ``step()`` directly MUST call ``idle_phase()``
        whenever ``step()`` returns False (and keep going if new
        messages appear) — exactly what ``run()`` does — or buffered
        work strands and the protocol stalls without error.
        """
        if self.wan is not None:
            self._wan_release()
        if self._wave_routing:
            return self._step_wave()
        columnar = self._columnar and self.fault_filter is None
        if columnar and self._unprepared:
            self._prepare_wave()
        while self._pending:
            if self._rng is None:
                item = self._pending.popleft()
            else:
                idx = self._rng.randrange(len(self._pending))
                item = self._pending[idx]
                self._pending[idx] = self._pending[-1]
                self._pending.pop()
            sender, receiver, wire, prefiltered, prepared = item
            if prepared is None and self._unprepared > 0:
                # frames skipped by a wave pass (crashed receiver)
                # deliver through the scalar fallback below
                self._unprepared -= 1
            if receiver in self._crashed or sender in self._crashed:
                continue
            if (sender, receiver) in self._partitions:
                continue
            ep = self._endpoints.get(receiver)
            if columnar and prepared is not None:
                # pre-waved frame: decode + MAC verdict already batched
                if ep is None:
                    continue
                msg, verdict = prepared
                if verdict is not True:
                    ep.rejected += 1
                    self._trace_rejected(ep, sender, verdict)
                    continue
                ep.delivered += 1
                ep.handler.serve_request(msg)  # staticcheck: allow[DET004] scalar comparison arm
                return True
            if self.fault_filter is not None and not prefiltered:
                maybe = self.fault_filter(sender, receiver, wire)
                if maybe is None:
                    continue
                if isinstance(maybe, list):
                    if not maybe:
                        continue
                    wire = maybe[0]
                    # duplicates / injections: deliver later WITHOUT
                    # re-filtering (a filtered frame re-entering the
                    # filter would branch exponentially)
                    for extra in maybe[1:]:
                        if len(self._pending) < self._queue_capacity:
                            self._pending.append(
                                [sender, receiver, extra, True, None]
                            )
                            self._unprepared += 1
                else:
                    wire = maybe
            if ep is None:
                continue
            try:
                msg, signing_prefix = decode_frame(
                    wire, payload_memo=self._payload_memo
                )
            except ValueError:
                ep.rejected += 1
                self._trace_rejected(ep, sender, "undecodable")
                continue
            ep.frames_decoded += 1
            self.frames_decoded += 1
            ep.mac_verify_batches += 1
            self.mac_verify_calls += 1
            if not ep.auth.verify_wire(msg, signing_prefix):
                # the implemented version of conn.go:134-137's TODO
                ep.rejected += 1
                self._trace_rejected(ep, sender, "bad_mac")
                continue
            ep.delivered += 1
            ep.handler.serve_request(msg)  # staticcheck: allow[DET004] scalar comparison arm
            return True
        return False

    @staticmethod
    def _trace_rejected(ep: ChannelEndpoint, sender: str, why: str) -> None:
        """One trace instant per rejected frame (when the receiving
        handler carries a flight recorder): adversarial tampering shows
        up in tracetool reports instead of only in a counter."""
        tr = getattr(ep.handler, "trace", None)
        if tr is not None:
            tr.instant("transport", "rejected", sender=sender, why=why)

    def idle_phase(self) -> None:
        """The pending queue drained: give every live endpoint its idle
        callback (deferred batched crypto + outbound bundle flush).
        Deterministic order — endpoints fire sorted by node id."""
        for node_id in sorted(self._endpoints):
            if node_id in self._crashed:
                continue
            ep = self._endpoints[node_id]
            if ep.on_idle is not None:
                ep.on_idle()
            elif ep.flush_outbound is not None:
                ep.flush_outbound()

    def run(
        self, max_steps: int = 10_000_000, deadline_s: Optional[float] = None
    ) -> int:
        """Deliver until quiescent (handlers may enqueue more while we
        drain).  Returns the number of delivery steps — one per
        message, or one per WAVE in wave-routing mode (``max_steps``
        bounds the same unit).

        Quiescence is two-level: when the pending queue drains, every
        endpoint gets its idle callback (running deferred crypto and
        flushing coalesced bundles); only when TWO consecutive idle
        phases produce no new traffic is the network done.  The second
        pass is the stall-watchdog window (protocol plane's
        ``_maybe_chase_stall``): a handler can only recognize "no
        inbound since my previous idle callback" on an idle that
        FOLLOWS the quiet one, so a single-pass exit would always
        terminate one callback too early for it to fire.  For handlers
        without a watchdog the extra pass flushes nothing and is
        behaviorally inert.
        """
        t0 = time.monotonic()
        steps = 0
        quiet_idles = 0
        while steps < max_steps:
            if deadline_s is not None and time.monotonic() - t0 > deadline_s:
                break
            if self.step():
                steps += 1
                quiet_idles = 0
                continue
            self.idle_phase()
            if not self._pending:
                if self._wan_holding:
                    # quiescent wall-side but WAN-held frames remain:
                    # the next step() advances the virtual clock to
                    # their deadline instead of declaring the network
                    # drained
                    continue
                quiet_idles += 1
                if quiet_idles >= 2:
                    break
            else:
                quiet_idles = 0
        return steps


__all__ = [
    "ChannelNetwork",
    "ChannelConnection",
    "ChannelEndpoint",
    "FaultFilter",
]
