"""Wire format: the message envelope and typed payloads.

Mirrors reference pb/message.proto: an envelope
``Message{signature, timestamp, oneof payload{RBC, BBA}}``
(message.proto:11-23) with ``RBC{payload bytes, type VAL|ECHO|READY}``
(message.proto:25-35) and ``BBA{payload bytes, type BVAL|AUX}``
(message.proto:37-46).  Inner request structs are marshalled into the
``payload`` field exactly as the reference notes ("marshaled data by
type", message.proto:27).

Payload kinds are added beyond the reference's proto — ``COIN``
(threshold common-coin shares, specified at docs/BBA-EN.md:163-181 but
never given a wire format), ``DEC`` (TPKE decryption shares,
docs/THRESHOLD_ENCRYPTION-EN.md:33-36), and the crash-recovery
``CATCHUP_REQ``/``CATCHUP_RESP`` pair (state transfer for rejoining
nodes) — because the reference never reached the point of needing
them on the wire.

The codec is a deliberate, self-contained binary framing (tag-length-
value with fixed-width ints) rather than generated protobuf: it keeps
the wire format dependency-free, deterministic byte-for-byte (needed
for envelope MACs and replay tests), and trivially portable to the C++
runtime.  The gRPC transport wraps these bytes in a single
``bytes``-typed stream method, preserving the reference's
one-bidi-stream-per-peer topology (message.proto:7-9).
"""

from __future__ import annotations

import dataclasses
import enum
import hashlib
import struct
from typing import List, NamedTuple, Optional, Tuple, Union

from cleisthenes_tpu.utils.memo import BoundedFifoMemo

_MAGIC = b"CLTP"  # cleisthenes-tpu wire magic
_VERSION = 1

# Hard cap on a decoded frame's declared sizes: a Byzantine peer must
# not be able to make us allocate unbounded memory from a length field.
MAX_FIELD_BYTES = 64 * 1024 * 1024


class RbcType(enum.IntEnum):
    """Reference pb/message.proto:29-34 (RBC.Type)."""

    VAL = 0
    ECHO = 1
    READY = 2


class BbaType(enum.IntEnum):
    """Reference pb/message.proto:39-43 (BBA.Type), extended with TERM.

    TERM is the Bracha-style termination gadget the reference's spec
    needs but never wires (docs/BBA-EN.md stops at the coin): a decided
    node broadcasts TERM(b) once; f+1 TERM(b) lets an undecided node
    adopt b; 2f+1 TERM(b) lets anyone halt the instance for good.
    """

    BVAL = 0
    AUX = 1
    TERM = 2


class RbcPayload(NamedTuple):
    """Reference pb/message.proto:25-35 + rbc/request.go:9-21.

    ``proposer``: which RBC instance (one per proposing validator,
    docs/HONEYBADGER-EN.md:85-89).  ``epoch``: HBBFT epoch.
    VAL/ECHO carry (root_hash, branch, shard, shard_index)
    (rbc/request.go:9-17); READY carries root_hash only
    (rbc/request.go:19-21).

    Payloads are NamedTuples, not dataclasses: a wave delivers
    O(N^2) of them per epoch and tuple construction is ~4x cheaper
    than a frozen dataclass's object.__setattr__ per field.
    """

    type: RbcType
    proposer: str
    epoch: int
    root_hash: bytes = b""
    branch: Tuple[bytes, ...] = ()
    shard: bytes = b""
    shard_index: int = 0


class BbaPayload(NamedTuple):
    """Reference pb/message.proto:37-46 + bba/request.go:6-13.

    ``proposer``: which BBA instance.  ``round``: the internal BBA
    round (bba/bba.go:45-46 keeps both epoch and round).  ``value``:
    the binary (bvalRequest.Value / auxRequest.Value).
    """

    type: BbaType
    proposer: str
    epoch: int
    round: int
    value: bool


class CoinPayload(NamedTuple):
    """Threshold common-coin share for one (instance, epoch, round)
    (docs/BBA-EN.md:163-181; no reference wire format exists).

    (index, d, e, z) is an ops.tpke.DhShare: share value plus its
    Chaum-Pedersen validity proof.
    """

    proposer: str
    epoch: int
    round: int
    index: int
    d: int
    e: int
    z: int


class DecSharePayload(NamedTuple):
    """TPKE decryption share for one proposer's ciphertext in one epoch
    (docs/THRESHOLD_ENCRYPTION-EN.md:35, docs/HONEYBADGER-EN.md:61-65).
    """

    proposer: str
    epoch: int
    index: int
    d: int
    e: int
    z: int


class CatchupReqPayload(NamedTuple):
    """CATCHUP request from a lagging/restarted node: "send me every
    committed batch from ``from_epoch`` on" (the state-transfer step
    HBBFT itself does not define; SURVEY.md §5.3-5.4 recovery story).
    Peers answer with a RUN of CatchupResp payloads — one per missed
    epoch they hold, up to a serving cap — so one round trip recovers
    a whole outage window instead of one epoch per round trip."""

    from_epoch: int


class CatchupRespPayload(NamedTuple):
    """One peer's committed batch for ``epoch`` (ledger body bytes,
    core.ledger.encode_batch_body).  A node adopts an epoch only after
    f+1 distinct senders return byte-identical bodies — at least one
    of them is honest, so the batch is the true committed one — and
    only in epoch order at its own commit frontier."""

    epoch: int
    body: bytes


class CatchupOrdPayload(NamedTuple):
    """One peer's ciphertext-ORDERED commit for ``epoch`` (COrd body
    bytes, core.ledger.encode_ordered_body) — the two-frontier twin of
    CatchupRespPayload (Config.order_then_settle).  A peer that has
    ordered but not yet settled an epoch cannot serve its plaintext,
    but CAN serve the agreed ciphertext ordering, so a lagging node
    advances its ordered frontier (and rejoins live epochs) without
    waiting for the roster's trailing decryption.  Adoption mirrors
    the CLOG rule: f+1 byte-identical bodies, in order, at the
    adopter's ORDERED frontier."""

    epoch: int
    body: bytes


class IngressStatus(enum.IntEnum):
    """Admission verdict carried in an IngressAckPayload.

    The backpressure contract (docs/ARCHITECTURE.md "Ingress plane"):
    a submit is never silently dropped — every frame gets exactly one
    ack, and the non-OK verdicts are distinguishable so a client knows
    whether to give up (REJECTED), wait (RETRY_AFTER, with a hint), or
    stop resending (DUPLICATE: the tx is already pending or settled).
    """

    OK = 0
    DUPLICATE = 1
    REJECTED = 2
    RETRY_AFTER = 3


class IngressSubmitPayload(NamedTuple):
    """One client transaction submission (the ingress plane's front
    door, transport/ingress.py).  ``client_id`` names the submitting
    client for per-client backpressure accounting; ``nonce`` is the
    client's own sequence number, echoed in the ack so a pipelining
    client can match acks to submits; ``fee`` is the priority bid the
    mempool orders and evicts by (core/mempool.py)."""

    client_id: str
    nonce: int
    fee: int
    tx: bytes


class IngressAckPayload(NamedTuple):
    """The admitting node's answer to one IngressSubmitPayload:
    verdict plus the node's two commit frontiers at admission time
    (ordered_epoch / settled_epoch — the PR-8 two-frontier split), so
    a client can bound when its tx can first appear in a batch.
    ``retry_after_ms`` is nonzero only with status RETRY_AFTER."""

    client_id: str
    nonce: int
    status: int
    ordered_epoch: int
    settled_epoch: int
    retry_after_ms: int


class IngressSubscribePayload(NamedTuple):
    """Open a committed-batch subscription: "stream me every settled
    batch from ``from_epoch`` on".  Epochs already settled replay from
    the node's committed history (the same state the BatchLog restores
    at startup); later epochs arrive as a live tail at the settled
    frontier."""

    from_epoch: int


class IngressBatchPayload(NamedTuple):
    """One settled batch streamed to a subscriber (ledger body bytes,
    core.ledger.encode_batch_body — the same canonical body CATCHUP
    serves, so subscribers and rejoining validators read one format).
    """

    epoch: int
    body: bytes


class ResharePayload(NamedTuple):
    """One dealer's reshare dealing for a pending RECONFIG (dynamic
    membership, protocol.reconfig).

    ``body`` is the full serialized dealing (Feldman commitments for
    the new TPKE and coin keys plus the per-receiver encrypted share
    blobs) — the exact bytes the dealer also submits as its dealing
    transaction.  The broadcast is the EAGER in-band distribution
    path: live nodes stage and pre-verify dealings while the old
    roster keeps committing, and a joiner receiving one learns a
    ceremony is underway and (re)starts its CATCHUP bootstrap.  The
    authoritative copy — the one qualified-set selection is judged on
    — is the committed dealing transaction, so a lost broadcast costs
    latency, never agreement."""

    version: int
    dealer: str
    body: bytes


class BundlePayload(NamedTuple):
    """Several protocol payloads in ONE authenticated envelope.

    HBBFT's per-epoch traffic is O(N^2) broadcast waves where a node
    emits one small payload per concurrent instance (N ECHOs, N BBA
    votes, N dec-shares...) to the same receiver within one handler
    turn.  Bundling them amortizes the envelope + MAC + frame decode
    to one per (sender, receiver, wave) instead of one per payload —
    the message-coalescing lever the reference never needed at its
    unimplemented scale (its cost model: docs/HONEYBADGER-EN.md:93-96).
    Nesting is rejected at both encode and decode.
    """

    items: Tuple["Payload", ...]


class LanePayload(NamedTuple):
    """One protocol payload addressed to a consensus lane (ISSUE 20).

    Horizontal shard-out runs S independent HBBFT lane instances over
    one roster; lanes > 0 wrap every outbound payload in this frame so
    lane traffic rides the SAME coalesced bundles, delivery waves and
    MAC passes as lane 0 — the receiver demuxes by ``lane`` before the
    epoch demux.  Lane 0 never wraps (S=1 wire streams stay
    byte-identical to the pre-lane build).  A LanePayload may appear
    inside a bundle; a bundle or another LanePayload may NOT appear
    inside a LanePayload (the lane axis is outermost-but-one, framing
    stays non-recursive).
    """

    lane: int
    inner: "Payload"


# -- columnar wave payloads -------------------------------------------------
#
# Within one wave a node emits the SAME logical vote across many
# concurrent instances: N BVALs that differ only in proposer, N coin
# shares differing in (proposer, d, e, z), N dec shares, N READYs.
# The coalescer merges such runs into ONE columnar payload per
# (receiver, key): the shared fields encode once and the per-instance
# fields are packed columns, so both the wire size and the per-item
# decode/dispatch cost drop by ~the instance count.  Receivers unpack
# straight into the instance handlers' scalar entry points.


class BbaBatchPayload(NamedTuple):
    """One BVAL/AUX/TERM vote replicated across many instances:
    (type, epoch, round, value) shared, proposers columnar."""

    type: BbaType
    epoch: int
    round: int
    value: bool
    proposers: Tuple[str, ...]


class CoinBatchPayload(NamedTuple):
    """One sender's coin shares for many instances of (epoch, round):
    share index shared, (proposer, d, e, z) columnar."""

    epoch: int
    round: int
    index: int
    proposers: Tuple[str, ...]
    d: Tuple[int, ...]
    e: Tuple[int, ...]
    z: Tuple[int, ...]


class DecShareBatchPayload(NamedTuple):
    """One sender's TPKE decryption shares for many proposers of one
    epoch: share index shared, (proposer, d, e, z) columnar."""

    epoch: int
    index: int
    proposers: Tuple[str, ...]
    d: Tuple[int, ...]
    e: Tuple[int, ...]
    z: Tuple[int, ...]


class ReadyBatchPayload(NamedTuple):
    """One sender's RBC READYs for many instances of one epoch:
    (proposer, root) columnar."""

    epoch: int
    proposers: Tuple[str, ...]
    roots: Tuple[bytes, ...]


class EchoBatchPayload(NamedTuple):
    """One sender's RBC ECHOes for many instances of one epoch: the
    sender's shard slot (``shard_index``) is shared — a node echoes
    the VAL it received, which always carries its own tree position
    (docs/RBC-EN.md:34) — while (proposer, root, branch, shard) are
    columnar.  The last of the O(N^2)-per-epoch payload classes to go
    columnar: at N=64 the scalar ECHO chain was ~262k handler calls
    per epoch (profiled round 5)."""

    epoch: int
    shard_index: int
    proposers: Tuple[str, ...]
    roots: Tuple[bytes, ...]
    branches: Tuple[Tuple[bytes, ...], ...]
    shards: Tuple[bytes, ...]


Payload = Union[
    RbcPayload,
    BbaPayload,
    CoinPayload,
    DecSharePayload,
    CatchupReqPayload,
    CatchupRespPayload,
    CatchupOrdPayload,
    ResharePayload,
    BundlePayload,
    BbaBatchPayload,
    CoinBatchPayload,
    DecShareBatchPayload,
    ReadyBatchPayload,
    EchoBatchPayload,
    IngressSubmitPayload,
    IngressAckPayload,
    IngressSubscribePayload,
    IngressBatchPayload,
    LanePayload,
]

# oneof discriminants (reference message.proto:18-22 has rbc=3, bba=4;
# we keep those two numbers and extend).  This block is the WIRE
# REGISTRY the whole-program analyzer indexes (staticcheck WIRE001):
# every kind must carry a unique number, an encode and a parse branch
# below, and either a pb-adapter slot (transport/pb_adapter.py) or a
# pragma saying why the capability stays native-only.
_KIND_RBC = 3
_KIND_BBA = 4
_KIND_COIN = 5  # staticcheck: allow[WIRE001] native-only: the reference oneof has no coin slot
_KIND_DEC = 6  # staticcheck: allow[WIRE001] native-only: the reference oneof has no dec-share slot
_KIND_CATCHUP_REQ = 7
_KIND_CATCHUP_RESP = 8
_KIND_BUNDLE = 9  # staticcheck: allow[WIRE001] native-only coalescing envelope (no pb slot)
_KIND_BBA_BATCH = 10  # staticcheck: allow[WIRE001] native-only columnar kind (wave coalescing)
_KIND_COIN_BATCH = 11  # staticcheck: allow[WIRE001] native-only columnar kind (wave coalescing)
_KIND_DEC_BATCH = 12  # staticcheck: allow[WIRE001] native-only columnar kind (wave coalescing)
_KIND_READY_BATCH = 13  # staticcheck: allow[WIRE001] native-only columnar kind (wave coalescing)
_KIND_ECHO_BATCH = 14  # staticcheck: allow[WIRE001] native-only columnar kind (wave coalescing)
_KIND_CATCHUP_ORD = 15
_KIND_RESHARE = 16
# client ingress plane (transport/ingress.py): submit/subscribe frames
# exchanged with UNTRUSTED clients.  They ride the same TLV codec (and
# pb extension slots, for stock-decoder interop) but a different frame
# magic (_INGRESS_MAGIC) with no envelope MAC: clients hold no roster
# keys, and admission control — not authentication — is the guard.
# Ingress frames therefore never enter the validator-to-validator
# dispatch path (VERIFY001's decode->verify->serve discipline).
_KIND_INGRESS_SUBMIT = 17
_KIND_INGRESS_ACK = 18
_KIND_INGRESS_SUB = 19
_KIND_INGRESS_BATCH = 20
_KIND_LANE = 21  # staticcheck: allow[WIRE001] native-only lane shard-out framing (no pb slot)

# DoS bound on per-instance columns (a roster is <= 256 under the
# GF(2^8) shard cap; 4096 leaves margin for multi-round merges)
MAX_BATCH_ITEMS = 4096

# DoS bound on sub-payloads per bundle (each item is >= 2 bytes on the
# wire, and the frame itself is capped by MAX_FIELD_BYTES)
MAX_BUNDLE_ITEMS = 1 << 20


@dataclasses.dataclass(frozen=True)
class Message:
    """The envelope (reference pb/message.proto:11-23).

    ``signature`` authenticates (sender_id, timestamp, payload) — the
    field the reference declares (message.proto:14) but never checks
    (conn.go:134-137 TODO); here it is a real MAC, see
    transport.base.Authenticator.  ``sender_id`` is carried explicitly
    because unlike the reference we authenticate it (the reference
    trusts the connection's uuid, comm.go:46).
    """

    sender_id: str
    timestamp: float
    payload: Payload
    signature: bytes = b""
    # Simulated-TEE attestation trailer (Config.attested_log,
    # protocol/attest.py): an opaque blob appended AFTER the signature
    # — (incarnation, sender counter, refused flag, attestation MAC)
    # issued by the sender's AttestationVault.  Empty on the baseline
    # arm, where the frame bytes are identical to the pre-attestation
    # wire format.  Not covered by the envelope MAC (it carries its
    # own MAC binding the signing prefix), so the codec treats it as
    # an optional TLV trailer.
    attestation: bytes = b""


# ---------------------------------------------------------------------------
# binary codec
# ---------------------------------------------------------------------------


def _pack_bytes(out: List[bytes], b: bytes) -> None:
    out.append(struct.pack(">I", len(b)))
    out.append(b)


def _pack_str(out: List[bytes], s: str) -> None:
    _pack_bytes(out, s.encode("utf-8"))


def _pack_int(out: List[bytes], x: int) -> None:
    """Arbitrary-precision non-negative int (group elements are 256-bit)."""
    if x < 0:
        raise ValueError("negative int on wire")
    b = x.to_bytes((x.bit_length() + 7) // 8 or 1, "big")
    _pack_bytes(out, b)


class _Reader:
    def __init__(self, data: bytes):
        self._d = data
        self._o = 0

    def bytes_(self) -> bytes:
        if self._o + 4 > len(self._d):
            raise ValueError("truncated frame")
        (n,) = struct.unpack_from(">I", self._d, self._o)
        if n > MAX_FIELD_BYTES:
            raise ValueError(f"field length {n} exceeds cap")
        self._o += 4
        if self._o + n > len(self._d):
            raise ValueError("truncated frame")
        out = self._d[self._o : self._o + n]
        self._o += n
        return out

    def str_(self) -> str:
        return self.bytes_().decode("utf-8")

    def int_(self) -> int:
        return int.from_bytes(self.bytes_(), "big")

    def u8(self) -> int:
        if self._o + 1 > len(self._d):
            raise ValueError("truncated frame")
        v = self._d[self._o]
        self._o += 1
        return v

    def u32(self) -> int:
        if self._o + 4 > len(self._d):
            raise ValueError("truncated frame")
        (v,) = struct.unpack_from(">I", self._d, self._o)
        self._o += 4
        return v

    def u64(self) -> int:
        if self._o + 8 > len(self._d):
            raise ValueError("truncated frame")
        (v,) = struct.unpack_from(">Q", self._d, self._o)
        self._o += 8
        return v

    def f64(self) -> float:
        if self._o + 8 > len(self._d):
            raise ValueError("truncated frame")
        (v,) = struct.unpack_from(">d", self._d, self._o)
        self._o += 8
        return v

    def done(self) -> bool:
        return self._o == len(self._d)


def _encode_payload(p: Payload) -> Tuple[int, bytes]:
    out: List[bytes] = []
    if isinstance(p, RbcPayload):
        out.append(struct.pack(">B", int(p.type)))
        _pack_str(out, p.proposer)
        out.append(struct.pack(">Q", p.epoch))
        _pack_bytes(out, p.root_hash)
        out.append(struct.pack(">I", len(p.branch)))
        for b in p.branch:
            _pack_bytes(out, b)
        _pack_bytes(out, p.shard)
        out.append(struct.pack(">I", p.shard_index))
        return _KIND_RBC, b"".join(out)
    if isinstance(p, BbaPayload):
        out.append(struct.pack(">B", int(p.type)))
        _pack_str(out, p.proposer)
        out.append(struct.pack(">QQB", p.epoch, p.round, int(p.value)))
        return _KIND_BBA, b"".join(out)
    if isinstance(p, CoinPayload):
        _pack_str(out, p.proposer)
        out.append(struct.pack(">QQI", p.epoch, p.round, p.index))
        _pack_int(out, p.d)
        _pack_int(out, p.e)
        _pack_int(out, p.z)
        return _KIND_COIN, b"".join(out)
    if isinstance(p, DecSharePayload):
        _pack_str(out, p.proposer)
        out.append(struct.pack(">QI", p.epoch, p.index))
        _pack_int(out, p.d)
        _pack_int(out, p.e)
        _pack_int(out, p.z)
        return _KIND_DEC, b"".join(out)
    if isinstance(p, CatchupReqPayload):
        out.append(struct.pack(">Q", p.from_epoch))
        return _KIND_CATCHUP_REQ, b"".join(out)
    if isinstance(p, CatchupRespPayload):
        out.append(struct.pack(">Q", p.epoch))
        _pack_bytes(out, p.body)
        return _KIND_CATCHUP_RESP, b"".join(out)
    if isinstance(p, CatchupOrdPayload):
        out.append(struct.pack(">Q", p.epoch))
        _pack_bytes(out, p.body)
        return _KIND_CATCHUP_ORD, b"".join(out)
    if isinstance(p, ResharePayload):
        out.append(struct.pack(">I", p.version))
        _pack_str(out, p.dealer)
        _pack_bytes(out, p.body)
        return _KIND_RESHARE, b"".join(out)
    if isinstance(p, IngressSubmitPayload):
        _pack_str(out, p.client_id)
        out.append(struct.pack(">QQ", p.nonce, p.fee))
        _pack_bytes(out, p.tx)
        return _KIND_INGRESS_SUBMIT, b"".join(out)
    if isinstance(p, IngressAckPayload):
        _pack_str(out, p.client_id)
        out.append(
            struct.pack(
                ">QBQQI",
                p.nonce,
                int(p.status),
                p.ordered_epoch,
                p.settled_epoch,
                p.retry_after_ms,
            )
        )
        return _KIND_INGRESS_ACK, b"".join(out)
    if isinstance(p, IngressSubscribePayload):
        out.append(struct.pack(">Q", p.from_epoch))
        return _KIND_INGRESS_SUB, b"".join(out)
    if isinstance(p, IngressBatchPayload):
        out.append(struct.pack(">Q", p.epoch))
        _pack_bytes(out, p.body)
        return _KIND_INGRESS_BATCH, b"".join(out)
    if isinstance(p, LanePayload):
        if not (0 <= p.lane <= 255):
            raise ValueError(f"lane {p.lane} out of wire range")
        kind, body = _encode_payload(p.inner)
        if kind in (_KIND_BUNDLE, _KIND_LANE):
            raise ValueError(
                "bundle/lane payloads are not allowed inside a lane frame"
            )
        out.append(struct.pack(">IB", p.lane, kind))
        _pack_bytes(out, body)
        return _KIND_LANE, b"".join(out)
    if isinstance(p, BundlePayload):
        if len(p.items) > MAX_BUNDLE_ITEMS:
            raise ValueError(f"bundle of {len(p.items)} items exceeds cap")
        out.append(struct.pack(">I", len(p.items)))
        for item in p.items:
            kind, body = _encode_payload(item)
            if kind == _KIND_BUNDLE:
                raise ValueError("nested bundles are not allowed")
            out.append(struct.pack(">B", kind))
            _pack_bytes(out, body)
        return _KIND_BUNDLE, b"".join(out)
    if isinstance(p, BbaBatchPayload):
        _check_batch_len(len(p.proposers))
        out.append(struct.pack(">BQQB", int(p.type), p.epoch, p.round,
                               int(p.value)))
        out.append(struct.pack(">I", len(p.proposers)))
        for s in p.proposers:
            _pack_str(out, s)
        return _KIND_BBA_BATCH, b"".join(out)
    if isinstance(p, CoinBatchPayload):
        _check_batch_len(len(p.proposers), len(p.d), len(p.e), len(p.z))
        out.append(struct.pack(">QQI", p.epoch, p.round, p.index))
        _pack_share_columns(out, p.proposers, p.d, p.e, p.z)
        return _KIND_COIN_BATCH, b"".join(out)
    if isinstance(p, DecShareBatchPayload):
        _check_batch_len(len(p.proposers), len(p.d), len(p.e), len(p.z))
        out.append(struct.pack(">QI", p.epoch, p.index))
        _pack_share_columns(out, p.proposers, p.d, p.e, p.z)
        return _KIND_DEC_BATCH, b"".join(out)
    if isinstance(p, ReadyBatchPayload):
        _check_batch_len(len(p.proposers), len(p.roots))
        out.append(struct.pack(">Q", p.epoch))
        out.append(struct.pack(">I", len(p.proposers)))
        for i, s in enumerate(p.proposers):
            _pack_str(out, s)
            _pack_bytes(out, p.roots[i])
        return _KIND_READY_BATCH, b"".join(out)
    if isinstance(p, EchoBatchPayload):
        _check_batch_len(
            len(p.proposers), len(p.roots), len(p.branches), len(p.shards)
        )
        out.append(struct.pack(">QI", p.epoch, p.shard_index))
        out.append(struct.pack(">I", len(p.proposers)))
        for i, s in enumerate(p.proposers):
            _pack_str(out, s)
            _pack_bytes(out, p.roots[i])
            br = p.branches[i]
            out.append(struct.pack(">I", len(br)))
            for b in br:
                _pack_bytes(out, b)
            _pack_bytes(out, p.shards[i])
        return _KIND_ECHO_BATCH, b"".join(out)
    raise TypeError(f"unknown payload type {type(p)!r}")


def _pack_share_columns(out, proposers, dcol, ecol, zcol) -> None:
    """(proposer, d, e, z) columns — shared by the coin and dec-share
    batch payloads so their framings cannot drift apart."""
    out.append(struct.pack(">I", len(proposers)))
    for i, s in enumerate(proposers):
        _pack_str(out, s)
        _pack_int(out, dcol[i])
        _pack_int(out, ecol[i])
        _pack_int(out, zcol[i])


def _check_batch_len(*lens: int) -> None:
    if not lens or min(lens) != max(lens):
        raise ValueError("columnar payload with ragged columns")
    if lens[0] == 0 or lens[0] > MAX_BATCH_ITEMS:
        raise ValueError(f"batch of {lens[0]} items out of range")


# Prebound structs: the payload decoder is the receive hot path (a
# wave delivers O(N^2) items per epoch), so field parsing is inlined
# offset arithmetic rather than _Reader method calls (~2.5x).
_U32 = struct.Struct(">I")
_U64 = struct.Struct(">Q")
_F64 = struct.Struct(">d")
_QQB = struct.Struct(">QQB")
_QQI = struct.Struct(">QQI")
_QI = struct.Struct(">QI")


def _parse_share_columns(d: bytes, o: int, end: int, count: int):
    """Inverse of _pack_share_columns; returns (proposers, d, e, z, o')."""
    proposers, dv, ev, zv = [], [], [], []
    for _ in range(count):
        s, o = _field(d, o, end)
        proposers.append(s.decode("utf-8"))
        x, o = _field(d, o, end)
        dv.append(int.from_bytes(x, "big"))
        x, o = _field(d, o, end)
        ev.append(int.from_bytes(x, "big"))
        x, o = _field(d, o, end)
        zv.append(int.from_bytes(x, "big"))
    return tuple(proposers), tuple(dv), tuple(ev), tuple(zv), o


def _check_batch_count(count: int) -> None:
    if count == 0 or count > MAX_BATCH_ITEMS:
        raise ValueError(f"batch count {count} out of range")


def _field(d: bytes, o: int, end: int):
    """One length-prefixed field within d[..end); returns (bytes, o')."""
    if o + 4 > end:
        raise ValueError("truncated frame")
    (n,) = _U32.unpack_from(d, o)
    if n > MAX_FIELD_BYTES:
        raise ValueError(f"field length {n} exceeds cap")
    o += 4
    if o + n > end:
        raise ValueError("truncated frame")
    return d[o : o + n], o + n


def _parse_payload(d: bytes, o: int, end: int, kind: int):
    """Parse one payload from d[o:end); returns (payload, offset after).
    The caller checks the offset against ``end`` where canonical
    (exactly-consumed) bodies are required."""
    if kind == _KIND_BBA:
        if o + 1 > end:
            raise ValueError("truncated frame")
        t = BbaType(d[o])
        proposer, o = _field(d, o + 1, end)
        if o + 17 > end:
            raise ValueError("truncated frame")
        epoch, rnd, val = _QQB.unpack_from(d, o)
        return (
            BbaPayload(t, proposer.decode("utf-8"), epoch, rnd, bool(val)),
            o + 17,
        )
    if kind == _KIND_COIN:
        proposer, o = _field(d, o, end)
        if o + 20 > end:
            raise ValueError("truncated frame")
        epoch, rnd, idx = _QQI.unpack_from(d, o)
        dv, o = _field(d, o + 20, end)
        ev, o = _field(d, o, end)
        zv, o = _field(d, o, end)
        return (
            CoinPayload(
                proposer.decode("utf-8"), epoch, rnd, idx,
                int.from_bytes(dv, "big"), int.from_bytes(ev, "big"),
                int.from_bytes(zv, "big"),
            ),
            o,
        )
    if kind == _KIND_DEC:
        proposer, o = _field(d, o, end)
        if o + 12 > end:
            raise ValueError("truncated frame")
        epoch, idx = _QI.unpack_from(d, o)
        dv, o = _field(d, o + 12, end)
        ev, o = _field(d, o, end)
        zv, o = _field(d, o, end)
        return (
            DecSharePayload(
                proposer.decode("utf-8"), epoch, idx,
                int.from_bytes(dv, "big"), int.from_bytes(ev, "big"),
                int.from_bytes(zv, "big"),
            ),
            o,
        )
    if kind == _KIND_RBC:
        if o + 1 > end:
            raise ValueError("truncated frame")
        t = RbcType(d[o])
        proposer, o = _field(d, o + 1, end)
        if o + 8 > end:
            raise ValueError("truncated frame")
        (epoch,) = _U64.unpack_from(d, o)
        root, o = _field(d, o + 8, end)
        if o + 4 > end:
            raise ValueError("truncated frame")
        (nbr,) = _U32.unpack_from(d, o)
        if nbr > 64:  # Merkle depth cap: 2^64 leaves is beyond any N
            raise ValueError(f"branch length {nbr} exceeds cap")
        o += 4
        branch = []
        for _ in range(nbr):
            b, o = _field(d, o, end)
            branch.append(b)
        shard, o = _field(d, o, end)
        if o + 4 > end:
            raise ValueError("truncated frame")
        (idx,) = _U32.unpack_from(d, o)
        return (
            RbcPayload(
                t, proposer.decode("utf-8"), epoch, root, tuple(branch),
                shard, idx,
            ),
            o + 4,
        )
    if kind == _KIND_BBA_BATCH:
        if o + 22 > end:
            raise ValueError("truncated frame")
        t = BbaType(d[o])
        epoch, rnd, val = _QQB.unpack_from(d, o + 1)
        (count,) = _U32.unpack_from(d, o + 18)
        _check_batch_count(count)
        o += 22
        proposers = []
        for _ in range(count):
            s, o = _field(d, o, end)
            proposers.append(s.decode("utf-8"))
        return (
            BbaBatchPayload(t, epoch, rnd, bool(val), tuple(proposers)),
            o,
        )
    if kind == _KIND_COIN_BATCH:
        if o + 24 > end:
            raise ValueError("truncated frame")
        epoch, rnd, idx = _QQI.unpack_from(d, o)
        (count,) = _U32.unpack_from(d, o + 20)
        _check_batch_count(count)
        proposers, dv, ev, zv, o = _parse_share_columns(d, o + 24, end, count)
        return (
            CoinBatchPayload(epoch, rnd, idx, proposers, dv, ev, zv),
            o,
        )
    if kind == _KIND_DEC_BATCH:
        if o + 16 > end:
            raise ValueError("truncated frame")
        epoch, idx = _QI.unpack_from(d, o)
        (count,) = _U32.unpack_from(d, o + 12)
        _check_batch_count(count)
        proposers, dv, ev, zv, o = _parse_share_columns(d, o + 16, end, count)
        return (
            DecShareBatchPayload(epoch, idx, proposers, dv, ev, zv),
            o,
        )
    if kind == _KIND_READY_BATCH:
        if o + 12 > end:
            raise ValueError("truncated frame")
        (epoch,) = _U64.unpack_from(d, o)
        (count,) = _U32.unpack_from(d, o + 8)
        _check_batch_count(count)
        o += 12
        proposers, roots = [], []
        for _ in range(count):
            s, o = _field(d, o, end)
            proposers.append(s.decode("utf-8"))
            r, o = _field(d, o, end)
            roots.append(r)
        return (
            ReadyBatchPayload(epoch, tuple(proposers), tuple(roots)),
            o,
        )
    if kind == _KIND_ECHO_BATCH:
        if o + 16 > end:
            raise ValueError("truncated frame")
        (epoch,) = _U64.unpack_from(d, o)
        (sidx,) = _U32.unpack_from(d, o + 8)
        (count,) = _U32.unpack_from(d, o + 12)
        _check_batch_count(count)
        o += 16
        proposers, roots, branches, shards = [], [], [], []
        for _ in range(count):
            s, o = _field(d, o, end)
            proposers.append(s.decode("utf-8"))
            r, o = _field(d, o, end)
            roots.append(r)
            if o + 4 > end:
                raise ValueError("truncated frame")
            (nbr,) = _U32.unpack_from(d, o)
            if nbr > 64:  # same Merkle depth cap as _KIND_RBC
                raise ValueError(f"branch length {nbr} exceeds cap")
            o += 4
            br = []
            for _ in range(nbr):
                b, o = _field(d, o, end)
                br.append(b)
            branches.append(tuple(br))
            sh, o = _field(d, o, end)
            shards.append(sh)
        return (
            EchoBatchPayload(
                epoch,
                sidx,
                tuple(proposers),
                tuple(roots),
                tuple(branches),
                tuple(shards),
            ),
            o,
        )
    if kind == _KIND_CATCHUP_REQ:
        if o + 8 > end:
            raise ValueError("truncated frame")
        (from_epoch,) = _U64.unpack_from(d, o)
        return CatchupReqPayload(from_epoch), o + 8
    if kind == _KIND_CATCHUP_RESP:
        if o + 8 > end:
            raise ValueError("truncated frame")
        (epoch,) = _U64.unpack_from(d, o)
        body, o = _field(d, o + 8, end)
        return CatchupRespPayload(epoch, body), o
    if kind == _KIND_CATCHUP_ORD:
        if o + 8 > end:
            raise ValueError("truncated frame")
        (epoch,) = _U64.unpack_from(d, o)
        body, o = _field(d, o + 8, end)
        return CatchupOrdPayload(epoch, body), o
    if kind == _KIND_RESHARE:
        if o + 4 > end:
            raise ValueError("truncated frame")
        (version,) = _U32.unpack_from(d, o)
        dealer, o = _field(d, o + 4, end)
        body, o = _field(d, o, end)
        return ResharePayload(version, dealer.decode("utf-8"), body), o
    if kind == _KIND_INGRESS_SUBMIT:
        client, o = _field(d, o, end)
        if o + 16 > end:
            raise ValueError("truncated frame")
        (nonce,) = _U64.unpack_from(d, o)
        (fee,) = _U64.unpack_from(d, o + 8)
        tx, o = _field(d, o + 16, end)
        return (
            IngressSubmitPayload(client.decode("utf-8"), nonce, fee, tx),
            o,
        )
    if kind == _KIND_INGRESS_ACK:
        client, o = _field(d, o, end)
        if o + 29 > end:
            raise ValueError("truncated frame")
        (nonce,) = _U64.unpack_from(d, o)
        status = IngressStatus(d[o + 8])
        (ordered,) = _U64.unpack_from(d, o + 9)
        (settled,) = _U64.unpack_from(d, o + 17)
        (retry_ms,) = _U32.unpack_from(d, o + 25)
        return (
            IngressAckPayload(
                client.decode("utf-8"), nonce, status, ordered, settled,
                retry_ms,
            ),
            o + 29,
        )
    if kind == _KIND_INGRESS_SUB:
        if o + 8 > end:
            raise ValueError("truncated frame")
        (from_epoch,) = _U64.unpack_from(d, o)
        return IngressSubscribePayload(from_epoch), o + 8
    if kind == _KIND_INGRESS_BATCH:
        if o + 8 > end:
            raise ValueError("truncated frame")
        (epoch,) = _U64.unpack_from(d, o)
        body, o = _field(d, o + 8, end)
        return IngressBatchPayload(epoch, body), o
    if kind == _KIND_LANE:
        if o + 9 > end:
            raise ValueError("truncated frame")
        (lane,) = _U32.unpack_from(d, o)
        if lane > 255:
            raise ValueError(f"lane {lane} out of wire range")
        k = d[o + 4]
        if k in (_KIND_BUNDLE, _KIND_LANE):
            raise ValueError(
                "bundle/lane payloads are not allowed inside a lane frame"
            )
        (ln,) = _U32.unpack_from(d, o + 5)
        if ln > MAX_FIELD_BYTES:
            raise ValueError(f"field length {ln} exceeds cap")
        o += 9
        item_end = o + ln
        if item_end > end:
            raise ValueError("truncated frame")
        inner, consumed = _parse_payload(d, o, item_end, k)
        if consumed != item_end:
            # canonical-or-reject: the MAC covers these bytes
            raise ValueError("trailing bytes in payload body")
        return LanePayload(lane, inner), item_end
    if kind == _KIND_BUNDLE:
        if o + 4 > end:
            raise ValueError("truncated frame")
        (count,) = _U32.unpack_from(d, o)
        if count > MAX_BUNDLE_ITEMS:
            raise ValueError(f"bundle count {count} exceeds cap")
        o += 4
        items = []
        append = items.append
        for _ in range(count):
            if o + 5 > end:
                raise ValueError("truncated frame")
            k = d[o]
            if k == _KIND_BUNDLE:
                raise ValueError("nested bundles are not allowed")
            (ln,) = _U32.unpack_from(d, o + 1)
            if ln > MAX_FIELD_BYTES:
                raise ValueError(f"field length {ln} exceeds cap")
            o += 5
            item_end = o + ln
            if item_end > end:
                raise ValueError("truncated frame")
            item, consumed = _parse_payload(d, o, item_end, k)
            if consumed != item_end:
                # canonical-or-reject: the MAC covers these bytes
                raise ValueError("trailing bytes in payload body")
            append(item)
            o = item_end
        return BundlePayload(tuple(items)), o
    raise ValueError(f"unknown payload kind {kind}")


def _decode_payload(kind: int, data: bytes) -> Payload:
    out, consumed = _parse_payload(data, 0, len(data), kind)
    if consumed != len(data):
        # reject non-canonical bodies: the MAC covers the re-encoded
        # canonical form, so trailing junk would make frames malleable
        raise ValueError("trailing bytes in payload body")
    return out


def signing_bytes(msg: Message) -> bytes:
    """The byte string the envelope MAC covers: everything except the
    signature itself (the reference's intended-but-absent semantics,
    message.proto:14, conn.go:134-137)."""
    kind, body = _encode_payload(msg.payload)
    return _assemble_signing(msg, kind, body)


def _assemble_signing(msg: Message, kind: int, body: bytes) -> bytes:
    out: List[bytes] = [_MAGIC, struct.pack(">BB", _VERSION, kind)]
    _pack_str(out, msg.sender_id)
    out.append(struct.pack(">d", msg.timestamp))
    _pack_bytes(out, body)
    return b"".join(out)


class FrameEncodeMemo(BoundedFifoMemo):
    """Shared outbound payload-encode memo (Config.egress_columnar) —
    the encode twin of ``FrameDecodeMemo``.

    One egress wave's per-receiver frames are mostly re-encodings of
    SHARED payload objects: a mixed flush folds the wave's broadcast
    run into each receiver's bundle, so N receiver bundles carry the
    same sub-payload objects and the scalar path re-encoded each of
    them once per receiver.  Keying the encoded ``(kind, body)`` on
    the payload OBJECT collapses those to one encode + N joins.

    The decode memo keys on the wire prefix's SHA-256 digest because
    the bytes already exist on arrival; on the send side the bytes are
    the memo's PRODUCT, so the pre-encode name of the content is the
    immutable payload object itself — entries pin the object (and hits
    re-check identity), so id reuse after GC can never alias, the same
    pin-the-inputs discipline as the hub's id-slot branch dedup.
    Eviction is the shared BoundedFifoMemo FIFO discipline (oldest
    insertion first, never clear-all).  ``hits``/``misses`` feed the
    transport egress metrics (``encode_memo_hit_rate`` in the bench
    sections); a miss is a payload body actually encoded — the
    ``frames_encoded`` counter's unit on both egress arms."""

    __slots__ = ("hits", "misses")

    def __init__(self, cap: int = 4096):
        super().__init__(cap)
        self.hits = 0
        self.misses = 0


def encode_payload_shared(
    p: Payload, memo: FrameEncodeMemo
) -> Tuple[int, bytes]:
    """(kind, body) for one NON-BUNDLE payload through the memo."""
    key = id(p)
    ent = memo.map.get(key)
    if ent is not None and ent[0] is p:
        memo.hits += 1
        return ent[1], ent[2]
    memo.misses += 1
    kind, body = _encode_payload(p)
    memo.put(key, (p, kind, body))
    return kind, body


def signing_bytes_shared(msg: Message, memo: FrameEncodeMemo) -> bytes:
    """``signing_bytes`` through the FrameEncodeMemo — byte-identical
    output (tests assert it), but a BundlePayload's sub-items and any
    repeated top-level payload encode once per distinct OBJECT across
    the wave instead of once per receiver frame."""
    p = msg.payload
    if isinstance(p, BundlePayload):
        if len(p.items) > MAX_BUNDLE_ITEMS:
            raise ValueError(f"bundle of {len(p.items)} items exceeds cap")
        out: List[bytes] = [struct.pack(">I", len(p.items))]
        for item in p.items:
            kind, body = encode_payload_shared(item, memo)
            if kind == _KIND_BUNDLE:
                raise ValueError("nested bundles are not allowed")
            out.append(struct.pack(">B", kind))
            _pack_bytes(out, body)
        return _assemble_signing(msg, _KIND_BUNDLE, b"".join(out))
    kind, body = encode_payload_shared(p, memo)
    return _assemble_signing(msg, kind, body)


def payload_body_count(p: Payload) -> int:
    """Payload bodies one envelope encode touches (bundle items, or
    1): the ``frames_encoded`` counter's unit on the SCALAR egress arm
    — the columnar arm counts FrameEncodeMemo misses, which probe per
    body, so both arms tally the same work unit."""
    return len(p.items) if isinstance(p, BundlePayload) else 1


# Tag byte opening the optional attestation trailer
# (``signing || len(sig) || sig || TAG || len(att) || att``).  A
# distinct tag keeps the trailer self-describing: a frame ending at
# the signature is the baseline arm, anything else must be exactly
# one tagged attestation blob (canonical-or-reject).
ATTEST_TAG = 0xA7


def attach_signature(
    signing: bytes, signature: bytes, attestation: bytes = b""
) -> bytes:
    """Complete a frame from its pre-computed signing bytes: the wire
    layout is ``signing_bytes || len(sig) || sig`` plus, when the
    attested-log arm is on, the tagged attestation trailer — so a
    broadcast can encode the envelope once and append a per-receiver
    MAC (and per-receiver attestation)."""
    frame = signing + struct.pack(">I", len(signature)) + signature
    if attestation:
        frame += (
            struct.pack(">BI", ATTEST_TAG, len(attestation)) + attestation
        )
    return frame


def encode_message(msg: Message) -> bytes:
    return attach_signature(
        signing_bytes(msg), msg.signature, msg.attestation
    )


class FrameDecodeMemo(BoundedFifoMemo):
    """Shared-prefix inbound decode memo (Config.delivery_columnar).

    A broadcast's N receiver frames are ``signing_bytes || len || MAC``
    (attach_signature) and differ ONLY in the 32-byte MAC — the
    signing prefix (sender, timestamp, payload body) is byte-identical
    across all N.  Keying the decoded (sender, ts, kind, payload)
    tuple on the SHA-256 digest of that prefix collapses N identical
    decodes to 1 decode + N cheap MAC checks, and shares the envelope
    fields too (the old (kind, body)-keyed payload memo still decoded
    sender/timestamp and copied the body bytes per frame).

    Two frames with equal digests but different prefix bytes would be
    a SHA-256 collision (a second preimage against honest traffic), so
    aliasing is cryptographically excluded — see docs/ARCHITECTURE.md
    "Delivery plane".

    Eviction is the shared BoundedFifoMemo discipline (oldest
    insertion first, utils.memo — the PR-7 hub memo hoisted), NEVER
    clear-all: a hot wave sitting at the cap loses one stale entry
    per fresh one instead of periodically re-decoding its whole
    working set.  ``hits``/``misses`` feed the transport metrics
    (decode_memo_hit_rate in the bench sections).
    """

    __slots__ = ("hits", "misses")

    def __init__(self, cap: int = 4096):
        super().__init__(cap)
        self.hits = 0
        self.misses = 0


def decode_frame_shared(
    data: bytes, memo: FrameDecodeMemo
) -> Tuple[Message, "memoryview"]:
    """Decode a frame through the shared-prefix memo (the columnar
    delivery arm of ``decode_frame``).

    The envelope is walked as OFFSETS over ``data`` — no body slice,
    no signing-prefix copy — and the returned signing prefix is a
    zero-copy ``memoryview`` (hashlib/hmac consume buffers directly).
    On a memo hit the entire payload decode is skipped and the shared
    immutable payload object is reused; per-frame work is then one
    digest + one dict probe + the Message envelope."""
    n = len(data)
    if n < 6 or data[:4] != _MAGIC:
        raise ValueError("bad magic")
    version, kind = data[4], data[5]
    if version != _VERSION:
        raise ValueError(f"unsupported wire version {version}")
    o = 6
    if o + 4 > n:
        raise ValueError("truncated frame")
    (sender_len,) = _U32.unpack_from(data, o)
    if sender_len > MAX_FIELD_BYTES:
        raise ValueError(f"field length {sender_len} exceeds cap")
    sender_off = o + 4
    o = sender_off + sender_len
    if o + 8 + 4 > n:
        raise ValueError("truncated frame")
    ts_off = o
    (body_len,) = _U32.unpack_from(data, o + 8)
    if body_len > MAX_FIELD_BYTES:
        raise ValueError(f"field length {body_len} exceeds cap")
    body_off = o + 12
    prefix_end = body_off + body_len
    if prefix_end + 4 > n:
        raise ValueError("truncated frame")
    (sig_len,) = _U32.unpack_from(data, prefix_end)
    if sig_len > MAX_FIELD_BYTES:
        raise ValueError(f"field length {sig_len} exceeds cap")
    sig_off = prefix_end + 4
    sig_end = sig_off + sig_len
    if sig_end > n:
        raise ValueError("truncated frame")
    attestation = b""
    if sig_end != n:
        # optional attested-log trailer: exactly one tagged blob
        if sig_end + 5 > n or data[sig_end] != ATTEST_TAG:
            raise ValueError("trailing bytes in frame")
        (att_len,) = _U32.unpack_from(data, sig_end + 1)
        if att_len > MAX_FIELD_BYTES:
            raise ValueError(f"field length {att_len} exceeds cap")
        att_off = sig_end + 5
        if att_off + att_len != n:
            raise ValueError(
                "truncated frame" if att_off + att_len > n
                else "trailing bytes in frame"
            )
        attestation = data[att_off:]
    view = memoryview(data)
    prefix = view[:prefix_end]
    digest = hashlib.sha256(prefix).digest()
    ent = memo.map.get(digest)
    if ent is None:
        memo.misses += 1
        sender = bytes(view[sender_off : sender_off + sender_len]).decode(
            "utf-8"
        )
        (ts,) = _F64.unpack_from(data, ts_off)
        payload, consumed = _parse_payload(data, body_off, prefix_end, kind)
        if consumed != prefix_end:
            # canonical-or-reject, same as _decode_payload: the MAC
            # covers these bytes and trailing junk is malleability
            raise ValueError("trailing bytes in payload body")
        ent = (sender, ts, payload)
        memo.put(digest, ent)
    else:
        memo.hits += 1
        sender, ts, payload = ent
    return (
        Message(
            sender_id=sender,
            timestamp=ts,
            payload=payload,
            signature=data[sig_off:sig_end],
            attestation=attestation,
        ),
        prefix,
    )


def decode_frame(
    data: bytes, payload_memo: Optional[dict] = None
) -> Tuple[Message, bytes]:
    """Decode a frame into (Message, signing_prefix).

    The wire layout is ``signing_bytes || len(sig) || sig``
    (attach_signature), so the exact byte string the MAC covers is a
    PREFIX of the frame — returning it lets authenticators verify
    without re-encoding the payload (at N=64 the re-encode was ~1/5 of
    the whole epoch's wall clock).

    ``payload_memo``: optional (kind, body) -> payload cache for
    transports that deliver one broadcast's IDENTICAL body bytes to
    many local receivers (the in-proc ChannelNetwork): the body parses
    once and the immutable payload object (NamedTuple / frozen
    dataclass) is shared.  Keyed on the exact bytes, so two distinct
    frames can never alias; per-receiver envelope fields (sender, ts,
    signature) are still decoded per frame, and MACs still verify per
    (sender, receiver) pair."""
    if len(data) < 6 or data[:4] != _MAGIC:
        raise ValueError("bad magic")
    version, kind = data[4], data[5]
    if version != _VERSION:
        raise ValueError(f"unsupported wire version {version}")
    r = _Reader(data[6:])
    sender = r.str_()
    ts = r.f64()
    body = r.bytes_()
    signing_prefix = data[: 6 + r._o]
    sig = r.bytes_()
    attestation = b""
    if not r.done():
        # optional attested-log trailer: exactly one tagged blob
        if r.u8() != ATTEST_TAG:
            raise ValueError("trailing bytes in frame")
        attestation = r.bytes_()
        if not r.done():
            raise ValueError("trailing bytes in frame")
    if payload_memo is None:
        payload = _decode_payload(kind, body)
    else:
        key = (kind, body)
        payload = payload_memo.get(key)
        if payload is None:
            payload = _decode_payload(kind, body)
            if len(payload_memo) >= _PAYLOAD_MEMO_CAP:
                payload_memo.clear()
            payload_memo[key] = payload
    return (
        Message(
            sender_id=sender,
            timestamp=ts,
            payload=payload,
            signature=sig,
            attestation=attestation,
        ),
        signing_prefix,
    )


# One wave's broadcast bodies stay hot; the cap bounds memory and a
# wholesale clear keeps lookups O(1) (bodies recur only within a wave,
# so eviction costs at most one re-parse per live body).
_PAYLOAD_MEMO_CAP = 4096


def decode_message(data: bytes) -> Message:
    return decode_frame(data)[0]


# ---------------------------------------------------------------------------
# client ingress frames
# ---------------------------------------------------------------------------

_INGRESS_MAGIC = b"CLIN"  # cleisthenes-tpu ingress (client) magic

# the only kinds a client frame may carry, in either direction; any
# validator-plane kind inside an ingress frame is rejected at decode,
# so a client can never smuggle protocol payloads past the MAC layer
_INGRESS_KINDS = frozenset(
    (
        _KIND_INGRESS_SUBMIT,
        _KIND_INGRESS_ACK,
        _KIND_INGRESS_SUB,
        _KIND_INGRESS_BATCH,
    )
)


def encode_client_frame(p: Payload) -> bytes:
    """One unauthenticated client<->validator ingress frame:
    ``CLIN | version | kind | TLV body``.  No envelope MAC — clients
    hold no roster keys; the mempool's admission control (dedup,
    per-client caps, priority eviction) is the abuse guard, and the
    gRPC stream supplies the length delimiting."""
    kind, body = _encode_payload(p)
    if kind not in _INGRESS_KINDS:
        raise ValueError(
            f"payload kind {kind} is not a client ingress kind"
        )
    return _INGRESS_MAGIC + struct.pack(">BB", _VERSION, kind) + body


def decode_client_frame(data: bytes) -> Payload:
    """Inverse of ``encode_client_frame``; canonical-or-reject like the
    validator codec, and restricted to the ingress kind set."""
    if len(data) < 6 or data[:4] != _INGRESS_MAGIC:
        raise ValueError("bad ingress magic")
    version, kind = data[4], data[5]
    if version != _VERSION:
        raise ValueError(f"unsupported wire version {version}")
    if kind not in _INGRESS_KINDS:
        raise ValueError(f"payload kind {kind} is not a client ingress kind")
    payload, consumed = _parse_payload(data, 6, len(data), kind)
    if consumed != len(data):
        raise ValueError("trailing bytes in ingress frame")
    return payload


__all__ = [
    "Message",
    "Payload",
    "RbcPayload",
    "BbaPayload",
    "CoinPayload",
    "DecSharePayload",
    "CatchupReqPayload",
    "CatchupRespPayload",
    "CatchupOrdPayload",
    "ResharePayload",
    "BundlePayload",
    "BbaBatchPayload",
    "CoinBatchPayload",
    "DecShareBatchPayload",
    "ReadyBatchPayload",
    "EchoBatchPayload",
    "IngressSubmitPayload",
    "IngressAckPayload",
    "IngressSubscribePayload",
    "IngressBatchPayload",
    "LanePayload",
    "IngressStatus",
    "RbcType",
    "BbaType",
    "encode_client_frame",
    "decode_client_frame",
    "encode_message",
    "decode_message",
    "decode_frame",
    "decode_frame_shared",
    "FrameDecodeMemo",
    "FrameEncodeMemo",
    "encode_payload_shared",
    "payload_body_count",
    "signing_bytes",
    "signing_bytes_shared",
    "attach_signature",
    "ATTEST_TAG",
    "MAX_FIELD_BYTES",
]
