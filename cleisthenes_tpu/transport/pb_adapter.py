"""Adapter for the reference's protobuf wire contract.

The reference's transport speaks proto3 ``pb.Message`` over one bidi
stream (reference pb/message.proto:7-46): an envelope
``Message{signature=1 bytes, timestamp=2 google.protobuf.Timestamp,
oneof payload{rbc=3 RBC, bba=4 BBA}}`` where ``RBC``/``BBA`` carry one
``payload=1 bytes`` field holding the marshalled inner request and
declare their type enums (VAL/ECHO/READY, BVAL/AUX).  The inner
marshalling format is unspecified at v0 (the skeleton never serialized
a request — "marshaled data by type", message.proto:27), so true
interop ends at the envelope; this adapter makes "same capabilities"
checkable AT THAT LAYER: our typed payloads round-trip through
byte-exact proto3 frames a stock protobuf decoder accepts.

Hand-rolled proto3 wire format (varints + length-delimited fields) —
no generated stubs, no protobuf dependency, byte-compatible with the
canonical encoder for this schema.  Inner requests are carried as our
deterministic TLV payload bodies (transport.message._encode_payload),
declared in an ``x-cleisthenes-tlv`` comment sense: a Go peer decodes
the envelope and the RBC/BBA type enum and sees the inner bytes
opaquely, exactly as the reference code would have.

This is deliberately an ADAPTER, not the native wire format: the
native codec (transport/message.py) stays the deterministic TLV
framing the MAC layer depends on (its rationale at message.py:17-24).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

from cleisthenes_tpu.transport.message import (
    BbaPayload,
    CatchupOrdPayload,
    CatchupReqPayload,
    CatchupRespPayload,
    IngressAckPayload,
    IngressBatchPayload,
    IngressSubmitPayload,
    IngressSubscribePayload,
    Message,
    Payload,
    RbcPayload,
    ResharePayload,
    _KIND_BBA,
    _KIND_CATCHUP_ORD,
    _KIND_CATCHUP_REQ,
    _KIND_CATCHUP_RESP,
    _KIND_INGRESS_ACK,
    _KIND_INGRESS_BATCH,
    _KIND_INGRESS_SUB,
    _KIND_INGRESS_SUBMIT,
    _KIND_RBC,
    _KIND_RESHARE,
    _encode_payload,
    _decode_payload,
)

_WT_VARINT = 0
_WT_LEN = 2

# The reference oneof numbers its rbc/bba slots 3 and 4
# (message.proto:18-22) and our native kind registry deliberately
# keeps the SAME numbers (message.py:300-302), so the oneof tags ARE
# the kind constants — spelled by name here so the wire registry
# analyzer (staticcheck WIRE001) sees the coverage and a renumbering
# on either side cannot drift silently.
_PB_TAG_RBC = _KIND_RBC
_PB_TAG_BBA = _KIND_BBA

# Extension slots beyond the reference's oneof (message.proto stops at
# bba=4): the crash-recovery CATCHUP pair rides high tag numbers as
# length-delimited messages carrying our TLV body in field 1.  A stock
# decoder built from the unextended schema skips them per proto3
# unknown-field semantics, so extended and stock peers interoperate —
# a reference peer simply cannot serve catch-up.
_PB_TAG_CATCHUP_REQ = 15
_PB_TAG_CATCHUP_RESP = 16
# ciphertext-ordered catch-up (Config.order_then_settle): same TLV-in-
# field-1 extension shape, next free tag
_PB_TAG_CATCHUP_ORD = 17
# dynamic membership: the reshare-dealing gossip kind (same field-1
# extension shape)
_PB_TAG_RESHARE = 18
# client ingress plane (transport/ingress.py): submit/ack/subscribe/
# batch-event frames, same TLV-in-field-1 extension shape — a stock
# decoder skips them as unknown fields, so a reference peer simply has
# no client door (the capability its skeleton never reached)
_PB_TAG_INGRESS_SUBMIT = 19
_PB_TAG_INGRESS_ACK = 20
_PB_TAG_INGRESS_SUB = 21
_PB_TAG_INGRESS_BATCH = 22
# attested sender log (protocol/attest.py): the envelope-level
# attestation trailer — NOT a payload kind, it rides beside the
# signature on every frame when Config.attested_log is armed.  Raw
# blob, next free tag; a stock decoder skips it per proto3
# unknown-field semantics, so a reference peer interoperates on the
# baseline arm and simply cannot join an attested roster (its frames
# carry no stamp and fail attestation verify — by design).
_PB_TAG_ATTEST = 23

# A Byzantine frame must not make us allocate from a length varint.
MAX_PB_FIELD = 64 * 1024 * 1024


def _varint(x: int) -> bytes:
    out = bytearray()
    while True:
        b = x & 0x7F
        x >>= 7
        if x:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(d: bytes, o: int) -> Tuple[int, int]:
    x = 0
    shift = 0
    while True:
        if o >= len(d) or shift > 63:
            raise ValueError("truncated/overlong varint")
        b = d[o]
        o += 1
        x |= (b & 0x7F) << shift
        if not (b & 0x80):
            return x, o
        shift += 7


def _len_field(tag: int, body: bytes) -> bytes:
    return _varint((tag << 3) | _WT_LEN) + _varint(len(body)) + body


def _varint_field(tag: int, value: int) -> bytes:
    if value == 0:  # proto3 default: omitted
        return b""
    return _varint((tag << 3) | _WT_VARINT) + _varint(value)


def _timestamp_body(ts: float) -> bytes:
    seconds = math.floor(ts)
    nanos = int(round((ts - seconds) * 1e9))
    if nanos >= 1_000_000_000:
        seconds += 1
        nanos = 0
    return _varint_field(1, seconds) + _varint_field(2, nanos)


def _parse_timestamp(body: bytes) -> float:
    seconds = nanos = 0
    o = 0
    while o < len(body):
        key, o = _read_varint(body, o)
        tag, wt = key >> 3, key & 7
        if wt != _WT_VARINT:
            raise ValueError("unexpected wire type in Timestamp")
        val, o = _read_varint(body, o)
        if tag == 1:
            seconds = val
        elif tag == 2:
            nanos = val
    return seconds + nanos / 1e9


def _inner_body(kind_tag: int, payload: Payload) -> bytes:
    """RBC/BBA message body: payload=1 bytes (our TLV bytes) +
    type as field 2 varint (the enum the reference declares)."""
    _tlv_kind, tlv = _encode_payload(payload)
    return _len_field(1, tlv) + _varint_field(2, int(payload.type))


def encode_pb_message(msg: Message) -> bytes:
    """Our envelope -> reference pb.Message bytes.

    Only RBC and BBA payloads exist in the reference's oneof
    (message.proto:19-22); other kinds raise — they are capabilities
    the reference never reached, with no slot in its contract."""
    p = msg.payload
    if isinstance(p, RbcPayload):
        one = _len_field(_PB_TAG_RBC, _inner_body(_PB_TAG_RBC, p))
    elif isinstance(p, BbaPayload):
        one = _len_field(_PB_TAG_BBA, _inner_body(_PB_TAG_BBA, p))
    elif isinstance(p, CatchupReqPayload):
        _k, tlv = _encode_payload(p)
        one = _len_field(_PB_TAG_CATCHUP_REQ, _len_field(1, tlv))
    elif isinstance(p, CatchupRespPayload):
        _k, tlv = _encode_payload(p)
        one = _len_field(_PB_TAG_CATCHUP_RESP, _len_field(1, tlv))
    elif isinstance(p, CatchupOrdPayload):
        _k, tlv = _encode_payload(p)
        one = _len_field(_PB_TAG_CATCHUP_ORD, _len_field(1, tlv))
    elif isinstance(p, ResharePayload):
        _k, tlv = _encode_payload(p)
        one = _len_field(_PB_TAG_RESHARE, _len_field(1, tlv))
    elif isinstance(p, IngressSubmitPayload):
        _k, tlv = _encode_payload(p)
        one = _len_field(_PB_TAG_INGRESS_SUBMIT, _len_field(1, tlv))
    elif isinstance(p, IngressAckPayload):
        _k, tlv = _encode_payload(p)
        one = _len_field(_PB_TAG_INGRESS_ACK, _len_field(1, tlv))
    elif isinstance(p, IngressSubscribePayload):
        _k, tlv = _encode_payload(p)
        one = _len_field(_PB_TAG_INGRESS_SUB, _len_field(1, tlv))
    elif isinstance(p, IngressBatchPayload):
        _k, tlv = _encode_payload(p)
        one = _len_field(_PB_TAG_INGRESS_BATCH, _len_field(1, tlv))
    else:
        raise ValueError(
            f"{type(p).__name__} has no slot in the reference's oneof"
        )
    att = (
        _len_field(_PB_TAG_ATTEST, msg.attestation)
        if msg.attestation
        else b""
    )
    return (
        _len_field(1, msg.signature)
        + _len_field(2, _timestamp_body(msg.timestamp))
        + one
        + att
    )


def decode_pb_message(data: bytes, sender_id: str = "") -> Message:
    """Reference pb.Message bytes -> our envelope.

    ``sender_id`` must come from the connection (the reference trusts
    the stream's uuid, comm.go:46 — its envelope has no sender field).
    """
    signature = b""
    attestation = b""
    ts = 0.0
    payload: Optional[Payload] = None
    o = 0
    while o < len(data):
        key, o = _read_varint(data, o)
        tag, wt = key >> 3, key & 7
        if wt != _WT_LEN:
            # unknown scalar fields skip per proto3 semantics (forward
            # compatibility); the KNOWN tags are all length-delimited
            if tag in (
                1, 2, _PB_TAG_RBC, _PB_TAG_BBA,
                _PB_TAG_CATCHUP_REQ, _PB_TAG_CATCHUP_RESP,
                _PB_TAG_CATCHUP_ORD, _PB_TAG_RESHARE,
                _PB_TAG_INGRESS_SUBMIT, _PB_TAG_INGRESS_ACK,
                _PB_TAG_INGRESS_SUB, _PB_TAG_INGRESS_BATCH,
                _PB_TAG_ATTEST,
            ):
                raise ValueError(
                    f"wire type {wt} for known tag {tag} (expected LEN)"
                )
            if wt == _WT_VARINT:
                _v, o = _read_varint(data, o)
            elif wt == 1:  # fixed64
                o += 8
            elif wt == 5:  # fixed32
                o += 4
            else:
                raise ValueError(f"unsupported wire type {wt}")
            if o > len(data):
                raise ValueError("truncated pb field")
            continue
        ln, o = _read_varint(data, o)
        if ln > MAX_PB_FIELD or o + ln > len(data):
            raise ValueError("truncated/oversized pb field")
        body = data[o : o + ln]
        o += ln
        if tag == 1:
            signature = body
        elif tag == 2:
            ts = _parse_timestamp(body)
        elif tag in (_PB_TAG_RBC, _PB_TAG_BBA):
            payload = _parse_inner(tag, body)
        elif tag in (
            _PB_TAG_CATCHUP_REQ, _PB_TAG_CATCHUP_RESP,
            _PB_TAG_CATCHUP_ORD, _PB_TAG_RESHARE,
            _PB_TAG_INGRESS_SUBMIT, _PB_TAG_INGRESS_ACK,
            _PB_TAG_INGRESS_SUB, _PB_TAG_INGRESS_BATCH,
        ):
            payload = _parse_catchup(tag, body)
        elif tag == _PB_TAG_ATTEST:
            attestation = body
        # unknown LEN fields are skipped, per proto3 semantics
    if payload is None:
        raise ValueError("pb.Message carries no rbc/bba payload")
    return Message(
        sender_id=sender_id, timestamp=ts, payload=payload,
        signature=signature, attestation=attestation,
    )


def _parse_catchup(tag: int, body: bytes) -> Payload:
    """Extension slots: TLV body in field 1, no type enum."""
    tlv = b""
    o = 0
    while o < len(body):
        key, o = _read_varint(body, o)
        ftag, wt = key >> 3, key & 7
        if wt != _WT_LEN:
            raise ValueError(f"unexpected wire type {wt} in Catchup")
        ln, o = _read_varint(body, o)
        if ln > MAX_PB_FIELD or o + ln > len(body):
            raise ValueError("truncated/oversized pb field")
        if ftag == 1:
            tlv = body[o : o + ln]
        o += ln
    if tag == _PB_TAG_CATCHUP_REQ:
        kind = _KIND_CATCHUP_REQ
    elif tag == _PB_TAG_CATCHUP_RESP:
        kind = _KIND_CATCHUP_RESP
    elif tag == _PB_TAG_RESHARE:
        kind = _KIND_RESHARE
    elif tag == _PB_TAG_INGRESS_SUBMIT:
        kind = _KIND_INGRESS_SUBMIT
    elif tag == _PB_TAG_INGRESS_ACK:
        kind = _KIND_INGRESS_ACK
    elif tag == _PB_TAG_INGRESS_SUB:
        kind = _KIND_INGRESS_SUB
    elif tag == _PB_TAG_INGRESS_BATCH:
        kind = _KIND_INGRESS_BATCH
    else:
        kind = _KIND_CATCHUP_ORD
    return _decode_payload(kind, tlv)


def _parse_inner(tag: int, body: bytes) -> Payload:
    tlv = b""
    o = 0
    while o < len(body):
        key, o = _read_varint(body, o)
        ftag, wt = key >> 3, key & 7
        if wt == _WT_LEN:
            ln, o = _read_varint(body, o)
            if ln > MAX_PB_FIELD or o + ln > len(body):
                raise ValueError("truncated/oversized pb field")
            if ftag == 1:
                tlv = body[o : o + ln]
            o += ln
        elif wt == _WT_VARINT:
            _val, o = _read_varint(body, o)  # type enum: informational
        else:
            raise ValueError(f"unexpected wire type {wt} in RBC/BBA")
    kind = _KIND_RBC if tag == _PB_TAG_RBC else _KIND_BBA
    payload = _decode_payload(kind, tlv)
    return payload


__all__ = ["encode_pb_message", "decode_pb_message"]
