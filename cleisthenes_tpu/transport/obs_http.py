"""Scrapeable telemetry endpoints: /metrics, /healthz, /vars.

Thetacrypt (PAPERS.md, arxiv 2502.03247) frames threshold crypto as a
*service* — and a service has an operational surface: health probes,
per-request metrics, something a fleet scheduler can scrape.  The
reference has none; this module gives every validator one, stdlib-only
(the container bakes no prometheus_client), opt-in via
``Config.obs_port``:

- ``/metrics``  Prometheus text exposition (version 0.0.4): counters,
  epoch-latency histograms with cumulative buckets, transport frame /
  dedup counters, per-peer dial health, flight-recorder stats, SLO
  alert counters, and the health verdict as a gauge.
- ``/healthz``  UP/DEGRADED/DOWN (HTTP 503 on DOWN) derived from the
  SLO watchdogs (utils/watchdog.py) + peer health — each GET runs the
  watchdog checks, so probes see fresh verdicts even with no sampler
  thread running.
- ``/vars``     the full ``Metrics.snapshot()`` JSON plus the bounded
  time-series rings (utils/timeseries.py) — the debugging firehose.

One ``ObsServer`` can front many nodes (the SimulatedCluster exposes
its whole roster through one port, each sample labeled
``node="..."``); a ValidatorHost runs its own single-target server.
Binds 127.0.0.1 only: telemetry is an operator surface, not a roster
protocol — nothing here is MAC'd and nothing must reach the open
network.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence

from cleisthenes_tpu.utils.metrics import Histogram, Metrics
from cleisthenes_tpu.utils.watchdog import (
    DEGRADED,
    DOWN,
    UP,
    SloWatchdog,
    worst_health,
)

CONTENT_TYPE_PROM = "text/plain; version=0.0.4; charset=utf-8"

_HEALTH_GAUGE = {UP: 2, DEGRADED: 1, DOWN: 0}


def escape_label_value(v: object) -> str:
    """Prometheus text-format label escaping: backslash, double quote
    and newline (in THAT order — escaping the escapes first)."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if isinstance(v, int) or float(v).is_integer():
        return str(int(v))
    return repr(float(v))


class _Exposition:
    """Accumulates samples grouped into metric families, so a
    multi-node scrape emits each # HELP/# TYPE header exactly once."""

    def __init__(self, prefix: str = "cleisthenes") -> None:
        self.prefix = prefix
        self._families: Dict[str, List[str]] = {}
        self._headers: Dict[str, str] = {}

    def family(self, name: str, kind: str, help_text: str) -> str:
        full = f"{self.prefix}_{name}"
        if full not in self._families:
            self._families[full] = []
            self._headers[full] = (
                f"# HELP {full} {help_text}\n# TYPE {full} {kind}"
            )
        return full

    def add(self, full: str, labels: Dict[str, object], value: float,
            suffix: str = "") -> None:
        lab = ",".join(
            f'{k}="{escape_label_value(v)}"' for k, v in labels.items()
        )
        self._families[full].append(
            f"{full}{suffix}{{{lab}}} {_fmt(value)}"
        )

    def render(self) -> str:
        out: List[str] = []
        for full, samples in self._families.items():
            out.append(self._headers[full])
            out.extend(samples)
        return "\n".join(out) + "\n"


def _expose_histogram(
    exp: _Exposition,
    name: str,
    help_text: str,
    hist: Histogram,
    labels: Dict[str, object],
) -> None:
    full = exp.family(name, "histogram", help_text)
    for le, count in hist.cumulative_buckets():
        exp.add(full, {**labels, "le": _fmt(le)}, count, suffix="_bucket")
    # lifetime tallies: the histogram type contract wants monotonic
    # counters (the percentile reservoir is a recency window)
    exp.add(full, labels, hist.total_sum, suffix="_sum")
    exp.add(full, labels, hist.total_count, suffix="_count")


class ObsTarget:
    """One scrapeable node: its metrics registry plus (optionally) the
    SLO watchdog and time-series sampler wired around it."""

    def __init__(
        self,
        node_id: str,
        metrics: Metrics,
        watchdog: Optional[SloWatchdog] = None,
        sampler=None,
    ) -> None:
        self.node_id = node_id
        self.metrics = metrics
        self.watchdog = watchdog
        self.sampler = sampler

    def health(self) -> str:
        if self.watchdog is None:
            return UP
        return self.watchdog.check()


def render_prometheus(targets: Sequence[ObsTarget]) -> str:
    """The /metrics body for a set of targets, each sample labeled by
    its node id."""
    exp = _Exposition()
    for t in targets:
        m = t.metrics
        labels = {"node": t.node_id}
        snap = m.snapshot()
        for name, counter, help_text in (
            ("msgs_in_total", m.msgs_in, "logical protocol messages received"),
            ("msgs_out_total", m.msgs_out, "logical protocol messages sent"),
            ("epochs_committed_total", m.epochs_committed,
             "epochs committed (consensus + catch-up adoption)"),
            ("txs_committed_total", m.txs_committed,
             "transactions committed"),
        ):
            exp.add(
                exp.family(name, "counter", help_text),
                labels,
                counter.value,
            )
        exp.add(
            exp.family("tx_per_sec", "gauge",
                       "committed transaction throughput since boot"),
            labels,
            float(snap["tx_per_sec"]),
        )
        for hname, hist, help_text in (
            ("epoch_latency_seconds", m.epoch_latency,
             "propose -> commit wall time per epoch"),
            ("acs_latency_seconds", m.acs_latency,
             "propose -> ACS output wall time per epoch"),
            ("decrypt_latency_seconds", m.decrypt_latency,
             "ACS output -> commit (threshold decryption) per epoch"),
            ("ordered_latency_seconds", m.ordered_latency,
             "propose -> ciphertext-ordered commit (two-frontier "
             "ordered frontier)"),
            ("settle_lag_seconds", m.settle_lag_latency,
             "ordered -> settled (trailing decrypt frontier lag)"),
        ):
            _expose_histogram(exp, hname, help_text, hist, labels)
        frontiers = snap["frontiers"]
        exp.add(
            exp.family(
                "epochs_ordered_total", "counter",
                "epochs whose ciphertext ordering committed "
                "(two-frontier commit split)",
            ),
            labels,
            int(frontiers["epochs_ordered"]),
        )
        exp.add(
            exp.family(
                "decrypt_lag_epochs", "gauge",
                "ordered frontier - settled frontier (0 on the "
                "coupled path; bounded by decrypt_lag_max)",
            ),
            labels,
            int(frontiers["decrypt_lag_epochs"]),
        )
        # dynamic-membership counters (always present — zeroed on
        # fixed-roster nodes per the schema-stability rule)
        reconfig = snap["reconfig"]
        exp.add(
            exp.family(
                "roster_version", "gauge",
                "the ACTIVE roster version (0 = genesis; bumps at "
                "every RECONFIG activation boundary)",
            ),
            labels,
            int(reconfig["roster_version"]),
        )
        exp.add(
            exp.family(
                "reconfigs_total", "counter",
                "completed roster switches activated by this node "
                "(joins, retirements, re-keys)",
            ),
            labels,
            int(reconfig["reconfigs_total"]),
        )
        transport = snap["transport"]
        frames = exp.family(
            "transport_frames_total", "counter",
            "inbound wire frames by verification result",
        )
        for result in ("delivered", "rejected"):
            exp.add(
                frames, {**labels, "result": result},
                int(transport[result]),
            )
        exp.add(
            exp.family(
                "dedup_absorbed_total", "counter",
                "duplicate protocol votes/shares absorbed by dedup",
            ),
            labels,
            int(transport["dedup_absorbed"]),
        )
        # delivery-plane columnarization counters (always present —
        # zeroed on the scalar arm per the schema-stability rule)
        exp.add(
            exp.family(
                "transport_frames_decoded_total", "counter",
                "inbound payload decodes actually executed "
                "(shared-prefix memo hits skip the decode)",
            ),
            labels,
            int(transport["frames_decoded"]),
        )
        memo = exp.family(
            "transport_decode_memo_total", "counter",
            "shared-prefix frame-decode memo probes by result",
        )
        for result, key in (
            ("hit", "decode_memo_hits"),
            ("miss", "decode_memo_misses"),
        ):
            exp.add(
                memo, {**labels, "result": result}, int(transport[key])
            )
        exp.add(
            exp.family(
                "transport_mac_verify_batches_total", "counter",
                "authenticator verify invocations (one per wave batch "
                "columnar; one per frame scalar)",
            ),
            labels,
            int(transport["mac_verify_batches"]),
        )
        # egress-columnarization counters (ISSUE 13; always present —
        # zeroed on the scalar arm per the schema-stability rule)
        exp.add(
            exp.family(
                "transport_frames_encoded_total", "counter",
                "outbound payload bodies actually encoded "
                "(shared-prefix encode memo hits skip the encode)",
            ),
            labels,
            int(transport["frames_encoded"]),
        )
        ememo = exp.family(
            "transport_encode_memo_total", "counter",
            "shared-prefix frame-encode memo probes by result",
        )
        for result, key in (
            ("hit", "encode_memo_hits"),
            ("miss", "encode_memo_misses"),
        ):
            exp.add(
                ememo, {**labels, "result": result}, int(transport[key])
            )
        exp.add(
            exp.family(
                "transport_mac_sign_batches_total", "counter",
                "authenticator sign invocations (one per egress wave "
                "columnar; one per post scalar)",
            ),
            labels,
            int(transport["mac_sign_batches"]),
        )
        hub = snap["hub"]
        exp.add(
            exp.family(
                "coin_share_batches_total", "counter",
                "native coin-share issue dispatches (one per staged "
                "pool per wave columnar; one per node per drain "
                "scalar)",
            ),
            labels,
            int(hub["coin_share_batches"]),
        )
        exp.add(
            exp.family(
                "coin_share_items_total", "counter",
                "coin shares issued through the batched coin kernels",
            ),
            labels,
            int(hub["coin_share_items"]),
        )
        # wave-routed ingest counters (always present — zeroed on the
        # scalar routing arm per the schema-stability rule)
        router = snap["router"]
        exp.add(
            exp.family(
                "router_handler_dispatches_total", "counter",
                "batch handler invocations crossing the router seam "
                "(one per payload scalar; one per kind per wave routed)",
            ),
            labels,
            int(router["handler_dispatches"]),
        )
        exp.add(
            exp.family(
                "router_waves_total", "counter",
                "delivery waves demuxed by the wave router",
            ),
            labels,
            int(router["waves_routed"]),
        )
        # K-deep pipelined-frontier counters (always present — zeroed
        # at depth 1 per the schema-stability rule)
        pipeline = snap["pipeline"]
        exp.add(
            exp.family(
                "pipeline_epochs_in_flight", "gauge",
                "epochs running RBC/BBA concurrently in the K-deep "
                "window (1 in steady lockstep)",
            ),
            labels,
            int(pipeline["epochs_in_flight"]),
        )
        exp.add(
            exp.family(
                "pipeline_eager_share_waves_total", "counter",
                "delivery waves whose flush carried eagerly "
                "piggybacked dec shares for a freshly ordered epoch",
            ),
            labels,
            int(pipeline["eager_share_waves"]),
        )
        # WAN emulation-plane counters (always present — zeroed on
        # real transports / unmounted profiles per the schema rule)
        wan = snap["wan"]
        exp.add(
            exp.family(
                "wan_enabled", "gauge",
                "1 while a seeded WAN link-model profile is mounted "
                "on the channel transport",
            ),
            labels,
            int(wan["enabled"]),
        )
        exp.add(
            exp.family(
                "wan_frames_delayed_total", "counter",
                "frames priced past their admission instant by the "
                "link model (latency/loss/bandwidth/straggler)",
            ),
            labels,
            int(wan["frames_delayed"]),
        )
        exp.add(
            exp.family(
                "wan_retransmits_total", "counter",
                "emulated reliable-transport retransmissions (each "
                "seeded loss adds one RTO to the delivery deadline)",
            ),
            labels,
            int(wan["retransmits"]),
        )
        exp.add(
            exp.family(
                "wan_straggler_episodes_total", "counter",
                "heavy-tailed straggler episodes started across the "
                "roster's node processes",
            ),
            labels,
            int(wan["straggler_episodes"]),
        )
        exp.add(
            exp.family(
                "wan_virtual_time_seconds", "gauge",
                "the emulation plane's virtual clock (never wall "
                "time; advances only at delivery deadlines)",
            ),
            labels,
            int(wan["virtual_time_ms"]) / 1e3,
        )
        # client ingress-plane counters (always present — zeroed
        # when no mempool is mounted per the schema rule)
        ingress = snap["ingress"]
        exp.add(
            exp.family(
                "ingress_submitted_total", "counter",
                "client transactions offered to the admission stage "
                "(every one got an explicit ack verdict)",
            ),
            labels,
            int(ingress["submitted"]),
        )
        exp.add(
            exp.family(
                "ingress_admitted_total", "counter",
                "submissions admitted into the fee-priority mempool",
            ),
            labels,
            int(ingress["admitted"]),
        )
        exp.add(
            exp.family(
                "ingress_rejected_total", "counter",
                "submissions rejected outright (malformed, "
                "oversized, negative fee)",
            ),
            labels,
            int(ingress["rejected"]),
        )
        exp.add(
            exp.family(
                "ingress_retried_total", "counter",
                "submissions answered RETRY_AFTER (per-client cap "
                "or global pressure — explicit backpressure, never "
                "a silent drop)",
            ),
            labels,
            int(ingress["retried"]),
        )
        exp.add(
            exp.family(
                "ingress_deduped_total", "counter",
                "submissions absorbed by the bounded seen-ring "
                "(already pending, in flight, or recently settled)",
            ),
            labels,
            int(ingress["deduped"]),
        )
        exp.add(
            exp.family(
                "ingress_evicted_total", "counter",
                "pending entries bumped by higher-priority "
                "newcomers under capacity pressure",
            ),
            labels,
            int(ingress["evicted"]),
        )
        exp.add(
            exp.family(
                "ingress_subscribers", "gauge",
                "open committed-batch subscription feeds",
            ),
            labels,
            int(ingress["subscribers"]),
        )
        exp.add(
            exp.family(
                "ingress_mempool_depth", "gauge",
                "live mempool entries (pending + drained-in-flight) "
                "— the depth the queue-backpressure watchdog reads",
            ),
            labels,
            int(ingress["mempool_depth"]),
        )
        # lane shard-out families (always present — the lanes block
        # is in every snapshot, collapsed to one lane at Config.lanes=1)
        lanes_blk = snap["lanes"]
        exp.add(
            exp.family(
                "lane_count", "gauge",
                "configured consensus lanes (Config.lanes; 1 = the "
                "single-lane build)",
            ),
            labels,
            int(lanes_blk["lanes"]),
        )
        exp.add(
            exp.family(
                "lane_merge_frontier", "gauge",
                "merge-emitted total-order slots (== the settled "
                "epoch count at one lane)",
            ),
            labels,
            int(lanes_blk["merge_frontier"]),
        )
        exp.add(
            exp.family(
                "lane_partition_skew", "gauge",
                "max-min lifetime admissions across lanes (the "
                "tx-hash partitioner's balance witness)",
            ),
            labels,
            int(lanes_blk["partition_skew"]),
        )
        for k, v in enumerate(lanes_blk["ordered_epochs"]):
            exp.add(
                exp.family(
                    "lane_ordered_epochs", "gauge",
                    "per-lane ordered frontier (labeled by lane)",
                ),
                {**labels, "lane": k},
                int(v),
            )
        for k, v in enumerate(lanes_blk["settled_epochs"]):
            exp.add(
                exp.family(
                    "lane_settled_epochs", "gauge",
                    "per-lane settled frontier (labeled by lane)",
                ),
                {**labels, "lane": k},
                int(v),
            )
        for k, v in enumerate(lanes_blk["lane_fill"]):
            exp.add(
                exp.family(
                    "lane_fill_total", "counter",
                    "lifetime mempool admissions per lane (labeled "
                    "by lane)",
                ),
                {**labels, "lane": k},
                int(v),
            )
        for peer, ph in snap.get("transport_health", {}).items():
            plabels = {**labels, "peer": peer}
            exp.add(
                exp.family(
                    "peer_health", "gauge",
                    "dial-layer peer state (labeled; value always 1)",
                ),
                {**plabels, "state": ph["state"]},
                1,
            )
            exp.add(
                exp.family("peer_reconnects_total", "counter",
                           "successful re-establishments after a loss"),
                plabels,
                int(ph["reconnects"]),
            )
            exp.add(
                exp.family("peer_dial_failures_total", "counter",
                           "failed dial attempts"),
                plabels,
                int(ph["dial_failures"]),
            )
        tr = snap.get("trace")
        if tr is not None:
            exp.add(
                exp.family("trace_events_recorded_total", "counter",
                           "flight-recorder events recorded"),
                labels,
                int(tr["events_recorded"]),
            )
            exp.add(
                exp.family("trace_events_dropped_total", "counter",
                           "flight-recorder ring-overflow drops"),
                labels,
                int(tr["events_dropped"]),
            )
        for alert, st in snap.get("alerts", {}).items():
            alabels = {**labels, "alert": alert}
            exp.add(
                exp.family("alerts_total", "counter",
                           "SLO watchdog firings (inactive->active)"),
                alabels,
                int(st["count"]),
            )
            exp.add(
                exp.family("alert_active", "gauge",
                           "1 while the named SLO alert is active"),
                alabels,
                1 if st["active"] else 0,
            )
        if t.watchdog is not None:
            exp.add(
                exp.family("health", "gauge",
                           "node health: 2=up 1=degraded 0=down"),
                labels,
                _HEALTH_GAUGE[t.watchdog.health()],
            )
    return exp.render()


class ObsServer:
    """The localhost telemetry listener (ThreadingHTTPServer on a
    daemon thread).  ``port=0`` binds an ephemeral port; read
    ``.port`` after ``start()``."""

    def __init__(
        self,
        targets: Sequence[ObsTarget],
        port: int = 0,
        host: str = "127.0.0.1",
    ) -> None:
        self.targets = list(targets)
        self._host = host
        self._requested_port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None

    def add_target(self, target: ObsTarget) -> None:
        """Fold one more node into the scrape (dynamic membership: a
        JOINER wired in mid-run).  List append is atomic under the
        GIL and request handlers only iterate, so no lock is needed
        for the read-mostly pattern here."""
        self.targets.append(target)

    # -- endpoint bodies (also the in-proc testing surface) ----------------

    def metrics_text(self) -> str:
        for t in self.targets:
            t.health()  # run watchdog checks: scrapes see fresh state
        return render_prometheus(self.targets)

    def healthz(self) -> Dict[str, object]:
        nodes = {t.node_id: t.health() for t in self.targets}
        return {"status": worst_health(nodes.values()), "nodes": nodes}

    def vars(self) -> Dict[str, object]:
        out: Dict[str, object] = {}
        for t in self.targets:
            entry: Dict[str, object] = {"metrics": t.metrics.snapshot()}
            if t.sampler is not None:
                entry["timeseries"] = {
                    name: points
                    for name, points in t.sampler.series().items()
                }
                entry["sampler"] = t.sampler.stats()
            out[t.node_id] = entry
        return out

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> int:
        if self._httpd is not None:
            return self.port  # type: ignore[return-value]
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet: NodeLogger owns stdout
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        self._send(
                            200,
                            server.metrics_text().encode("utf-8"),
                            CONTENT_TYPE_PROM,
                        )
                    elif path == "/healthz":
                        doc = server.healthz()
                        self._send(
                            503 if doc["status"] == DOWN else 200,
                            (json.dumps(doc) + "\n").encode("utf-8"),
                            "application/json",
                        )
                    elif path == "/vars":
                        self._send(
                            200,
                            (json.dumps(server.vars()) + "\n").encode(
                                "utf-8"
                            ),
                            "application/json",
                        )
                    else:
                        self._send(
                            404, b"not found\n", "text/plain"
                        )
                except Exception as exc:  # scrape must never kill the server
                    try:
                        self._send(
                            500,
                            f"scrape failed: {exc!r}\n".encode("utf-8"),
                            "text/plain",
                        )
                    except OSError:
                        pass  # peer already hung up

        self._httpd = ThreadingHTTPServer(
            (self._host, self._requested_port), Handler
        )
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"obs-http:{self.port}",
            daemon=True,
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)


__all__ = [
    "CONTENT_TYPE_PROM",
    "ObsServer",
    "ObsTarget",
    "escape_label_value",
    "render_prometheus",
]
