"""Payload-level broadcast seam between protocol instances and transport.

The reference's protocol structs hold a ``cleisthenes.Broadcaster``
(reference rbc/rbc.go:35, bba/bba.go:60) and never touch gRPC directly;
this module is that seam for payloads: the protocol layer emits typed
payloads, the broadcaster wraps them in the authenticated envelope and
hands them to a concrete transport.

``broadcast`` includes the sending node itself: HBBFT quorum counting
treats the local node as a normal peer (its own ECHO/READY/BVAL votes
count), and routing self-delivery through the same transport keeps the
deterministic scheduler in charge of *all* message interleavings.
"""

from __future__ import annotations

import time
from typing import (
    Dict,
    List,
    Optional,
    Protocol,
    Sequence,
    Tuple,
    runtime_checkable,
)

from cleisthenes_tpu.transport.message import (
    BbaBatchPayload,
    BbaPayload,
    BundlePayload,
    CoinBatchPayload,
    CoinPayload,
    DecShareBatchPayload,
    DecSharePayload,
    EchoBatchPayload,
    LanePayload,
    Message,
    Payload,
    RbcPayload,
    RbcType,
    ReadyBatchPayload,
)


@runtime_checkable
class PayloadBroadcaster(Protocol):
    def broadcast(self, payload: Payload) -> None: ...

    def send_to(self, member_id: str, payload: Payload) -> None: ...


class ChannelBroadcaster:
    """PayloadBroadcaster over an in-proc ChannelNetwork.

    Envelope signing happens inside the network at post time (each
    endpoint's Authenticator), mirroring the reference where the conn
    layer owns signatures (conn.go:134-137's intent)."""

    def __init__(self, network, node_id: str, member_ids: Sequence[str]):
        self._network = network
        self._node_id = node_id
        self._members: List[str] = sorted(member_ids)

    def set_members(self, member_ids: Sequence[str]) -> None:
        """Swap the broadcast target set (dynamic membership: the
        roster at an activation boundary; CoalescingBroadcaster
        propagates its own set_members here)."""
        self._members = sorted(member_ids)

    def _wrap(self, payload: Payload) -> Message:
        return Message(
            sender_id=self._node_id, timestamp=time.time(), payload=payload
        )

    def broadcast(self, payload: Payload) -> None:
        self._network.post_many(
            self._node_id, self._members, self._wrap(payload)
        )

    def send_to(self, member_id: str, payload: Payload) -> None:
        self._network.post(self._node_id, member_id, self._wrap(payload))

    def post_wave(self, entries) -> None:
        """One egress wave (Config.egress_columnar): ``entries`` are
        ``(member_id | None, payload)`` pairs — None addresses the full
        broadcast set.  The whole wave crosses into the network in ONE
        call, where the sender endpoint's ``sign_wire_wave`` encodes
        each distinct body once and MACs the wave in one batched
        pass."""
        wave = [
            (
                self._members if member_id is None else (member_id,),
                self._wrap(payload),
            )
            for member_id, payload in entries
        ]
        self._network.post_wave(self._node_id, wave)


def _columnarize(buf: List[Payload]) -> List[Payload]:
    """Merge a wave buffer's per-instance runs into columnar payloads.

    One wave makes a node emit the same logical message across many
    concurrent instances — N BVAL(v)s, N coin shares, N dec shares,
    N READYs differing only in per-instance fields.  Grouping by the
    shared key (first-occurrence order, so the merge is deterministic)
    turns O(N) bundle items into one columnar item each: both wire
    bytes and the receiver's per-item decode/dispatch drop by ~N.
    Singleton groups stay scalar; VAL/ECHO (bulky per-instance data)
    and sync payloads pass through unchanged.
    """
    groups: dict = {}
    order: List[tuple] = []
    for p in buf:
        cls = p.__class__
        # lane shard-out (ISSUE 20): a lane's runs merge under a
        # lane-prefixed key — the merged column re-wraps below, so S
        # lanes' traffic columnarizes exactly as lane 0's does and
        # still shares the one bundle per (receiver, wave)
        lane = 0
        q = p
        if cls is LanePayload:
            lane = p.lane
            q = p.inner
            cls = q.__class__
        if cls is BbaPayload:
            key = ("b", q.type, q.epoch, q.round, q.value)
        elif cls is CoinPayload:
            key = ("c", q.epoch, q.round, q.index)
        elif cls is DecSharePayload:
            key = ("d", q.epoch, q.index)
        elif cls is RbcPayload and q.type is RbcType.READY:
            key = ("r", q.epoch)
        elif cls is RbcPayload and q.type is RbcType.ECHO:
            # one turn's ECHO fan-out shares the sender's shard slot
            # (it echoes the VALs it received, all at its own index)
            key = ("e", q.epoch, q.shard_index)
        else:
            key = ("solo", len(order))  # preserves position, no merge
        if lane and key[0] != "solo":
            key = ("L", lane) + key
        if key in groups:
            groups[key].append(p)
        else:
            groups[key] = [p]
            order.append(key)
    out: List[Payload] = []
    for key in order:
        run = groups[key]
        if len(run) == 1:
            out.append(run[0])
            continue
        lane = 0
        if key[0] == "L":
            lane = key[1]
            key = key[2:]
            run = [p.inner for p in run]
        tag = key[0]
        if tag == "b":
            p0 = run[0]
            col = BbaBatchPayload(
                p0.type, p0.epoch, p0.round, p0.value,
                tuple(p.proposer for p in run),
            )
        elif tag == "c":
            p0 = run[0]
            col = CoinBatchPayload(
                p0.epoch, p0.round, p0.index,
                tuple(p.proposer for p in run),
                tuple(p.d for p in run),
                tuple(p.e for p in run),
                tuple(p.z for p in run),
            )
        elif tag == "d":
            p0 = run[0]
            col = DecShareBatchPayload(
                p0.epoch, p0.index,
                tuple(p.proposer for p in run),
                tuple(p.d for p in run),
                tuple(p.e for p in run),
                tuple(p.z for p in run),
            )
        elif tag == "r":
            p0 = run[0]
            col = ReadyBatchPayload(
                p0.epoch,
                tuple(p.proposer for p in run),
                tuple(p.root_hash for p in run),
            )
        else:  # "e"
            p0 = run[0]
            col = EchoBatchPayload(
                p0.epoch,
                p0.shard_index,
                tuple(p.proposer for p in run),
                tuple(p.root_hash for p in run),
                tuple(p.branch for p in run),
                tuple(p.shard for p in run),
            )
        out.append(LanePayload(lane, col) if lane else col)
    return out


class CoalescingBroadcaster:
    """Per-receiver outbound buffering in front of any PayloadBroadcaster.

    HBBFT's traffic is O(N^2) broadcast waves of tiny payloads: within
    one protocol turn a node emits one ECHO/READY/BVAL/AUX/coin/share
    per concurrent instance, all to the same N receivers.  Buffering
    them and flushing ONE ``BundlePayload`` envelope per receiver per
    wave amortizes the envelope encode + MAC + frame decode + verify to
    one per (sender, receiver, wave) instead of one per payload — the
    coalescing lever VERDICT round 2 identified as the wall between the
    N=16 measurement and the BASELINE N=64/128 metric (the reference's
    per-message cost model: docs/HONEYBADGER-EN.md:93-96).

    ``flush()`` is called by the owner at wave boundaries (a transport
    idle callback, or the end of a handler turn).  When every buffered
    payload since the last flush was a broadcast, all receivers' bundles
    are byte-identical and the flush takes the inner broadcaster's
    broadcast fast path (one envelope encode, per-receiver MACs only —
    transport.base.Authenticator.sign_wire_many).
    """

    def __init__(
        self,
        inner,
        member_ids: Sequence[str],
        trace=None,
        egress_columnar: bool = False,
    ) -> None:
        self._inner = inner
        self._members: List[str] = sorted(member_ids)
        # Config.egress_columnar: hand each flush's whole wave of
        # folded bundles to the inner broadcaster in ONE post_wave
        # call — the transport signs it through one
        # Authenticator.sign_wire_wave pass (shared-prefix
        # FrameEncodeMemo, batched MACs) and writes one frame per peer
        # per flush.  Falls back to the scalar per-post path when the
        # inner broadcaster has no wave entry point (bare test
        # broadcasters).
        self._egress_wave = (
            egress_columnar and getattr(inner, "post_wave", None) is not None
        )
        # Broadcast payloads buffer ONCE on a shared list (a wave is
        # ~50k broadcasts at N=64; appending each to N per-receiver
        # buffers was ~1 s of epoch wall).  send_to payloads park per
        # receiver as (anchor, payload), anchor = the shared-list
        # position they arrived at, so the flush can reconstruct each
        # receiver's exact arrival-order interleaving.
        self._shared: List[Payload] = []
        self._extras: Dict[str, List[tuple]] = {
            m: [] for m in self._members
        }
        self._dirty = False
        self._broadcast_only = True  # no send_to since last flush
        self.bundles_flushed = 0
        self.payloads_buffered = 0
        # flight recorder (utils/trace.py): each flush records one
        # "transport/flush" span covering fold + envelope encode + MAC
        # + post for the wave.  None = tracing off.
        self.trace = trace

    def set_members(self, member_ids: Sequence[str]) -> None:
        """Swap the receiver set at a roster-activation boundary
        (dynamic membership).  Flushes buffered payloads FIRST — they
        belong to waves addressed under the outgoing roster — then
        rebuilds the per-receiver buffers and propagates to the inner
        broadcaster when it exposes ``set_members`` (the in-proc
        ChannelBroadcaster; the gRPC pool derives its receiver set
        from dialed connections instead)."""
        self.flush()
        self._members = sorted(member_ids)
        self._extras = {m: [] for m in self._members}
        inner_set = getattr(self._inner, "set_members", None)
        if inner_set is not None:
            inner_set(self._members)

    def broadcast(self, payload: Payload) -> None:
        self._shared.append(payload)
        self.payloads_buffered += len(self._members)
        self._dirty = True

    def send_to(self, member_id: str, payload: Payload) -> None:
        buf = self._extras.get(member_id)
        if buf is None:  # not a roster member: pass through untouched
            self._inner.send_to(member_id, payload)
            return
        buf.append((len(self._shared), payload))
        self.payloads_buffered += 1
        self._dirty = True
        self._broadcast_only = False

    @staticmethod
    def _fold(buf: List[Payload]) -> Payload:
        if len(buf) == 1:
            return buf[0]
        items = _columnarize(buf)
        return items[0] if len(items) == 1 else BundlePayload(tuple(items))

    def flush(self) -> None:
        """Ship every buffered payload.  Exception-safe: a transport
        failure mid-flush (queue overflow, missing pair key) re-marks
        the unsent buffers dirty and re-raises, so the next flush
        retries instead of silently stranding a wave's bundles."""
        if not self._dirty:
            return
        tr = self.trace
        if tr is None:
            self._flush_dirty()
            return
        t0 = tr.now()
        bundles0 = self.bundles_flushed
        payloads = len(self._shared) * len(self._members) + sum(
            len(b) for b in self._extras.values()
        )
        try:
            self._flush_dirty()
        finally:
            tr.complete(
                "transport",
                "flush",
                t0,
                bundles=self.bundles_flushed - bundles0,
                payloads=payloads,
            )

    def _merged(self, shared: List[Payload], extras: List[tuple]):
        """One receiver's arrival-order payload list: extras spliced
        back at their anchors (anchors are nondecreasing)."""
        out: List[Payload] = []
        i = 0
        for anchor, p in extras:
            if i < anchor:
                out.extend(shared[i:anchor])
                i = anchor
            out.append(p)
        out.extend(shared[i:])
        return out

    def _flush_dirty(self) -> None:
        self._dirty = False
        broadcast_only = self._broadcast_only
        self._broadcast_only = True
        if broadcast_only:
            # every receiver's bundle is the shared list by
            # construction: one fold, one envelope for all
            shared = self._shared
            if shared:
                try:
                    folded = self._fold(shared)
                    if self._egress_wave:
                        # whole wave in ONE transport call: the wave
                        # signer encodes the envelope once and MACs
                        # all receivers in one batched pass
                        self._inner.post_wave([(None, folded)])
                    else:
                        self._inner.broadcast(folded)
                except Exception:
                    self._dirty = True
                    self._broadcast_only = broadcast_only
                    raise
                self._shared = []
                self.bundles_flushed += len(self._members)
            return
        if self._egress_wave:
            self._flush_mixed_wave()
            return
        # mixed wave (rare: VAL fan-outs, CATCHUP serves): materialize
        # every receiver's merged view FIRST, then post — a transport
        # failure mid-loop must leave unsent members' payloads
        # buffered for the retry, already merged (anchor 0: they
        # precede anything buffered later)
        shared, merged = self._merged_views()
        for mi, m in enumerate(self._members):
            buf = merged.get(m)
            if not buf:
                continue
            try:
                self._inner.send_to(m, self._fold(buf))
            except Exception:
                for m2 in self._members[mi:]:
                    left = merged.get(m2)
                    if left:
                        self._extras[m2] = [(0, p) for p in left]
                self._dirty = True
                self._broadcast_only = False
                raise
            self.bundles_flushed += 1

    def _merged_views(
        self,
    ) -> Tuple[List[Payload], Dict[str, List[Payload]]]:
        """Pop the wave's buffers into every receiver's arrival-order
        merged view (shared between the scalar mixed path and the
        columnar wave path, so the two byte-equivalence arms cannot
        diverge here).  Receivers with no extras ALIAS the shared
        list — never mutated downstream; the columnar path keys on
        that identity to fold it once."""
        shared, self._shared = self._shared, []
        merged: Dict[str, List[Payload]] = {}
        for m in self._members:
            extras = self._extras[m]
            if extras:
                self._extras[m] = []
                merged[m] = self._merged(shared, extras)
            elif shared:
                merged[m] = shared  # never mutated below
        return shared, merged

    def _flush_mixed_wave(self) -> None:
        """Mixed-wave columnar flush (Config.egress_columnar): every
        receiver's merged bundle ships in ONE ``post_wave`` call.
        Receivers whose bundle is exactly the shared broadcast run
        share one folded payload OBJECT, so the transport's
        FrameEncodeMemo collapses their envelope bodies to a single
        encode; per-receiver merges (VAL fan-outs, CATCHUP serves,
        injected per-receiver lies) fold individually but still share
        their sub-payload objects with the run.  A transport failure
        re-parks every receiver's merged view for the retry, exactly
        like the scalar mixed path."""
        shared, merged = self._merged_views()
        entries: List[tuple] = []
        shared_fold: Optional[Payload] = None
        for m in self._members:
            buf = merged.get(m)
            if not buf:
                continue
            if buf is shared:
                if shared_fold is None:
                    shared_fold = self._fold(shared)
                entries.append((m, shared_fold))
            else:
                entries.append((m, self._fold(buf)))
        if not entries:
            return
        try:
            self._inner.post_wave(entries)
        except Exception:
            for m, buf in merged.items():
                if buf:
                    self._extras[m] = [(0, p) for p in buf]
            self._dirty = True
            self._broadcast_only = False
            raise
        self.bundles_flushed += len(entries)


__all__ = ["PayloadBroadcaster", "ChannelBroadcaster", "CoalescingBroadcaster"]
