"""Payload-level broadcast seam between protocol instances and transport.

The reference's protocol structs hold a ``cleisthenes.Broadcaster``
(reference rbc/rbc.go:35, bba/bba.go:60) and never touch gRPC directly;
this module is that seam for payloads: the protocol layer emits typed
payloads, the broadcaster wraps them in the authenticated envelope and
hands them to a concrete transport.

``broadcast`` includes the sending node itself: HBBFT quorum counting
treats the local node as a normal peer (its own ECHO/READY/BVAL votes
count), and routing self-delivery through the same transport keeps the
deterministic scheduler in charge of *all* message interleavings.
"""

from __future__ import annotations

import time
from typing import List, Protocol, Sequence, runtime_checkable

from cleisthenes_tpu.transport.message import Message, Payload


@runtime_checkable
class PayloadBroadcaster(Protocol):
    def broadcast(self, payload: Payload) -> None: ...

    def send_to(self, member_id: str, payload: Payload) -> None: ...


class ChannelBroadcaster:
    """PayloadBroadcaster over an in-proc ChannelNetwork.

    Envelope signing happens inside the network at post time (each
    endpoint's Authenticator), mirroring the reference where the conn
    layer owns signatures (conn.go:134-137's intent)."""

    def __init__(self, network, node_id: str, member_ids: Sequence[str]):
        self._network = network
        self._node_id = node_id
        self._members: List[str] = sorted(member_ids)

    def _wrap(self, payload: Payload) -> Message:
        return Message(
            sender_id=self._node_id, timestamp=time.time(), payload=payload
        )

    def broadcast(self, payload: Payload) -> None:
        msg = self._wrap(payload)
        for member in self._members:
            self._network.post(self._node_id, member, msg)

    def send_to(self, member_id: str, payload: Payload) -> None:
        self._network.post(self._node_id, member_id, self._wrap(payload))


__all__ = ["PayloadBroadcaster", "ChannelBroadcaster"]
