"""Single framework configuration object.

The reference declares an (empty) ``Config`` struct as the intended
one-stop config (reference cleisthenes.go:3-4, consumed by
``NewRBC(config cleisthenes.Config)`` at rbc/rbc.go:38); its real knobs
live in constructor args (``NewHoneyBadger(batchSize, nodes)``,
honeybadger.go:36) and constants (``DefaultDialTimeout = 3s``,
comm.go:107-109; channel caps 200, conn.go:60-61).  Here the config is a
real dataclass carrying every knob, including the TPU-build additions:
``crypto_backend`` (the ``--crypto=tpu`` flag from BASELINE.json) and
the device-mesh layout for the batched crypto plane.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


# The both-arms registry (staticcheck ARM001): every flag named here
# selects between a fast path and a LIVE byte-equivalence comparison
# arm, and the whole-program analyzer cross-checks the declaration —
# each entry must be a bool Config field, read by the package, pinned
# explicitly (flag=True/False) in the equivalence tests, and a
# perfgate fingerprint key (a mode flip must never gate against the
# other mode's trend records); every ``*_wave`` entry point must be
# reachable from a module that reads one of these flags.  Adding an
# arm seam = add its flag here + the fingerprint key + the pinned
# equivalence test, or the analyzer gates the merge.
ARM_FLAGS = (
    "epoch_pipelining",
    "hub_wave_flush",
    "order_then_settle",
    "delivery_columnar",
    "wave_routing",
    "egress_columnar",
    "attested_log",
    "reduced_quorum",
    # int-valued arm: lanes=1 is the byte-equivalence baseline arm,
    # lanes>1 the shard-out fast path (ARM001 accepts int flags whose
    # tests pin >= 2 distinct values; see tools/staticcheck).
    "lanes",
)

DEFAULT_DIAL_TIMEOUT_S = 3.0  # reference comm.go:107-109
# K-deep pipelined frontiers (Config.pipeline_depth): the protocol
# plane may run at most this many epochs' RBC/BBA concurrently.  The
# cap is the demux window's forward horizon
# (protocol.honeybadger.EPOCH_HORIZON, cross-checked there): an
# in-flight epoch past the horizon could not be delivered to a peer
# at the same frontier.
MAX_PIPELINE_DEPTH = 8
# Horizontal shard-out (Config.lanes): at most this many parallel
# consensus lanes over one roster.  The cap bounds the per-node state
# multiplier (S lane instances share one hub/coalescer/WAL) and keeps
# the lane id in a u32 wire field with headroom to spare.
MAX_LANES = 8
DEFAULT_CHANNEL_CAPACITY = 200  # reference conn.go:60-61 (out/read chans)
# Self-healing dial layer (transport/host.py): first retry delay and
# the cap of the exponential backoff.  The reference redials never
# (a lost stream stays lost); a fixed-interval retry is the other
# failure mode — it synchronizes a whole roster's redial storms.
DEFAULT_DIAL_RETRY_BASE_S = 0.05
DEFAULT_DIAL_RETRY_MAX_S = 5.0


@dataclasses.dataclass
class Config:
    """Framework-wide configuration.

    Attributes:
      n: number of validators in the network (N).
      f: Byzantine fault budget; requires N >= 3f+1
         (reference docs/BBA-EN.md:26, docs/HONEYBADGER-EN.md:35).
         Defaults to floor((n-1)/3), the maximum tolerable.
      batch_size: target committed transactions per epoch (B). The
        effective per-node proposal is B/N randomly sampled from the
        head of the queue (reference honeybadger.go:36-49,62-104;
        docs/HONEYBADGER-EN.md:49-56).
      crypto_backend: 'cpu' (numpy reference), 'cpp' (native compiled
        GF kernels) or 'tpu' (batched JAX/XLA kernels) — the
        BatchCrypto/ErasureCoder seam from BASELINE.json.
      dial_timeout_s: client dial timeout (reference comm.go:107-109).
      dial_retry_base_s / dial_retry_max_s: redial policy for the
        self-healing gRPC transport — capped exponential backoff with
        seeded jitter, both for boot-time dials and for streams lost
        mid-run (transport/host.py, transport/health.py).
      channel_capacity: per-connection mailbox depth (conn.go:60-61).
      ledger_fsync: fsync-on-commit policy for the durable batch log
        (core/ledger.py).  False (default) flushes to the OS on every
        append — surviving process crashes; True additionally fsyncs —
        surviving host power loss, at ~ms/commit cost.
      ledger_checkpoint_every: append a dedup-set checkpoint record to
        the batch log every this-many commits, so a restart seeds the
        duplicate filter from the checkpoint instead of re-deriving it
        from every logged batch.  0 disables checkpointing.
      seed: None (default) draws batch-sampling randomness from the OS
        CSPRNG — production mode, keeping proposal selection
        unpredictable (part of HBBFT's censorship-resistance story).
        An int makes sampling deterministic, for tests/benchmarks only.
      coin_seed: shared setup seed for the threshold common-coin and
        TPKE key generation in trusted-dealer mode.
      mesh_shape: optional ('v', 'l') device-mesh layout — (validator
        axis, shard-length axis) — for sharding the crypto plane
        across TPU devices via parallel.mesh.CryptoMesh; None means
        single-device.  Only consumed by the 'tpu' backend.
      trace: enable the per-node flight recorder (utils/trace.py):
        quorum crossings, hub flushes, wave boundaries and WAL
        appends record into a bounded ring, mergeable into one
        Perfetto-loadable artifact by tools/tracetool.py.  False (the
        default) constructs NO recorder at all — instrumentation
        sites hold None and the hot path pays one identity check.
      trace_buffer: per-node trace ring capacity (newest events win;
        overflow counts as drops in Metrics.snapshot()["trace"]).
      obs_port: opt-in live telemetry endpoints (transport/obs_http.py):
        None (default) serves nothing; 0 binds an ephemeral localhost
        port (tests/demo); N binds 127.0.0.1:N.  Serves /metrics
        (Prometheus text exposition), /healthz (UP/DEGRADED/DOWN from
        peer health + SLO watchdogs) and /vars (full JSON snapshot +
        sampled time series) on ValidatorHost and SimulatedCluster.
      obs_sample_period_s: telemetry sampling cadence for the bounded
        time-series rings (utils/timeseries.py) when the obs plane is
        on; each tick also runs the SLO watchdog checks.
      slo_stall_factor / slo_stall_grace_s: the epoch-stall watchdog's
        commit budget is max(grace, factor * recent epoch p50) — no
        commit within it while txs are pending flips health to DOWN
        (utils/watchdog.py).
      slo_queue_depth: pending-transaction depth above which the
        backpressure alarm fires (ingress outrunning commit).
      slo_peer_lag_epochs: epoch-frontier gap above which a trailing
        peer counts as lagging (peer-lag detector; in-proc clusters).
      order_then_settle: two-frontier commit split (see the field
        comment below): ciphertext-ordered commit at ACS output, with
        threshold decryption trailing in an idle-driven settler.
      pipeline_depth: K-deep pipelined frontiers (see the field
        comment below): epochs [ordered frontier, ordered frontier +
        K - 1] run their RBC propose/ECHO/READY and BBA rounds
        concurrently; ordering still advances strictly in epoch
        order and parks at decrypt_lag_max.  1 (lockstep — only the
        frontier epoch runs, today's pre-K behavior byte-identically)
        .. MAX_PIPELINE_DEPTH (the demux window's forward horizon).
        Effective only on the pipelined two-frontier path
        (epoch_pipelining and order_then_settle both on — the
        epoch_pipelining arm flag gates the whole K-deep plane).
      decrypt_lag_max: backpressure bound on ordered-ahead epochs
        (ordered frontier - settled frontier); also the settle-stall
        SLO watchdog's lag budget.
      reconfig_lead: dynamic membership (protocol.reconfig): epochs
        between the settlement completing a reshare ceremony and the
        new roster's activation; must exceed pipeline_depth +
        decrypt_lag_max so the activation boundary lands past every
        epoch the old roster could already have ordered OR still
        have in flight in the K-deep window.
      delivery_columnar: columnar inbound delivery plane — wave-batched
        MAC verification + shared-prefix frame-decode memoization on
        both transports (see the field comment below).  False is the
        scalar byte-equivalence arm.
      wave_routing: wave-routed protocol ingest — the routing-layer
        twin of delivery_columnar: one batch handler dispatch per
        (message kind, delivery wave) through protocol.router's
        WaveRouter instead of one Python call chain per payload (see
        the field comment below).  False is the scalar per-payload
        routing comparison arm.
      egress_columnar: columnar outbound plane — one batched
        encode+MAC-sign pass per node per wave (Authenticator
        .sign_wire_wave + FrameEncodeMemo), coalesced frame writes,
        and wave-batched native coin-share issue through the hub's
        coin column (see the field comment below).  False is the
        scalar per-send egress comparison arm.
    """

    n: int = 4
    f: Optional[int] = None
    batch_size: int = 256
    crypto_backend: str = "cpu"
    dial_timeout_s: float = DEFAULT_DIAL_TIMEOUT_S
    dial_retry_base_s: float = DEFAULT_DIAL_RETRY_BASE_S
    dial_retry_max_s: float = DEFAULT_DIAL_RETRY_MAX_S
    channel_capacity: int = DEFAULT_CHANNEL_CAPACITY
    ledger_fsync: bool = False
    ledger_checkpoint_every: int = 32
    seed: Optional[int] = None
    coin_seed: int = 1
    mesh_shape: Optional[tuple] = None
    trace: bool = False
    trace_buffer: int = 1 << 16
    obs_port: Optional[int] = None
    obs_sample_period_s: float = 1.0
    slo_stall_factor: float = 8.0
    slo_stall_grace_s: float = 10.0
    slo_queue_depth: int = 100_000
    slo_peer_lag_epochs: int = 8
    # Epoch pipelining (BASELINE config 5): propose into epoch e+1 the
    # moment epoch e's ACS outputs, so e+1's RS-encode/Merkle-forest
    # and VAL/ECHO exchange overlap e's decryption-share phase.
    # Commit order is unaffected (commits gate on the epoch counter).
    epoch_pipelining: bool = True
    # Wave-deferred hub flushing (the columnar fast path): on
    # transports that promise an idle callback, batched crypto runs
    # ONLY at quiescence points, one columnar flush per message wave.
    # False reverts to the pre-wave scalar discipline — every quorum
    # event flushes the hub immediately — kept as the comparison arm
    # of the cross-path equivalence test (seeded runs must commit
    # byte-identical ledgers under either discipline).
    hub_wave_flush: bool = True
    # Order-then-decrypt (the two-frontier commit split, after "The
    # Latency Price of Threshold Cryptosystems in Blockchains"): at
    # ACS output the epoch commits its CIPHERTEXT-ORDERED batch — a
    # deterministic {proposer: ct} record, WAL-durable as a COrd
    # record — and the epoch counter advances immediately, so epoch
    # e+1's RBC/BBA runs at full speed while epoch e's TPKE dec-share
    # verify/combine trails in a settler driven from the transports'
    # idle callbacks.  The settled frontier writes the plaintext CLOG
    # record, applies the dedup filter and fires on_commit, strictly
    # in epoch order.  False = the coupled arm: commit blocks on the
    # full decryption exchange exactly as before (kept as the
    # byte-equivalence comparison arm — same seed, same settled
    # plaintext log).
    order_then_settle: bool = True
    # Delivery-plane columnarization (the inbound twin of
    # hub_wave_flush): transports buffer inbound frames per message
    # wave and verify their MACs through ONE
    # Authenticator.verify_wire_many batch call per wave, and frame
    # decode memoizes on the signing-prefix digest so a broadcast's N
    # receiver frames decode once (transport.message.FrameDecodeMemo,
    # FIFO-evicting).  False reverts to the per-frame scalar receive
    # path — kept as the live byte-equivalence comparison arm (seeded
    # runs must commit byte-identical ledgers under either arm;
    # tests/test_delivery_equivalence.py).
    delivery_columnar: bool = True
    # Wave-routed protocol ingest (the routing-layer twin of
    # delivery_columnar): transports hand a delivery wave's verified,
    # decoded frames to the handler in ONE serve_wave call; the
    # WaveRouter (protocol.router) demuxes them in a single pass into
    # typed ingest columns keyed by (epoch, message kind) and invokes
    # ONE batch handler entry point per (kind, wave) on ACS/RBC/BBA —
    # replacing the per-payload HoneyBadger.handle_message -> ACS ->
    # RBC/BBA Python call chain.  Effective only together with
    # delivery_columnar on the channel wave path; the gRPC transport
    # additionally folds a wave into one SerialDispatcher mailbox
    # entry.  False reverts to the per-payload scalar routing chain —
    # kept as the live byte-equivalence comparison arm (seeded runs
    # must commit byte-identical ledgers under either arm;
    # tests/test_delivery_equivalence.py).
    wave_routing: bool = True
    # Egress columnarization (the send-side twin of delivery_columnar,
    # mirroring PR 9 on the outbound path): the CoalescingBroadcaster
    # hands each flush's whole wave of folded bundles to ONE
    # Authenticator.sign_wire_wave call per node per wave — the
    # envelope body encodes once per distinct payload object (the
    # shared-prefix FrameEncodeMemo, transport.message) and the
    # per-receiver HMACs run as one batched pass over the PR-7
    # precomputed key schedules — and the resulting frames coalesce
    # into one write per peer per flush on both transports (one
    # pending-queue post carrying the wave on ChannelNetwork; one
    # stream write per peer on the gRPC send loop).  The same flag
    # routes the protocol plane's pending coin-share issues through
    # the CryptoHub's coin work column (ops.coin.share_batch): a
    # wave's coin issues across ALL BBA instances and rounds execute
    # as one native multi-exponentiation dispatch with one CP-nonce
    # draw, instead of one issue_shares_batch call per node per wave.
    # False reverts to the per-send scalar egress path (one
    # sign_wire_many per post, one coin issue batch per node per
    # drain) — kept as the live byte-equivalence comparison arm
    # (seeded runs must commit byte-identical ledgers under either
    # arm; tests/test_egress_equivalence.py).
    egress_columnar: bool = True
    # K-deep pipelined epoch frontiers (ISSUE 15, the PR-8 split
    # generalized): epochs [self.epoch, self.epoch + K - 1] run their
    # RBC/BBA concurrently against the K-deep ordered window, each
    # with its own _EpochState — K concurrent epochs' traffic lands
    # in the SAME delivery waves, so the hub/router/egress columnar
    # planes amortize K epochs' crypto into one dispatch per kind per
    # wave.  Ordering still advances strictly in epoch order
    # (_maybe_order) and parks at decrypt_lag_max exactly as at depth
    # 1.  Depth 1 reproduces the pre-K behavior byte-identically and
    # stays live as the comparison arm (tests/test_pipeline_depth.py);
    # the plane as a whole is gated by the epoch_pipelining ARM flag
    # (epoch_pipelining=False forces lockstep regardless of depth).
    pipeline_depth: int = 2
    # Bounded ordered-but-unsettled window: the ordered frontier may
    # run at most this many epochs ahead of the settled frontier
    # before ordering parks (backpressure).  A Byzantine coalition
    # delaying settlement (share forgery) therefore stalls ordering
    # AT this bound, never unboundedly ahead of durable plaintext.
    decrypt_lag_max: int = 4
    # Dynamic membership (protocol.reconfig): epochs between the
    # SETTLEMENT that completes a reshare ceremony's qualified dealer
    # set and the new roster's activation epoch.  Must exceed
    # decrypt_lag_max: when the completing epoch settles, the ordered
    # frontier is at most decrypt_lag_max ahead, so no epoch at or
    # past the activation boundary can have been ordered under the
    # OLD roster — the switch point is clean on every honest node.
    reconfig_lead: int = 8
    # --- ingress plane (transport/ingress.py + core/mempool.py) ---
    # mempool_capacity > 0 mounts the fee-priority mempool ahead of
    # the FIFO TxQueue: client submissions admit through it (dedup,
    # per-client + global backpressure, priority eviction) and batch
    # selection drains it highest-fee-first into the TxQueue seam.
    # 0 disables the mempool: add_transaction feeds the TxQueue
    # directly, exactly the pre-ingress behavior.
    mempool_capacity: int = 0
    # per-client pending cap: a client with this many unsettled
    # admitted txs gets RETRY_AFTER (open-loop fairness: one hot
    # client cannot monopolize the global capacity).
    mempool_client_cap: int = 64
    # bounded ingress-side seen-set (digest ring): resubmits of
    # pending or recently-settled txs ack DUPLICATE without re-entry.
    # Coordinated with (not replacing) the settle-time dedup filter:
    # this ring is the fast front-door check, the committed-history
    # filter at batch selection remains the authoritative one.
    mempool_seen_cap: int = 1 << 16
    # the RETRY_AFTER hint handed to backpressured clients, in ms.
    mempool_retry_after_ms: int = 100
    # TCP port for the client-facing gRPC ingress service (None =
    # no listener; the in-process twin is always available).
    ingress_port: Optional[int] = None
    # --- attested trust model (protocol/attest.py) ----------------
    # attested_log mounts the simulated-TEE attestation plane: every
    # outbound frame carries a MAC'd (incarnation, counter) attestation
    # issued by a per-node AttestationVault that REFUSES to attest two
    # different digests for the same protocol slot — so an equivocating
    # sender is forced to ship counter-fork evidence (a refused=1
    # trailer); honest receivers record the accusation and reject the
    # lied frames themselves, so equivocation degrades to omission of
    # exactly the forked statements while the sender's honest traffic
    # keeps feeding the quorums (load-bearing at n = 2f+1).  The
    # vault sits BELOW the protocol plane's Behavior seam
    # (protocol.byzantine): a semantic adversary can rewrite payloads
    # but cannot forge, fork or suppress attestations.  False is the
    # baseline arm: no trailers, no per-link counter state, frames
    # byte-identical to the pre-attestation wire format.
    attested_log: bool = False
    # reduced_quorum switches the large-quorum arithmetic (the 2f+1
    # READY/deliver/bin_values/TERM-halt thresholds) to n-f, the
    # TEE-reduced form of arxiv 2102.01970: with equivocation excluded
    # by the attested log, any two (n-f)-quorums of an n >= 2f+1
    # roster intersect in a non-equivocating node and safety holds at
    # rosters a third smaller.  f defaults to floor((n-1)/2) in this
    # mode and Config enforces n >= 2f+1 instead of 3f+1.  At the
    # baseline roster shape n = 3f+1 exactly, n-f == 2f+1, so the
    # False arm's arithmetic is bit-identical to the historical
    # thresholds.  Sound only together with attested_log (enforced).
    reduced_quorum: bool = False
    # --- horizontal shard-out (ISSUE 20) --------------------------
    # lanes = S runs S independent HBBFT lane instances over the SAME
    # validator set, transports and roster schedule.  Admission
    # tx-hash-partitions across lanes (core.merge.lane_of: seeded
    # sha256(seed || digest) % S, node- and PYTHONHASHSEED-identical);
    # each lane keeps its own epoch frontiers and lane-tagged WAL
    # record stream, and the settled frontiers merge into ONE
    # deterministic total order (core.merge.MergeCursor: epoch-major,
    # lane-minor — a pure function of the committed bytes, so honest
    # nodes' merged orders are byte-identical).  Lane traffic rides
    # the SAME coalescer flushes, delivery waves and hub columns as
    # lane 0 (LanePayload wire framing + lane-qualified hub scopes),
    # so S lanes' crypto amortizes into the same native dispatches
    # instead of multiplying them.  1 (default) is byte-identical to
    # the pre-lane build: no LanePayload ever hits the wire, no lane
    # records hit the WAL.  Dynamic membership (RECONFIG) is not
    # supported at lanes > 1.
    lanes: int = 1

    def __post_init__(self) -> None:
        if self.n < 1:
            raise ValueError(f"n={self.n} must be >= 1")
        if self.reduced_quorum and not self.attested_log:
            raise ValueError(
                "reduced_quorum=True requires attested_log=True: the "
                "n-f quorum intersection argument only holds once "
                "equivocation is excluded by the attested sender log"
            )
        if self.f is None:
            self.f = (
                (self.n - 1) // 2
                if self.reduced_quorum
                else (self.n - 1) // 3
            )
        if self.f < 0:
            raise ValueError(f"f={self.f} must be >= 0")
        if self.reduced_quorum:
            if self.n < 2 * self.f + 1:
                raise ValueError(
                    f"n={self.n} must be >= 2f+1={2 * self.f + 1} "
                    "in reduced-quorum mode (arxiv 2102.01970)"
                )
        elif self.n < 3 * self.f + 1:
            raise ValueError(
                f"n={self.n} must be >= 3f+1={3 * self.f + 1} "
                "(docs/BBA-EN.md:26: t < n/3)"
            )
        if self.dial_retry_base_s <= 0 or (
            self.dial_retry_max_s < self.dial_retry_base_s
        ):
            raise ValueError(
                f"dial retry policy base={self.dial_retry_base_s} "
                f"max={self.dial_retry_max_s}: need 0 < base <= max"
            )
        if self.ledger_checkpoint_every < 0:
            raise ValueError(
                f"ledger_checkpoint_every={self.ledger_checkpoint_every} "
                "must be >= 0 (0 disables checkpoints)"
            )
        if self.crypto_backend not in ("cpu", "cpp", "tpu"):
            raise ValueError(f"unknown crypto_backend {self.crypto_backend!r}")
        if self.trace_buffer <= 0:
            raise ValueError(
                f"trace_buffer={self.trace_buffer} must be > 0"
            )
        if self.obs_port is not None and not (0 <= self.obs_port <= 65535):
            raise ValueError(
                f"obs_port={self.obs_port} must be None or 0..65535"
            )
        if self.obs_sample_period_s <= 0:
            raise ValueError(
                f"obs_sample_period_s={self.obs_sample_period_s} "
                "must be > 0"
            )
        if self.slo_stall_factor <= 0 or self.slo_stall_grace_s <= 0:
            raise ValueError(
                f"stall SLO needs factor>0 grace>0, got "
                f"{self.slo_stall_factor}/{self.slo_stall_grace_s}"
            )
        if self.slo_queue_depth <= 0 or self.slo_peer_lag_epochs <= 0:
            raise ValueError(
                f"SLO thresholds must be > 0: queue_depth="
                f"{self.slo_queue_depth} peer_lag="
                f"{self.slo_peer_lag_epochs}"
            )
        if self.decrypt_lag_max < 1:
            raise ValueError(
                f"decrypt_lag_max={self.decrypt_lag_max} must be >= 1 "
                "(1 = order at most one epoch ahead of settlement)"
            )
        if self.pipeline_depth < 1:
            raise ValueError(
                f"pipeline_depth={self.pipeline_depth} must be >= 1 "
                "(1 = lockstep: only the ordered frontier's epoch "
                "runs its RBC/BBA)"
            )
        if self.pipeline_depth > MAX_PIPELINE_DEPTH:
            raise ValueError(
                f"pipeline_depth={self.pipeline_depth} exceeds "
                f"MAX_PIPELINE_DEPTH={MAX_PIPELINE_DEPTH} (the demux "
                "window's forward horizon: an in-flight epoch past it "
                "could not reach a same-frontier peer)"
            )
        if self.reconfig_lead <= self.pipeline_depth + self.decrypt_lag_max:
            raise ValueError(
                f"reconfig_lead={self.reconfig_lead} must exceed "
                f"pipeline_depth + decrypt_lag_max = "
                f"{self.pipeline_depth + self.decrypt_lag_max} (the "
                "roster switch point must land past every epoch the "
                "old roster could already have ordered or still have "
                "in flight in the K-deep window)"
            )
        if self.mempool_capacity < 0:
            raise ValueError(
                f"mempool_capacity={self.mempool_capacity} must be "
                ">= 0 (0 disables the mempool)"
            )
        if self.mempool_client_cap < 1:
            raise ValueError(
                f"mempool_client_cap={self.mempool_client_cap} must "
                "be >= 1"
            )
        if self.mempool_seen_cap < 1:
            raise ValueError(
                f"mempool_seen_cap={self.mempool_seen_cap} must be >= 1"
            )
        if self.mempool_retry_after_ms < 0:
            raise ValueError(
                f"mempool_retry_after_ms={self.mempool_retry_after_ms} "
                "must be >= 0"
            )
        if self.ingress_port is not None and not (
            0 <= self.ingress_port <= 65535
        ):
            raise ValueError(
                f"ingress_port={self.ingress_port} must be None or "
                "0..65535"
            )
        if not (1 <= self.lanes <= MAX_LANES):
            raise ValueError(
                f"lanes={self.lanes} must be 1..{MAX_LANES} (S parallel "
                "consensus lanes over one roster; 1 = single-lane "
                "pre-shard-out behavior)"
            )
        if self.mesh_shape is not None:
            from cleisthenes_tpu.parallel.mesh import validate_mesh_shape

            self.mesh_shape = validate_mesh_shape(self.mesh_shape)

    @property
    def data_shards(self) -> int:
        """K = N - 2f data shards for RS coding (docs/RBC-EN.md:30)."""
        return self.n - 2 * self.f

    @property
    def parity_shards(self) -> int:
        """2f parity shards so any N-2f of N shards reconstruct."""
        return 2 * self.f

    @property
    def decryption_threshold(self) -> int:
        """f+1 decryption shares recover a TPKE plaintext
        (docs/HONEYBADGER-EN.md:40-42, docs/THRESHOLD_ENCRYPTION-EN.md:33-36)."""
        return self.f + 1

    @property
    def quorum_large(self) -> int:
        """The large-quorum threshold: READY amplification to deliver,
        BVAL bin_values growth, TERM halt.  Baseline 2f+1; in
        reduced-quorum mode n-f (identical when n = 3f+1 exactly, so
        every historical roster's arithmetic is unchanged).  The f+1
        relay thresholds and the n-f input-wait thresholds are mode-
        independent."""
        return (self.n - self.f) if self.reduced_quorum else (2 * self.f + 1)
