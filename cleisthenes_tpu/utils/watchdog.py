"""SLO watchdogs: the plane that watches a *running* validator.

PR 3's flight recorder explains a finished run; the dial layer's
health tracker sees only sockets.  Nothing watched the protocol-level
SLOs — "are we still committing?", "is the queue runaway?", "is a peer
being starved/left behind?" — which is exactly what a per-link
omission adversary (protocol.byzantine.SelectiveMute) or a silent
partition exploits.  This module is that watcher, three detectors per
node:

- **epoch_stall**: no commit within a budget *derived from the node's
  own recent epoch p50* (``max(grace, factor * p50)``) while work is
  pending.  Self-calibrating: an N=128 cluster with 3 s epochs gets a
  proportionally longer leash than a 4-node demo, with the grace floor
  covering cold starts before any p50 exists.
- **queue_backpressure**: pending transactions above a configured
  depth — ingress outrunning commit throughput.
- **peer_lag**: any peer reported DOWN by the transport health
  tracker, or (in-proc clusters) any peer whose epoch frontier trails
  the roster's by more than a configured gap.
- **settle_stall**: the two-frontier commit split
  (Config.order_then_settle) has its ordered frontier sitting at the
  ``decrypt_lag_max`` backpressure bound — ciphertext ordering is
  parked because plaintext settlement stopped trailing it (e.g. a
  share-forging coalition delaying the decryption exchange).  Flips
  DEGRADED, not DOWN: ordering holds safely at the bound.

Each firing increments a monotonic alert counter, records the reason,
and emits a trace instant (category ``alert``) so alerts land on the
PR-3 merged timeline next to the protocol events that explain them.
Detector state folds into ``Metrics.snapshot()["alerts"]`` and drives
the /healthz verdict: DOWN on an active stall, DEGRADED on any other
active alert or non-UP peer, UP otherwise.

Determinism: the watchdog lives in utils/ (outside the determinism
plane), reads protocol state only through provider callables, and
writes NOTHING back — protocol code never branches on watchdog state.
``check(now=...)`` takes a synthetic clock so fault tests fire
detectors without sleeping.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from cleisthenes_tpu.utils.determinism import guarded_by
from cleisthenes_tpu.utils.lockcheck import new_lock
from cleisthenes_tpu.utils.metrics import Metrics

UP = "up"
DEGRADED = "degraded"
DOWN = "down"
# a peer-LINK state (not a health verdict): the transport reports the
# peer alive but inside a WAN straggler episode.  Counts as non-UP for
# the DEGRADED scan, never as DOWN — a slow honest node is the one
# failure mode a BFT watchdog must not escalate (ISSUE 16).
STRAGGLING = "straggling"

# detector names (the ``alert=`` label vocabulary of the exposition)
EPOCH_STALL = "epoch_stall"
QUEUE_BACKPRESSURE = "queue_backpressure"
PEER_LAG = "peer_lag"
SETTLE_STALL = "settle_stall"


class _Alert:
    __slots__ = ("name", "count", "active", "reason")

    def __init__(self, name: str) -> None:
        self.name = name
        self.count = 0  # lifetime firings (inactive -> active edges)
        self.active = False
        self.reason = ""


@guarded_by("_lock", "_alerts")
class SloWatchdog:
    """One node's detector set.  Thread-safe: ``check`` runs on the
    sampler tick thread and on every HTTP scrape, while
    ``Metrics.snapshot`` reads ``alerts_block`` from arbitrary
    callers."""

    def __init__(
        self,
        *,
        metrics: Metrics,
        pending_fn: Callable[[], int],
        stall_factor: float = 8.0,
        stall_grace_s: float = 10.0,
        queue_depth_limit: int = 100_000,
        peer_lag_epochs: int = 8,
        peer_states_fn: Optional[Callable[[], Dict[str, object]]] = None,
        peer_lag_fn: Optional[Callable[[], Dict[str, int]]] = None,
        decrypt_lag_budget: int = 4,
        budget_floor_fn: Optional[Callable[[], float]] = None,
        trace=None,
    ) -> None:
        if stall_factor <= 0 or stall_grace_s <= 0:
            raise ValueError(
                f"stall budget needs factor>0 grace>0, got "
                f"{stall_factor}/{stall_grace_s}"
            )
        self._metrics = metrics
        self._pending = pending_fn
        self.stall_factor = stall_factor
        self.stall_grace_s = stall_grace_s
        self.queue_depth_limit = queue_depth_limit
        self.peer_lag_epochs = peer_lag_epochs
        self._peer_states = peer_states_fn
        self._peer_lag = peer_lag_fn
        # the settle-stall SLO budget: ordered - settled at (or past)
        # this bound means the trailing decrypt frontier is wedged and
        # ordering is parked on backpressure.  The natural value is
        # Config.decrypt_lag_max — the same bound the protocol parks
        # at — read via metrics.decrypt_lag_epochs() (zero on the
        # coupled path, so the detector is inert there).
        self.decrypt_lag_budget = decrypt_lag_budget
        # transport-aware leash floor (ISSUE 16): when the transport
        # prices links like a WAN profile, a p50 self-calibrated on
        # fast epochs must not flip DOWN the moment the tail of the
        # link-delay distribution lands — the floor provider (e.g.
        # WanEmulator.stall_floor_s) raises the budget's lower bound
        # to what the mounted link model can legitimately cost
        self._budget_floor = budget_floor_fn
        self.trace = trace
        self._alerts: Dict[str, _Alert] = {
            name: _Alert(name)
            for name in (
                EPOCH_STALL,
                QUEUE_BACKPRESSURE,
                PEER_LAG,
                SETTLE_STALL,
            )
        }
        self._lock = new_lock()

    # -- detectors ---------------------------------------------------------

    def stall_budget_s(self) -> float:
        """The commit-progress SLO: ``max(grace, factor * epoch p50,
        transport floor)`` — derived from this node's own recent
        latency, so the leash scales with roster size and batch
        weight; the optional transport floor keeps a LAN-calibrated
        p50 from flipping DOWN under WAN-priced links."""
        floor = 0.0
        if self._budget_floor is not None:
            floor = self._budget_floor()
        p50 = self._metrics.epoch_latency.p50
        if p50 is None:
            return max(self.stall_grace_s, floor)
        return max(self.stall_grace_s, self.stall_factor * p50, floor)

    def check(self, now: Optional[float] = None) -> str:
        """Evaluate every detector once; returns the health verdict.
        ``now`` (a monotonic instant) lets tests drive synthetic
        clocks; live callers pass nothing."""
        if now is None:
            # never read back by protocol state (pure observability)
            now = time.monotonic()  # watchdog clock (outside the plane)
        pending = self._pending()
        budget = self.stall_budget_s()
        stalled = (
            pending > 0
            and self._metrics.last_commit_age_s(now) > budget
        )
        self._transition(
            EPOCH_STALL,
            stalled,
            lambda: f"no commit for > {round(budget, 3)}s "
            f"with {pending} txs pending",
        )
        self._transition(
            QUEUE_BACKPRESSURE,
            pending > self.queue_depth_limit,
            lambda: f"{pending} txs pending > limit "
            f"{self.queue_depth_limit}",
        )
        lagging = self._lagging_peers()
        self._transition(
            PEER_LAG,
            bool(lagging),
            lambda: "peers down/lagging: " + ",".join(lagging[:8]),
        )
        decrypt_lag = self._metrics.decrypt_lag_epochs()
        # lag AT the bound alone is the intended steady state of a
        # decrypt-bound node (ordering oscillates at the backpressure
        # bound while settlement streams behind); the alert condition
        # is the bound WITH settlement no longer progressing — same
        # self-calibrating leash as EPOCH_STALL, since settles are
        # commits on the two-frontier path
        self._transition(
            SETTLE_STALL,
            decrypt_lag >= self.decrypt_lag_budget
            and self._metrics.last_commit_age_s(now) > budget,
            lambda: f"ordered frontier {decrypt_lag} epochs ahead of "
            f"settlement (budget {self.decrypt_lag_budget}) with no "
            f"settle for > {round(budget, 3)}s; ordering parked on "
            "decrypt-lag backpressure",
        )
        return self.health()

    def _peer_state_map(self) -> Dict[str, str]:
        """Peer -> link-state string.  The provider may return plain
        strings (gRPC PeerHealthTracker) or per-link dicts with a
        ``state`` field (ChannelNetwork.link_states with its WAN
        model fields) — both transports feed the same detector."""
        if self._peer_states is None:
            return {}
        out: Dict[str, str] = {}
        for peer, state in self._peer_states().items():
            if isinstance(state, dict):
                state = state.get("state", UP)
            out[peer] = str(state)
        return out

    def _lagging_peers(self) -> List[str]:
        out: List[str] = []
        if self._peer_states is not None:
            # DOWN only: a STRAGGLING peer is alive and must degrade,
            # not alert — the epoch-gap clause below still catches it
            # if it genuinely falls behind the roster
            out.extend(
                peer
                for peer, state in sorted(self._peer_state_map().items())
                if state == DOWN
            )
        if self._peer_lag is not None:
            out.extend(
                peer
                for peer, lag in sorted(self._peer_lag().items())
                if lag > self.peer_lag_epochs and peer not in out
            )
        return out

    def _transition(
        self, name: str, active: bool, reason_fn: Callable[[], str]
    ) -> None:
        # reason_fn defers the f-string build to active ticks only:
        # this path runs per scrape and per sampler tick on every node
        fired = False
        reason = ""
        with self._lock:
            alert = self._alerts[name]
            if active:
                reason = reason_fn()
                if not alert.active:
                    alert.count += 1
                    fired = True
                alert.reason = reason
            alert.active = active
        if fired and self.trace is not None:
            # on the node's own timeline, next to the stalled epoch's
            # protocol events (args stay deterministic: no timestamps)
            self.trace.instant("alert", name, reason=reason)

    # -- verdicts ----------------------------------------------------------

    def health(self) -> str:
        """UP / DEGRADED / DOWN from detector + peer state.  An active
        stall is DOWN (the node is not doing its job); every other
        active alert — or any peer not UP — is DEGRADED."""
        with self._lock:
            if self._alerts[EPOCH_STALL].active:
                return DOWN
            degraded = any(a.active for a in self._alerts.values())
        if not degraded and self._peer_states is not None:
            degraded = any(
                state != UP for state in self._peer_state_map().values()
            )
        return DEGRADED if degraded else UP

    def alerts_block(self) -> Dict[str, Dict[str, object]]:
        """The ``Metrics.snapshot()["alerts"]`` block."""
        with self._lock:
            return {
                name: {
                    "count": a.count,
                    "active": a.active,
                    "reason": a.reason,
                }
                for name, a in sorted(self._alerts.items())
            }


def worst_health(verdicts) -> str:
    """Fold many verdicts into one (/healthz over a whole cluster)."""
    order = {UP: 0, DEGRADED: 1, DOWN: 2}
    worst = UP
    for v in verdicts:
        if order.get(v, 2) > order[worst]:
            worst = v
    return worst


__all__ = [
    "UP",
    "DEGRADED",
    "DOWN",
    "STRAGGLING",
    "EPOCH_STALL",
    "QUEUE_BACKPRESSURE",
    "PEER_LAG",
    "SETTLE_STALL",
    "SloWatchdog",
    "worst_health",
]
