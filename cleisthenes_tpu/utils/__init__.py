"""Cross-cutting utilities: metrics, tracing, deterministic helpers."""

from cleisthenes_tpu.utils.determinism import guarded_by, proposal_rng
from cleisthenes_tpu.utils.metrics import (
    Counter,
    EpochTrace,
    Histogram,
    Metrics,
)

__all__ = [
    "Counter",
    "Histogram",
    "EpochTrace",
    "Metrics",
    "guarded_by",
    "proposal_rng",
]
