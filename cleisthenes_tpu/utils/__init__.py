"""Cross-cutting utilities: metrics, tracing, deterministic helpers."""

from cleisthenes_tpu.utils.determinism import guarded_by, proposal_rng
from cleisthenes_tpu.utils.metrics import (
    Counter,
    EpochTrace,
    Histogram,
    Metrics,
)
from cleisthenes_tpu.utils.trace import TraceRecorder, maybe_recorder

__all__ = [
    "Counter",
    "Histogram",
    "EpochTrace",
    "Metrics",
    "TraceRecorder",
    "guarded_by",
    "maybe_recorder",
    "proposal_rng",
]
