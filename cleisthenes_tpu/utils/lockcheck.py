"""Opt-in runtime lock sanitizer: the dynamic twin of staticcheck's
CONC001/CONC003 rules, over the SAME ``@guarded_by`` registry.

``CLEISTHENES_LOCKCHECK=1`` in the environment arms it; otherwise
every entry point here compiles down to the plain ``threading``
primitives — zero per-access overhead on hot paths, which is why
``guarded_by`` is a declaration and not an always-on wrapper.

Armed, two things change:

- ``new_lock()`` / ``new_rlock()`` (the factories every guarded class
  uses for its lock attributes) return ``_CheckedLock`` wrappers that
  record the owning thread and reentrancy count.
- ``guarded_by`` (utils/determinism.py) installs ``__getattribute__``
  / ``__setattr__`` instrumentation on the decorated class: every
  access to a declared attribute asserts the declared lock is held by
  the CURRENT thread, raising ``LockCheckError`` naming the class,
  attribute, lock, acquiring thread and current holder.  Accesses
  from ``__init__``/``__del__`` frames are exempt (single-threaded
  construction/teardown, mirroring the static rules' exemption).

The sanitizer is a TSan analog for the annotation registry: a
``@guarded_by`` contract is either statically proven (CONC001 inside
the class, CONC003 across call boundaries) or dynamically watched
here — never merely commented.  ci.sh runs the lock-sensitive tier-1
subset and a fuzz band under the sanitizer.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Optional

_ENABLED = os.environ.get("CLEISTHENES_LOCKCHECK") == "1"


def is_enabled() -> bool:
    """True when the sanitizer is armed.  Read DYNAMICALLY at every
    decoration/factory call (not baked at import) so tests can flip
    ``lockcheck._ENABLED`` and define instrumented classes."""
    return _ENABLED


class LockCheckError(AssertionError):
    """A ``@guarded_by`` attribute was touched without its lock.

    Subclasses AssertionError so existing except-clauses treating
    sanitizer trips as assertion failures do the right thing.
    """

    def __init__(
        self,
        cls_name: str,
        attr: str,
        lock_attr: str,
        acquirer: str,
        holder: Optional[str],
    ) -> None:
        self.cls_name = cls_name
        self.attr = attr
        self.lock_attr = lock_attr
        self.acquirer = acquirer
        self.holder = holder
        super().__init__(
            f"{cls_name}.{attr} accessed by thread {acquirer!r} "
            f"without holding {lock_attr} "
            f"(held by {holder!r})"
            if holder
            else f"{cls_name}.{attr} accessed by thread {acquirer!r} "
            f"without holding {lock_attr} (unheld)"
        )


class _CheckedLock:
    """Lock/RLock wrapper recording the owning thread.

    Context-manager and acquire/release compatible with the stdlib
    primitives (including use under ``threading.Condition``).  The
    reentrancy count makes one wrapper type serve both: a plain Lock
    simply never re-enters.
    """

    __slots__ = ("_inner", "_owner", "_count")

    def __init__(self, inner) -> None:
        self._inner = inner
        self._owner: Optional[threading.Thread] = None
        self._count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._owner = threading.current_thread()
            self._count += 1
        return ok

    def release(self) -> None:
        self._count -= 1
        if self._count <= 0:
            self._owner = None
            self._count = 0
        self._inner.release()

    def __enter__(self) -> "_CheckedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def held_by_current(self) -> bool:
        return self._owner is threading.current_thread()

    @property
    def holder_name(self) -> Optional[str]:
        owner = self._owner
        return owner.name if owner is not None else None


def new_lock():
    """A mutex for a ``@guarded_by`` lock attribute: plain
    ``threading.Lock`` unless the sanitizer is armed."""
    if is_enabled():
        return _CheckedLock(threading.Lock())
    return threading.Lock()


def new_rlock():
    """Reentrant variant of ``new_lock``."""
    if is_enabled():
        return _CheckedLock(threading.RLock())
    return threading.RLock()


_EXEMPT_FRAMES = frozenset(("__init__", "__del__"))


def _assert_held(obj: object, attr: str, lock_attr: str) -> None:
    # frame 0 = here, 1 = the __getattribute__/__setattr__ wrapper,
    # 2 = the code performing the attribute access; synthetic frames
    # (<listcomp>/<genexpr>/<lambda>, pre-3.12) defer to their definer
    try:
        frame = sys._getframe(2)
        for _ in range(4):
            if frame is None or not frame.f_code.co_name.startswith(
                "<"
            ):
                break
            frame = frame.f_back
        co_name = frame.f_code.co_name if frame is not None else ""
    except ValueError:  # shallower stack than expected
        co_name = ""
    if co_name in _EXEMPT_FRAMES:
        return
    try:
        lock = object.__getattribute__(obj, lock_attr)
    except AttributeError:
        return  # mid-construction: the lock attr does not exist yet
    if not isinstance(lock, _CheckedLock):
        return  # lock predates arming (or a test stubbed it)
    if not lock.held_by_current():
        raise LockCheckError(
            type(obj).__name__,
            attr,
            lock_attr,
            threading.current_thread().name,
            lock.holder_name,
        )


def install(cls):
    """Install guarded-attribute instrumentation on ``cls`` (called by
    ``guarded_by`` when the sanitizer is armed).

    The wrappers read ``type(self).__guarded_by__`` live, so stacked
    decorators and subclass re-decoration extend coverage without
    re-installation; the marker flag keeps one wrapper layer per
    hierarchy."""
    if cls.__dict__.get("__lockcheck_installed__") or getattr(
        cls, "__lockcheck_installed__", False
    ):
        return cls
    orig_get = cls.__getattribute__
    orig_set = cls.__setattr__

    def __getattribute__(self, name):
        guarded = type(self).__guarded_by__
        if name in guarded:
            _assert_held(self, name, guarded[name])
        return orig_get(self, name)

    def __setattr__(self, name, value):
        guarded = type(self).__guarded_by__
        if name in guarded:
            _assert_held(self, name, guarded[name])
        orig_set(self, name, value)

    cls.__getattribute__ = __getattribute__
    cls.__setattr__ = __setattr__
    cls.__lockcheck_installed__ = True
    return cls


__all__ = [
    "LockCheckError",
    "install",
    "is_enabled",
    "new_lock",
    "new_rlock",
]
