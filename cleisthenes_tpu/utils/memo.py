"""BoundedFifoMemo: the one bounded-memo eviction discipline.

Several planes memoize pure-function results behind a capacity bound
— the hub's verdict memos (protocol/hub.py), the cluster tx-parse
memo (protocol/honeybadger.py), the shared-prefix frame-decode memo
(transport/message.py).  They must all evict the same way: at the
cap, the OLDEST insertion goes (dict order), never the whole table —
a hot working set sitting near the cap loses one stale entry per
fresh one instead of periodically dropping everything and re-running
its whole wave of pure computations.  Keeping the discipline in ONE
class means an eviction-policy fix lands everywhere at once, and the
transport plane can use it without importing protocol code.
"""

from __future__ import annotations

from typing import Dict


class BoundedFifoMemo:
    """Bounded memo of pure-function results with FIFO eviction."""

    __slots__ = ("map", "cap")

    def __init__(self, cap: int):
        self.map: Dict = {}
        self.cap = cap

    def put(self, key, val) -> None:
        m = self.map
        if len(m) >= self.cap and key not in m:
            del m[next(iter(m))]  # FIFO: oldest insertion goes first
        m[key] = val


__all__ = ["BoundedFifoMemo"]
