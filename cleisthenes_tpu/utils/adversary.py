"""Byzantine adversary toolkit for the in-proc transport.

SURVEY.md §5.3: the reference has no fault-injection framework and its
mock stream is "the natural injection point".  Here that idea is a
library of composable message-level adversaries for
``ChannelNetwork.fault_filter``, modeling a Byzantine coalition that
fully controls the traffic *of the faulty nodes* (the HBBFT threat
model: f arbitrary nodes, reliable channels between correct ones):

  - drop: lose a fraction of the coalition's messages
  - tamper: flip bytes (caught by envelope MACs)
  - duplicate: deliver the coalition's frames multiple times
  - replay: capture ANY node's frames and re-inject them later
    (valid MACs — the protocol's per-sender dedup must absorb them)
  - delay: hold the coalition's frames and release them much later

All randomness is seeded so every adversarial run replays exactly.
"""

from __future__ import annotations

import random
from typing import Callable, List, Optional, Sequence


class Coalition:
    """Composable fault filter builder for a set of Byzantine senders."""

    def __init__(self, members: Sequence[str], seed: int = 0):
        self.members = frozenset(members)
        self._rng = random.Random(seed)
        # stages: fn(sender, receiver, wire) -> list of frames
        self._stages: List[Callable] = []
        self._captured: List[bytes] = []
        self._capture_cap = 4096
        # delay stage state: filter-call clock + held frames
        # (release_at, sender, receiver, frame), release bounded so a
        # pathological build-up cannot grow without bound
        self._calls = 0
        self._held: List[tuple] = []
        self._held_cap = 4096
        self.held_total = 0  # observability: frames ever delayed
        self.released_total = 0

    # -- builders ----------------------------------------------------------

    def drop(self, fraction: float) -> "Coalition":
        def stage(sender, receiver, frames):
            return [
                f for f in frames if self._rng.random() >= fraction
            ]

        self._stages.append(stage)
        return self

    def tamper(self, fraction: float) -> "Coalition":
        def stage(sender, receiver, frames):
            out = []
            for f in frames:
                if self._rng.random() < fraction and len(f) > 8:
                    i = self._rng.randrange(8, len(f))
                    f = f[:i] + bytes([f[i] ^ 0xFF]) + f[i + 1 :]
                out.append(f)
            return out

        self._stages.append(stage)
        return self

    def duplicate(self, fraction: float, copies: int = 2) -> "Coalition":
        def stage(sender, receiver, frames):
            out = []
            for f in frames:
                n = copies if self._rng.random() < fraction else 1
                out.extend([f] * n)
            return out

        self._stages.append(stage)
        return self

    def delay(self, fraction: float, hold: int = 16) -> "Coalition":
        """Hold a fraction of the coalition's frames and release them
        much later: a held frame re-enters delivery on the first
        ``filter`` call for the SAME (sender, receiver) pair at least
        ``hold`` filter calls in the future (pairwise envelope MACs
        make cross-pair release pointless — the receiver would just
        reject the frame).  Releases ride the filter-call clock, not
        wall time, so seeded runs replay exactly.  Frames whose pair
        never speaks again within the run simply stay held — in an
        asynchronous network an arbitrarily-delayed frame and a lost
        frame are indistinguishable."""

        def stage(sender, receiver, frames):
            out = []
            for f in frames:
                if self._rng.random() < fraction and (
                    len(self._held) < self._held_cap
                ):
                    self._held.append(
                        (self._calls + hold, sender, receiver, f)
                    )
                    self.held_total += 1
                else:
                    out.append(f)
            return out

        self._stages.append(stage)
        return self

    def _release_matured(self, sender: str, receiver: str) -> List[bytes]:
        """Held frames for this (sender, receiver) pair whose clock
        matured; removed from the hold queue."""
        if not self._held:
            return []
        out: List[bytes] = []
        kept: List[tuple] = []
        for item in self._held:
            release_at, s, r, f = item
            if s == sender and r == receiver and release_at <= self._calls:
                out.append(f)
            else:
                kept.append(item)
        if out:
            self._held = kept
            self.released_total += len(out)
        return out

    def replay(self, fraction: float) -> "Coalition":
        """Re-inject previously captured (any-sender) frames alongside
        the coalition's own traffic."""

        def stage(sender, receiver, frames):
            out = list(frames)
            if self._captured and self._rng.random() < fraction:
                out.append(self._rng.choice(self._captured))
            return out

        self._stages.append(stage)
        return self

    # -- the ChannelNetwork hook -------------------------------------------

    def filter(self, sender: str, receiver: str, wire: bytes):
        # capture everything (for replay), mutate only coalition traffic
        self._calls += 1
        if len(self._captured) < self._capture_cap:
            self._captured.append(wire)
        if sender not in self.members:
            return wire
        frames: List[bytes] = [wire]
        for stage in self._stages:
            frames = stage(sender, receiver, frames)
            if not frames:
                break
        # matured delayed frames for this pair rejoin delivery even if
        # the current frame itself was dropped/held
        frames = list(frames) + self._release_matured(sender, receiver)
        if not frames:
            return None
        return frames


__all__ = ["Coalition"]
