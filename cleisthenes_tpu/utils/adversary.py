"""Byzantine adversary toolkit for the in-proc transport.

SURVEY.md §5.3: the reference has no fault-injection framework and its
mock stream is "the natural injection point".  Here that idea is a
library of composable message-level adversaries for
``ChannelNetwork.fault_filter``, modeling a Byzantine coalition that
fully controls the traffic *of the faulty nodes* (the HBBFT threat
model: f arbitrary nodes, reliable channels between correct ones):

  - drop: lose a fraction of the coalition's messages
  - tamper: flip bytes (caught by envelope MACs)
  - duplicate: deliver the coalition's frames multiple times
  - replay: capture ANY node's frames and re-inject them later
    (valid MACs — the protocol's per-sender dedup must absorb them)
  - delay: hold the coalition's frames and release them much later
  - reorder: permute nearby frames of one (sender, receiver) pair

All randomness is seeded so every adversarial run replays exactly.

These stages attack the WIRE: everything here is absorbed by envelope
MACs and per-sender dedup.  The attacks the MAC layer explicitly does
NOT cover — a key-holding node lying to each peer separately — live
one layer up in ``protocol.byzantine`` (see docs/FAULTS.md).
"""

from __future__ import annotations

import random
from typing import Callable, List, Optional, Sequence


class Coalition:
    """Composable fault filter builder for a set of Byzantine senders."""

    def __init__(self, members: Sequence[str], seed: int = 0):
        self.members = frozenset(members)
        self._rng = random.Random(seed)
        # stages: fn(sender, receiver, wire) -> list of frames
        self._stages: List[Callable] = []
        # replay capture: a seeded RESERVOIR over the whole run (not
        # the first N frames — see replay()); separate rng so capture
        # draws never perturb the stage randomness stream
        self._captured: List[bytes] = []
        self._capture_cap = 4096
        self._capture_seen = 0
        self._capture_rng = random.Random(seed ^ 0x5EED0)
        self._wants_capture = False
        # delay stage state: filter-call clock + held frames
        # (release_at, sender, receiver, frame), release bounded so a
        # pathological build-up cannot grow without bound
        self._calls = 0
        self._held: List[tuple] = []
        self._held_cap = 4096
        self.held_total = 0  # observability: frames ever delayed
        self.released_total = 0

    # -- builders ----------------------------------------------------------

    def drop(self, fraction: float) -> "Coalition":
        def stage(sender, receiver, frames):
            return [
                f for f in frames if self._rng.random() >= fraction
            ]

        self._stages.append(stage)
        return self

    def tamper(self, fraction: float) -> "Coalition":
        def stage(sender, receiver, frames):
            out = []
            for f in frames:
                if self._rng.random() < fraction and len(f) > 8:
                    i = self._rng.randrange(8, len(f))
                    f = f[:i] + bytes([f[i] ^ 0xFF]) + f[i + 1 :]
                out.append(f)
            return out

        self._stages.append(stage)
        return self

    def duplicate(self, fraction: float, copies: int = 2) -> "Coalition":
        def stage(sender, receiver, frames):
            out = []
            for f in frames:
                n = copies if self._rng.random() < fraction else 1
                out.extend([f] * n)
            return out

        self._stages.append(stage)
        return self

    def delay(self, fraction: float, hold: int = 16) -> "Coalition":
        """Hold a fraction of the coalition's frames and release them
        much later: a held frame re-enters delivery on the first
        ``filter`` call for the SAME (sender, receiver) pair at least
        ``hold`` filter calls in the future (pairwise envelope MACs
        make cross-pair release pointless — the receiver would just
        reject the frame).  Releases ride the filter-call clock, not
        wall time, so seeded runs replay exactly.  Frames whose pair
        never speaks again within the run simply stay held — in an
        asynchronous network an arbitrarily-delayed frame and a lost
        frame are indistinguishable."""

        def stage(sender, receiver, frames):
            out = []
            for f in frames:
                if self._rng.random() < fraction and (
                    len(self._held) < self._held_cap
                ):
                    self._held.append(
                        (self._calls + hold, sender, receiver, f)
                    )
                    self.held_total += 1
                else:
                    out.append(f)
            return out

        self._stages.append(stage)
        return self

    def reorder(self, fraction: float, window: int = 4) -> "Coalition":
        """Permute the delivery order of nearby coalition frames.

        A held frame waits for the next passing frame of the SAME
        (sender, receiver) pair, then the whole group — held frames
        plus the current one — is released in a seeded-shuffled order
        (pairwise envelope MACs make cross-pair reordering pointless:
        the receiver would just reject the frame).  ``window`` caps how
        many frames one pair can hold at once, bounding both memory and
        how far out of order a frame can arrive.  Frames still held
        when the pair last speaks stay held — in an asynchronous
        network an arbitrarily-delayed frame and a lost frame are
        indistinguishable (same caveat as ``delay``).  Seeded and
        replay-exact like every other stage.
        """
        held: dict = {}  # (sender, receiver) -> [frame, ...]

        def stage(sender, receiver, frames):
            out: List[bytes] = []
            key = (sender, receiver)
            buf = held.get(key)
            if buf is None:
                buf = held[key] = []
            for f in frames:
                if len(buf) < window and self._rng.random() < fraction:
                    buf.append(f)
                    self.held_total += 1
                elif buf:
                    group = buf + [f]
                    self._rng.shuffle(group)
                    out.extend(group)
                    self.released_total += len(buf)
                    del buf[:]
                else:
                    out.append(f)
            return out

        self._stages.append(stage)
        return self

    def _release_matured(self, sender: str, receiver: str) -> List[bytes]:
        """Held frames for this (sender, receiver) pair whose clock
        matured; removed from the hold queue."""
        if not self._held:
            return []
        out: List[bytes] = []
        kept: List[tuple] = []
        for item in self._held:
            release_at, s, r, f = item
            if s == sender and r == receiver and release_at <= self._calls:
                out.append(f)
            else:
                kept.append(item)
        if out:
            self._held = kept
            self.released_total += len(out)
        return out

    def replay(self, fraction: float) -> "Coalition":
        """Re-inject previously captured (any-sender) frames alongside
        the coalition's own traffic.

        Capture is a seeded RESERVOIR sample over every frame of the
        run, not the first ``_capture_cap`` frames: a first-N capture
        never sampled late-run traffic, so replay attacks could only
        ever resend epoch-0-era frames (the capture-bias fix)."""

        self._wants_capture = True

        def stage(sender, receiver, frames):
            out = list(frames)
            if self._captured and self._rng.random() < fraction:
                out.append(self._rng.choice(self._captured))
            return out

        self._stages.append(stage)
        return self

    def _capture(self, wire: bytes) -> None:
        """Algorithm-R reservoir: every frame of the run has equal
        probability ``cap/seen`` of being resident when replay picks."""
        self._capture_seen += 1
        if len(self._captured) < self._capture_cap:
            self._captured.append(wire)
            return
        j = self._capture_rng.randrange(self._capture_seen)
        if j < self._capture_cap:
            self._captured[j] = wire

    # -- the ChannelNetwork hook -------------------------------------------

    def filter(self, sender: str, receiver: str, wire: bytes):
        # capture everything (for replay), mutate only coalition traffic
        self._calls += 1
        if self._wants_capture:
            self._capture(wire)
        if sender not in self.members:
            return wire
        frames: List[bytes] = [wire]
        for stage in self._stages:
            frames = stage(sender, receiver, frames)
            if not frames:
                break
        # matured delayed frames for this pair rejoin delivery even if
        # the current frame itself was dropped/held
        frames = list(frames) + self._release_matured(sender, receiver)
        if not frames:
            return None
        return frames


__all__ = ["Coalition"]
