"""Bounded-ring time series: the live half of the telemetry plane.

`Metrics.snapshot()` is a point-in-time read; the flight recorder
(utils/trace.py) is a post-hoc artifact.  Neither answers the
operator's live questions — "is commit latency drifting *right now*?",
"has the queue been growing for the last minute?" — which need short
HISTORY, not a single sample or a full trace.  This module folds
periodic snapshots into per-metric bounded rings cheap enough to stay
always-on next to a validator: `cap` points per metric, oldest
evicted, no unbounded growth ever.

The sampler is the one place in the telemetry plane that owns a
clock + thread:

- `sample(now=None)` is the pure fold (provider snapshot -> rings),
  callable manually — tests and the deterministic in-proc cluster
  drive it with synthetic `now` values and never start the thread.
- `start(period_s)` runs that fold on a daemon thread for live
  deployments (ValidatorHost, demo --obs-port), and gives registered
  tick callbacks (the SLO watchdog's `check`) their heartbeat.

utils/ sits outside the determinism plane, so the wall clock is legal
here — but the same discipline as utils/trace.py applies: protocol
code never reads these timestamps back, and the clock stays confined
to `_now()` below (the staticcheck fixture
tests/staticcheck_fixtures/protocol/det001_obs_bad.py proves a
hand-rolled sampler loop in protocol/ still gates).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Deque, Dict, List, Optional, Tuple

from cleisthenes_tpu.utils.determinism import guarded_by
from cleisthenes_tpu.utils.lockcheck import new_lock

DEFAULT_CAP = 512

Point = Tuple[float, float]  # (sample instant, value)


def _now() -> float:
    """The sampler's clock (monotonic: series are for rate/age math,
    never wall-calendar display).  Confined here the way
    TraceRecorder.now() confines the trace clock."""
    return time.monotonic()  # telemetry clock (outside the determinism plane)


def flatten_snapshot(
    snap: Dict[str, object], prefix: str = ""
) -> Dict[str, float]:
    """Flatten a nested snapshot dict into dotted scalar series names:
    ``{"transport": {"delivered": 3}} -> {"transport.delivered": 3.0}``.
    Non-numeric leaves (states, lists, None) are dropped — they belong
    to /vars and /healthz, not to numeric series."""
    out: Dict[str, float] = {}
    for key, val in snap.items():
        name = f"{prefix}.{key}" if prefix else str(key)
        if isinstance(val, dict):
            out.update(flatten_snapshot(val, name))
        elif isinstance(val, bool):
            out[name] = 1.0 if val else 0.0
        elif isinstance(val, (int, float)):
            out[name] = float(val)
    return out


@guarded_by("_lock", "_series", "_samples")
class TimeSeriesSampler:
    """Folds a snapshot provider into per-metric bounded rings.

    One sampler serves one node (provider = that node's
    ``Metrics.snapshot``); the observability endpoints read
    ``series()``/``latest()`` and the trend tooling reads ``rate()``.
    """

    def __init__(
        self,
        provider: Callable[[], Dict[str, object]],
        cap: int = DEFAULT_CAP,
    ) -> None:
        if cap <= 0:
            raise ValueError(f"timeseries cap {cap} must be > 0")
        self._provider = provider
        self.cap = cap
        self._series: Dict[str, Deque[Point]] = {}
        self._samples = 0
        self._lock = new_lock()
        self._on_tick: List[Callable[[], None]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- the fold ----------------------------------------------------------

    def on_tick(self, fn: Callable[[Optional[float]], None]) -> None:
        """Register a callback run on every sample (manual or
        threaded) BEFORE the snapshot is read — the SLO watchdog's
        ``check`` rides here so each sample records post-check state.
        The callback receives the sample instant, so a synthetic
        ``sample(now=...)`` drives the watchdog's clock too (rings and
        verdicts must tell one consistent story)."""
        self._on_tick.append(fn)

    def sample(self, now: Optional[float] = None) -> Dict[str, float]:
        """One fold: run tick callbacks (passing the sample instant),
        snapshot, append every numeric leaf to its ring.  Returns the
        flattened sample."""
        t = _now() if now is None else now
        for fn in self._on_tick:
            fn(t)
        flat = flatten_snapshot(self._provider())
        with self._lock:
            self._samples += 1
            for name, value in flat.items():
                ring = self._series.get(name)
                if ring is None:
                    ring = self._series[name] = collections.deque(
                        maxlen=self.cap
                    )
                ring.append((t, value))
        return flat

    # -- reading -----------------------------------------------------------

    def series(self) -> Dict[str, List[Point]]:
        """Every ring, oldest point first."""
        with self._lock:
            return {name: list(ring) for name, ring in self._series.items()}

    def latest(self) -> Dict[str, float]:
        with self._lock:
            return {
                name: ring[-1][1]
                for name, ring in self._series.items()
                if ring
            }

    def rate(self, name: str) -> Optional[float]:
        """Per-second delta of a (monotonic counter) series across its
        ring window; None with < 2 points or a zero-length window."""
        with self._lock:
            ring = self._series.get(name)
            if ring is None or len(ring) < 2:
                return None
            (t0, v0), (t1, v1) = ring[0], ring[-1]
        if t1 <= t0:
            return None
        return (v1 - v0) / (t1 - t0)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"samples": self._samples, "series": len(self._series)}

    # -- the live loop -----------------------------------------------------

    def start(self, period_s: float = 1.0) -> None:
        """Spawn the sampling daemon; idempotent."""
        if period_s <= 0:
            raise ValueError(f"sample period {period_s} must be > 0")
        if self._thread is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(period_s):
                try:
                    self.sample()
                except Exception:
                    # a failing provider must not kill telemetry;
                    # the next tick retries
                    import traceback

                    traceback.print_exc()

        self._thread = threading.Thread(
            target=loop, name="obs-sampler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)


__all__ = [
    "DEFAULT_CAP",
    "TimeSeriesSampler",
    "flatten_snapshot",
]
