"""Structured leveled logging (the reference's iLogger parity).

The reference logs through DE-labtory/iLogger with structured fields
(reference comm.go:82,92,95 — its only observability besides tests).
Here: stdlib logging with a per-node adapter that prefixes every line
with the validator id and renders keyword fields deterministically —
enough to correlate multi-node interleavings in one process.
"""

from __future__ import annotations

import logging
import sys
from typing import Optional

_ROOT = "cleisthenes_tpu"


def configure(level: int = logging.INFO, stream=None) -> None:
    """Install a handler on the framework's root logger (idempotent)."""
    logger = logging.getLogger(_ROOT)
    logger.setLevel(level)
    if not logger.handlers:
        h = logging.StreamHandler(stream or sys.stderr)
        h.setFormatter(
            logging.Formatter(
                "%(asctime)s %(levelname).1s %(name)s %(message)s",
                datefmt="%H:%M:%S",
            )
        )
        logger.addHandler(h)


class NodeLogger:
    """Per-validator logger with structured key=value fields."""

    def __init__(self, node_id: Optional[str] = None, subsystem: str = ""):
        name = _ROOT
        if subsystem:
            name += f".{subsystem}"
        self._log = logging.getLogger(name)
        self._prefix = f"[{node_id}] " if node_id else ""

    def _fmt(self, msg: str, fields: dict) -> str:
        if not fields:
            return self._prefix + msg
        kv = " ".join(f"{k}={fields[k]!r}" for k in sorted(fields))
        return f"{self._prefix}{msg} {kv}"

    def debug(self, msg: str, **fields) -> None:
        self._log.debug(self._fmt(msg, fields))

    def info(self, msg: str, **fields) -> None:
        self._log.info(self._fmt(msg, fields))

    def warning(self, msg: str, **fields) -> None:
        self._log.warning(self._fmt(msg, fields))

    def error(self, msg: str, **fields) -> None:
        self._log.error(self._fmt(msg, fields))


__all__ = ["configure", "NodeLogger"]
