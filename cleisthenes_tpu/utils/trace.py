"""Epoch flight recorder: the structured-tracing half of observability.

The cost model of this stack is dispatch count per epoch, not FLOPs
(docs/ARCHITECTURE.md), yet until this module the only instruments
were coarse counters (`utils/metrics.py`): an N=64 epoch read as one
~12 s number with no way to say whether RBC echo waves, BBA coin
rounds, TPKE verify+combine, or hub flush scheduling bounded the
commit.  The recorder is a per-node bounded ring buffer of typed
events; `tools/tracetool.py` merges N node buffers into one
Chrome-trace-event artifact (Perfetto-loadable) and derives the
per-epoch critical-path report (docs/TRACING.md).

Design constraints, in order:

1. **Compiled-out when off.**  `Config.trace=False` (the default)
   means NO recorder exists: instrumentation sites hold `None` and
   guard with one attribute load + identity check — no allocation, no
   call (`tests/test_trace.py` asserts the zero-allocation property).
2. **Determinism-plane safe.**  Ordering comes from per-node
   **sequence numbers** assigned at record time; `perf_counter`
   timestamps ride along as PURE OBSERVABILITY data that no protocol
   state ever reads back.  This file is the single sanctioned home of
   that clock (the `allow[DET001]` pragmas below); protocol/transport
   code calls `recorder.now()` and never touches `time` itself.  Two
   `PYTHONHASHSEED` runs of one seeded cluster must produce identical
   event sequences — only the timestamps may differ.
3. **Bounded.**  The ring keeps the NEWEST `cap` events and counts
   drops (`stats()`), so an unbounded run can never leak memory into
   the protocol plane.

Event tuple shape (storage; `to_chrome` renders the JSON form):

    (seq, ts, dur, cat, name, args)

    seq   deterministic per-node sequence number (ordering truth)
    ts    perf_counter seconds at record time (observability only)
    dur   None for instant events; span length in seconds otherwise
    cat   one of CATEGORIES
    name  short event name, e.g. "open", "flush", "reveal"
    args  dict of JSON-scalar details (counts, epochs, proposers) —
          MUST be deterministic: no timestamps, no id()s, no set order
"""

from __future__ import annotations

import collections
import contextlib
import time
from typing import Deque, Dict, Iterable, List, Optional, Tuple

from cleisthenes_tpu.utils.determinism import guarded_by
from cleisthenes_tpu.utils.lockcheck import new_lock

# The stage vocabulary: every event belongs to exactly one plane, and
# the critical-path report attributes epoch wall time to these names.
CATEGORIES = frozenset(
    (
        "epoch",  # epoch open / ACS output / commit markers
        "rbc",  # reliable broadcast: VAL/ECHO/READY/deliver
        "bba",  # binary agreement rounds and decisions
        "coin",  # threshold-coin share issue + reveal
        "tpke",  # threshold encryption: encrypt/share/combine
        "settle",  # the trailing decrypt frontier (two-frontier commit
        # split): dec-share issue/combine run by the settler, plus the
        # per-epoch ordered->settled decrypt_lag bracket — kept apart
        # from "tpke" so open->ordered critical paths show exactly the
        # mass that LEFT them
        "hub",  # CryptoHub batched-dispatch flushes
        "router",  # wave-routed ingest demux (protocol.router): one
        # "route" span per delivery wave, args carry frame/payload/
        # dispatch counts — the handler-dispatch amortization record
        "transport",  # envelope coalescing, waves, queue depth
        "ledger",  # WAL appends / checkpoints
        "catchup",  # state-transfer requests/serves/adopts
        "alert",  # SLO watchdog firings (epoch stall, backpressure…)
        "reconfig",  # dynamic membership: one "ceremony" span per
        # reshare (discovery -> qualified set -> finalize) plus
        # discovered/deal/staged/install/activate/teardown instants
        # — the roster-switch timeline tools/tracetool.py reports
        "ingress",  # client admission pipeline (transport/ingress +
        # core/mempool): submit spans per ingress frame, admit/evict
        # instants with the verdict, and one "stream" span per
        # subscriber batch delivery — the client-visible latency
        # timeline the ingress_load bench section measures against
    )
)

DEFAULT_CAP = 1 << 16

Event = Tuple[int, float, Optional[float], str, str, dict]


@guarded_by("_lock", "_events", "_seq", "_dropped", "_high_water")
class TraceRecorder:
    """One node's flight recorder: a bounded ring of typed events.

    Thread-safe (the gRPC transport records from its dispatcher thread
    while `Metrics.snapshot()` reads stats from callers), but sequence
    numbers are only *meaningful* ordering when the owner records from
    one thread — exactly the single-threaded-actor discipline the
    protocol plane already has.
    """

    def __init__(self, node_id: str, cap: int = DEFAULT_CAP) -> None:
        if cap <= 0:
            raise ValueError(f"trace ring cap {cap} must be > 0")
        self.node_id = node_id
        self.cap = cap
        self._events: Deque[Event] = collections.deque(maxlen=cap)
        self._seq = 0
        self._dropped = 0
        self._high_water = 0
        self._lock = new_lock()

    @staticmethod
    def now() -> float:
        """The observability clock.  Pure data: nothing in the
        protocol plane may branch on this value."""
        return time.perf_counter()  # pure observability (outside the plane)

    # -- recording ---------------------------------------------------------

    def _record(
        self, cat: str, name: str, ts: float, dur: Optional[float], args: dict
    ) -> None:
        with self._lock:
            self._seq += 1
            ring = self._events
            if len(ring) >= self.cap:  # deque(maxlen) evicts the OLDEST
                self._dropped += 1
            ring.append((self._seq, ts, dur, cat, name, args))
            if len(ring) > self._high_water:
                self._high_water = len(ring)

    def instant(self, cat: str, name: str, **args) -> None:
        """A zero-duration marker (quorum crossing, commit, adopt)."""
        self._record(cat, name, self.now(), None, args)

    def complete(self, cat: str, name: str, t0: float, **args) -> None:
        """A span recorded at its END: ``t0`` came from ``now()``
        before the work (the begin/end pair in one call — no nesting
        bookkeeping on the hot path)."""
        t1 = self.now()
        self._record(cat, name, t0, t1 - t0, args)

    @contextlib.contextmanager
    def span(self, cat: str, name: str, **args):
        """Context-manager form of ``complete`` for non-hot-path use
        (tools, tests, demo drivers)."""
        t0 = self.now()
        try:
            yield self
        finally:
            self.complete(cat, name, t0, **args)

    # -- reading -----------------------------------------------------------

    def events(self) -> List[Event]:
        """Snapshot of the ring, oldest first."""
        with self._lock:
            return list(self._events)

    def stats(self) -> Dict[str, int]:
        """The Metrics.snapshot()["trace"] block: lifetime recorded
        count, ring-overflow drops, and the buffer high-water mark."""
        with self._lock:
            return {
                "events_recorded": self._seq,
                "events_dropped": self._dropped,
                "high_water": self._high_water,
            }


def maybe_recorder(config, node_id: str) -> Optional[TraceRecorder]:
    """The one construction seam: a recorder iff ``config.trace``,
    else None — and None IS the compiled-out fast path (sites guard
    with ``if tr is not None``)."""
    if getattr(config, "trace", False):
        return TraceRecorder(
            node_id, getattr(config, "trace_buffer", DEFAULT_CAP)
        )
    return None


# ---------------------------------------------------------------------------
# Chrome-trace-event rendering (the Perfetto-loadable artifact)
# ---------------------------------------------------------------------------


def to_chrome(events_by_node: Dict[str, Iterable[Event]]) -> dict:
    """Merge N node buffers into one Chrome trace-event document:
    one track (tid) per node, instants as 'i' events, spans as 'X'
    complete events (self-nesting in the viewer), timestamps
    normalized to the earliest event and scaled to microseconds.

    The per-node ``seq`` rides in ``args.seq`` — it is the ordering
    ground truth (`tools/tracetool.py --validate` checks it is
    strictly increasing per track; timestamps are allowed to be
    whatever the clock said).
    """
    nodes = sorted(events_by_node)
    all_events = {n: list(events_by_node[n]) for n in nodes}
    t_min = min(
        (ev[1] for evs in all_events.values() for ev in evs),
        default=0.0,
    )
    trace_events: List[dict] = []
    for tid, node in enumerate(nodes, start=1):
        trace_events.append(
            {
                "ph": "M",
                "pid": 1,
                "tid": tid,
                "name": "thread_name",
                "args": {"name": node},
            }
        )
        for seq, ts, dur, cat, name, args in all_events[node]:
            ev = {
                "pid": 1,
                "tid": tid,
                "cat": cat,
                "name": name,
                "ts": round((ts - t_min) * 1e6, 3),
                "args": {"seq": seq, **args},
            }
            if dur is None:
                ev["ph"] = "i"
                ev["s"] = "t"  # thread-scoped instant
            else:
                ev["ph"] = "X"
                ev["dur"] = round(dur * 1e6, 3)
            trace_events.append(ev)
    return {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "metadata": {
            "tool": "cleisthenes_tpu.utils.trace",
            "nodes": nodes,
        },
    }


def write_chrome(path: str, events_by_node: Dict[str, Iterable[Event]]) -> None:
    """Serialize ``to_chrome`` to ``path`` (open the file in Perfetto
    via ui.perfetto.dev -> Open trace file; see docs/TRACING.md)."""
    import json

    with open(path, "w", encoding="utf-8") as fh:
        json.dump(to_chrome(events_by_node), fh)


__all__ = [
    "CATEGORIES",
    "DEFAULT_CAP",
    "TraceRecorder",
    "maybe_recorder",
    "to_chrome",
    "write_chrome",
]
