"""Determinism-plane helpers: audited entropy routing + lock
annotations.

This module is the ONLY sanctioned doorway between the determinism
plane (protocol/, core/, ops/ — everything whose state can reach wire
or ledger bytes) and OS entropy / concurrency hazards:

- ``proposal_rng`` centralizes the ``config.seed is None ->
  SystemRandom`` branch.  Production keeps batch sampling unpredictable
  (HBBFT's censorship-resistance story needs it); a seed makes every
  node's sampling a pure function of (seed, node_id) so replays and
  cross-PYTHONHASHSEED runs commit byte-identical ledgers.  Plane code
  must call this instead of touching ``random`` directly — the
  staticcheck DET001 rule enforces exactly that.
- ``guarded_by`` declares which instance attributes a class's lock
  protects.  By default it is a *declaration*, not a runtime wrapper
  (no per-access overhead on hot paths): the metadata lands on the
  class as ``__guarded_by__`` for tests/tooling, and the staticcheck
  CONC001/CONC003 rules statically require every access to sit inside
  ``with self.<lock>:`` (methods named ``*_locked`` assert the caller
  already holds it).  With ``CLEISTHENES_LOCKCHECK=1`` the SAME
  registry arms the runtime sanitizer (utils/lockcheck.py): the
  decorator installs per-access lock assertions, so the contract is
  either statically proven or dynamically watched — never merely
  commented.

utils/ sits OUTSIDE the determinism plane precisely so this module can
legally touch ``random.SystemRandom`` — one audited site instead of N
scattered ones.
"""

from __future__ import annotations

import random
from typing import Dict, Optional

from cleisthenes_tpu.utils import lockcheck


def proposal_rng(seed: Optional[int], node_id: str) -> random.Random:
    """The audited seed->entropy fork for batch sampling.

    ``seed=None`` (production): OS-CSPRNG-backed SystemRandom —
    proposal contents stay unpredictable to an adversary watching the
    wire.  With a seed: a per-node deterministic stream keyed by
    (seed, node_id), so no two nodes share a stream yet every replay
    matches (Config.seed docs).
    """
    if seed is None:
        return random.SystemRandom()
    return random.Random(f"{seed}|{node_id}")


def wan_rng(seed: Optional[int], *lane: str) -> random.Random:
    """The audited entropy fork for the WAN emulation plane
    (transport/wan.py — in the determinism plane: its draws decide
    delivery order, which decides ledger bytes under a seeded
    schedule).

    Every independent stream in the emulator — one per link, one per
    node straggler process — names itself with a ``lane`` tuple, e.g.
    ``wan_rng(seed, "link", sender, receiver)``.  Keying streams by
    name (not by creation order) makes the whole plane insensitive to
    lazy-construction order: a link first touched by a metrics scrape
    draws the same delays as one first touched by a frame.

    ``seed=None`` (production): SystemRandom — emulated delays are
    unpredictable, replay is not claimed.  With a seed: a pure
    function of (seed, lane), byte-identical across processes and
    PYTHONHASHSEED values.
    """
    if seed is None:
        return random.SystemRandom()
    return random.Random(f"{seed}|wan|{'|'.join(lane)}")


def guarded_by(lock_attr: str, *attrs: str):
    """Class decorator declaring ``attrs`` as protected by
    ``self.<lock_attr>``.

    Stacks/merges across multiple decorators (a class may hold several
    locks).  The declaration is enforced statically by staticcheck's
    CONC001/CONC003 rules; at runtime it records
    ``cls.__guarded_by__ = {attr: lock_attr}`` so tests can assert
    coverage — and, when the lock sanitizer is armed
    (``CLEISTHENES_LOCKCHECK=1``), installs per-access held-lock
    assertions over exactly that registry (utils/lockcheck.py).
    """
    if not attrs:
        raise ValueError("guarded_by needs at least one attribute name")

    def deco(cls):
        merged: Dict[str, str] = dict(getattr(cls, "__guarded_by__", {}))
        for a in attrs:
            merged[a] = lock_attr
        cls.__guarded_by__ = merged
        if lockcheck.is_enabled():
            lockcheck.install(cls)
        return cls

    return deco


__all__ = ["proposal_rng", "wan_rng", "guarded_by"]
