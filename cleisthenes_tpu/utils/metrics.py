"""Metrics & tracing: the observability the reference lacks.

SURVEY.md §5.1/§5.5: the reference's only observability is three log
lines on listen errors (reference comm.go:82,92,95) — no metrics
registry, no per-epoch timing, even though the BASELINE metric is
"tx/sec & epoch p50".  This module provides exactly that: counters,
streaming histograms with percentiles, and per-epoch phase traces
(propose -> ACS output -> commit), cheap enough to stay always-on.
"""

from __future__ import annotations

import bisect
import collections
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from cleisthenes_tpu.utils.determinism import guarded_by
from cleisthenes_tpu.utils.lockcheck import new_lock


@guarded_by("_lock", "_v")
class Counter:
    """Monotonic counter (thread-safe)."""

    def __init__(self) -> None:
        self._v = 0
        self._lock = new_lock()

    def inc(self, by: int = 1) -> None:
        with self._lock:
            self._v += by

    @property
    def value(self) -> int:
        with self._lock:
            return self._v


# Default cumulative-bucket bounds for the Prometheus exposition
# (seconds): epoch latencies span ~10 ms in-proc mini-clusters to
# multi-minute N=128 message-passing epochs, so the ladder is
# log-spaced across that whole range.
DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)


@guarded_by(
    "_lock", "_sorted", "_ring", "_bucket_counts", "_total_sum",
    "_total_count",
)
class Histogram:
    """Sorted-reservoir histogram with exact percentiles.

    Bounded: keeps the most recent ``cap`` observations (epoch
    latencies arrive at network pace, so thousands of samples cover
    hours of operation).  Percentiles read the reservoir (a recency
    window); the Prometheus export (``cumulative_buckets`` /
    ``total_sum`` / ``total_count``) reads SEPARATE lifetime tallies
    that only ever grow — the histogram type contract requires
    monotonic counters, and reservoir eviction would read as counter
    resets (spurious rate() spikes on dashboards)."""

    def __init__(
        self, cap: int = 4096, buckets: Sequence[float] = DEFAULT_BUCKETS
    ) -> None:
        self._sorted: List[float] = []
        self._ring: "collections.deque[float]" = collections.deque()
        self._cap = cap
        self.bucket_bounds: List[float] = sorted(buckets)
        # lifetime (monotonic) tallies for the Prometheus exposition
        self._bucket_counts: List[int] = [0] * len(self.bucket_bounds)
        self._total_sum = 0.0
        self._total_count = 0
        self._lock = new_lock()

    def observe(self, v: float) -> None:
        with self._lock:
            if len(self._ring) >= self._cap:
                old = self._ring.popleft()
                idx = bisect.bisect_left(self._sorted, old)
                self._sorted.pop(idx)
            self._ring.append(v)
            bisect.insort(self._sorted, v)
            self._total_sum += v
            self._total_count += 1
            i = bisect.bisect_left(self.bucket_bounds, v)
            if i < len(self._bucket_counts):
                self._bucket_counts[i] += 1

    def percentile(self, p: float) -> Optional[float]:
        """p in [0, 100]; None when empty."""
        with self._lock:
            if not self._sorted:
                return None
            idx = min(
                len(self._sorted) - 1,
                int(round((p / 100.0) * (len(self._sorted) - 1))),
            )
            return self._sorted[idx]

    def cumulative_buckets(self) -> List[Tuple[float, int]]:
        """Prometheus-style cumulative buckets over the histogram's
        LIFETIME: ``[(le, observations <= le), ...]`` ending with the
        ``(inf, total)`` catch-all — monotonic counters per the
        text-exposition ``_bucket{le=...}`` contract, never affected
        by reservoir eviction."""
        with self._lock:
            out: List[Tuple[float, int]] = []
            running = 0
            for le, n in zip(self.bucket_bounds, self._bucket_counts):
                running += n
                out.append((le, running))
            out.append((float("inf"), self._total_count))
            return out

    @property
    def total_sum(self) -> float:
        """Lifetime sum — the exposition's monotonic ``_sum``."""
        with self._lock:
            return self._total_sum

    @property
    def total_count(self) -> int:
        """Lifetime observation count — the exposition's ``_count``."""
        with self._lock:
            return self._total_count

    @property
    def count(self) -> int:
        """Reservoir size (bounded by ``cap``) — the percentile
        window, NOT the exposition counter."""
        with self._lock:
            return len(self._ring)

    @property
    def p50(self) -> Optional[float]:
        return self.percentile(50)

    @property
    def p95(self) -> Optional[float]:
        return self.percentile(95)


class EpochTrace:
    """Phase timestamps for one epoch: propose -> acs_output ->
    [ordered ->] commit (the per-epoch phase timing of SURVEY.md §5.1;
    ``t_ordered`` is set only on the two-frontier path,
    Config.order_then_settle, where commit = settle)."""

    __slots__ = (
        "epoch", "t_propose", "t_acs_output", "t_ordered", "t_commit",
        "n_txs",
    )

    def __init__(self, epoch: int):
        self.epoch = epoch
        self.t_propose: Optional[float] = None
        self.t_acs_output: Optional[float] = None
        self.t_ordered: Optional[float] = None
        self.t_commit: Optional[float] = None
        self.n_txs: int = 0

    @property
    def total_s(self) -> Optional[float]:
        if self.t_propose is None or self.t_commit is None:
            return None
        return self.t_commit - self.t_propose

    @property
    def acs_s(self) -> Optional[float]:
        if self.t_propose is None or self.t_acs_output is None:
            return None
        return self.t_acs_output - self.t_propose

    @property
    def decrypt_s(self) -> Optional[float]:
        if self.t_acs_output is None or self.t_commit is None:
            return None
        return self.t_commit - self.t_acs_output

    @property
    def ordered_s(self) -> Optional[float]:
        """Propose -> ciphertext-ordered commit: the protocol-plane
        latency as the APPLICATION'S ordering sees it."""
        if self.t_propose is None or self.t_ordered is None:
            return None
        return self.t_ordered - self.t_propose

    @property
    def settle_lag_s(self) -> Optional[float]:
        """Ordered -> settled: how long the epoch's plaintext trailed
        its ordering (the decrypt-lag wall)."""
        if self.t_ordered is None or self.t_commit is None:
            return None
        return self.t_commit - self.t_ordered


@guarded_by("_lock", "_traces", "_last_commit_t")
class Metrics:
    """Per-node metrics registry."""

    def __init__(self, trace_cap: int = 1024) -> None:
        self.msgs_in = Counter()
        self.msgs_out = Counter()
        self.epochs_committed = Counter()
        self.txs_committed = Counter()
        # duplicate protocol votes/shares dropped by one-vote-per-
        # sender dedup (RBC echo/ready slots, VoteBank rows, share
        # pools): the counter that makes replay/duplication attacks
        # VISIBLE — before it, absorption happened silently across a
        # dozen private sets
        self.dedup_absorbed = Counter()
        # two-frontier commit (Config.order_then_settle): epochs whose
        # ciphertext ordering committed (the ordered frontier's tally;
        # settlement lands in epochs_committed as before)
        self.epochs_ordered = Counter()
        # dynamic membership (protocol.reconfig): completed roster
        # switches this node activated (joins, retirements, re-keys)
        self.reconfigs_total = Counter()
        # wave-routed ingest (Config.wave_routing): batch handler
        # invocations crossing the router seam into protocol logic
        # (ACS/RBC/BBA/dec-share entry points).  The scalar routing
        # arm counts one per payload; the wave arm counts one per
        # (message kind, delivery wave) — DETERMINISTIC for a seeded
        # schedule, the counter perfgate gates like hub dispatches.
        self.handler_dispatches = Counter()
        # delivery waves the router demuxed (0 on the scalar arm)
        self.waves_routed = Counter()
        # K-deep pipelined frontiers (Config.pipeline_depth): waves
        # whose coalescer flush carried eagerly piggybacked dec
        # shares for a freshly ordered epoch (0 at depth 1 — the
        # eager path is gated to the K-deep plane)
        self.eager_share_waves = Counter()
        self.epoch_latency = Histogram()  # seconds, propose -> commit
        self.acs_latency = Histogram()
        self.decrypt_latency = Histogram()
        # propose -> ciphertext-ordered commit (the ordered frontier's
        # epoch latency) and ordered -> settled (the decrypt lag wall)
        self.ordered_latency = Histogram()
        self.settle_lag_latency = Histogram()
        self._traces: Dict[int, EpochTrace] = {}
        self._trace_cap = trace_cap
        self._t0 = time.monotonic()
        # monotonic instant of the last committed epoch: the SLO
        # watchdog's stall detector measures "time since progress"
        # against this (never-committed reads as age since boot)
        self._last_commit_t: Optional[float] = None
        self._lock = new_lock()
        # transport-health provider (transport.health.PeerHealthTracker
        # .snapshot, set by the host that owns the dial layer): folds a
        # per-peer UP/DEGRADED/DOWN block into snapshot()
        self._transport_health: Optional[Callable[[], Dict]] = None
        # flight-recorder stats provider (utils.trace.TraceRecorder
        # .stats, set by the node when Config.trace is on): folds the
        # {events_recorded, events_dropped, high_water} block in
        self._trace_stats: Optional[Callable[[], Dict]] = None
        # transport frame-counter provider (ChannelNetwork
        # .endpoint_stats / ValidatorHost connection counters): folds
        # {delivered, rejected} into snapshot()["transport"], making
        # MAC rejections reachable without touching private transport
        # internals
        self._transport_stats: Optional[Callable[[], Dict]] = None
        # SLO watchdog provider (utils.watchdog.SloWatchdog
        # .alerts_block, set by the host/cluster that owns the
        # watchdog): folds health + per-alert counters into snapshot()
        self._alerts: Optional[Callable[[], Dict]] = None
        # crypto-hub counter provider (set by the owning HoneyBadger):
        # folds the coin-issue dispatch tallies into snapshot()["hub"]
        # (a cluster-SHARED hub reports cluster-wide numbers on every
        # node, the same convention as bench.py's hub_dispatches)
        self._hub_stats: Optional[Callable[[], Dict]] = None
        # frontier provider (set by the owning HoneyBadger): () ->
        # (ordered_frontier, settled_frontier).  decrypt_lag_epochs =
        # ordered - settled is THE two-frontier health signal — zero on
        # the coupled path, bounded by Config.decrypt_lag_max on the
        # order-then-settle path.
        self._frontiers: Optional[Callable[[], Tuple[int, int]]] = None
        # roster-version provider (set by the owning HoneyBadger):
        # () -> the ACTIVE roster version (0 = the genesis roster)
        self._roster_version: Optional[Callable[[], int]] = None
        # pipeline provider (set by the owning HoneyBadger): () ->
        # the number of epochs currently running RBC/BBA concurrently
        # (proposed, consensus live, not yet ordered) — the K-deep
        # window's in-flight gauge, 1 in steady lockstep
        self._pipeline: Optional[Callable[[], int]] = None
        # WAN-emulation provider (set by the owning cluster when
        # SimulatedCluster(wan_profile=) mounts a link model;
        # WanEmulator.stats): folds the virtual-clock plane's tallies
        # into snapshot()["wan"]
        self._wan_stats: Optional[Callable[[], Dict]] = None
        # ingress-plane provider (set by the owning HoneyBadger:
        # mempool admission tallies + subscriber gauge) — folds into
        # the ALWAYS-present zeroed snapshot()["ingress"] block
        self._ingress: Optional[Callable[[], Dict]] = None
        # lane shard-out provider (set by the owning lane-0 primary:
        # per-lane frontier gauges, merge frontier, partition skew) —
        # folds into the ALWAYS-present snapshot()["lanes"] block
        self._lanes: Optional[Callable[[], Dict]] = None

    def set_transport_health(
        self, provider: Optional[Callable[[], Dict]]
    ) -> None:
        self._transport_health = provider

    def set_transport_stats(
        self, provider: Optional[Callable[[], Dict]]
    ) -> None:
        self._transport_stats = provider

    def set_trace_stats(
        self, provider: Optional[Callable[[], Dict]]
    ) -> None:
        self._trace_stats = provider

    def set_alerts(self, provider: Optional[Callable[[], Dict]]) -> None:
        self._alerts = provider

    def set_hub_stats(
        self, provider: Optional[Callable[[], Dict]]
    ) -> None:
        self._hub_stats = provider

    def set_frontiers(
        self, provider: Optional[Callable[[], Tuple[int, int]]]
    ) -> None:
        self._frontiers = provider

    def set_reconfig(self, provider: Optional[Callable[[], int]]) -> None:
        """Roster-version provider (dynamic membership)."""
        self._roster_version = provider

    def set_pipeline(self, provider: Optional[Callable[[], int]]) -> None:
        """Epochs-in-flight provider (K-deep pipelined frontiers)."""
        self._pipeline = provider

    def set_wan_stats(
        self, provider: Optional[Callable[[], Dict]]
    ) -> None:
        """WAN emulation-plane provider (WanEmulator.stats)."""
        self._wan_stats = provider

    def set_ingress(self, provider: Optional[Callable[[], Dict]]) -> None:
        """Ingress-plane provider (mempool tallies + subscribers)."""
        self._ingress = provider

    def set_lanes(self, provider: Optional[Callable[[], Dict]]) -> None:
        """Lane shard-out provider (Config.lanes: per-lane frontiers,
        merge frontier, partition skew)."""
        self._lanes = provider

    def decrypt_lag_epochs(self) -> int:
        """Ordered frontier - settled frontier (0 when no provider is
        registered, and 0 by construction on the coupled path)."""
        if self._frontiers is None:
            return 0
        ordered, settled = self._frontiers()
        return max(0, ordered - settled)

    def trace(self, epoch: int) -> EpochTrace:
        with self._lock:
            tr = self._traces.get(epoch)
            if tr is None:
                tr = EpochTrace(epoch)
                self._traces[epoch] = tr
                if len(self._traces) > self._trace_cap:
                    del self._traces[min(self._traces)]
            return tr

    def epoch_proposed(self, epoch: int) -> None:
        self.trace(epoch).t_propose = time.monotonic()

    def epoch_acs_output(self, epoch: int) -> None:
        self.trace(epoch).t_acs_output = time.monotonic()

    def epoch_ordered(self, epoch: int) -> None:
        """The ciphertext-ordered commit instant (two-frontier path):
        the ordered frontier advanced past ``epoch``."""
        tr = self.trace(epoch)
        tr.t_ordered = time.monotonic()
        self.epochs_ordered.inc()
        if tr.ordered_s is not None:
            self.ordered_latency.observe(tr.ordered_s)

    def epoch_committed(self, epoch: int, n_txs: int) -> None:
        tr = self.trace(epoch)
        tr.t_commit = time.monotonic()
        tr.n_txs = n_txs
        with self._lock:  # read cross-thread by the SLO watchdog
            self._last_commit_t = tr.t_commit
        self.epochs_committed.inc()
        self.txs_committed.inc(n_txs)
        if tr.total_s is not None:
            self.epoch_latency.observe(tr.total_s)
        if tr.acs_s is not None:
            self.acs_latency.observe(tr.acs_s)
        if tr.decrypt_s is not None:
            self.decrypt_latency.observe(tr.decrypt_s)
        if tr.settle_lag_s is not None:
            self.settle_lag_latency.observe(tr.settle_lag_s)

    def epoch_spans(self) -> List[Tuple[int, float, float]]:
        """(epoch, t_propose, t_commit) for every retained epoch trace
        with both endpoints — the per-epoch serial walls an overlap
        ratio needs (serial sum / elapsed wall > 1 means epochs
        genuinely overlapped)."""
        with self._lock:
            traces = list(self._traces.items())
        return sorted(
            (epoch, t.t_propose, t.t_commit)
            for epoch, t in traces
            if t.t_propose is not None and t.t_commit is not None
        )

    def tx_per_sec(self) -> float:
        dt = time.monotonic() - self._t0
        return self.txs_committed.value / dt if dt > 0 else 0.0

    def last_commit_age_s(self, now: Optional[float] = None) -> float:
        """Seconds (monotonic) since the last committed epoch — since
        construction when nothing committed yet.  ``now`` lets the
        watchdog tests drive synthetic clocks."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            base = (
                self._last_commit_t
                if self._last_commit_t is not None
                else self._t0
            )
        return max(0.0, now - base)

    def snapshot(self) -> Dict[str, object]:
        """One flat dict for logging/export (the BASELINE metrics),
        plus the transport-health block when a dial layer registered
        its provider."""
        out: Dict[str, object] = {
            "msgs_in": self.msgs_in.value,
            "msgs_out": self.msgs_out.value,
            "epochs_committed": self.epochs_committed.value,
            "txs_committed": self.txs_committed.value,
            "tx_per_sec": round(self.tx_per_sec(), 3),
            "epoch_p50_s": self.epoch_latency.p50,
            "epoch_p95_s": self.epoch_latency.p95,
            "acs_p50_s": self.acs_latency.p50,
            "decrypt_p50_s": self.decrypt_latency.p50,
        }
        # two-frontier block: ALWAYS present (zeroed on the coupled
        # path) — same appear/disappear contract as "transport" below
        frontiers: Dict[str, object] = {
            "epochs_ordered": self.epochs_ordered.value,
            "ordered_p50_s": self.ordered_latency.p50,
            "settle_lag_p50_s": self.settle_lag_latency.p50,
            "decrypt_lag_epochs": 0,
            "ordered_frontier": 0,
            "settled_frontier": 0,
        }
        if self._frontiers is not None:
            ordered, settled = self._frontiers()
            frontiers["ordered_frontier"] = ordered
            frontiers["settled_frontier"] = settled
            frontiers["decrypt_lag_epochs"] = max(0, ordered - settled)
        out["frontiers"] = frontiers
        # reconfig block: ALWAYS present with every key, zeroed on
        # fixed-roster nodes (the PR-9 schema-stability rule — a
        # scraper must never see a key appear/disappear between
        # snapshots because a roster happened to change)
        reconfig: Dict[str, object] = {
            "roster_version": 0,
            "reconfigs_total": self.reconfigs_total.value,
        }
        if self._roster_version is not None:
            reconfig["roster_version"] = int(self._roster_version())
        out["reconfig"] = reconfig
        # wave-routing block: ALWAYS present with every key, zeroed on
        # the scalar arm / bare nodes (the PR-9 schema-stability rule
        # — scrapers and the timeseries sampler must never see a key
        # appear or disappear between snapshots)
        out["router"] = {
            "handler_dispatches": self.handler_dispatches.value,
            "waves_routed": self.waves_routed.value,
        }
        # K-deep pipeline block: ALWAYS present with every key,
        # zeroed at depth 1 / on bare nodes (same schema rule)
        pipeline: Dict[str, object] = {
            "epochs_in_flight": 0,
            "eager_share_waves": self.eager_share_waves.value,
        }
        if self._pipeline is not None:
            pipeline["epochs_in_flight"] = int(self._pipeline())
        out["pipeline"] = pipeline
        # every transport key is ALWAYS present (zeroed when no frame
        # counters registered): scrapers and the timeseries sampler
        # must never see a key appear/disappear between snapshots —
        # nodes without a transport provider (bare HoneyBadger, early
        # boot) used to omit delivered/rejected entirely
        transport: Dict[str, object] = {
            "delivered": 0,
            "rejected": 0,
            "dedup_absorbed": self.dedup_absorbed.value,
            # delivery-plane counters (Config.delivery_columnar): the
            # PR-5 schema-stability rule — every key present and
            # zeroed on EVERY path (scalar arm, bare HoneyBadger,
            # early boot); transports with counters overwrite below
            "frames_decoded": 0,
            "decode_memo_hits": 0,
            "decode_memo_misses": 0,
            "mac_verify_batches": 0,
            # egress-plane twins (Config.egress_columnar): same
            # zeroed-key schema rule on both egress arms
            "frames_encoded": 0,
            "encode_memo_hits": 0,
            "encode_memo_misses": 0,
            "mac_sign_batches": 0,
        }
        if self._transport_stats is not None:
            transport.update(self._transport_stats())
        out["transport"] = transport
        # crypto-hub block: ALWAYS present with every key, zeroed on
        # bare nodes (the PR-9 schema-stability rule); the coin-issue
        # dispatch tallies are counted on BOTH egress arms, so the
        # scalar arm reports its per-node-per-drain batches here too
        hub: Dict[str, object] = {
            "coin_share_batches": 0,
            "coin_share_items": 0,
        }
        if self._hub_stats is not None:
            hub.update(self._hub_stats())
        out["hub"] = hub
        # WAN-emulation block: ALWAYS present with every key, zeroed
        # on real transports / unmounted profiles (the PR-9 schema
        # rule); with SimulatedCluster(wan_profile=) the emulator's
        # provider overwrites with the virtual-clock plane's tallies
        wan: Dict[str, object] = {
            "enabled": 0,
            "profile": "",
            "frames_delayed": 0,
            "retransmits": 0,
            "straggler_episodes": 0,
            "virtual_time_ms": 0,
        }
        if self._wan_stats is not None:
            wan.update(self._wan_stats())
        out["wan"] = wan
        # ingress block: ALWAYS present with every key, zeroed on
        # nodes without a mounted mempool (the PR-9 schema rule);
        # with Config.mempool_capacity > 0 the owning node's provider
        # overwrites with the admission pipeline's tallies
        ingress: Dict[str, object] = {
            "submitted": 0,
            "admitted": 0,
            "rejected": 0,
            "retried": 0,
            "deduped": 0,
            "evicted": 0,
            "subscribers": 0,
            "mempool_depth": 0,
        }
        if self._ingress is not None:
            ingress.update(self._ingress())
        out["ingress"] = ingress
        # lane shard-out block: ALWAYS present with every key (the
        # PR-9 schema-stability rule) — a single-lane node reports
        # lanes=1 with one-element gauge lists, so scrapers see the
        # same shape at every S
        lanes: Dict[str, object] = {
            "lanes": 1,
            "merge_frontier": 0,
            "ordered_epochs": [0],
            "settled_epochs": [0],
            "lane_fill": [0],
            "partition_skew": 0,
        }
        if self._lanes is not None:
            lanes.update(self._lanes())
        out["lanes"] = lanes
        if self._transport_health is not None:
            out["transport_health"] = self._transport_health()
        if self._trace_stats is not None:
            out["trace"] = self._trace_stats()
        if self._alerts is not None:
            out["alerts"] = self._alerts()
        return out


__all__ = [
    "Counter",
    "DEFAULT_BUCKETS",
    "Histogram",
    "EpochTrace",
    "Metrics",
]
