"""Metrics & tracing: the observability the reference lacks.

SURVEY.md §5.1/§5.5: the reference's only observability is three log
lines on listen errors (reference comm.go:82,92,95) — no metrics
registry, no per-epoch timing, even though the BASELINE metric is
"tx/sec & epoch p50".  This module provides exactly that: counters,
streaming histograms with percentiles, and per-epoch phase traces
(propose -> ACS output -> commit), cheap enough to stay always-on.
"""

from __future__ import annotations

import bisect
import collections
import threading
import time
from typing import Callable, Dict, List, Optional

from cleisthenes_tpu.utils.determinism import guarded_by


@guarded_by("_lock", "_v")
class Counter:
    """Monotonic counter (thread-safe)."""

    def __init__(self) -> None:
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, by: int = 1) -> None:
        with self._lock:
            self._v += by

    @property
    def value(self) -> int:
        with self._lock:
            return self._v


@guarded_by("_lock", "_sorted", "_ring")
class Histogram:
    """Sorted-reservoir histogram with exact percentiles.

    Bounded: keeps the most recent ``cap`` observations (epoch
    latencies arrive at network pace, so thousands of samples cover
    hours of operation)."""

    def __init__(self, cap: int = 4096) -> None:
        self._sorted: List[float] = []
        self._ring: "collections.deque[float]" = collections.deque()
        self._cap = cap
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            if len(self._ring) >= self._cap:
                old = self._ring.popleft()
                idx = bisect.bisect_left(self._sorted, old)
                self._sorted.pop(idx)
            self._ring.append(v)
            bisect.insort(self._sorted, v)

    def percentile(self, p: float) -> Optional[float]:
        """p in [0, 100]; None when empty."""
        with self._lock:
            if not self._sorted:
                return None
            idx = min(
                len(self._sorted) - 1,
                int(round((p / 100.0) * (len(self._sorted) - 1))),
            )
            return self._sorted[idx]

    @property
    def count(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def p50(self) -> Optional[float]:
        return self.percentile(50)

    @property
    def p95(self) -> Optional[float]:
        return self.percentile(95)


class EpochTrace:
    """Phase timestamps for one epoch: propose -> acs_output -> commit
    (the per-epoch phase timing of SURVEY.md §5.1)."""

    __slots__ = ("epoch", "t_propose", "t_acs_output", "t_commit", "n_txs")

    def __init__(self, epoch: int):
        self.epoch = epoch
        self.t_propose: Optional[float] = None
        self.t_acs_output: Optional[float] = None
        self.t_commit: Optional[float] = None
        self.n_txs: int = 0

    @property
    def total_s(self) -> Optional[float]:
        if self.t_propose is None or self.t_commit is None:
            return None
        return self.t_commit - self.t_propose

    @property
    def acs_s(self) -> Optional[float]:
        if self.t_propose is None or self.t_acs_output is None:
            return None
        return self.t_acs_output - self.t_propose

    @property
    def decrypt_s(self) -> Optional[float]:
        if self.t_acs_output is None or self.t_commit is None:
            return None
        return self.t_commit - self.t_acs_output


@guarded_by("_lock", "_traces")
class Metrics:
    """Per-node metrics registry."""

    def __init__(self, trace_cap: int = 1024) -> None:
        self.msgs_in = Counter()
        self.msgs_out = Counter()
        self.epochs_committed = Counter()
        self.txs_committed = Counter()
        # duplicate protocol votes/shares dropped by one-vote-per-
        # sender dedup (RBC echo/ready slots, VoteBank rows, share
        # pools): the counter that makes replay/duplication attacks
        # VISIBLE — before it, absorption happened silently across a
        # dozen private sets
        self.dedup_absorbed = Counter()
        self.epoch_latency = Histogram()  # seconds, propose -> commit
        self.acs_latency = Histogram()
        self.decrypt_latency = Histogram()
        self._traces: Dict[int, EpochTrace] = {}
        self._trace_cap = trace_cap
        self._t0 = time.monotonic()
        self._lock = threading.Lock()
        # transport-health provider (transport.health.PeerHealthTracker
        # .snapshot, set by the host that owns the dial layer): folds a
        # per-peer UP/DEGRADED/DOWN block into snapshot()
        self._transport_health: Optional[Callable[[], Dict]] = None
        # flight-recorder stats provider (utils.trace.TraceRecorder
        # .stats, set by the node when Config.trace is on): folds the
        # {events_recorded, events_dropped, high_water} block in
        self._trace_stats: Optional[Callable[[], Dict]] = None
        # transport frame-counter provider (ChannelNetwork
        # .endpoint_stats / ValidatorHost connection counters): folds
        # {delivered, rejected} into snapshot()["transport"], making
        # MAC rejections reachable without touching private transport
        # internals
        self._transport_stats: Optional[Callable[[], Dict]] = None

    def set_transport_health(
        self, provider: Optional[Callable[[], Dict]]
    ) -> None:
        self._transport_health = provider

    def set_transport_stats(
        self, provider: Optional[Callable[[], Dict]]
    ) -> None:
        self._transport_stats = provider

    def set_trace_stats(
        self, provider: Optional[Callable[[], Dict]]
    ) -> None:
        self._trace_stats = provider

    def trace(self, epoch: int) -> EpochTrace:
        with self._lock:
            tr = self._traces.get(epoch)
            if tr is None:
                tr = EpochTrace(epoch)
                self._traces[epoch] = tr
                if len(self._traces) > self._trace_cap:
                    del self._traces[min(self._traces)]
            return tr

    def epoch_proposed(self, epoch: int) -> None:
        self.trace(epoch).t_propose = time.monotonic()

    def epoch_acs_output(self, epoch: int) -> None:
        self.trace(epoch).t_acs_output = time.monotonic()

    def epoch_committed(self, epoch: int, n_txs: int) -> None:
        tr = self.trace(epoch)
        tr.t_commit = time.monotonic()
        tr.n_txs = n_txs
        self.epochs_committed.inc()
        self.txs_committed.inc(n_txs)
        if tr.total_s is not None:
            self.epoch_latency.observe(tr.total_s)
        if tr.acs_s is not None:
            self.acs_latency.observe(tr.acs_s)
        if tr.decrypt_s is not None:
            self.decrypt_latency.observe(tr.decrypt_s)

    def tx_per_sec(self) -> float:
        dt = time.monotonic() - self._t0
        return self.txs_committed.value / dt if dt > 0 else 0.0

    def snapshot(self) -> Dict[str, object]:
        """One flat dict for logging/export (the BASELINE metrics),
        plus the transport-health block when a dial layer registered
        its provider."""
        out: Dict[str, object] = {
            "msgs_in": self.msgs_in.value,
            "msgs_out": self.msgs_out.value,
            "epochs_committed": self.epochs_committed.value,
            "txs_committed": self.txs_committed.value,
            "tx_per_sec": round(self.tx_per_sec(), 3),
            "epoch_p50_s": self.epoch_latency.p50,
            "epoch_p95_s": self.epoch_latency.p95,
            "acs_p50_s": self.acs_latency.p50,
            "decrypt_p50_s": self.decrypt_latency.p50,
        }
        transport: Dict[str, object] = {
            "dedup_absorbed": self.dedup_absorbed.value,
        }
        if self._transport_stats is not None:
            transport.update(self._transport_stats())
        out["transport"] = transport
        if self._transport_health is not None:
            out["transport_health"] = self._transport_health()
        if self._trace_stats is not None:
            out["trace"] = self._trace_stats()
        return out


__all__ = ["Counter", "Histogram", "EpochTrace", "Metrics"]
