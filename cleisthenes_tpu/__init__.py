"""cleisthenes-tpu: a TPU-native HoneyBadgerBFT consensus framework.

A from-scratch, complete implementation of asynchronous Byzantine
fault-tolerant consensus (HoneyBadgerBFT: ACS = N x RBC + N x BBA, with
threshold encryption for censorship resistance), with the same
capabilities and API shape as the Go reference library ``cleisthenes``
(see /root/reference, surveyed in SURVEY.md) — but architected for TPU:

- The asynchronous *protocol plane* (connections, epochs, RBC/BBA state
  machines, quorum counting) runs host-side on asyncio, mirroring the
  reference's goroutine-actor design (reference conn.go:104-128,
  bba/bba.go:113-123).
- The *crypto plane* — GF(2^8) Reed-Solomon erasure coding, SHA-256
  Merkle forests, threshold-encryption share operations and the
  threshold common coin — is batched, fixed-shape JAX/XLA vmapped across
  the validator axis, behind a ``BatchCrypto``/``ErasureCoder`` seam
  with a CPU reference backend (numpy + native C++), selected by config.

Public API parity map (reference file:line -> here):
  NewHoneyBadger(batchSize, nodes)   honeybadger.go:36  -> HoneyBadger
  HoneyBadger.AddTransaction(tx)     honeybadger.go:52  -> HoneyBadger.add_transaction
  Transaction interface{}            honeybadger.go:115 -> Transaction (opaque bytes/any)
  Batch.TxList()                     honeybadger.go:14  -> Batch.tx_list
  Config                             cleisthenes.go:3   -> Config
  Member/MemberMap                   member_map.go      -> core.member
  Connection/Broadcaster/Handler     conn.go:27-38,182  -> transport.base
"""

from cleisthenes_tpu.config import Config
from cleisthenes_tpu.core.batch import Batch
from cleisthenes_tpu.core.member import Address, Member, MemberMap
from cleisthenes_tpu.core.queue import (
    EmptyQueueError,
    IndexBoundaryError,
    Transaction,
    TxQueue,
)

__version__ = "0.1.0"

__all__ = [
    "Config",
    "Batch",
    "Address",
    "Member",
    "MemberMap",
    "TxQueue",
    "Transaction",
    "EmptyQueueError",
    "IndexBoundaryError",
    "__version__",
]
