"""Local-cluster demo: ``python -m cleisthenes_tpu.demo``.

Boots N HBBFT validators over localhost gRPC (the reference is a
library with no runnable main; this is the 5-minute proof the
framework works end to end), feeds transactions, and prints each
committed epoch plus the node-0 metrics snapshot.

    python -m cleisthenes_tpu.demo --n 4 --txs 64 --batch-size 16 \
        --crypto cpu|cpp|tpu [--log-dir /tmp/hbbft-logs]
"""

from __future__ import annotations

import argparse
import logging
import os
import queue
import threading
import time

from cleisthenes_tpu.config import Config
from cleisthenes_tpu.protocol.honeybadger import setup_keys
from cleisthenes_tpu.transport.host import ValidatorHost
from cleisthenes_tpu.utils.log import configure as configure_logging


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=4, help="validator count")
    ap.add_argument("--txs", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument(
        "--crypto", default="cpu", choices=["cpu", "cpp", "tpu"]
    )
    ap.add_argument("--timeout", type=float, default=120.0)
    ap.add_argument(
        "--log-dir",
        default=None,
        help="directory for durable committed-batch logs (restart demo)",
    )
    ap.add_argument(
        "--verbose", action="store_true", help="debug-level node logs"
    )
    ap.add_argument(
        "--mode",
        default="grpc",
        choices=["grpc", "lockstep"],
        help="grpc: N real validator processes-in-threads over "
        "localhost sockets; lockstep: the batched SPMD executor "
        "(protocol.spmd) — the mode for big-N capacity runs",
    )
    ap.add_argument(
        "--dkg",
        action="store_true",
        help="generate threshold keys by distributed key generation "
        "(ops.dkg) instead of the trusted dealer",
    )
    ap.add_argument(
        "--trace",
        metavar="OUT_JSON",
        default=None,
        help="run under the flight recorder (utils/trace.py) and "
        "write the merged Chrome-trace artifact here on exit — open "
        "it at ui.perfetto.dev (grpc mode only; see docs/TRACING.md)",
    )
    ap.add_argument(
        "--obs-port",
        type=int,
        default=None,
        metavar="BASE",
        help="serve live telemetry (/metrics /healthz /vars, "
        "transport/obs_http.py) on 127.0.0.1: node i listens on "
        "BASE+i; 0 picks ephemeral ports (printed at boot; grpc "
        "mode only — see docs/OBSERVABILITY.md)",
    )
    ap.add_argument(
        "--ingress-port",
        type=int,
        default=None,
        metavar="BASE",
        help="serve the client submit/subscribe API "
        "(transport/ingress.py) on 127.0.0.1: node i listens on "
        "BASE+i; 0 picks ephemeral ports (printed at boot).  The "
        "demo then submits its transactions as a real gRPC client "
        "through the fee-priority mempool instead of in-process "
        "(grpc mode only — see docs/ARCHITECTURE.md 'Ingress plane')",
    )
    args = ap.parse_args(argv)
    if args.obs_port is not None and (
        args.obs_port < 0 or args.obs_port + args.n - 1 > 65535
    ):
        ap.error(
            f"--obs-port {args.obs_port}: need 0 (ephemeral) or a base "
            f"with BASE+{args.n - 1} <= 65535 (one port per node)"
        )
    if args.ingress_port is not None and (
        args.ingress_port < 0 or args.ingress_port + args.n - 1 > 65535
    ):
        ap.error(
            f"--ingress-port {args.ingress_port}: need 0 (ephemeral) "
            f"or a base with BASE+{args.n - 1} <= 65535 (one per node)"
        )
    configure_logging(logging.DEBUG if args.verbose else logging.INFO)

    cfg = Config(
        n=args.n,
        batch_size=args.batch_size,
        crypto_backend=args.crypto,
        # tracing instruments the message-passing path only: lockstep
        # mode must not pay for recorders nobody ever reads
        trace=args.trace is not None and args.mode == "grpc",
    )
    ids = [f"node{i}" for i in range(args.n)]
    print(
        f"== cleisthenes-tpu demo: n={args.n} f={cfg.f} "
        f"batch={args.batch_size} crypto={args.crypto} mode={args.mode}"
        + (" keys=dkg" if args.dkg else " keys=dealer")
    )
    if args.mode == "lockstep":
        if args.trace:
            print(
                "== note: --trace instruments the message-passing "
                "path; lockstep mode has no per-node timelines "
                "(flag ignored)"
            )
        if args.obs_port is not None:
            print(
                "== note: --obs-port serves per-validator telemetry; "
                "lockstep mode has no per-node metrics (flag ignored)"
            )
        if args.ingress_port is not None:
            print(
                "== note: --ingress-port serves the per-validator "
                "client API; lockstep mode has no per-node transport "
                "(flag ignored)"
            )
        return _lockstep_main(args, cfg)
    keys = setup_keys(cfg, ids)
    if args.dkg:
        keys = _dkg_rekey(cfg, ids, keys)
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)

    def node_cfg(rank: int) -> Config:
        """Per-node config: telemetry and ingress ports fan out from
        their bases (--obs-port 9100 -> node i scrapes at 9100+i;
        0 = ephemeral).  --ingress-port also mounts the fee-priority
        mempool the client API admits into."""
        if args.obs_port is None and args.ingress_port is None:
            return cfg
        import dataclasses

        fields = {}
        if args.obs_port is not None:
            fields["obs_port"] = (
                args.obs_port + rank if args.obs_port > 0 else 0
            )
        if args.ingress_port is not None:
            fields["ingress_port"] = (
                args.ingress_port + rank if args.ingress_port > 0 else 0
            )
            fields["mempool_capacity"] = max(1024, 4 * args.batch_size)
        return dataclasses.replace(cfg, **fields)

    hosts = {
        i: ValidatorHost(
            node_cfg(rank),
            i,
            ids,
            keys[i],
            batch_log_path=(
                os.path.join(args.log_dir, f"{i}.log")
                if args.log_dir
                else None
            ),
        )
        for rank, i in enumerate(ids)
    }
    addrs = {i: h.listen() for i, h in hosts.items()}
    print(f"== listening: {addrs}")
    if args.obs_port is not None:
        obs_addrs = {
            i: f"127.0.0.1:{h.obs.port}" for i, h in hosts.items()
        }
        print(f"== telemetry (/metrics /healthz /vars): {obs_addrs}")
    if args.ingress_port is not None:
        ingress_addrs = {
            i: f"127.0.0.1:{h.ingress_server.port}"
            for i, h in hosts.items()
        }
        print(f"== client ingress (submit/subscribe): {ingress_addrs}")
    threads = [
        threading.Thread(target=h.connect, args=(addrs,))
        for h in hosts.values()
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    print("== all peers connected")

    # run-unique prefix: with --log-dir, a restarted demo's txs must
    # not collide with the previous run's (already-committed names are
    # dup-filtered by design)
    prefix = b"demo-%d" % time.time_ns()
    txs = [b"%s-tx-%05d" % (prefix, i) for i in range(args.txs)]
    if args.ingress_port is not None:
        # real client path: submit over the ingress gRPC API through
        # the fee-priority mempool, one pipelined stream per node
        from cleisthenes_tpu.transport.ingress import IngressGrpcClient

        ok = 0
        for rank, nid in enumerate(ids):
            client = IngressGrpcClient(
                f"127.0.0.1:{hosts[nid].ingress_server.port}"
            )
            batch = [
                (f"demo-client-{i % 8}", i, 1 + i % 5, tx)
                for i, tx in enumerate(txs)
                if i % args.n == rank
            ]
            acks = client.submit_many(batch)
            ok += sum(1 for a in acks if int(a.status) == 0)
            client.close()
        print(f"== ingress: {ok}/{len(txs)} submits acked OK")
    else:
        for i, tx in enumerate(txs):
            hosts[ids[i % args.n]].submit(tx)

    committed = set()
    t0 = time.monotonic()
    watcher = hosts[ids[0]]
    while committed != set(txs) and time.monotonic() - t0 < args.timeout:
        for h in hosts.values():
            h.propose()
        try:
            epoch, batch = watcher.wait_commit(timeout=2.0)
        except queue.Empty:
            continue
        batch_txs = batch.tx_list()
        committed |= set(batch_txs) & set(txs)
        print(
            f"== epoch {epoch}: committed {len(batch_txs)} txs "
            f"({len(committed)}/{len(txs)} total)"
        )

    snap = watcher.node.metrics.snapshot()
    print(f"== node0 metrics: {snap}")
    if args.trace:
        from cleisthenes_tpu.utils.trace import write_chrome

        events = {
            i: h.node.trace.events()
            for i, h in hosts.items()
            if h.node.trace is not None
        }
        write_chrome(args.trace, events)
        n_events = sum(len(e) for e in events.values())
        print(
            f"== trace: {n_events} events -> {args.trace} "
            "(open at ui.perfetto.dev; validate/report with "
            "python -m tools.tracetool)"
        )
    for h in hosts.values():
        h.stop()
    ok = committed == set(txs)
    print(f"== {'SUCCESS' if ok else 'TIMEOUT'}: {len(committed)}/{len(txs)} txs committed")
    return 0 if ok else 1


def _dkg_rekey(cfg: Config, ids, dealer_keys):
    """Replace the dealer's threshold keys with DKG-generated ones
    (pairwise MAC keys keep the dealer — they are symmetric transport
    secrets, not threshold material; see ops/dkg.py on carriage)."""
    from cleisthenes_tpu.ops import dkg
    from cleisthenes_tpu.protocol.honeybadger import NodeKeys

    tpke_pub, tpke_shares, q1 = dkg.run_dkg(
        n=cfg.n, threshold=cfg.decryption_threshold
    )
    coin_pub, coin_shares, q2 = dkg.run_dkg(n=cfg.n, threshold=cfg.f + 1)
    print(
        f"== DKG complete: {len(q1)}/{cfg.n} qualified dealers (tpke), "
        f"{len(q2)}/{cfg.n} (coin); no trusted dealer"
    )
    return {
        nid: NodeKeys(
            tpke_pub=tpke_pub,
            tpke_share=tpke_shares[i],
            coin_pub=coin_pub,
            coin_share=coin_shares[i],
            mac_keys=dealer_keys[nid].mac_keys,
        )
        for i, nid in enumerate(sorted(ids))
    }


def _lockstep_main(args, cfg: Config) -> int:
    """--mode lockstep: the SPMD executor end to end."""
    from cleisthenes_tpu.protocol.spmd import LockstepCluster

    cluster = LockstepCluster(config=cfg)
    if args.dkg:
        # swap the dealer's threshold keys for DKG-generated ones
        # before any traffic (the --dkg flag was silently ignored in
        # lockstep mode until the round-4 review caught it)
        cluster.keys = _dkg_rekey(cfg, cluster.ids, cluster.keys)
        k0 = cluster.keys[cluster.ids[0]]
        cluster.tpke = cluster.crypto.tpke(k0.tpke_pub)
        cluster.coin = cluster.crypto.coin(k0.coin_pub)
    prefix = b"demo-%d" % time.time_ns()
    txs = [b"%s-tx-%05d" % (prefix, i) for i in range(args.txs)]
    for tx in txs:
        cluster.submit(tx)
    t0 = time.monotonic()
    epochs = cluster.run_epochs()
    wall = time.monotonic() - t0
    committed = set()
    for batch in cluster.committed():
        committed |= set(batch.tx_list()) & set(txs)
    s = cluster.last_stats
    print(
        f"== {epochs} lockstep epoch(s) in {wall:.2f}s; last epoch: "
        + " ".join(
            f"{k}={v:.3f}s" for k, v in s.items() if k.endswith("_s")
        )
    )
    ok = committed == set(txs)
    print(
        f"== {'SUCCESS' if ok else 'INCOMPLETE'}: "
        f"{len(committed)}/{len(txs)} txs committed"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
