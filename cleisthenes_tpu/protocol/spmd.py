"""LockstepCluster: one HBBFT epoch for ALL N validators as batched
array programs — the SPMD answer to BASELINE configs 4 and 5.

The message-passing path (protocol.cluster.SimulatedCluster) executes
the protocol one delivered frame at a time; faithful, asynchronous,
Byzantine-capable — and at N=128 the per-message host work dominates
any accelerator.  This module is the other end of the framework's
design space: under a BENIGN schedule (no crashes, no equivocation,
reliable in-order delivery — the schedule every benchmark of the
reference's lineage measures, docs/HONEYBADGER-EN.md:110-113) the
protocol's data flow is a fixed sequence of synchronous waves, and
each wave is a single batched crypto call over every (node, instance)
pair at once:

  propose   N TPKE encryptions
  RBC       1 batched RS encode (N proposals) + 1 Merkle forest build
            + 1 batched verify of the N^2 distinct (proposer, shard)
            ECHO branches + 1 fused decode/re-encode/root-recheck over
            N proposals
  BBA       per round: N^2 coin-share issues (one batched
            exponentiation dispatch), (f+1) x N CP verifications (one
            dispatch), N Lagrange combines (one dispatch)
  decrypt   N^2 decryption-share issues (one dispatch) + N optimistic
            combines (one dispatch) with ciphertext-tag checks
  commit    the reference dedup/commit rule, one Batch per epoch

Work accounting is the DEDUPLICATED cluster total — each distinct
pure computation once, exactly like the shared-hub CryptoHub memo
(protocol.hub): per-node honest work is preserved, only the
single-process artifact of re-running identical math N times is gone.
Share ISSUANCE is not deduplicable (each node's secret differs) and
runs at full N^2 volume.

Every cryptographic operation is the real one, from the same ops/
kernels the live protocol uses; the commit rule is HoneyBadger's own
(protocol.honeybadger._maybe_commit).  What the lockstep path does NOT
exercise: the wire codec, MAC authentication, asynchronous scheduling,
and fault handling — tests/test_spmd.py cross-validates its committed
output against the full message-passing cluster instead.

The coin is the real threshold VUF: per (instance, round) all N
shares are issued with CP proofs, f+1 verify, and the combined value
decides the round exactly as protocol.bba does — so round counts are
the true geometric distribution, not a stub.
"""

# staticcheck: allow-file[DET001] bench executor: time.perf_counter here
# only fills the returned stats dict (wall-clock observability); no
# timing value ever feeds protocol state, wire bytes, or the commit rule

# staticcheck: allow-file[DET003] the lockstep plane IS its own columnar
# batch layer: every epoch's crypto already runs as a handful of wide
# dispatches with no hub in the loop, which is exactly the discipline
# DET003 protects on the async path

from __future__ import annotations

import collections
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from cleisthenes_tpu.config import Config
from cleisthenes_tpu.core.batch import Batch
from cleisthenes_tpu.ops.backend import get_backend
from cleisthenes_tpu.ops.payload import join_payload, split_payload
from cleisthenes_tpu.ops.tpke import (
    combine_shares_batch,
    issue_shares_batch,
    verify_and_combine_share_groups,
)
from cleisthenes_tpu.protocol.honeybadger import (
    deserialize_ciphertext,
    deserialize_txs,
    serialize_ciphertext,
    serialize_txs,
    setup_keys,
)

# A round decides with probability 1/2 per instance; 64 rounds is
# P ~ 2^-64 per instance — the same class of bound as bba.MAX_ROUNDS.
MAX_COIN_ROUNDS = 64


class LockstepCluster:
    """N validators, synchronous benign schedule, batched waves."""

    def __init__(
        self,
        n: int = 4,
        *,
        config: Optional[Config] = None,
        batch_size: int = 256,
        crypto_backend: str = "cpu",
        key_seed: int = 1,
        member_ids: Optional[Sequence[str]] = None,
        group=None,
        coin_block_doubling: bool = True,
        coin_block_initial: int = 1,
    ) -> None:
        if config is not None:
            if n != 4 and n != config.n:
                raise ValueError(
                    f"n={n} conflicts with config.n={config.n}; pass one"
                )
            self.config = config
        else:
            self.config = Config(
                n=n, batch_size=batch_size, crypto_backend=crypto_backend
            )
        cfg = self.config
        if member_ids is None:
            member_ids = [f"node{i:03d}" for i in range(cfg.n)]
        self.ids: List[str] = sorted(member_ids)
        self._base_key_seed = key_seed
        self._group = group
        self.keys = setup_keys(cfg, self.ids, seed=key_seed, group=group)
        self.crypto = get_backend(cfg)
        k0 = self.keys[self.ids[0]]
        self.tpke = self.crypto.tpke(k0.tpke_pub)
        self.coin = self.crypto.coin(k0.coin_pub)
        self.queues: Dict[str, collections.deque] = {
            nid: collections.deque() for nid in self.ids
        }
        self.committed_batches: List[Batch] = []
        self.epoch = 0
        self._rr = 0
        # b = max(B, n): the reference's batch floor
        # (honeybadger.go:62-104 via protocol.honeybadger)
        self.b = max(cfg.batch_size, cfg.n)
        # doubling coin-round blocks amortize relay RTT; block=1 is
        # the serial comparator for the on-chip A/B (r4 verdict weak
        # #3: speculation's win has to be MEASURED against the relay,
        # not assumed)
        self.coin_block_doubling = coin_block_doubling
        # first block's round count: 1 = the measured-default doubling
        # schedule ([0],[1],[2,3],...); 4 = RTT-aggressive ([0..3],
        # [8-wide],...) — E[decided after 4 rounds] = 15/16 of the
        # roster, so the extra speculative issue mass buys two fewer
        # sequential relay round-trips (chip A/B: AB_COIN_BLOCKS)
        self.coin_block_initial = max(1, int(coin_block_initial))
        self.last_stats: Dict[str, float] = {}

    # -- application surface ----------------------------------------------

    def submit(self, tx: bytes, node_id: Optional[str] = None) -> None:
        if node_id is None:
            node_id = self.ids[self._rr % len(self.ids)]
            self._rr += 1
        self.queues[node_id].append(tx)

    def pending_tx_count(self) -> int:
        return sum(len(q) for q in self.queues.values())

    def committed(self, node_id: Optional[str] = None) -> List[Batch]:
        """Per the agreement property every node's history is the
        same list; ``node_id`` is accepted for SimulatedCluster API
        compatibility."""
        return list(self.committed_batches)

    def reconfigure(
        self,
        join: Sequence[str] = (),
        retire: Sequence[str] = (),
        key_seed: Optional[int] = None,
    ) -> None:
        """The lockstep analogue of the reshare ceremony's ACTIVATION
        boundary: between epochs, swap the roster and rebind fresh
        threshold key material.  The asynchronous plane reaches the
        same switch through the in-band ceremony (PVSS dealings, the
        RCFG record, the frontier-gated activation); the lockstep
        plane models the BENIGN schedule only, so it applies the
        already-agreed outcome as one synchronous step — same roster
        arithmetic (n, f, data shards re-derived under the active
        quorum mode), same commit rule, continuous epoch counter.
        Pending txs queued at a retiring member re-route round-robin
        to the survivors (the message-passing twin's clients fail
        over the same way)."""
        import dataclasses as _dc

        ids = sorted((set(self.ids) | set(join)) - set(retire))
        if not ids:
            raise ValueError("reconfigure would empty the roster")
        stranded: List[bytes] = []
        for nid in retire:
            stranded.extend(self.queues.get(nid, ()))
        cfg = _dc.replace(self.config, n=len(ids), f=None)
        self.config = cfg
        self.ids = ids
        self.keys = setup_keys(
            cfg,
            ids,
            seed=self._next_key_seed() if key_seed is None else key_seed,
            group=self._group,
        )
        self.crypto = get_backend(cfg)
        k0 = self.keys[ids[0]]
        self.tpke = self.crypto.tpke(k0.tpke_pub)
        self.coin = self.crypto.coin(k0.coin_pub)
        self.queues = {
            nid: self.queues.get(nid, collections.deque()) for nid in ids
        }
        self.b = max(cfg.batch_size, cfg.n)
        for tx in stranded:
            self.submit(tx)

    def _next_key_seed(self) -> int:
        """Deterministic proactive-rekey schedule: version v uses
        key_seed + v (the async ceremony derives fresh material from
        the dealings; here the seed schedule stands in for it)."""
        self._key_version = getattr(self, "_key_version", 0) + 1
        return self._base_key_seed + self._key_version

    # -- one epoch ---------------------------------------------------------

    def run_epoch(self) -> Dict[str, float]:
        cfg = self.config
        n, f, k = cfg.n, cfg.f, cfg.data_shards
        ids = self.ids
        group = self.tpke.group
        backend = self.crypto.engine_backend
        mesh = self.crypto.mesh
        stats: Dict[str, float] = {}
        t_all = time.perf_counter()

        # ---- propose: batch select + TPKE encrypt (N ciphertexts) ----
        t0 = time.perf_counter()
        per_node = self.b // n
        my_txs: Dict[str, List[bytes]] = {}
        values: List[bytes] = []
        for nid in ids:
            q = self.queues[nid]
            txs = [q.popleft() for _ in range(min(per_node, len(q)))]
            my_txs[nid] = txs
            ct = self.tpke.encrypt(serialize_txs(txs))
            values.append(serialize_ciphertext(ct, group))
        stats["propose_s"] = time.perf_counter() - t0

        # ---- RBC: encode + forest + N^2 branch verify + decode ----
        t0 = time.perf_counter()
        mats = [split_payload(v, k) for v in values]
        L = max(m.shape[1] for m in mats)
        data = np.zeros((n, k, L), dtype=np.uint8)
        for i, m in enumerate(mats):
            data[i, :, : m.shape[1]] = m
        full = self.crypto.erasure.encode_batch(data)  # (n, n, L)
        trees = self.crypto.merkle.build_batch(full)
        roots = [t.root for t in trees]
        stats["rbc_encode_s"] = time.perf_counter() - t0

        # the N^2 distinct ECHO-phase proofs (docs/HONEYBADGER-EN.md:96),
        # one batched verify — the deduplicated receiver-side work
        t0 = time.perf_counter()
        root_arr = np.repeat(
            np.frombuffer(b"".join(roots), dtype=np.uint8).reshape(n, 32),
            n,
            axis=0,
        )
        leaves = np.ascontiguousarray(full.reshape(n * n, L))
        depth = trees[0].depth
        branches = np.zeros((n * n, depth, 32), dtype=np.uint8)
        leaf_idx = np.arange(n)
        for i, tree in enumerate(trees):
            for d_ in range(depth):
                # sibling of leaf j at depth d_ is level[d_][(j>>d_)^1]
                branches[i * n : (i + 1) * n, d_] = tree.levels[d_][
                    (leaf_idx >> d_) ^ 1
                ]
        indices = np.tile(np.arange(n), n)
        ok = self.crypto.merkle.verify_batch(
            root_arr, leaves, branches, indices
        )
        if not bool(np.all(ok)):
            raise AssertionError("honest branch failed verification")
        stats["rbc_verify_s"] = time.perf_counter() - t0

        # delivery: fused decode + re-encode + root recheck over all N
        t0 = time.perf_counter()
        idx_arr = np.tile(np.arange(k), (n, 1))
        shard_arr = np.ascontiguousarray(full[:, :k, :])
        dec_data, dec_roots, _disp = self.crypto.decode_recheck_batch(
            idx_arr, shard_arr
        )
        delivered: List[bytes] = []
        for i in range(n):
            if dec_roots[i].tobytes() != roots[i]:
                raise AssertionError("decode root recheck failed")
            delivered.append(join_payload(dec_data[i]))
        stats["rbc_decode_s"] = time.perf_counter() - t0

        # ---- BBA: every instance gets input 1 (all RBCs delivered);
        # vals == {1} each round, so the instance decides when its real
        # threshold coin tosses 1 (docs/BBA-EN.md:163-181).
        #
        # Rounds run in DOUBLING BLOCKS — [0], [1], [2,3], [4..7], … —
        # each block one issue dispatch + one fused verify/combine
        # dispatch for every (instance, round) pair in it.  A round-r
        # coin share is a deterministic VUF of (epoch, proposer, r),
        # independent of any protocol state, so precomputing a block
        # for instances that may decide mid-block only wastes a
        # BOUNDED slice of issue mass (~N^2/4 expected, ~12% over the
        # sequential minimum — the undecided set halves each round
        # while block sizes double), and the number of sequential
        # device waves falls from E[max rounds] ~ log2 N + 2 to
        # O(log log-rounds): 7 rounds of N=128 take 4 waves x 2
        # dispatches instead of 7 x 3.  (The round-3 flat-speculation
        # knob lost on the relay because it issued EVERY round for
        # EVERY instance; the doubling schedule keeps the waste
        # proportional to the tail, not the roster.)
        t0 = time.perf_counter()
        coin_pub = self.coin.pub
        coin_vks = coin_pub.verification_keys
        rounds_used = 0
        coin_issues = 0
        coin_verifies = 0
        undecided = list(range(n))
        coin_bits: Dict[tuple, bool] = {}  # (inst, rnd) -> toss

        # the decrypt wave (N^2 share issues + N optimistic combines)
        # depends only on the RBC-delivered ciphertexts, never on the
        # coin — so its issue items ride BBA round 0's issue dispatch
        # and its combines ride round 0's fused verify/combine
        # dispatch: the whole wave costs ZERO extra device round-trips
        tpke_pub = self.tpke.pub
        tpke_vks = tpke_pub.verification_keys
        cts = [deserialize_ciphertext(v, group) for v in delivered]
        dec_items = []
        for ct in cts:
            context = self.tpke.context(ct)
            for nid in ids:
                sec = self.keys[nid].tpke_share
                dec_items.append(
                    (sec, ct.c1, context, tpke_vks[sec.index - 1])
                )
        # riding round 0 requires one shared Lagrange threshold;
        # distinct thresholds (non-default configs) fall back to a
        # separate decrypt wave after BBA
        fuse_dec = tpke_pub.threshold == coin_pub.threshold
        dec_subsets: List[list] = []

        def run_rounds(rnd_list, inst_list, dec=False):
            """Issue + fused verify/combine + toss for every
            (inst, rnd) pair — two dispatches total; fills coin_bits.
            With ``dec``, the decrypt wave's issues and combines ride
            the same two dispatches."""
            nonlocal coin_issues, coin_verifies
            items = []
            metas = []
            for rnd in rnd_list:
                for inst in inst_list:
                    coin_id = b"%d|%s|%d" % (
                        self.epoch, ids[inst].encode(), rnd,
                    )
                    pub, base, context = self.coin.group_params(coin_id)
                    metas.append((inst, rnd, coin_id, pub, base, context))
                    for nid in ids:
                        sec = self.keys[nid].coin_share
                        items.append(
                            (sec, base, context, coin_vks[sec.index - 1])
                        )
            n_coin = len(items)
            if dec:
                items = items + dec_items
            shares = issue_shares_batch(
                items, group=group, backend=backend, mesh=mesh
            )
            coin_issues += n_coin
            if dec:
                dec_shares = shares[n_coin:]
                dec_subsets.extend(
                    dec_shares[i * n : i * n + tpke_pub.threshold]
                    for i in range(len(cts))
                )
            # receivers verify the first f+1 pooled shares per
            # instance (the honest-case minimum) and combine the same
            # subset — one fused dispatch for both
            groups = []
            subsets = []
            for mi, (inst, rnd, coin_id, pub, base, context) in enumerate(
                metas
            ):
                sub = shares[mi * n : mi * n + (f + 1)]
                subsets.append(sub)
                groups.append((pub, base, sub, context))
            verdicts, _sigmas, _dec_vals = verify_and_combine_share_groups(
                groups,
                coin_pub.threshold,
                backend=backend,
                mesh=mesh,
                combine_only_sets=dec_subsets if dec else (),
                combine_only_group=group,
            )
            coin_verifies += sum(len(v) for v in verdicts)
            if not all(all(v) for v in verdicts):
                raise AssertionError("honest coin share failed CP check")
            for (inst, rnd, coin_id, *_rest), sub in zip(metas, subsets):
                # pure memo hit on the fused combine: no dispatch
                coin_bits[(inst, rnd)] = self.coin.toss(coin_id, sub)

        next_rnd = 0
        block = self.coin_block_initial
        coin_waves = 0
        while undecided and next_rnd < MAX_COIN_ROUNDS:
            rnds = range(
                next_rnd, min(next_rnd + block, MAX_COIN_ROUNDS)
            )
            run_rounds(rnds, undecided, dec=fuse_dec and next_rnd == 0)
            coin_waves += 1
            for rnd in rnds:
                rounds_used = rnd + 1
                undecided = [
                    inst
                    for inst in undecided
                    if not coin_bits[(inst, rnd)]
                ]
                if not undecided:
                    break
            next_rnd = rnds.stop
            if self.coin_block_doubling:
                block = block * 2 if next_rnd > 1 else 1
        if undecided:
            raise AssertionError(
                f"instances undecided after {MAX_COIN_ROUNDS} rounds"
            )
        stats["bba_s"] = time.perf_counter() - t0
        stats["bba_rounds"] = rounds_used
        stats["coin_waves"] = coin_waves
        stats["coin_issues"] = coin_issues
        stats["coin_verifies"] = coin_verifies
        # attribution note: with dec_fused=1 the decrypt wave's device
        # work is timed inside bba_s (it rides round 0's dispatches)
        # and decrypt_s measures only the memo-hit tail — not
        # comparable with pre-fusion artifacts' decrypt_s
        stats["dec_fused"] = float(fuse_dec)

        # ---- decrypt tail: combines are memo hits from round 0 ----
        t0 = time.perf_counter()
        if not fuse_dec:
            dec_shares = issue_shares_batch(
                dec_items, group=group, backend=backend, mesh=mesh
            )
            dec_subsets.extend(
                dec_shares[i * n : i * n + tpke_pub.threshold]
                for i in range(len(cts))
            )
            # optimistic combine (protocol.honeybadger._try_decrypt):
            # the ciphertext tag authenticates the KEM value, so the
            # honest case spends zero CP verifications on dec shares
            combine_shares_batch(
                dec_subsets,
                tpke_pub.threshold,
                group=group,
                backend=backend,
                mesh=mesh,
            )
        decrypted: Dict[str, List[bytes]] = {}
        for i, (ct, sub) in enumerate(zip(cts, dec_subsets)):
            plain = self.tpke.combine(ct, sub)  # memo hit + tag check
            decrypted[ids[i]] = deserialize_txs(plain)
        stats["decrypt_s"] = time.perf_counter() - t0
        stats["dec_issues"] = len(dec_items)

        # ---- commit: the reference dedup/ordering rule ----
        # (protocol.honeybadger._maybe_commit)
        t0 = time.perf_counter()
        seen: set = set()
        contributions: Dict[str, List[bytes]] = {}
        for proposer in sorted(decrypted):
            mine = []
            for tx in decrypted[proposer]:
                if tx not in seen:
                    seen.add(tx)
                    mine.append(tx)
            if mine:
                contributions[proposer] = mine
        self.committed_batches.append(Batch(contributions=contributions))
        stats["commit_s"] = time.perf_counter() - t0

        stats["epoch_s"] = time.perf_counter() - t_all
        self.epoch += 1
        self.last_stats = stats
        return stats

    def run_epochs(self, max_epochs: int = 50) -> int:
        """Drive epochs until every queue drains (or the cap)."""
        for e in range(max_epochs):
            self.run_epoch()
            if self.pending_tx_count() == 0:
                return e + 1
        return max_epochs


__all__ = ["LockstepCluster", "MAX_COIN_ROUNDS"]
