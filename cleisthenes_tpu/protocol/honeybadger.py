"""HoneyBadger: the top-level consensus object and epoch loop.

Completes the reference's L4 (reference honeybadger.go): the tx FIFO
buffer, the batch policy b = max(batchSize, n) with uniform sampling of
b/n candidates (honeybadger.go:36-49, 62-104; docs/HONEYBADGER-EN.md:
49-56), and the missing epoch pipeline the TODOs call for
(honeybadger.go:19-21, 57-59):

  per epoch e (docs/HONEYBADGER-EN.md:58-65):
    batch   <- select B/N random txs from the queue head
    ct      <- TPKE.Encrypt(master_pk, batch)      [censorship resistance]
    ACS_e   <- input ct; output {proposer: ct_j}
    share   -> broadcast TPKE.DecShare for every ct_j in the output
    commit  <- TPKE.Decrypt each ct_j from f+1 verified shares;
               union, dedupe, deterministic order -> committed Batch

Epoch demux keeps a sliding window of live epoch states: messages for
future epochs (peers ahead of us) are routed into lazily-created
states, the role of the reference's IncomingRequestRepository
(bba/request.go:28-32); states a few epochs behind stay alive so
lagging peers still get our participation, then are GC'd.

Trusted-dealer key setup (``setup_keys``) issues the TPKE and coin
share sets plus the envelope-MAC master secret — the standard HBBFT
deployment model (docs/THRESHOLD_ENCRYPTION-EN.md:33: "SetUp").
"""

from __future__ import annotations

import dataclasses
import hashlib
import struct
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from cleisthenes_tpu.config import MAX_PIPELINE_DEPTH, Config
from cleisthenes_tpu.core.batch import Batch
from cleisthenes_tpu.core.ledger import (
    decode_batch_body,
    decode_ordered_body,
    encode_batch_body,
    encode_ordered_body,
)
from cleisthenes_tpu.core.queue import TxQueue
from cleisthenes_tpu.protocol.hub import _Memo
from cleisthenes_tpu.ops import tpke as tpke_mod
from cleisthenes_tpu.ops.backend import BatchCrypto, get_backend
from cleisthenes_tpu.ops.coin import CommonCoin
from cleisthenes_tpu.ops.tpke import (
    Ciphertext,
    DhShare,
    SharePool,
    ThresholdPublicKey,
    ThresholdSecretShare,
    Tpke,
    issue_shares_batch,
)
from cleisthenes_tpu.protocol.acs import ACS
from cleisthenes_tpu.utils.determinism import proposal_rng
from cleisthenes_tpu.utils.log import NodeLogger
from cleisthenes_tpu.utils.metrics import Metrics
from cleisthenes_tpu.utils.trace import maybe_recorder
from cleisthenes_tpu.transport.broadcast import CoalescingBroadcaster
from cleisthenes_tpu.transport.message import (
    BbaBatchPayload,
    BbaPayload,
    BundlePayload,
    CatchupOrdPayload,
    CatchupReqPayload,
    CatchupRespPayload,
    CoinBatchPayload,
    CoinPayload,
    DecShareBatchPayload,
    DecSharePayload,
    EchoBatchPayload,
    LanePayload,
    Message,
    RbcPayload,
    ReadyBatchPayload,
    ResharePayload,
)

# Sliding epoch window: how many settled epochs stay responsive for
# lagging peers, and how far ahead a fast peer may pull us.
KEEP_BEHIND = 2
EPOCH_HORIZON = 8
# the K-deep pipeline window (Config.pipeline_depth) must fit the
# demux window's forward horizon, or an in-flight epoch's traffic
# could not reach a same-frontier peer (Config validates depth
# against MAX_PIPELINE_DEPTH; this pins the two constants together)
assert MAX_PIPELINE_DEPTH <= EPOCH_HORIZON
# epochs of committed-tx memory for lazy duplicate filtering
COMMITTED_MEMORY_EPOCHS = 64
# CATCHUP serving cap: epochs one CatchupReq answers with (the
# requester chases the next window as it adopts), and how far past a
# node's own frontier it tallies responses (bounds tally memory
# against a Byzantine peer spraying far-future epochs)
CATCHUP_MAX_EPOCHS = 32
CATCHUP_WINDOW = 128
# serving-side amplification guard: a sender whose from_epoch does not
# advance past the window already served it gets this many repeat
# serves, re-armed on every local epoch advance (an 8-byte CatchupReq
# otherwise buys CATCHUP_MAX_EPOCHS full batch bodies — a free 32x
# bandwidth/CPU amplifier for a Byzantine member looping requests).
# Counted, not clocked: seeded deterministic runs replay exactly.
CATCHUP_REPEAT_BUDGET = 2
# a laggard whose CatchupReq (or its responses) was lost re-broadcasts
# after every this-many further sightings of far-ahead traffic — a
# deterministic, traffic-driven retry (no timers in the protocol plane)
CATCHUP_RENUDGE_EVERY = 32
# reduced-quorum stall watchdog (Config.reduced_quorum only): forced
# catch-up chases per stuck settled frontier, fired at quiet idle
# boundaries (no inbound since the previous idle callback while
# settled < live frontier), re-armed whenever settlement advances.
# At n-f quorums the READY amplification threshold (f+1) EQUALS the
# delivery quorum, so Bracha totality no longer follows from honest
# traffic alone: a node that missed a lossy coalition member's frames
# can sit one READY short of an instance the rest of the roster
# delivered, wedging its ACS forever in an otherwise quiescent
# cluster.  The repair is retrieval, not lower thresholds (lowering
# amplification below f+1 would let an attested-but-lying coalition
# lock honest READYs onto a fabricated root): chase the committed
# batches through CATCHUP, whose f+1 byte-identical adoption rule is
# loss-tolerant under retry.  Counted, not clocked — seeded runs
# replay exactly.  Baseline (3f+1) arms never fire this: totality
# holds from honest traffic alone, and gating on the flag keeps every
# historical schedule byte-identical.
CATCHUP_STALL_BUDGET = 4

MAX_TXS_PER_LIST = 1_000_000


# ---------------------------------------------------------------------------
# serialization: tx lists and ciphertexts (RBC values are opaque bytes)
# ---------------------------------------------------------------------------


def serialize_txs(txs: Sequence[bytes]) -> bytes:
    out = [struct.pack(">I", len(txs))]
    for tx in txs:
        out.append(struct.pack(">I", len(tx)))
        out.append(tx)
    return b"".join(out)


def make_tx_parse_memo() -> _Memo:
    """Content-keyed parse memo for CLUSTER SIMULATIONS: every in-proc
    node decrypts the SAME plaintext per proposer and re-parses it
    (N x N parses of N distinct blobs per epoch; ~1.7 s at
    N=64/B=16k).  Keyed by digest — blobs are distinct bytes objects
    per node, so id-keying cannot hit.  A real per-node deployment
    parses N distinct blobs that never recur, so it passes NO memo
    (the default): pinning megabyte blobs and hashing every parse
    would be pure overhead there — same reasoning, and the same
    seam, as CryptoHub's dedup flag.  Instance-scoped (the cluster
    shares ONE across its nodes and drops it with the cluster), never
    process-global."""
    return _Memo(1 << 10)


def deserialize_txs(
    data: bytes, memo: Optional[_Memo] = None
) -> List[bytes]:
    if memo is not None and len(data) >= 256:
        # small blobs: the digest costs about as much as the parse
        key = hashlib.sha256(data).digest()
        hit = memo.map.get(key)
        if hit is not None:
            return list(hit)
        out = _deserialize_txs_uncached(data)
        memo.put(key, tuple(out))
        return out
    return _deserialize_txs_uncached(data)


def _deserialize_txs_uncached(data: bytes) -> List[bytes]:
    if len(data) < 4:
        raise ValueError("truncated tx list")
    (count,) = struct.unpack_from(">I", data, 0)
    if count > MAX_TXS_PER_LIST:
        raise ValueError(f"tx count {count} exceeds cap")
    off = 4
    txs: List[bytes] = []
    for _ in range(count):
        if off + 4 > len(data):
            raise ValueError("truncated tx list")
        (ln,) = struct.unpack_from(">I", data, off)
        off += 4
        if off + ln > len(data):
            raise ValueError("truncated tx")
        txs.append(data[off : off + ln])
        off += ln
    if off != len(data):
        raise ValueError("trailing bytes in tx list")
    return txs


def serialize_ciphertext(ct: Ciphertext, group=None) -> bytes:
    """c1 is fixed-width at the roster's group size (a roster-wide
    constant: every node's NodeKeys carry the same GroupParams, so the
    wire format is unambiguous — the modulus seam reaches the protocol
    plane end to end)."""
    group = group or tpke_mod.DEFAULT_GROUP
    return (
        ct.c1.to_bytes(group.nbytes, "big")
        + struct.pack(">I", len(ct.c2))
        + ct.c2
        + ct.tag
    )


def deserialize_ciphertext(data: bytes, group=None) -> Ciphertext:
    group = group or tpke_mod.DEFAULT_GROUP
    nb = group.nbytes
    if len(data) < nb + 4:
        raise ValueError("truncated ciphertext")
    c1 = int.from_bytes(data[:nb], "big")
    if not tpke_mod.is_group_element(c1, group):
        # c1 outside the prime-order subgroup (0, identity, order-2,
        # non-residue) would make every honest node's decryption share
        # fail verification forever — consensus-halting.  Raising here
        # routes the proposer into the deterministic-exclusion junk
        # path every correct node takes identically (ADVICE.md round-1
        # high finding).
        raise ValueError("ciphertext c1 not in the prime-order subgroup")
    (ln,) = struct.unpack_from(">I", data, nb)
    if nb + 4 + ln + 32 != len(data):
        raise ValueError("bad ciphertext framing")
    return Ciphertext(
        c1=c1, c2=data[nb + 4 : nb + 4 + ln], tag=data[nb + 4 + ln :]
    )


# ---------------------------------------------------------------------------
# trusted-dealer setup
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class NodeKeys:
    """Everything one validator needs from the dealer."""

    tpke_pub: ThresholdPublicKey
    tpke_share: Optional[ThresholdSecretShare]
    coin_pub: ThresholdPublicKey
    coin_share: Optional[ThresholdSecretShare]
    # this node's pairwise MAC keys: peer_id -> k_{self,peer}.  The
    # dealer's master never leaves setup_keys, so no single member can
    # reconstruct another pair's key (ADVICE.md round-1 high finding).
    mac_keys: Dict[str, bytes]
    # dynamic membership (protocol.reconfig): a JOINER's static-DH
    # enrollment secret — its share-blob decryption and MAC-derivation
    # identity until the reshare ceremony hands it real threshold
    # shares.  None for dealer-provisioned roster members (their coin
    # share doubles as the DH identity).  A joiner boots with
    # tpke_share/coin_share None: it holds no threshold material
    # before its activation epoch.
    enroll_secret: Optional[int] = None


def setup_keys(
    config: Config,
    member_ids: Sequence[str],
    seed: Optional[int] = None,
    group=None,
) -> Dict[str, NodeKeys]:
    """TPKE.SetUp + coin setup + MAC master for the whole roster
    (docs/THRESHOLD_ENCRYPTION-EN.md:33; share x-coordinates follow
    sorted roster order).

    With ``seed=None`` (production) all key material comes from the
    OS CSPRNG.  A seed makes the whole key set reproducible — for
    tests and benchmarks ONLY: a seeded deployment's MAC and shares
    are computable by anyone who knows the seed.
    """
    members = sorted(member_ids)
    if len(members) != config.n:
        raise ValueError(f"roster size {len(members)} != n={config.n}")
    group = group or tpke_mod.DEFAULT_GROUP
    tpke_pub, tpke_shares = tpke_mod.deal(
        config.n, config.decryption_threshold, seed=seed, group=group
    )
    coin_pub, coin_shares = tpke_mod.deal(
        config.n,
        config.f + 1,
        seed=None if seed is None else seed + 1,
        group=group,
    )
    if seed is None:
        import secrets

        # the envelope-MAC master MUST be unpredictable; it never
        # influences protocol scheduling, so it is sanctioned entropy:
        mac_master = secrets.token_bytes(32)  # staticcheck: allow[DET001] dealer keygen
    else:
        mac_master = b"cleisthenes-tpu-test-mac|%d" % seed
    # dealer-side pairwise key schedule: node i receives ONLY the keys
    # of pairs it belongs to; the master itself is never distributed
    from cleisthenes_tpu.transport.base import HmacAuthenticator

    mac_key_maps = {
        m: HmacAuthenticator.key_map(mac_master, m, members) for m in members
    }
    return {
        m: NodeKeys(
            tpke_pub=tpke_pub,
            tpke_share=tpke_shares[i],
            coin_pub=coin_pub,
            coin_share=coin_shares[i],
            mac_keys=mac_key_maps[m],
        )
        for i, m in enumerate(members)
    }


# ---------------------------------------------------------------------------
# per-epoch state
# ---------------------------------------------------------------------------


# the payload classes the ACS layer consumes (set-membership dispatch:
# _serve_payload runs O(N^2) times per wave and the isinstance chain
# was measurable at N=64)
_ACS_PAYLOADS = frozenset(
    (
        RbcPayload,
        BbaPayload,
        CoinPayload,
        BbaBatchPayload,
        CoinBatchPayload,
        ReadyBatchPayload,
        EchoBatchPayload,
    )
)


def _logical_count(p) -> int:
    """Logical protocol messages in one payload: a columnar batch
    carries one vote/share PER INSTANCE, and msgs_in counts logical
    messages so throughput numbers stay comparable across the
    scalar->columnar wire change."""
    if p.__class__ is LanePayload:
        p = p.inner  # lane framing is transport plumbing, not a message
    proposers = getattr(p, "proposers", None)
    return len(proposers) if proposers is not None else 1


def _logical_count_many(items) -> int:
    return sum(_logical_count(p) for p in items)


class _RosterView:
    """One roster version's resolved runtime state: the derived
    Config (n/f/thresholds), the sorted member table, this node's key
    set and the crypto service objects bound to it.  Every epoch-
    scoped structure — ACS (and its EchoBank/VoteBank), the demux
    window, the dec-share pools, the WaveRouter's dispatch targets —
    resolves n/f/keys through the EPOCH's view instead of the
    construction-time constants (the dynamic-membership refactor;
    staticcheck DET005 gates regressions).

    ``keys``/``tpke``/``coin`` are None exactly when ``local`` is
    False (this node is not a member under the version — a joiner
    before its activation epoch, or a retiree after): such a node
    never constructs protocol state for the version's epochs.
    """

    __slots__ = (
        "rv",
        "config",
        "member_ids",
        "member_set",
        "keys",
        "crypto",
        "tpke",
        "coin",
        "local",
    )

    def __init__(
        self, rv, config, member_ids, keys, crypto, tpke, coin
    ) -> None:
        self.rv = rv
        self.config = config
        self.member_ids: Tuple[str, ...] = tuple(sorted(member_ids))
        self.member_set = frozenset(self.member_ids)
        self.keys = keys
        # the version's OWN BatchCrypto: the erasure coder is sized
        # (n, k = n - 2f) per roster, so RBC under a resized roster
        # encodes/decodes with the right geometry
        self.crypto = crypto
        self.tpke = tpke
        self.coin = coin
        self.local = keys is not None


class _EpochState:
    __slots__ = (
        "acs",
        "view",
        "proposed",
        "my_txs",
        "output",
        "ciphertexts",
        "dec_shares",
        "decrypted",
        "opt_failed",
        "opt_short",
        "committed",
        "ordered",
        "shares_issued",
        "t_ordered",
    )

    def __init__(
        self, acs: Optional[ACS], view: Optional[_RosterView] = None
    ) -> None:
        # ``acs`` is None for SETTLE-ONLY states (two-frontier mode):
        # epochs whose ordering is already durable — WAL replay after a
        # crash between COrd and CLOG, or COrd catch-up adoption — that
        # only need the trailing decryption, never a consensus re-run.
        self.acs = acs
        # the roster version this epoch runs under (set by every
        # construction site; epoch-scoped membership/threshold/key
        # reads resolve through it)
        self.view = view
        self.proposed = False
        self.my_txs: List[bytes] = []
        self.output: Optional[Dict[str, bytes]] = None
        self.ciphertexts: Dict[str, Ciphertext] = {}
        # proposer -> sender-keyed verified-share pool
        self.dec_shares: Dict[str, SharePool] = {}
        # proposer -> tx list, or None = deterministically excluded
        self.decrypted: Dict[str, Optional[List[bytes]]] = {}
        # proposers whose optimistic (unverified-subset) combine hit a
        # bad tag: their shares take the CP-verified path instead
        self.opt_failed: Set[str] = set()
        # proposers whose pool hit the size threshold without enough
        # DISTINCT Shamir indices (duplicate-index share from a
        # Byzantine sender): later adds must keep re-probing, the
        # exact-crossing trigger alone would stall them forever
        self.opt_short: Set[str] = set()
        self.committed = False
        # two-frontier bookkeeping (Config.order_then_settle): the
        # ciphertext ordering is durable / this node's dec shares went
        # out / the trace clock at ordering (decrypt_lag span start)
        self.ordered = False
        self.shares_issued = False
        self.t_ordered = 0.0


class _CountingBroadcaster:
    """Wraps the node's PayloadBroadcaster to count outbound protocol
    PAYLOADS (one per logical message per receiver).  Envelope counts
    live at the transport (ChannelNetwork.messages_posted): with
    coalescing, a wave's payloads share far fewer envelopes."""

    def __init__(self, inner, metrics: Metrics, n_members: int) -> None:
        self._inner = inner
        self._metrics = metrics
        self._n = n_members

    def broadcast(self, payload) -> None:
        self._metrics.msgs_out.inc(self._n)
        self._inner.broadcast(payload)

    def send_to(self, member_id: str, payload) -> None:
        self._metrics.msgs_out.inc()
        self._inner.send_to(member_id, payload)


class _LaneTagger:
    """Outbound lane framing for sibling lanes (Config.lanes > 1).

    A lane-k (k > 0) HoneyBadger's protocol payloads wrap in
    ``LanePayload(k, inner)`` BEFORE entering the node's ONE shared
    CoalescingBroadcaster, so all S lanes' traffic of a turn rides the
    same per-receiver bundle (one flush, one envelope per receiver per
    wave — the dispatch-flatness requirement).  The coalescer's
    columnar merge understands the tag: runs of same-lane same-kind
    payloads still merge into one lane-wrapped column.  Lane 0 never
    wraps (its wire frames stay byte-identical to the single-lane
    build), and the receiver's demux routes lane k frames into its
    lane-k sibling."""

    __slots__ = ("_inner", "_lane")

    def __init__(self, inner, lane: int) -> None:
        self._inner = inner
        self._lane = lane

    def broadcast(self, payload) -> None:
        self._inner.broadcast(LanePayload(self._lane, payload))

    def send_to(self, member_id: str, payload) -> None:
        self._inner.send_to(member_id, LanePayload(self._lane, payload))

    def set_members(self, member_ids) -> None:
        # membership is the PRIMARY coalescer's concern (dynamic
        # membership is unsupported at lanes > 1 anyway)
        pass


class HoneyBadger:
    """One validator node (reference honeybadger.go:18-34 + the absent
    epoch driver).  Implements transport.base.Handler, plus the
    wave-ingest extension ``serve_wave`` (Config.wave_routing)."""

    # the demux window's forward horizon, re-exported as a class
    # attribute so the WaveRouter reads it off its owner without a
    # circular module import
    EPOCH_HORIZON = EPOCH_HORIZON

    def __init__(
        self,
        *,
        config: Config,
        node_id: str,
        member_ids: Sequence[str],
        keys: NodeKeys,
        out,
        auto_propose: bool = True,
        batch_log=None,
        hub=None,
        tx_parse_memo: Optional[_Memo] = None,
        behavior=None,
        authenticator=None,
        joining: bool = False,
        roster_version_base: int = 0,
        lane: int = 0,
        _primary=None,
    ) -> None:
        self.config = config
        # -- horizontal shard-out (Config.lanes, ISSUE 20) ---------------
        # ``lane`` is this instance's shard index; ``_primary`` is the
        # lane-0 instance when THIS instance is a sibling lane it
        # constructed (internal — external construction sites always
        # build lane 0, which builds its own siblings below).  The
        # scope id qualifies every hub scope key with the lane so the
        # S sibling lanes sharing one hub GC only their own epochs'
        # clients; lane 0 keeps the bare node id, byte-identical to
        # the single-lane build.
        if not (0 <= lane < config.lanes):
            raise ValueError(f"lane={lane} out of range for lanes={config.lanes}")
        if (_primary is None) != (lane == 0):
            raise ValueError("sibling lanes are built by their lane-0 primary")
        self.lane = lane
        self._primary = _primary
        self._scope_id = node_id if lane == 0 else (node_id, lane)
        # trace-event lane tag: empty at lanes=1 so the historical
        # event shapes (and goldens) stay byte-identical
        self._lane_kw = {"lane": lane} if config.lanes > 1 else {}
        # populated at the END of __init__ (lane-0 primary only):
        # sibling lane instances + the cross-lane merge cursor
        self.lanes: List["HoneyBadger"] = [self]
        self._merge = None
        # cluster simulations pass one shared make_tx_parse_memo()
        # across all nodes; real deployments leave it None
        self._tx_parse_memo = tx_parse_memo
        self.node_id = node_id
        self.members: List[str] = sorted(member_ids)
        self._member_set = frozenset(self.members)
        if node_id not in self.members and not joining:
            # ``joining=True`` is the dynamic-membership bootstrap: a
            # JOINER constructs against the current roster it is NOT a
            # member of, adopts the log via CATCHUP, and participates
            # from the activation epoch the reshare ceremony fixes
            raise ValueError(f"{node_id!r} not in roster")
        self.keys = keys
        self.auto_propose = auto_propose
        # the node's envelope-MAC authenticator (optional): dynamic
        # membership installs joiner pair keys / drops retired ones
        # through it; None keeps the historical fixed-roster behavior
        self._authenticator = authenticator

        self.crypto: BatchCrypto = get_backend(config)
        self.tpke = self.crypto.tpke(keys.tpke_pub)
        self.coin = self.crypto.coin(keys.coin_pub)
        # the per-node batched-crypto service every protocol instance
        # (RBC/BBA across all live epochs, plus this node's TPKE
        # decryption pools) shares — SURVEY.md §7 hard part 3
        from cleisthenes_tpu.protocol.hub import CryptoHub

        # ``hub`` may be SHARED by every in-proc validator of a
        # simulated cluster: one wave-deferred flush then executes the
        # WHOLE roster's crypto in single cluster-wide dispatches — the
        # north star's "vmap across all N validators" framing, and the
        # only sane shape under a remote TPU attachment where dispatch
        # round-trips dominate.  Scopes are node-qualified so one
        # node's epoch GC never drops a peer's clients.  Real
        # deployments (one validator per host) keep per-node hubs.
        self.hub = CryptoHub(self.crypto) if hub is None else hub
        # permanent: dec-share pools (lane-qualified under shard-out)
        self.hub.register((self._scope_id, "hb"), self)

        self.que = TxQueue()
        self._pending_coin_issues: List[tuple] = []
        self.epoch = 0
        # b = max(batchSize, n) (reference honeybadger.go:36-49)
        self.b = max(config.batch_size, config.n)
        self.committed_batches: List[Batch] = []
        self.on_commit: Optional[Callable[[int, Batch], None]] = None
        self.metrics = Metrics()
        # coin-issue dispatch tallies -> snapshot()["hub"] (a shared
        # hub reports cluster-wide numbers, like hub_dispatches; the
        # counters move on BOTH egress arms — see _drain_coin_issues)
        self.metrics.set_hub_stats(
            lambda: {
                "coin_share_batches": self.hub.coin_issue_batches,
                "coin_share_items": self.hub.coin_issue_items,
            }
        )
        self.log = NodeLogger(node_id, "hb")
        # flight recorder (utils/trace.py): None when Config.trace is
        # off — every instrumentation site below guards on that, so
        # the disabled path is one attribute load + identity check
        # sibling lanes share the primary's recorder: one node, one
        # timeline — lane-scoped events carry the ``lane`` tag instead
        self.trace = (
            maybe_recorder(config, node_id)
            if _primary is None
            else _primary.trace
        )
        if self.trace is not None:
            self.metrics.set_trace_stats(self.trace.stats)
            if hub is None:  # a private hub reports on our timeline
                self.hub.trace = self.trace
        # messages served since the last transport idle callback (the
        # wave-size series the trace's "transport/wave" events carry)
        self._trace_wave_msgs = 0
        # Outbound path: protocol payloads -> per-receiver coalescing
        # buffers -> (at wave boundaries) bundled envelopes on the
        # inner transport.  In self-draining mode (no transport idle
        # callback) buffers flush at the end of every entry point; a
        # transport that calls transport_manages_idle() moves flushing
        # to its quiescence point for whole-wave bundles.
        if _primary is None:
            self._coalesce = CoalescingBroadcaster(
                out,
                self.members,
                trace=self.trace,
                egress_columnar=config.egress_columnar,
            )
        else:
            # ONE coalescer per node: sibling lanes tag their payloads
            # (see _LaneTagger below) and ride the primary's
            # per-receiver buffers, so a wave's flush ships ALL S
            # lanes' traffic in the same bundles — S lanes must not
            # multiply flushes or envelopes
            self._coalesce = _primary._coalesce
        self._transport_managed = False
        # semantic-adversary seam (protocol.byzantine): when a behavior
        # is mounted, every outbound payload is offered to it once per
        # receiver BEFORE coalescing, so a Byzantine node can lie to
        # each peer separately while its frames still MAC and bundle
        # exactly like honest traffic.  None (the default) adds nothing
        # to the path.
        self.behavior = behavior
        outward = (
            self._coalesce
            if _primary is None
            else _LaneTagger(self._coalesce, lane)
        )
        if behavior is not None:
            from cleisthenes_tpu.protocol.byzantine import (
                BehaviorBroadcaster,
            )

            outward = BehaviorBroadcaster(
                outward, self.members, behavior
            )
            behavior.attach(self)
        self.out = _CountingBroadcaster(
            outward, self.metrics, len(self.members)
        )
        self._epochs: Dict[int, _EpochState] = {}
        # epoch -> COrd body bytes for every epoch this node ORDERED
        # (locally or via COrd catch-up): the ordered CATCHUP serving
        # store and the cross-node byte-identity invariant's witness.
        # Epochs adopted via plaintext catch-up alone have no entry;
        # entries one serving window behind the settled frontier are
        # pruned (_advance_epoch), bounding the store.
        self._ordered_bodies: Dict[int, bytes] = {}
        # wave-routed ingest (Config.wave_routing): transports in wave
        # mode hand whole delivery waves to serve_wave; the router
        # demuxes them into typed columns and makes one batch handler
        # dispatch per (kind, wave).  Constructed unconditionally
        # (cheap); only transports that saw wave_routing on call it.
        from cleisthenes_tpu.protocol.router import WaveRouter

        self._router = WaveRouter(self)
        # settler reentrancy guard (settling starts the next epoch,
        # whose turn exit would recurse into the settler) and the
        # one-instant-per-parked-epoch trace dedup
        self._settler_active = False
        self._park_traced = -1
        # K-deep pipelined frontiers (Config.pipeline_depth): the
        # window-top-up drive's reentrancy guard (proposing runs the
        # RBC propose path, whose turn exit would recurse back here)
        # and the eager dec-share flag — True while this node has
        # issue work staged in the hub's dec-share column awaiting
        # the turn's piggyback drain (_drain_dec_issues)
        self._pipeline_active = False
        self._eager_staged = False
        self.metrics.set_frontiers(
            lambda: (self.epoch, len(self.committed_batches))
        )
        self.metrics.set_pipeline(
            # read from observability threads (ValidatorHost sampler):
            # list() snapshots the dict against concurrent protocol-
            # thread mutation; ``not committed`` keeps the coupled
            # arm honest (it never sets es.ordered, and committed
            # epochs linger within KEEP_BEHIND of the frontier)
            lambda: sum(
                1
                for s in list(self._epochs.values())
                if s.proposed
                and s.acs is not None
                and not s.ordered
                and not s.committed
            )
        )
        # production: unpredictable sampling (censorship resistance);
        # seeded: reproducible for tests (config.seed docs).  The
        # seed-vs-SystemRandom fork lives in ONE audited helper
        # (utils.determinism.proposal_rng) — plane code never touches
        # the random module directly (staticcheck DET001).
        # lane > 0 salts the stream with the lane id: sibling lanes
        # are independent protocol instances and must not mirror lane
        # 0's candidate sampling; lane 0 keeps the historical salt
        # (byte-identical draws at lanes=1)
        self._rng = proposal_rng(
            config.seed,
            node_id if lane == 0 else f"{node_id}#lane{lane}",
        )
        # recently committed txs, for lazy dedup at candidate-poll time
        # (bounded: one entry per remembered epoch)
        self._committed_filter: Set[bytes] = set()
        self._committed_history: List[Set[bytes]] = []
        # -- ingress plane (core.mempool + transport.ingress) ------------
        # The fee-priority admission pool ahead of the TxQueue seam:
        # client submissions admit through it (dedup / backpressure /
        # priority eviction) and _create_batch drains it highest-fee-
        # first into self.que.  mempool_capacity=0 keeps the exact
        # pre-ingress shape: add_transaction -> TxQueue directly.
        self.mempool = None
        if _primary is not None:
            # ONE admission pool per node: admit() routes each tx to
            # its hash-assigned lane's drain heap, and every lane
            # drains only its own heap (_create_batch) — the per-lane
            # ledgers stay disjoint by construction
            self.mempool = _primary.mempool
        elif config.mempool_capacity > 0:
            from cleisthenes_tpu.core.mempool import Mempool

            self.mempool = Mempool(
                capacity=config.mempool_capacity,
                client_cap=config.mempool_client_cap,
                seen_cap=config.mempool_seen_cap,
                retry_after_ms=config.mempool_retry_after_ms,
                seed=config.seed if config.seed is not None else 0,
                on_evict=self._mempool_evicted,
                lanes=config.lanes,
            )
        self.metrics.set_ingress(self._ingress_block)
        self.metrics.set_lanes(self._lanes_block)
        # committed-batch fan-out beyond the single on_commit slot:
        # the ingress plane's subscription server registers here (one
        # listener per live subscriber feed), while on_commit stays
        # the transport host's private hook
        self._commit_listeners: List[Callable[[int, Batch], None]] = []
        # the ingress subscription server's live-feed gauge (None
        # until a subscription server mounts)
        self._subscriber_count: Optional[Callable[[], int]] = None
        # -- dynamic membership (protocol.reconfig) ----------------------
        # Versioned rosters: v0 is the construction-time roster; every
        # later version installs from a committed RECONFIG ceremony.
        # Epoch-scoped state resolves through roster_for(epoch); the
        # self.members/self.keys/self.tpke/self.coin fields above track
        # the ACTIVE version (swapped at the activation boundary).
        from cleisthenes_tpu.core.member import (
            Member as _Member,
            RosterSchedule,
            RosterVersion,
        )
        from cleisthenes_tpu.protocol.reconfig import ReconfigManager

        genesis = RosterVersion(
            # a joiner's base version is the cluster's CURRENT one:
            # the next RECONFIG it discovers must extend it
            version=roster_version_base,
            activation_epoch=0,
            members=tuple(_Member(id=m) for m in self.members),
        )
        self.rosters = RosterSchedule(genesis)
        v0_local = node_id in self._member_set
        self._views: Dict[int, _RosterView] = {
            genesis.version: _RosterView(
                genesis,
                config,
                self.members,
                keys if v0_local else None,
                self.crypto,
                self.tpke if v0_local else None,
                self.coin if v0_local else None,
            )
        }
        self._active_version = genesis.version
        # set True when this node's id leaves the active roster: it
        # orders its last epoch at the boundary and parks (serving
        # CATCHUP until peers tear it down)
        self._retired_self = False
        # (activation_epoch, retired_ids, new_view): armed at version
        # install, fired when the SETTLED frontier crosses the
        # boundary — retired pair keys drop, broadcast set narrows,
        # transports tear down dial state (on_peer_retired)
        self._pending_teardown: Optional[tuple] = None
        # transport hooks (set by ValidatorHost / harnesses): called
        # at reconfig discovery with a joiner's (id, "ip:port") so the
        # dial layer opens a lane, and at teardown with a retiree's id
        self.on_peer_added: Optional[Callable[[str, str], None]] = None
        self.on_peer_retired: Optional[Callable[[str], None]] = None
        self._reconfig = ReconfigManager(self)
        self.metrics.set_reconfig(lambda: self._active_version)
        # CATCHUP: epoch -> sender -> response body.  Epochs adopt in
        # order at the commit frontier, each on f+1 identical bodies
        # (>= 1 honest sender => the true committed batch).
        self._catchup_tallies: Dict[int, Dict[str, bytes]] = {}
        # ordered-frontier CATCHUP tallies (COrd bodies), the
        # two-frontier twin of the plaintext tallies above
        self._catchup_ord_tallies: Dict[int, Dict[str, bytes]] = {}
        self._last_catchup_request: Optional[int] = None
        self._farahead_sightings = 0
        # reduced-quorum stall watchdog state: inbound-ingest tick
        # (any serve_wave/serve_request call), the tick value seen at
        # the previous idle callback, and the per-stuck-frontier
        # forced-chase budget (CATCHUP_STALL_BUDGET)
        self._idle_rx = 0
        self._idle_rx_seen = -1
        self._stall_frontier = -1
        self._stall_nudges = 0
        # serving-side guard state (all counted, never clocked):
        # sender -> end of the last window served (its next request
        # must reach it to be served unconditionally); sender ->
        # remaining non-advancing repeat serves; sender -> the last
        # from_epoch it asked for (re-served when its link heals)
        self._catchup_floor: Dict[str, int] = {}
        self._catchup_repeats: Dict[str, int] = {}
        self._catchup_last_req: Dict[str, int] = {}
        # sender -> (next_epoch, limit): plaintext continuation owed
        # after a window we could only answer with COrd bodies (the
        # epochs were ordered here but not yet settled).  Pushed as we
        # settle — the requester's repeat budget is spent by then and
        # budgets re-arm only on ordering advances, so without the
        # push a quiescent cluster wedges.  ``limit`` is fixed at
        # serve time, so one request never buys an unbounded stream.
        self._catchup_plain_owed: Dict[str, Tuple[int, int]] = {}
        # sender -> from_epoch of a request we could serve NOTHING for
        # (it asked at our own frontier): re-served when settlement
        # advances past it.  Without the park, a requester exactly one
        # epoch behind at quiescence wedges — its per-frontier dedup
        # never re-asks and no traffic renudges it (the dynamic-
        # membership joiner chasing the activation boundary hits this
        # on its final window).  One entry per sender, one window per
        # settlement advance: no amplification beyond a normal serve.
        self._catchup_parked: Dict[str, int] = {}
        # durable committed-batch log (core.ledger.BatchLog): restore
        # the committed history + epoch counter + dup-filter on restart
        self.batch_log = batch_log
        if batch_log is not None and self.trace is not None:
            batch_log.trace = self.trace  # WAL appends on our timeline
        self._commits_since_ckpt = 0
        if batch_log is not None and batch_log.last_epoch is not None:
            # seed the dup-filter from the last checkpoint (if any) and
            # fold only the batches logged after it; the full batch
            # history is still replayed for catch-up serving
            self._reconfig.replaying = True
            ckpt_epoch = -1
            ckpt = batch_log.last_checkpoint
            if ckpt is not None:
                ckpt_epoch, history = ckpt
                for seen in history:
                    self._remember_committed(set(seen))
            for epoch, batch in batch_log.replay():
                self.committed_batches.append(batch)
                if epoch > ckpt_epoch:
                    self._remember_committed(set(batch.tx_list()))
                # re-derive the reconfig plane (RECONFIG + dealing txs
                # are ordinary committed txs): roster versions, key
                # material and activation boundaries replay
                # deterministically from the batch content alone
                self._reconfig.on_batch_settled(epoch, batch)
            self.epoch = batch_log.last_epoch + 1
        if (
            self._two_frontier
            and batch_log is not None
            and batch_log.last_ordered_epoch is not None
        ):
            # ordered-ahead epochs (COrd records with no CLOG yet — a
            # crash landed between order and settle): re-enter them
            # into the settler as settle-only states.  The ordering is
            # NEVER re-run; the plaintext arrives via the re-issued
            # dec-share exchange (every restarted node re-broadcasts
            # its own shares from the settler) and/or CLOG catch-up
            # from peers that already settled.
            for oepoch, body in batch_log.replay_ordered():
                if oepoch < self.epoch:
                    continue  # its CLOG follows in the log: settled
                _e, output = decode_ordered_body(body)
                es = _EpochState(None, self.roster_for(oepoch))
                es.proposed = True
                es.output = output
                es.ordered = True
                self._epochs[oepoch] = es
                self._ordered_bodies[oepoch] = body
                self.epoch = oepoch + 1
        if batch_log is not None:
            # leave replay mode: cross-check the re-derived roster
            # schedule against the WAL's RCFG records, re-deal if a
            # ceremony is still pending, and fast-forward the ACTIVE
            # roster to whatever version self.epoch runs under
            self._reconfig.after_replay()
            self._maybe_activate_roster()
            self._maybe_teardown_retired()
        # -- horizontal shard-out: sibling lanes + the merge ------------
        # The lane-0 primary builds its S-1 sibling lane instances
        # here, so every external construction site (hosts, clusters,
        # harnesses) stays single-object: the primary IS the node.
        # Siblings share the primary's hub, coalescer, mempool and
        # trace recorder; each gets its own lane view of the WAL
        # (lane-tagged record streams in the same file) and replays
        # its own ordered-unsettled window independently.
        if lane == 0 and config.lanes > 1:
            from cleisthenes_tpu.core.merge import MergeCursor

            for k in range(1, config.lanes):
                self.lanes.append(
                    HoneyBadger(
                        config=config,
                        node_id=node_id,
                        member_ids=member_ids,
                        keys=keys,
                        out=out,
                        auto_propose=auto_propose,
                        batch_log=(
                            None
                            if batch_log is None
                            else batch_log.lane_view(k)
                        ),
                        hub=self.hub,
                        tx_parse_memo=tx_parse_memo,
                        joining=joining,
                        roster_version_base=roster_version_base,
                        lane=k,
                        _primary=self,
                    )
                )
            # the deterministic total-order merge over the S settled
            # lane streams; restart replay re-seeds the emitted prefix
            # WITHOUT firing commit listeners (matching single-lane
            # replay, which never re-fires on_commit)
            self._merge = MergeCursor(config.lanes)
            for k, hb in enumerate(self.lanes):
                for e, b in enumerate(hb.committed_batches):
                    self._merge.push(k, e, b)
            self._merge.drain()

    def _remember_committed(self, seen: Set[bytes]) -> None:
        """Fold one epoch's committed txs into the bounded duplicate
        filter (shared by live commits and restart replay)."""
        self._committed_history.append(seen)
        self._committed_filter |= seen
        while len(self._committed_history) > COMMITTED_MEMORY_EPOCHS:
            self._committed_filter -= self._committed_history.pop(0)

    # -- public API (reference honeybadger.go:36-59) -----------------------

    def add_transaction(self, tx: bytes) -> None:
        """Reference honeybadger.go:52-54.  Under lane shard-out the
        primary routes each tx to its hash-assigned lane's queue (the
        same ``lane_of`` partition admission uses), so direct pushes
        and mempool-admitted txs land in the same lane."""
        if not isinstance(tx, (bytes, bytearray)):
            raise TypeError("transactions are opaque bytes")
        tx = bytes(tx)
        if self._merge is not None:
            from cleisthenes_tpu.core.merge import lane_of
            from cleisthenes_tpu.core.mempool import tx_digest

            seed = self.config.seed if self.config.seed is not None else 0
            self.lanes[lane_of(seed, tx_digest(tx), self.config.lanes)].que.push(tx)
            return
        self.que.push(tx)

    # -- ingress plane (core.mempool + transport.ingress) ------------------

    def submit_ingress(self, client_id: str, fee: int, tx: bytes):
        """Admit one client transaction through the mempool (the
        ingress plane's policy call; transport/ingress.py wraps the
        verdict in an IngressAckPayload).  Requires a mounted mempool
        (Config.mempool_capacity > 0)."""
        if self.mempool is None:
            raise RuntimeError(
                "no mempool mounted (Config.mempool_capacity=0)"
            )
        if not isinstance(tx, (bytes, bytearray)):
            raise TypeError("transactions are opaque bytes")
        verdict = self.mempool.admit(bytes(tx), client_id, fee)
        if self.trace is not None:
            self.trace.instant(
                "ingress", "admit", status=verdict.status, fee=fee
            )
        return verdict

    def _mempool_evicted(self, digest: bytes, client_id: str) -> None:
        """Mempool on_evict hook: surface priority evictions on the
        flight-recorder timeline (the counter itself lives in the
        mempool and reaches snapshot()["ingress"] via the provider)."""
        if self.trace is not None:
            self.trace.instant(
                "ingress", "evict", digest=digest[:4].hex()
            )

    def _ingress_block(self) -> Dict[str, object]:
        """snapshot()["ingress"] provider: mempool admission tallies
        plus the subscription gauge (zeroed keys when no mempool /
        no subscription server is mounted)."""
        out: Dict[str, object] = {}
        if self.mempool is not None:
            s = self.mempool.stats()
            out.update(
                submitted=s["submitted"],
                admitted=s["admitted"],
                rejected=s["rejected"],
                retried=s["retried"],
                deduped=s["deduped"],
                evicted=s["evicted"],
                mempool_depth=s["depth"],
            )
        if self._subscriber_count is not None:
            out["subscribers"] = self._subscriber_count()
        return out

    def _lanes_block(self) -> Dict[str, object]:
        """snapshot()["lanes"] provider: per-lane frontier gauges,
        the merged settled frontier, and the admission partition's
        skew witness.  On the lane-0 primary the lists span all S
        lanes; at lanes=1 they are one-element (the schema-stable
        single-lane shape)."""
        lanes = self.lanes
        fill = (
            list(self.mempool.lane_fill())
            if self.mempool is not None
            else [0] * len(lanes)
        )
        return {
            "lanes": len(lanes),
            "merge_frontier": self.merged_settled_frontier,
            "ordered_epochs": [hb.epoch for hb in lanes],
            "settled_epochs": [
                len(hb.committed_batches) for hb in lanes
            ],
            "lane_fill": fill,
            "partition_skew": (max(fill) - min(fill)) if fill else 0,
        }

    def set_subscriber_provider(
        self, provider: Optional[Callable[[], int]]
    ) -> None:
        """The ingress subscription server's live-feed gauge."""
        self._subscriber_count = provider

    def add_commit_listener(
        self, fn: Callable[[int, Batch], None]
    ) -> None:
        """Register a committed-batch listener beyond the single
        on_commit slot (the subscription server's live tail).  Fired
        after on_commit, in registration order, at every settlement
        (local or adopted via CATCHUP), strictly in epoch order."""
        self._commit_listeners.append(fn)

    def _notify_commit(self, epoch: int, batch: Batch) -> None:
        """The single settlement fan-out point: retire the batch's txs
        from the mempool's in-flight accounting, then fire on_commit
        and every registered listener.  Under lane shard-out the
        settlement instead feeds the primary's merge cursor; listeners
        fire from the MERGED total order (with merged sequence
        numbers), never per lane."""
        if self.mempool is not None:
            self.mempool.mark_settled(batch.tx_list())
        if self._primary is not None:
            self._primary._on_lane_settled(self.lane, epoch, batch)
            return
        if self._merge is not None:
            self._on_lane_settled(0, epoch, batch)
            return
        if self.on_commit is not None:
            self.on_commit(epoch, batch)
        for fn in self._commit_listeners:
            fn(epoch, batch)

    def _on_lane_settled(self, lane: int, epoch: int, batch: Batch) -> None:
        """Primary-side merge feed: one lane settled one epoch.  Push
        the slot, then emit every newly contiguous merged slot (a
        pure function of the committed bytes — identical on every
        honest node however the lanes' settlements interleave)."""
        self._merge.push(lane, epoch, batch)
        for seq, mlane, mepoch, mbatch in self._merge.drain():
            if self.trace is not None:
                self.trace.instant(
                    "merge", "emit", seq=seq, lane=mlane, epoch=mepoch,
                    txs=len(mbatch),
                )
            if self.on_commit is not None:
                self.on_commit(seq, mbatch)
            for fn in self._commit_listeners:
                fn(seq, mbatch)

    # -- merged total-order accessors (lane shard-out) ---------------------

    @property
    def merged_batches(self) -> List[Batch]:
        """The settled batches in MERGED total order (== the per-lane
        committed list at lanes=1): the ledger every cross-node
        byte-identity comparison and subscription replay reads."""
        return (
            self.committed_batches
            if self._merge is None
            else self._merge.merged
        )

    @property
    def merged_settled_frontier(self) -> int:
        """Number of merge-emitted slots (== the settled epoch count
        at lanes=1)."""
        return (
            len(self.committed_batches)
            if self._merge is None
            else self._merge.frontier
        )

    @property
    def merged_ordered_frontier(self) -> int:
        """Sum of the lanes' ordered frontiers (== ``self.epoch`` at
        lanes=1): the ingress plane's ordered-work gauge."""
        if self._merge is None:
            return self.epoch
        return sum(hb.epoch for hb in self.lanes)

    def start_epoch(self, epoch: Optional[int] = None) -> None:
        """Select a batch, encrypt it, and input it to this epoch's ACS
        (the intended body of reference honeybadger.go:57-59 sendBatch).

        ``epoch`` defaults to the commit frontier; the pipelining path
        passes ``self.epoch + 1`` to propose ahead (BASELINE config 5).
        A frontier-default call (``epoch=None`` — the external kick)
        additionally tops up the K-deep in-flight window
        (Config.pipeline_depth; no-op at depth 1).
        """
        try:
            if epoch is None:
                self._propose_into(self.epoch)
                self._drive_pipeline()
                for hb in self.lanes[1:]:
                    # the external kick reaches every lane: siblings
                    # propose into their own frontiers (empty batches
                    # are fine — lanes run independent HBBFT streams)
                    if not hb._retired_self:
                        hb._propose_into(hb.epoch)
                        hb._drive_pipeline()
            else:
                self._propose_into(epoch)
        finally:
            self._exit_turn()

    def _propose_into(self, target: int) -> None:
        """One epoch's proposal (the historical start_epoch body):
        batch select, TPKE encrypt, ACS input.  Callers propose in
        ascending epoch order — the per-node proposal RNG is a
        stream, so the draw order is part of the deterministic
        schedule (K-deep runs must consume it exactly like depth 1)."""
        es = self._epoch_state(target)
        if es is None or es.proposed:
            return
        es.proposed = True
        self.metrics.epoch_proposed(target)
        tr = self.trace
        if tr is not None:
            ahead = target - self.epoch
            if ahead > 0:  # K-deep window position; frontier opens
                tr.instant(
                    "epoch", "open", epoch=target, ahead=ahead,
                    **self._lane_kw,
                )
            else:  # keep the depth-1 event byte-stable
                tr.instant("epoch", "open", epoch=target, **self._lane_kw)
        t0 = 0.0 if tr is None else tr.now()
        es.my_txs = self._create_batch()
        # the EPOCH's key set (an epoch past an activation
        # boundary encrypts under the reshared key even while the
        # proposer's active roster is still the old one)
        view = es.view
        ct = view.tpke.encrypt(serialize_txs(es.my_txs))
        if tr is not None:
            tr.complete(
                "tpke", "encrypt", t0, epoch=target, txs=len(es.my_txs)
            )
        es.acs.input(
            serialize_ciphertext(ct, view.keys.tpke_pub.group)
        )

    @property
    def _pipeline_depth(self) -> int:
        """The K-deep protocol-plane window width: epochs
        [self.epoch, self.epoch + K - 1] may run RBC/BBA
        concurrently.  Depth is an ordered-frontier concept, so it
        collapses to 1 (lockstep) whenever the two-frontier split is
        off — the epoch_pipelining ARM flag gates the whole plane."""
        return self.config.pipeline_depth if self._two_frontier else 1

    def _drive_pipeline(self) -> None:
        """Top up the K-deep in-flight window (Config.pipeline_depth):
        propose into epochs [self.epoch + 1, self.epoch + K - 1] so
        their RBC/BBA runs concurrently with the frontier epoch's,
        while ordering itself still advances strictly in epoch order
        (_maybe_order) and parks at decrypt_lag_max.  Per-epoch
        propose rule matches _advance_epoch's: local work pending, or
        the epoch already live from peer traffic.  Ascending order
        (the proposal-RNG stream rule, see _propose_into).  No-op at
        depth 1 — the byte-identical comparison arm."""
        depth = self._pipeline_depth
        if (
            depth <= 1
            or self._pipeline_active
            or not self.auto_propose
            or self._retired_self
        ):
            return
        self._pipeline_active = True
        try:
            for e in range(self.epoch + 1, self.epoch + depth):
                es = self._epochs.get(e)
                if es is not None and es.proposed:
                    continue
                if self._queue_work() or es is not None:
                    self._propose_into(e)
        finally:
            self._pipeline_active = False

    def maybe_follow_epoch(self, epoch: int, es: _EpochState) -> None:
        """Follow-the-epoch — THE shared rule of both routing arms
        (the scalar `_serve_payload` chain and the WaveRouter call
        here, so the arms' follow windows can never drift apart):
        peer traffic showed an epoch inside our pipeline window
        [self.epoch, self.epoch + depth - 1] running without our
        proposal — contribute it (every correct node must propose or
        ACS never reaches n-f ones).  Any unproposed epochs BELOW it
        propose first: the K-deep window admits traffic for
        self.epoch + k before self.epoch's own proposal, and the
        proposal-RNG stream must still be consumed in epoch order.
        The turn exit mirrors the historical start_epoch() call here,
        so the depth-1 flush schedule stays byte-identical."""
        if not (
            self.auto_propose
            and self.epoch <= epoch < self.epoch + self._pipeline_depth
            and not es.proposed
        ):
            return
        try:
            for e in range(self.epoch, epoch + 1):
                st = self._epochs.get(e)
                if st is None or not st.proposed:
                    self._propose_into(e)
        finally:
            self._exit_turn()

    def _queue_work(self) -> bool:
        """Is there local work to propose?  Queue depth OR mempool
        entries awaiting their drain into the TxQueue seam — the
        propose-gating twin of pending_tx_count."""
        if len(self.que) > 0:
            return True
        return self._staged_count() > 0

    def _staged_count(self) -> int:
        """Mempool entries awaiting THIS lane's drain (the whole pool
        at lanes=1 — the historical single-heap read)."""
        if self.mempool is None:
            return 0
        if self.config.lanes > 1:
            return self.mempool.pending_count(self.lane)
        return self.mempool.pending_count()

    def pending_tx_count(self) -> int:
        own = len(self.que) + self._staged_count()
        for hb in self.lanes[1:]:  # primary fans in; empty otherwise
            own += hb.pending_tx_count()
        return own

    def outstanding_tx_count(self) -> int:
        """Queue depth PLUS transactions absorbed into in-flight
        (proposed but not yet committed/settled) epochs' own
        proposals — the work-outstanding signal the SLO stall
        watchdog reads.  The K-deep pipeline window can drain the
        whole queue into its in-flight epochs' ``my_txs``, and a
        stalled node must still read as holding pending work.
        Called from observability threads (the SLO watchdog's
        pending_fn): list() snapshots the dict against concurrent
        protocol-thread mutation.  Mempool entries still awaiting
        drain count too — client-acked work invisible to the queue
        and to every epoch's my_txs must still trip the
        queue-backpressure detector."""
        total = self._staged_count() + len(self.que) + sum(
            len(es.my_txs)
            for es in list(self._epochs.values())
            if es.proposed and not es.committed
        )
        for hb in self.lanes[1:]:  # primary fans in; empty otherwise
            total += hb.outstanding_tx_count()
        return total

    @property
    def _two_frontier(self) -> bool:
        """Two-frontier commit (Config.order_then_settle): self.epoch
        is the ORDERED frontier (the epoch the live protocol runs in);
        the SETTLED frontier is len(self.committed_batches) — plain-
        text durable, dedup applied, on_commit fired.  The split is
        the epoch-pipelining mechanism upgraded, so the
        ``epoch_pipelining=False`` strict-sequencing diagnostic arm
        keeps its meaning: with pipelining off, commit stays coupled.
        A property (not cached) because tests toggle both flags on a
        constructed node."""
        cfg = self.config
        return cfg.order_then_settle and cfg.epoch_pipelining

    @property
    def settled_epoch(self) -> int:
        """The SETTLED frontier: epochs whose plaintext batch is
        durable, dedup-filtered and delivered (on_commit).  Equal to
        the ordered frontier ``self.epoch`` on the coupled path; at
        most Config.decrypt_lag_max behind it in two-frontier mode."""
        return len(self.committed_batches)

    def ordered_record(self, epoch: int) -> Optional[bytes]:
        """The COrd body this node ordered for ``epoch`` (None when the
        epoch arrived via plaintext catch-up without ever ordering
        locally) — the bytes the cross-node byte-identity invariant
        compares and ordered CATCHUP serves."""
        return self._ordered_bodies.get(epoch)

    # -- dynamic membership (protocol.reconfig) ----------------------------

    @property
    def group(self):
        """The crypto group every roster version of this deployment
        shares (the modulus seam: reconfig ceremonies deal over the
        same group the genesis keys use)."""
        return self.keys.tpke_pub.group

    @property
    def active_view(self) -> _RosterView:
        """The ACTIVE roster version's resolved view (the one
        ``self.epoch`` runs under after every boundary crossing)."""
        return self._views[self._active_version]

    @property
    def roster_version(self) -> int:
        return self._active_version

    def roster_for(self, epoch: int) -> _RosterView:
        """Resolve the roster version an epoch runs under — THE
        accessor every epoch-scoped n/f/key read goes through
        (staticcheck DET005 gates direct construction-time reads)."""
        return self._views[self.rosters.version_for(epoch).version]

    def on_reconfig_discovered(self, spec, joiners) -> None:
        """A RECONFIG transaction settled: install the transition's
        pair keys, widen the broadcast set to old ∪ new (pre-
        activation epochs still need the retirees; ceremony traffic
        and post-activation epochs need the joiners), and open dial/
        serving lanes toward the joiners."""
        pair_keys = self._reconfig.joiner_pair_keys(spec)
        if self._authenticator is not None:
            for peer in sorted(pair_keys):
                self._authenticator.set_peer_key(peer, pair_keys[peer])
            # MAC rotation step 1: stage the surviving pairs' fresh
            # version keys (inbound verifies under either key from
            # here; signing switches at the activation boundary)
            staged = self._reconfig.rotation_pair_keys(spec)
            for peer in sorted(staged):
                self._authenticator.stage_peer_key(peer, staged[peer])
        old_ids = set(self.active_view.member_ids)
        if self.node_id not in old_ids:
            return  # a joiner widens nothing: it adopts, then activates
        union = sorted(old_ids | set(spec.member_ids))
        self._set_broadcast_members(union)
        addr_of = {m[0]: (m[1], m[2]) for m in spec.members}
        for j in joiners:
            # a joiner's very first CATCHUP request may predate our
            # knowledge of it (MAC-rejected): remember a standing
            # from-0 request so the serving side initiates
            self._catchup_last_req.setdefault(j, 0)
            if self.on_peer_added is not None:
                # async transports (gRPC): the dial layer opens the
                # lane and fires peer_reconnected on success, which
                # serves the standing request
                ip, port = addr_of[j]
                self.on_peer_added(j, f"{ip}:{port}")
            elif not self._reconfig.replaying:
                # in-proc transports deliver immediately: serve the
                # joiner's bootstrap window now
                self._handle_catchup_req(
                    j, CatchupReqPayload(from_epoch=0)
                )

    def install_roster_version(self, rv, keys, spec) -> None:
        """A reshare ceremony finalized: bind the version's runtime
        view, arm the retirement teardown, and write the RCFG WAL
        record — all strictly before any epoch orders under it."""
        import dataclasses as _dc

        cfg = _dc.replace(self.config, n=rv.n, f=None)
        local = self.node_id in rv.member_ids
        if local and keys is None:
            raise ValueError("member view installed without keys")
        crypto = (
            self.crypto
            if cfg.n == self.config.n and cfg.f == self.config.f
            else get_backend(cfg)
        )
        view = _RosterView(
            rv,
            cfg,
            rv.member_ids,
            keys,
            crypto,
            crypto.tpke(keys.tpke_pub) if local else None,
            crypto.coin(keys.coin_pub) if local else None,
        )
        self._views[rv.version] = view
        self.rosters.install(rv)
        prev = self.rosters.version_for(rv.activation_epoch - 1)
        retired = sorted(
            set(prev.member_ids) - set(rv.member_ids)
        )
        self._pending_teardown = (rv.activation_epoch, retired, view)
        if self.trace is not None:
            self.trace.instant(
                "reconfig",
                "install",
                version=rv.version,
                activation_epoch=rv.activation_epoch,
            )
        self.log.info(
            "roster version installed",
            version=rv.version,
            activation_epoch=rv.activation_epoch,
            n=rv.n,
        )
        if (
            self.batch_log is not None
            and not self._reconfig.replaying
        ):
            self.batch_log.append_reconfig(
                rv.version,
                rv.activation_epoch,
                [(m.id, m.addr.ip, m.addr.port) for m in rv.members],
                rv.key_material_digest,
            )
        # a laggard can have built epoch states PAST the boundary
        # under the old roster before learning of the ceremony (it
        # ordered ahead of its settled frontier): those states are
        # wrong-view by construction and can never complete — drop
        # them; the epochs re-enter via live traffic or CATCHUP
        for e in sorted(self._epochs):
            if (
                e >= rv.activation_epoch
                and self._epochs[e].view is not view
            ):
                del self._epochs[e]
                self.hub.drop_scope((self.node_id, e))
        # the boundary only activates when the frontier reaches it:
        # if the cluster is otherwise quiescent, kick the epoch drive
        # now (the _advance_epoch condition keeps it rolling to the
        # switch) instead of wedging mid-transition until the next
        # client transaction
        if (
            self.auto_propose
            and not self._reconfig.replaying
            and not self._retired_self
            and self.epoch < rv.activation_epoch
        ):
            self.start_epoch()

    def _maybe_activate_roster(self) -> None:
        """Cross the activation boundary when the live frontier
        reaches it: swap the ACTIVE view (keys, batch policy, metrics
        identity).  Runs at every epoch advance; a restart replaying
        far past a boundary crosses every intermediate version in
        order."""
        while True:
            rv = self.rosters.version_for(self.epoch)
            if rv.version == self._active_version:
                return
            nxt = None
            for candidate in self.rosters:
                if candidate.version == self._active_version + 1:
                    nxt = candidate
                    break
            view = self._views[nxt.version]
            self._active_version = nxt.version
            self.metrics.reconfigs_total.inc()
            if self.trace is not None:
                self.trace.instant(
                    "reconfig",
                    "activate",
                    version=nxt.version,
                    epoch=self.epoch,
                )
            if not view.local:
                # retired: order nothing further; keep serving
                # CATCHUP and settling the pre-boundary epochs
                self._retired_self = True
                self.log.info(
                    "retired from roster", version=nxt.version
                )
                continue
            self._retired_self = False
            prev_members = self.members
            self.members = list(view.member_ids)
            self._member_set = view.member_set
            self.keys = view.keys
            self.tpke = view.tpke
            self.coin = view.coin
            if self._authenticator is not None:
                # MAC rotation step 2: signing switches to the staged
                # version key for every surviving pair (no-op for a
                # joiner, and for pairs with nothing staged — e.g.
                # when a catch-up adopter's teardown already pinned
                # the fresh keys)
                for peer in view.member_ids:
                    self._authenticator.promote_staged_key(peer)
            self.b = max(self.config.batch_size, view.config.n)
            # fan out to old ∪ new until the settled frontier crosses
            # the boundary (teardown narrows to the new roster): the
            # outgoing roster still needs our dec shares for pre-
            # boundary epochs, and — the JOINER's case — our own
            # post-boundary votes must reach ourselves and any
            # co-joiner from the very first new-roster epoch, not
            # only once settlement catches up.  If this boundary's
            # teardown ALREADY fired (a catch-up adopter can settle
            # past the boundary before its ordered frontier crosses
            # it), the retirees' pair keys are gone — never re-widen
            # to peers we can no longer sign for.
            pt = self._pending_teardown
            if pt is not None and pt[2] is view:
                fanout = set(prev_members) | set(view.member_ids)
            else:
                fanout = set(view.member_ids)
            self._set_broadcast_members(sorted(fanout))
            self.log.info(
                "roster activated",
                version=nxt.version,
                n=view.config.n,
            )

    def _maybe_teardown_retired(self) -> None:
        """The settled frontier crossed an activation boundary: every
        pre-boundary epoch is plaintext-durable, so the retirees'
        duties are over — narrow the broadcast set to the new roster,
        drop their pair keys, and tear down their transport lanes."""
        pt = self._pending_teardown
        if pt is None:
            return
        activation, retired, view = pt
        if len(self.committed_batches) < activation:
            return
        self._pending_teardown = None
        if not view.local:
            return  # the retiree keeps its lanes for CATCHUP serving
        self._set_broadcast_members(view.member_ids)
        for peer in retired:
            if self._authenticator is not None:
                self._authenticator.drop_peer(peer)
            if self.on_peer_retired is not None:
                self.on_peer_retired(peer)
        if self._authenticator is not None and view.keys is not None:
            # MAC rotation step 3: pin every surviving pair to the
            # version's fresh key (idempotent after the activation-
            # time promote, and correct even when a catch-up
            # adopter's settle crosses the boundary before its
            # ordered frontier does) and drop the alternates — a
            # frame MAC'd under a pre-rotation key is rejected from
            # here on
            for peer in view.member_ids:
                self._authenticator.set_peer_key(
                    peer, view.keys.mac_keys[peer]
                )
                self._authenticator.drop_alt_key(peer)
        if retired and self.trace is not None:
            self.trace.instant(
                "reconfig",
                "teardown",
                version=view.rv.version,
                retired=len(retired),
            )

    def _set_broadcast_members(self, member_ids) -> None:
        """Swap the outbound fan-out set (coalescer + inner
        broadcaster + the semantic-adversary wrapper when mounted)."""
        ids = sorted(member_ids)
        self._coalesce.set_members(ids)
        behavior_out = getattr(self.out, "_inner", None)
        set_members = getattr(behavior_out, "set_members", None)
        if set_members is not None and behavior_out is not self._coalesce:
            set_members(ids)
        self.out._n = len(ids)

    # -- batch policy (reference honeybadger.go:62-104) --------------------

    def _create_batch(self) -> List[bytes]:
        # the TxQueue seam: admitted client txs flow highest-fee-first
        # from the mempool into the FIFO queue AHEAD of candidate
        # polling, so selection below (and its committed-filter dedup)
        # is unchanged whether a tx arrived via add_transaction or
        # through the ingress admission pipeline
        if self.mempool is not None:
            # each lane drains ONLY its own heap (lane 0 == the only
            # heap at lanes=1): the partition is admission-time
            self.mempool.drain_into(self.que, self.b, lane=self.lane)
        candidates = self._load_candidate_txs(min(self.b, len(self.que)))
        # the ACTIVE roster's width (b/n sampling follows the live n)
        n = self.active_view.config.n
        return self._select_random_txs(candidates, self.b // n)

    def _load_candidate_txs(self, count: int) -> List[bytes]:
        """Poll up to ``count`` txs off the queue head
        (honeybadger.go:75-86), lazily dropping any that already
        committed in a recent epoch (duplicate submissions — filtered
        here at poll time instead of rewriting the whole queue on
        every commit)."""
        out: List[bytes] = []
        while len(out) < count and len(self.que):
            tx = self.que.poll()
            if tx not in self._committed_filter:
                out.append(tx)
        return out

    def _select_random_txs(
        self, candidates: List[bytes], count: int
    ) -> List[bytes]:
        """Uniformly sample ``count`` candidates; re-push the rest
        (honeybadger.go:89-104 selectRandomTx + cleanUp)."""
        picked_idx = set(
            self._rng.sample(range(len(candidates)), min(count, len(candidates)))
        )
        picked = [tx for i, tx in enumerate(candidates) if i in picked_idx]
        for i, tx in enumerate(candidates):  # cleanUp: restore the rest
            if i not in picked_idx:
                self.que.push(tx)
        return picked

    # -- transport integration (coalescing + idle hooks) -------------------

    def transport_manages_idle(self) -> None:
        """Called by a transport that promises to invoke ``on_idle()``
        at its quiescence points (ChannelNetwork.run's drained-queue
        phase; SerialDispatcher's empty-mailbox check).  Moves outbound
        flushing and batched-crypto execution to those points, so one
        hub flush + one bundle per receiver absorbs an entire message
        wave.  ``Config.hub_wave_flush=False`` keeps the hub on the
        pre-wave scalar discipline (flush per quorum event) — the
        equivalence-test comparison arm; outbound coalescing still
        moves to the idle callback either way."""
        self._transport_managed = True
        for hb in self.lanes[1:]:  # siblings drain at OUR idle points
            hb._transport_managed = True
        if self.config.hub_wave_flush:
            self.hub.defer = True

    def flush_outbound(self) -> None:
        self._coalesce.flush()

    def on_idle(self) -> None:
        """Transport idle callback: run the crypto flush the wave
        requested (quorum events only record the want in deferred
        mode), then ship everything it produced."""
        tr = self.trace
        if tr is not None and self._trace_wave_msgs:
            # one wave boundary: how many envelopes this quiescence
            # point absorbed (the dispatch-amortization denominator)
            tr.instant("transport", "wave", msgs=self._trace_wave_msgs)
            self._trace_wave_msgs = 0
        # lane fan-out: ``self.lanes`` is [self] at lanes=1, so the
        # single-lane call order below is byte-identical to the
        # historical body.  All S lanes' drains run around ONE hub
        # flush and ONE coalescer flush — the dispatch-flatness
        # requirement (S lanes share the wave's dispatches instead of
        # multiplying them).
        lanes = self.lanes
        self._drive_lane_lockstep()
        for hb in lanes:
            hb._drain_coin_issues()
            # the trailing settler (two-frontier mode) runs HERE, off
            # the ordered critical path: issue pending dec shares,
            # probe combines, settle ready epochs in order.  It runs
            # before the hub flush so any CP-verification work it
            # requests rides this wave's batched dispatch, not the
            # next one's.
            hb._drive_settler()
            # top up the K-deep in-flight window before the hub flush:
            # fresh proposals' RBC traffic joins this turn's bundle
            hb._drive_pipeline()
        self.hub.run_deferred()
        for hb in lanes:
            # the flush itself can advance rounds and queue NEW coin
            # issues (coin reveal -> advance -> next round's aux
            # quorum); drain again so they ride this turn's bundle,
            # not the next inbound message's
            hb._drain_coin_issues()
            # eagerly staged dec shares (epochs ordered during this
            # wave, including inside run_deferred) piggyback on this
            # flush
            hb._drain_dec_issues()
            hb._maybe_chase_stall()
        self._coalesce.flush()

    def _drive_lane_lockstep(self) -> None:
        """Drag lagging lanes toward the fastest lane's ordered
        frontier (primary only, lanes > 1).  The merged total order
        enumerates slots epoch-major, so a lane that quiesces epochs
        behind its siblings parks the merge; proposing (possibly
        empty) epochs into the gap fills the slots.  Every honest
        node runs the same rule, so the catch-up epochs reach their
        n-f proposal quorums.  Terminates: lanes at the max frontier
        are never kicked."""
        if self._merge is None or not self.auto_propose:
            return
        lanes = self.lanes
        target = max(hb.epoch for hb in lanes)
        for hb in lanes:
            if hb.epoch >= target or hb._retired_self:
                continue
            es = hb._epochs.get(hb.epoch)
            if es is None or not es.proposed:
                hb._propose_into(hb.epoch)

    def _exit_turn(self) -> None:
        """Self-draining mode: every public entry point leaves no
        buffered outbound behind (transports without idle callbacks
        would otherwise strand the turn's messages).  ``self.lanes``
        is [self] at lanes=1 — the historical body, byte-identical."""
        if not self._transport_managed:
            self._drive_lane_lockstep()
            for hb in self.lanes:
                hb._drain_coin_issues()
                hb._drive_settler()
                hb._drive_pipeline()
                hb._drain_dec_issues()
            self._coalesce.flush()

    def _queue_coin_issue(self, bba, rnd: int) -> None:
        """BBA coin_issue_sink: park the (instance, round) want; the
        turn-exit / idle drain issues every parked share in ONE
        batched exponentiation dispatch instead of 4 scalar host exps
        per instance (a vote wave triggers a whole roster's worth of
        aux quorums at once).  Under ``Config.egress_columnar`` the
        want ALSO stages into the CryptoHub's coin-issue column at
        queue time — during the message wave — so the idle phase's
        FIRST drain executes the whole roster's wants (shared-hub
        cluster) in one ``ops.coin.share_batch`` dispatch and later
        drains claim precomputed shares."""
        self._pending_coin_issues.append((bba, rnd))
        if self.config.egress_columnar:
            # per-instance key material: a wave can span an activation
            # boundary (dynamic membership), so each BBA issues under
            # ITS epoch's coin key/share — the group is deployment-
            # wide, so the whole mixed pool still batches into one
            # dispatch
            pub, base, context = bba.coin.group_params(bba._coin_id(rnd))
            sec = bba.coin_secret
            self.hub.stage_coin_issue(
                self,
                (bba, rnd),
                (sec, base, context,
                 pub.verification_keys[sec.index - 1]),
                self.group,
            )

    def _drain_coin_issues(self) -> None:
        pend = self._pending_coin_issues
        if not pend:
            return
        tr = self.trace
        t0 = 0.0 if tr is None else tr.now()
        self._pending_coin_issues = []
        if self.config.egress_columnar:
            # wave-batched coin kernel (ISSUE 13): the hub's coin
            # column hands back this node's shares, dispatching the
            # WHOLE staged pool natively iff some of ours are still
            # pending — broadcast site, order, and timing identical
            # to the scalar arm below
            for (bba, rnd), share in self.hub.take_coin_issues(self):
                bba.broadcast_coin_share(rnd, share)
            if tr is not None:
                tr.complete("coin", "issue_batch", t0, n=len(pend))
            return
        # per-instance key material: a wave can span an activation
        # boundary (dynamic membership), so each BBA issues under ITS
        # epoch's coin key/share — the group is deployment-wide, so
        # the whole mixed wave still batches into one dispatch
        group = self.group
        items = []
        metas = []
        for bba, rnd in pend:
            # halted BBAs still contribute: the issue was queued when
            # the aux quorum fired, and withholding the (public,
            # deterministic) share after a TERM decision can leave
            # slower peers one share short of the coin threshold
            pub, base, context = bba.coin.group_params(bba._coin_id(rnd))
            sec = bba.coin_secret
            items.append(
                (sec, base, context,
                 pub.verification_keys[sec.index - 1])
            )
            metas.append((bba, rnd))
        if not items:
            return
        # the scalar comparison arm counts its native dispatches on
        # the same hub counters the columnar arm uses, so
        # coin_dispatches_per_epoch compares like for like across arms
        self.hub.coin_issue_batches += 1
        self.hub.coin_issue_items += len(items)
        shares = issue_shares_batch(
            items,
            group=group,
            backend=self.crypto.engine_backend,
            mesh=self.crypto.mesh,
        )
        for (bba, rnd), share in zip(metas, shares):
            bba.broadcast_coin_share(rnd, share)
        if tr is not None:
            tr.complete("coin", "issue_batch", t0, n=len(items))

    # -- message demux (transport Handler) ---------------------------------

    def serve_wave(self, msgs) -> None:
        """Wave-ingest entry (Config.wave_routing): one call carries a
        whole delivery wave of verified, decoded frames; the router
        demuxes them into typed columns and invokes one batch handler
        per (message kind, wave) — the per-payload scalar chain below
        stays live as the byte-equivalence comparison arm."""
        try:
            self._idle_rx += len(msgs)
            if self.trace is not None:
                self._trace_wave_msgs += len(msgs)
            self._router.route(msgs)
        finally:
            self._exit_turn()

    def serve_request(self, msg: Message) -> None:
        try:
            self._idle_rx += 1
            if self.trace is not None:
                self._trace_wave_msgs += 1
            payload = msg.payload
            if isinstance(payload, BundlePayload):
                items = payload.items
                self.metrics.msgs_in.inc(_logical_count_many(items))
                serve = self._serve_payload
                sender = msg.sender_id
                for item in items:
                    serve(sender, item)
            else:
                self.metrics.msgs_in.inc(_logical_count(payload))
                self._serve_payload(msg.sender_id, payload)
        finally:
            self._exit_turn()

    def _serve_payload(self, sender_id: str, payload) -> None:
        # CATCHUP traffic is deliberately NOT epoch-window gated: it
        # exists exactly for nodes outside the window (CatchupReq has
        # no ``epoch`` field at all — it carries a range start)
        pcls = payload.__class__
        if pcls is LanePayload:
            # lane shard-out demux: lane-k frames route into the
            # lane-k sibling instance (only the lane-0 primary ever
            # receives these — lane 0 traffic is never wrapped, so
            # the single-lane build never reaches this branch)
            lanes = self.lanes
            l = payload.lane
            if self.lane == 0 and 0 < l < len(lanes):
                sib = lanes[l]
                sib._idle_rx += 1  # the sibling's stall-watchdog clock
                sib._serve_payload(sender_id, payload.inner)
            return
        if pcls is CatchupReqPayload:
            self._handle_catchup_req(sender_id, payload)
            return
        if pcls is CatchupRespPayload:
            self._handle_catchup_resp(sender_id, payload)
            return
        if pcls is CatchupOrdPayload:
            self._handle_catchup_ord(sender_id, payload)
            return
        if pcls is ResharePayload:
            # reconfig gossip (epoch-unscoped like CATCHUP): staged
            # by the reshare plane; for a joiner it doubles as the
            # "a ceremony is underway, chase the log" nudge
            if self._reconfig.known_member(sender_id):
                self._reconfig.on_reshare_payload(sender_id, payload)
            return
        epoch = getattr(payload, "epoch", None)
        if epoch is None:
            return
        # fast path: an existing state is by construction inside the
        # window (stale ones are GC'd), so skip the bounds arithmetic
        # that _epoch_state re-derives for every one of the O(N^2)
        # payloads per wave
        es = self._epochs.get(epoch) or self._epoch_state(epoch)
        if es is None:  # outside the sliding window, or not a member
            if epoch > self.epoch + EPOCH_HORIZON:
                # peers are far ahead: we missed epochs, catch up
                self._note_farahead()
            elif (
                epoch > self.epoch
                and not self.roster_for(epoch).local
            ):
                # traffic for an epoch we cannot participate in
                # (dynamic membership: a joiner watching the old
                # roster run ahead of its adopted frontier): every
                # sighting ticks the same traffic-clocked catch-up
                # chase the far-ahead path uses
                self._note_farahead()
            return
        cls = pcls
        if cls is DecSharePayload:
            self.metrics.handler_dispatches.inc()
            self._handle_dec_share(
                epoch, es, sender_id, payload.proposer, payload.index,
                payload.d, payload.e, payload.z,
            )
            return
        if cls is DecShareBatchPayload:
            self.metrics.handler_dispatches.inc()
            self._handle_dec_share_batch(epoch, es, sender_id, payload)
            return
        if cls in _ACS_PAYLOADS:
            if es.acs is None:
                # settle-only state (two-frontier mode: the ordering
                # is already durable) — consensus traffic for it is
                # stale by definition, only dec shares still matter
                return
            # follow the epoch: a peer is running it, so contribute our
            # (possibly empty) proposal too (the shared rule of both
            # routing arms — window and RNG-order discipline live in
            # maybe_follow_epoch)
            self.maybe_follow_epoch(epoch, es)
            self.metrics.handler_dispatches.inc()
            if cls is BbaBatchPayload:
                es.acs.handle_bba_batch(sender_id, payload)
            elif cls is CoinBatchPayload:
                es.acs.handle_coin_batch(sender_id, payload)
            elif cls is EchoBatchPayload:
                es.acs.handle_echo_batch(sender_id, payload)
            elif cls is ReadyBatchPayload:
                es.acs.handle_ready_batch(sender_id, payload)
            else:
                es.acs.handle_message(sender_id, payload)

    def _note_farahead(self) -> None:
        """One sighting of traffic beyond the forward demux horizon
        (shared by the scalar chain and the wave router, per payload
        so the renudge cadence matches across arms).  The first
        sighting requests catch-up immediately (dedup'd per
        frontier); if the frontier then fails to move (our request or
        its responses were lost), every further CATCHUP_RENUDGE_EVERY
        sightings force a re-broadcast — a retry clocked by traffic,
        not wall time."""
        self._farahead_sightings += 1
        self._request_catchup(
            force=self._farahead_sightings % CATCHUP_RENUDGE_EVERY == 0
        )

    def _epoch_state(self, epoch: int) -> Optional[_EpochState]:
        if not (
            self.epoch - KEEP_BEHIND <= epoch <= self.epoch + EPOCH_HORIZON
        ):
            return None
        es = self._epochs.get(epoch)
        if es is None:
            # every epoch-scoped structure — the ACS and its
            # EchoBank/VoteBank, the coin, the dec-share pools —
            # resolves n/f/keys through the EPOCH's roster version
            view = self.roster_for(epoch)
            if not view.local:
                # not a member under this epoch's roster: a joiner
                # before activation (adopts via CATCHUP), or a
                # retiree after (parks) — no protocol state exists
                return None
            acs = ACS(
                config=view.config,
                crypto=view.crypto,
                epoch=epoch,
                owner=self.node_id,
                member_ids=view.member_ids,
                coin=view.coin,
                coin_secret=view.keys.coin_share,
                out=self.out,
                hub=self.hub,
                coin_issue_sink=self._queue_coin_issue,
                trace=self.trace,
                metrics=self.metrics,
                scope=self._scope_id,
            )
            acs.on_output = self._on_acs_output
            es = _EpochState(acs, view)
            self._epochs[epoch] = es
        return es

    # -- decryption phase (docs/HONEYBADGER-EN.md:61-65) -------------------

    def _on_acs_output(self, epoch: int, output: Dict[str, bytes]) -> None:
        es = self._epochs.get(epoch)
        if es is None or es.output is not None:
            return
        es.output = output
        self.metrics.epoch_acs_output(epoch)
        tr = self.trace
        if tr is not None:
            tr.instant(
                "epoch", "acs_output", epoch=epoch, proposers=len(output),
                **self._lane_kw,
            )
        if self._two_frontier:
            # Two-frontier split: commit the CIPHERTEXT ordering now
            # (WAL-durable, frontier advance — epoch e+1's RBC/BBA
            # starts immediately); the whole TPKE dec-share exchange
            # trails in the settler at the transports' idle callbacks.
            self._maybe_order()
            return
        # -- coupled arm (Config.order_then_settle=False) ----------------
        # Epoch pipelining (BASELINE config 5): this epoch has entered
        # its decryption-share phase — overlap it with the NEXT epoch's
        # proposal (RS encode + Merkle forest + VAL/ECHO round trips).
        if (
            self.auto_propose
            and self.config.epoch_pipelining
            and epoch == self.epoch
            and self._queue_work()
        ):
            self.start_epoch(epoch + 1)
        # share issue AFTER the pipelined next-epoch proposal: the
        # share-issue stage must not absorb epoch e+1's encode time
        self._issue_dec_shares(epoch, es)
        for proposer in list(es.ciphertexts):
            self._try_decrypt(epoch, es, proposer)
        self._maybe_commit(epoch, es)

    def _issue_dec_shares(self, epoch: int, es: _EpochState) -> None:
        """Parse the agreed ciphertexts and broadcast this node's
        decryption share for each — ALL of the epoch's shares in ONE
        batched exponentiation dispatch (and one CP-nonce entropy
        draw).  The coupled path runs this at ACS output, on the
        commit critical path; in two-frontier mode the settler runs it
        off the ordered frontier at an idle boundary."""
        if es.shares_issued or es.output is None:
            return
        es.shares_issued = True
        view = es.view
        local_share = (
            view.local and view.keys.tpke_share is not None
        )
        tr = self.trace
        t_share0 = 0.0 if tr is None else tr.now()
        issue_cts, issue_proposers = self._parse_output_cts(
            es, local_share
        )
        if not local_share:
            # no threshold share under this epoch's roster (a joiner
            # bootstrapping, or an adopted ordering from before our
            # membership): the plaintext arrives via peers' shares or
            # CLOG catch-up — nothing to issue
            return
        dec_shares = view.tpke.dec_share_batch(
            view.keys.tpke_share, issue_cts
        )
        self._broadcast_dec_shares(epoch, issue_proposers, dec_shares)
        if tr is not None:
            tr.complete(
                # the settler runs this off the ordered critical path
                # in two-frontier mode: its mass belongs to the settle
                # track, not the open->ordered window's tpke share
                "settle" if self._two_frontier else "tpke",
                "dec_share_issue",
                t_share0,
                epoch=epoch,
                ciphertexts=len(es.ciphertexts),
            )

    def _parse_output_cts(
        self, es: _EpochState, local_share: bool
    ) -> Tuple[List[Ciphertext], List[str]]:
        """Parse the agreed ciphertexts out of ``es.output`` into
        ``es.ciphertexts`` (junk -> the deterministic-exclusion path
        every correct node takes identically); returns the fresh
        (cts, proposers) still needing this node's decryption share —
        shared by the settler's issue path and the K-deep eager
        staging path."""
        view = es.view
        issue_cts: List[Ciphertext] = []
        issue_proposers: List[str] = []
        for proposer, ct_bytes in es.output.items():
            if proposer in es.ciphertexts or proposer in es.decrypted:
                continue
            try:
                ct = deserialize_ciphertext(
                    ct_bytes, view.keys.tpke_pub.group
                    if local_share
                    else self.group
                )
            except ValueError:
                # Byzantine proposer RBC'd junk: every correct node
                # sees the same bytes, so exclusion is deterministic
                es.decrypted[proposer] = None
                continue
            es.ciphertexts[proposer] = ct
            issue_cts.append(ct)
            issue_proposers.append(proposer)
        return issue_cts, issue_proposers

    def _broadcast_dec_shares(
        self, epoch: int, proposers: Sequence[str], shares
    ) -> None:
        for proposer, share in zip(proposers, shares):
            self.out.broadcast(
                DecSharePayload(
                    proposer=proposer,
                    epoch=epoch,
                    index=share.index,
                    d=share.d,
                    e=share.e,
                    z=share.z,
                )
            )

    def _stage_eager_dec_shares(
        self, epoch: int, es: _EpochState
    ) -> None:
        """Eager dec-share piggybacking (K-deep mode only): ordering
        lands mid-wave — often inside the hub flush, AFTER this
        wave's settler pass already ran — so the classic path would
        park the freshly ordered epoch's dec shares until the NEXT
        wave's idle pass.  Instead, stage the issue work into the
        hub's dec-share column NOW: the first taker of the wave
        executes every staged owner's items in one batched
        exponentiation (ops.tpke.issue_shares_batch — one dispatch
        and one CP-nonce draw for all K epochs and, on a shared-hub
        cluster, all nodes the wave ordered through), and
        _drain_dec_issues broadcasts this node's shares before the
        turn's coalescer flush, so they piggyback on the current
        wave's outbound bundle instead of waiting a full wave."""
        if es.shares_issued or es.output is None:
            return
        es.shares_issued = True
        view = es.view
        local_share = (
            view.local and view.keys.tpke_share is not None
        )
        issue_cts, issue_proposers = self._parse_output_cts(
            es, local_share
        )
        if not local_share:
            return
        # item construction shared with Tpke.dec_share_batch (one
        # home for the CP context/vk binding)
        items = view.tpke.dec_share_items(
            view.keys.tpke_share, issue_cts
        )
        for proposer, item in zip(issue_proposers, items):
            self.hub.stage_dec_issue(
                self,
                (epoch, proposer),
                item,
                view.keys.tpke_pub.group,
            )
            self._eager_staged = True
        if self.trace is not None and issue_proposers:
            self.trace.instant(
                "settle",
                "dec_share_stage",
                epoch=epoch,
                ciphertexts=len(issue_proposers),
            )

    def _drain_dec_issues(self) -> None:
        """Collect this node's eagerly staged dec shares from the
        hub's dec-share column (the first taker executes the WHOLE
        staged pool — see CryptoHub.take_dec_issues) and broadcast
        them: the piggyback send that rides the current wave's
        coalescer flush.  One eager_share_waves tick per wave that
        actually carried eager shares."""
        if not self._eager_staged:
            return
        self._eager_staged = False
        rows = self.hub.take_dec_issues(self)
        if not rows:
            return
        for (epoch, proposer), share in rows:
            # one shared payload-construction path with the settler's
            # issue (per row: stage order spans epochs)
            self._broadcast_dec_shares(epoch, (proposer,), (share,))
        self.metrics.eager_share_waves.inc()

    # -- the ordered frontier (two-frontier mode) --------------------------

    def _maybe_order(self) -> None:
        """Advance the ORDERED frontier: the moment the current
        epoch's ACS output is agreed, durably commit the ciphertext
        ordering (COrd record) and open the next epoch — without
        waiting for the decryption exchange.  Parks while the settled
        frontier trails by Config.decrypt_lag_max epochs, so a
        coalition delaying settlement (share forgery) stalls ordering
        AT the bound instead of letting the durable-plaintext lag grow
        without limit."""
        while True:
            es = self._epochs.get(self.epoch)
            if es is None or es.output is None or es.ordered:
                return
            epoch = self.epoch
            lag = epoch - len(self.committed_batches)
            if lag >= self.config.decrypt_lag_max:
                if (
                    self.trace is not None
                    and self._park_traced != epoch
                ):
                    self._park_traced = epoch
                    self.trace.instant(
                        "epoch", "order_parked", epoch=epoch, lag=lag,
                        **self._lane_kw,
                    )
                return
            self._record_ordered(epoch, es)
            if self._pipeline_depth > 1:
                # K-deep eager path: the epoch's dec shares stage
                # into the hub's dec-share column during the CURRENT
                # message wave and piggyback on this turn's coalescer
                # flush (_drain_dec_issues) instead of waiting for
                # the next wave's settler pass
                self._stage_eager_dec_shares(epoch, es)
            if self.trace is not None:
                self.trace.instant(
                    "epoch",
                    "ordered",
                    epoch=epoch,
                    proposers=len(es.output),
                    **self._lane_kw,
                )
            self.log.debug("ordered", epoch=epoch)
            self._advance_epoch()

    def _record_ordered(
        self,
        epoch: int,
        es: _EpochState,
        body: Optional[bytes] = None,
    ) -> None:
        """The ordered-frontier bookkeeping shared by the local path
        and COrd catch-up adoption: ONE body is the durable WAL
        record, the catch-up serving store, and the fuzzer's
        byte-identity witness — pass the adopted quorum bytes when
        they exist, or the canonical encoding of ``es.output`` is
        used."""
        if body is None:
            body = encode_ordered_body(epoch, es.output)
        es.ordered = True
        tr = self.trace
        es.t_ordered = 0.0 if tr is None else tr.now()
        if self.batch_log is not None:
            self.batch_log.append_ordered_body(epoch, body)
        self._ordered_bodies[epoch] = body
        self.metrics.epoch_ordered(epoch)

    def _drive_settler(self) -> None:
        """The trailing settle track: issue pending dec shares for
        ordered epochs, probe combines, and settle ready epochs
        strictly in order — all OFF the ordered frontier's critical
        path (runs at the transports' idle callbacks, and at turn exit
        on self-draining transports).  Reentrancy-guarded: settling an
        epoch can start the next one, whose turn exit recurses here."""
        if not self._two_frontier or self._settler_active:
            return
        self._settler_active = True
        try:
            for epoch in range(len(self.committed_batches), self.epoch):
                es = self._epochs.get(epoch)
                if es is None or not es.ordered:
                    continue
                if not es.shares_issued:
                    self._issue_dec_shares(epoch, es)
                for proposer in list(es.ciphertexts):
                    if proposer not in es.decrypted:
                        self._try_decrypt(epoch, es, proposer)
            self._maybe_settle()
        finally:
            self._settler_active = False

    def _maybe_settle(self) -> None:
        """Settle ordered epochs in order at the SETTLED frontier:
        write the plaintext CLOG record, apply the dedup filter, fire
        on_commit.  Each settlement may unlock the next epoch's
        already-complete decryption — and releases backpressure on the
        ordered frontier."""
        while True:
            epoch = len(self.committed_batches)
            if epoch >= self.epoch:
                return  # nothing ordered ahead of settlement
            es = self._epochs.get(epoch)
            if (
                es is None
                or not es.ordered
                or es.committed
                or es.output is None
                or any(p not in es.decrypted for p in es.output)
            ):
                return
            self._commit_batch(epoch, es)
            self._prune_epoch_states()
            # settling may release backpressure: resume ordering (and
            # with it, proposing) the moment lag drops below the bound
            # — on BOTH ordering paths, or a catch-up node parked at
            # the bound with a full f+1 COrd tally wedges in a
            # quiescent cluster
            self._maybe_order()
            self._maybe_adopt_ordered()

    def _handle_dec_share(
        self,
        epoch: int,
        es: _EpochState,
        sender: str,
        proposer: str,
        index: int,
        d: int,
        e: int,
        z: int,
    ) -> None:
        view = es.view
        if not view.local:
            return  # no threshold material: the epoch settles via CLOG
        if (
            sender not in view.member_set
            or proposer not in view.member_set  # bounds es.dec_shares
            or not (1 <= index <= view.config.n)
        ):
            return
        pool = es.dec_shares.setdefault(
            proposer, SharePool(view.keys.tpke_pub.threshold)
        )
        if not pool.add_lazy(sender, index, d, e, z):
            self.metrics.dedup_absorbed.inc()
            return
        if self._two_frontier:
            # shares only POOL on the message path; the settler probes
            # combines and settles at the next idle boundary, so the
            # decrypt work batches per wave instead of per frame
            return
        self._try_decrypt(epoch, es, proposer)
        self._maybe_commit(epoch, es)

    def _handle_dec_share_batch(
        self, epoch: int, es: _EpochState, sender: str, payload
    ) -> None:
        """One sender's decryption shares across many proposers
        (DecShareBatchPayload): a width-1 wave — probes once per
        touched proposer, commit check once per frame (the shared
        pooling loop lives in _handle_dec_share_wave, so the scalar
        and wave arms cannot drift apart on the crossing rule)."""
        self._handle_dec_share_wave(epoch, es, ((sender, payload),))

    def _handle_dec_share_wave(
        self, epoch: int, es: _EpochState, items
    ) -> None:
        """One delivery wave's decryption shares for one epoch across
        ALL senders (the WaveRouter's dec column; DecShareBatchPayload
        delegates here as a width-1 wave): every share pools under the
        same per-(sender, proposer) dedup as the scalar handler; the
        threshold probes run once per TOUCHED proposer and the commit
        check once per WAVE — identical outcomes, since neither has
        observable effects below its threshold.  Probes fire only on
        the threshold CROSSING (below it nothing can combine; above it
        the only consumers of fresh shares are a flagged pool needing
        CP-path replacements and an index-short pool awaiting a
        distinct Shamir index); missed-window cases re-probe via
        _on_acs_output (output arrives after crossing) and
        _on_dec_verdicts (burn with replacements parked)."""
        view = es.view
        if not view.local:
            return  # no threshold material: the epoch settles via CLOG
        member = view.member_set
        pools = es.dec_shares
        threshold = view.keys.tpke_pub.threshold
        n = view.config.n
        opt_failed = es.opt_failed
        opt_short = es.opt_short
        probe = not self._two_frontier  # two-frontier: settler probes
        touched: List[str] = []
        touched_set: Set[str] = set()
        for sender, p in items:
            if sender not in member:
                continue
            index = p.index
            if not (1 <= index <= n):
                continue
            if p.__class__ is DecSharePayload:
                proposers = (p.proposer,)
                dcol, ecol, zcol = (p.d,), (p.e,), (p.z,)
            else:
                proposers = p.proposers
                dcol, ecol, zcol = p.d, p.e, p.z
            for i, proposer in enumerate(proposers):
                if proposer not in member:
                    continue
                pool = pools.get(proposer)
                if pool is None:
                    pool = pools.setdefault(
                        proposer, SharePool(threshold)
                    )
                if pool.add_lazy(
                    sender, index, dcol[i], ecol[i], zcol[i]
                ):
                    if not probe or proposer in touched_set:
                        continue
                    n_pool = len(pool)
                    if n_pool == threshold or (
                        n_pool > threshold
                        and (
                            proposer in opt_failed
                            or proposer in opt_short
                        )
                    ):
                        touched_set.add(proposer)
                        touched.append(proposer)
                else:
                    self.metrics.dedup_absorbed.inc()
        if not touched:
            return
        for proposer in touched:
            self._try_decrypt(epoch, es, proposer)
        self._maybe_commit(epoch, es)

    def _try_decrypt(
        self, epoch: int, es: _EpochState, proposer: str
    ) -> None:
        """Threshold reached: optimistic combine first — the ciphertext
        tag authenticates the combined KEM value, so in the honest case
        NO per-share CP verification runs at all (it replaces 2(f+1)
        dual-exponentiations per proposer).  A bad tag means a selected
        share was invalid: flag the proposer onto the CP-verified hub
        path, which burns the culprit and combines valid shares."""
        if es.output is None or proposer in es.decrypted:
            return
        ct = es.ciphertexts.get(proposer)
        if ct is None:
            return
        view = es.view
        pool = es.dec_shares.get(proposer)
        if pool is None or len(pool) < view.keys.tpke_pub.threshold:
            return
        if proposer not in es.opt_failed:
            subset = pool.optimistic_subset()
            if subset is None:
                # size threshold met but too few distinct indices —
                # keep the batched handler probing on later adds
                es.opt_short.add(proposer)
                return
            es.opt_short.discard(proposer)
            tr = self.trace
            t0 = 0.0 if tr is None else tr.now()
            try:
                plain = view.tpke.combine(ct, subset)
            except ValueError:  # bad tag: an invalid share slipped in
                es.opt_failed.add(proposer)
                self.hub.mark_dirty(self)
                self.hub.request_flush()
                return
            if tr is not None:
                tr.complete(
                    "settle" if self._two_frontier else "tpke",
                    "combine",
                    t0,
                    epoch=epoch,
                    proposer=proposer,
                )
            try:
                es.decrypted[proposer] = deserialize_txs(
                    plain, self._tx_parse_memo
                )
            except ValueError:
                # authentic plaintext, malformed framing: the
                # proposer's own doing, identical at every node
                es.decrypted[proposer] = None
            return
        # flagged proposer: freshly pooled shares need CP verification
        self.hub.mark_dirty(self)
        self.hub.request_flush()

    # -- hub client protocol (protocol.hub.CryptoHub) ----------------------

    def drain_pending(self, wave) -> None:
        for epoch, es in self._epochs.items():
            if es.output is None or es.committed or not es.view.local:
                continue
            view = es.view
            for proposer, ct in es.ciphertexts.items():
                if proposer in es.decrypted:
                    continue
                if proposer not in es.opt_failed:
                    # honest path: the optimistic combine needs no CP
                    # verification; don't burn modexps on its shares
                    continue
                pool = es.dec_shares.get(proposer)
                if pool is None:
                    continue
                senders, shs = pool.collect_pending(pool.need_more())
                if not senders:
                    continue
                wave.add_share(
                    view.keys.tpke_pub,
                    ct.c1,
                    view.tpke.context(ct),
                    senders,
                    shs,
                    lambda snd, ok, pool=pool: self._on_dec_verdicts(
                        pool, snd, ok
                    ),
                )

    def _on_dec_verdicts(self, pool, senders, ok) -> None:
        pool.apply_verdicts(senders, ok)
        if not all(ok) and pool.need_more():
            # burned slot, replacements already parked: re-mark or the
            # dirty-set flush never collects them again (same liveness
            # hazard as BBA._on_coin_verdicts; round-3 review)
            self.hub.mark_dirty(self)

    def after_crypto_flush(self) -> None:
        for epoch, es in list(self._epochs.items()):
            if es.output is None or es.committed or not es.view.local:
                continue
            for proposer, ct in list(es.ciphertexts.items()):
                if proposer in es.decrypted:
                    continue
                pool = es.dec_shares.get(proposer)
                if pool is None:
                    continue
                valid = pool.ready()
                if valid is None:
                    continue
                try:
                    plain = es.view.tpke.combine(ct, valid)
                    es.decrypted[proposer] = deserialize_txs(
                        plain, self._tx_parse_memo
                    )
                except ValueError:
                    # combined KEM value is independent of the share
                    # subset, so a failed tag/framing fails identically
                    # at every node
                    es.decrypted[proposer] = None
            self._maybe_commit(epoch, es)

    # -- CATCHUP (crash-recovery state transfer; SURVEY.md §5.3-5.4) -------

    def request_catchup(self) -> None:
        """Ask the roster for every committed batch from our commit
        frontier on (call after a restart; also fired automatically
        when peer traffic shows we are more than EPOCH_HORIZON
        behind).  Peers each answer with up to CATCHUP_MAX_EPOCHS
        CatchupResp payloads; epochs adopt in order as each collects
        f+1 identical bodies."""
        try:
            self._request_catchup(force=True)
        finally:
            self._exit_turn()

    def _maybe_chase_stall(self) -> None:
        """Reduced-quorum stall watchdog (see CATCHUP_STALL_BUDGET).

        Runs at every transport idle callback, right before the
        outbound flush so a fired chase ships with this wave.  A
        "quiet" idle — no serve_wave/serve_request ingest since the
        previous idle callback — while epochs sit started-but-unsettled
        is the signature of the n-f totality wedge: the roster went
        quiescent around an instance this node is one attested READY
        short of delivering (a lossy coalition sender's frame that
        nobody will re-send).  Chasing the settled frontier through
        CATCHUP retrieves the committed batches instead; the budget
        (re-armed on every settle advance) bounds the extra traffic so
        a genuinely unservable frontier — fewer than f+1 peers hold
        the batch — still quiesces."""
        if not self.config.reduced_quorum:
            return
        rx = self._idle_rx
        quiet = rx == self._idle_rx_seen
        self._idle_rx_seen = rx
        settled = len(self.committed_batches)
        # stuck = settled behind the live frontier, OR a live-frontier
        # epoch whose ACS/settle never finished (a node wedged inside
        # its very first epoch has settled == self.epoch == 0 — the
        # frontier comparison alone would read as healthy)
        stuck = settled < self.epoch or any(
            not es.committed for es in self._epochs.values()
        )
        if not stuck:
            self._stall_nudges = 0
            return
        if not quiet:
            return
        if settled != self._stall_frontier:
            self._stall_frontier = settled
            self._stall_nudges = 0
        if self._stall_nudges >= CATCHUP_STALL_BUDGET:
            return
        self._stall_nudges += 1
        if self.trace is not None:
            self.trace.instant(
                "catchup", "stall_chase", settled=settled, live=self.epoch
            )
        self._request_catchup(force=True)

    def _request_catchup(self, force: bool = False) -> None:
        # the SETTLED frontier is what we are missing durably; peers
        # answer with CLOG bodies from there plus (two-frontier mode)
        # COrd bodies up to their ordered frontier.  On the coupled
        # path settled == self.epoch, the historical behavior.
        frontier = len(self.committed_batches)
        if not force and self._last_catchup_request == frontier:
            return  # one broadcast per frontier (re-fired as we adopt)
        self._last_catchup_request = frontier
        if self.trace is not None:
            self.trace.instant("catchup", "request", from_epoch=frontier)
        self.out.broadcast(CatchupReqPayload(from_epoch=frontier))

    def _handle_catchup_req(
        self, sender: str, p: CatchupReqPayload
    ) -> None:
        # membership over time: any known roster version's member —
        # a bootstrapping joiner or a not-yet-torn-down retiree is a
        # legitimate catch-up correspondent during the transition
        if not self._reconfig.known_member(sender):
            return
        start = p.from_epoch
        # remembered even when unservable: if the link to the sender
        # heals later, peer_reconnected re-serves from here
        self._catchup_last_req[sender] = start
        end = min(len(self.committed_batches), start + CATCHUP_MAX_EPOCHS)
        # two-frontier mode: epochs we ORDERED but have not settled yet
        # have no plaintext to serve, but their agreed ciphertext
        # ordering (COrd body) still lets the requester advance its
        # ordered frontier and rejoin the live epochs
        ord_start = max(start, len(self.committed_batches))
        ord_end = (
            min(self.epoch, start + CATCHUP_MAX_EPOCHS)
            if self._two_frontier
            else 0
        )
        serve_ord = [
            e
            for e in range(ord_start, ord_end)
            if e in self._ordered_bodies
        ]
        if not (0 <= start < end) and not serve_ord:
            if 0 <= start and start >= len(self.committed_batches):
                # asked at (or past) our own frontier: park it and
                # re-serve when settlement advances past the ask
                self._catchup_parked[sender] = start
            return  # nothing committed there (yet) that we can serve
        self._catchup_parked.pop(sender, None)
        end = max(end, start)  # plaintext range may be empty
        # amplification guard: a legitimately catching-up node's
        # from_epoch strictly advances past each window we served it;
        # a request that does NOT advance (replayed frame, Byzantine
        # request loop, or an honest retry after lost responses) draws
        # from a small repeat budget re-armed on every local epoch
        # advance and on link heal — counted, not clocked, so seeded
        # deterministic runs replay exactly, yet an 8-byte request no
        # longer buys unlimited 32-batch responses
        if start < self._catchup_floor.get(sender, 0):
            budget = self._catchup_repeats.get(
                sender, CATCHUP_REPEAT_BUDGET
            )
            if budget <= 0:
                return
            self._catchup_repeats[sender] = budget - 1
        self._catchup_floor[sender] = max(
            self._catchup_floor.get(sender, 0), end, ord_end
        )
        if self.trace is not None:
            self.trace.instant(
                "catchup",
                "serve",
                from_epoch=start,
                epochs=max(0, end - start),
                ordered=len(serve_ord),
            )
        # one response per missed epoch; the coalescing broadcaster
        # bundles the run into a single envelope for the requester
        self._send_clog_range(sender, start, end)
        for epoch in serve_ord:
            self.out.send_to(
                sender,
                CatchupOrdPayload(
                    epoch=epoch, body=self._ordered_bodies[epoch]
                ),
            )
        if serve_ord:
            # part of the window went out as ciphertext orderings
            # only: owe the requester those epochs' plaintext, pushed
            # from _serve_owed_plaintext as settlement reaches them
            self._catchup_plain_owed[sender] = (
                end,
                serve_ord[-1] + 1,
            )

    def _serve_owed_plaintext(self) -> None:
        """Settlement made new plaintext servable: push the CLOG
        bodies owed to requesters whose last window we could only
        answer with COrd bodies.  By the time we settle, such a
        requester's repeat budget is typically spent and budgets
        re-arm only on ORDERING advances — without this push a
        quiescent cluster wedges with the requester parked at the
        decrypt-lag bound.  Bounded by the limit fixed at serve time:
        each request buys at most its own window, once as COrd and
        once as CLOG."""
        if self._catchup_parked:
            settled = len(self.committed_batches)
            for sender, start in sorted(self._catchup_parked.items()):
                if start < settled:
                    # re-enter the normal serve path (it pops the
                    # park on success and applies every guard)
                    self._handle_catchup_req(
                        sender, CatchupReqPayload(from_epoch=start)
                    )
        if not self._catchup_plain_owed:
            return
        settled = len(self.committed_batches)
        for sender, (nxt, limit) in list(
            self._catchup_plain_owed.items()
        ):
            end = min(settled, limit)
            if nxt >= end:
                if nxt >= limit:
                    del self._catchup_plain_owed[sender]
                continue
            if self.trace is not None:
                self.trace.instant(
                    "catchup",
                    "serve_settled",
                    from_epoch=nxt,
                    epochs=end - nxt,
                )
            self._send_clog_range(sender, nxt, end)
            if end >= limit:
                del self._catchup_plain_owed[sender]
            else:
                self._catchup_plain_owed[sender] = (end, limit)

    def _send_clog_range(
        self, sender: str, start: int, end: int
    ) -> None:
        """One CatchupResp per committed epoch in [start, end) — the
        serve loop shared by direct catch-up answers and the
        owed-plaintext push."""
        for epoch in range(start, end):
            self.out.send_to(
                sender,
                CatchupRespPayload(
                    epoch=epoch,
                    body=encode_batch_body(
                        epoch, self.committed_batches[epoch]
                    ),
                ),
            )

    def peer_reconnected(self, member_id: str) -> None:
        """Transport event: our link to ``member_id`` was just
        (re-)established.  Responses served while the link was down
        went into the void, and the requester's per-frontier dedup
        means it will not ask again on its own — so re-arm the
        sender's serving budget and re-serve its last requested
        window.  This is what completes an interrupted state transfer
        once the self-healing dial layer heals the path (the gRPC
        crash/rejoin flow); event-driven, so deterministic transports
        stay deterministic."""
        try:
            if not self._reconfig.known_member(member_id):
                return
            self._catchup_repeats.pop(member_id, None)
            last = self._catchup_last_req.get(member_id)
            servable = len(self.committed_batches)
            if self._two_frontier:
                servable = max(servable, self.epoch)  # COrd bodies too
            if last is not None and last < servable:
                self._catchup_floor.pop(member_id, None)
                self._handle_catchup_req(
                    member_id, CatchupReqPayload(from_epoch=last)
                )
        finally:
            self._exit_turn()

    def _tally_winner(self, tally, expected_epoch, decode):
        """The shared f+1 quorum rule of BOTH catch-up planes
        (plaintext CLOG and ordered COrd bodies): pick the most-voted
        body; below f+1 votes nothing adopts.  An f+1 quorum always
        contains an honest sender, so a winning body that fails
        ``decode`` / claims the wrong epoch is pure-Byzantine — shed
        its votes and re-tally.  Returns (decoded_value, body) or
        None; sheds mutate ``tally`` in place."""
        while tally:
            counts: Dict[bytes, int] = {}
            for body in tally.values():
                counts[body] = counts.get(body, 0) + 1
            body, votes = max(counts.items(), key=lambda kv: kv[1])
            # the quorum width follows the EPOCH's roster (an adopted
            # epoch past an activation boundary counts under f')
            if votes < self.roster_for(expected_epoch).config.f + 1:
                return None
            try:
                epoch, decoded = decode(body)
            except (ValueError, struct.error, UnicodeDecodeError):
                epoch = decoded = None
            if epoch != expected_epoch:
                for snd in [s for s, b in tally.items() if b == body]:
                    del tally[snd]
                continue
            return decoded, body
        return None

    def _handle_catchup_resp(
        self, sender: str, p: CatchupRespPayload
    ) -> None:
        if not self._reconfig.known_member(sender):
            return
        # plaintext adoption happens at the SETTLED frontier (== the
        # live frontier on the coupled path); in two-frontier mode an
        # ordered-ahead node accepts CLOG bodies for epochs it ordered
        # but could not settle (e.g. a restart lost its peers' shares)
        frontier = len(self.committed_batches)
        if not (frontier <= p.epoch < frontier + CATCHUP_WINDOW):
            return  # stale, or absurdly far ahead: bound tally memory
        # one vote per (epoch, sender); a re-send overwrites, never adds
        self._catchup_tallies.setdefault(p.epoch, {})[sender] = p.body
        adopted = False
        # adopt in epoch order at the frontier; each adoption may
        # unlock the NEXT epoch's already-collected quorum
        while True:
            frontier = len(self.committed_batches)
            tally = self._catchup_tallies.get(frontier)
            if not tally:
                break
            won = self._tally_winner(tally, frontier, decode_batch_body)
            if won is None:
                break
            batch, _body = won
            self._adopt_catchup_batch(frontier, batch)
            adopted = True
        if adopted:
            # the frontier moved: peers may hold more epochs than one
            # serving window.  Non-forced => the per-frontier dedup
            # broadcasts exactly once per new frontier value, even if
            # a sub-quorum (or Byzantine) tally already sits there —
            # that tally alone must never suppress the chase, or a
            # single dropped/forged response wedges the catch-up in a
            # quiescent cluster.
            self._request_catchup()

    def _adopt_catchup_batch(self, epoch: int, batch: Batch) -> None:
        """Commit a batch learned via CATCHUP instead of running the
        (long-gone) epoch ourselves."""
        self.log.info("adopted catch-up batch", epoch=epoch, txs=len(batch))
        if self.trace is not None:
            self.trace.instant(
                "catchup", "adopt", epoch=epoch, txs=len(batch)
            )
        self.committed_batches.append(batch)
        seen = set(batch.tx_list())
        self._remember_committed(seen)
        self.metrics.epoch_committed(epoch, len(batch))
        if self.batch_log is not None:
            self.batch_log.append(epoch, batch)
            self._maybe_log_checkpoint(epoch)
        self._epochs.pop(epoch, None)  # any partial local state is moot
        self.hub.drop_scope((self.node_id, epoch))
        self._catchup_tallies.pop(epoch, None)
        # adopted batches feed the reconfig plane exactly like local
        # settlements: a crashed/partitioned node learns a ceremony
        # happened from the log it catches up on
        self._reconfig.on_batch_settled(epoch, batch)
        self._maybe_teardown_retired()
        self._serve_owed_plaintext()
        self._notify_commit(epoch, batch)
        if self._two_frontier and epoch < self.epoch:
            # plaintext for an epoch we had already ORDERED (restart
            # with an ordered-ahead window, or a settle stall peers
            # resolved first): the settled frontier advanced; the live
            # frontier is already past.  The next ordered epoch may be
            # ready, and settling may release ordering backpressure.
            self._catchup_ord_tallies.pop(epoch, None)
            self._maybe_settle()
            self._maybe_order()
            self._maybe_adopt_ordered()
            return
        self._advance_epoch()
        if self._two_frontier:
            self._maybe_order()  # a buffered ACS output may be next

    # -- ordered-frontier CATCHUP (two-frontier mode) ----------------------

    def _handle_catchup_ord(
        self, sender: str, p: CatchupOrdPayload
    ) -> None:
        if not self._two_frontier or not self._reconfig.known_member(
            sender
        ):
            return
        if not (self.epoch <= p.epoch < self.epoch + CATCHUP_WINDOW):
            return  # stale, or absurdly far ahead: bound tally memory
        self._catchup_ord_tallies.setdefault(p.epoch, {})[sender] = p.body
        self._maybe_adopt_ordered()

    def _maybe_adopt_ordered(self) -> None:
        """Adopt ciphertext orderings learned via COrd catch-up, in
        order at the ORDERED frontier, each on f+1 byte-identical
        bodies (>= 1 honest sender => the agreed ACS output) — the
        exact adoption rule of the plaintext path, one frontier up.
        Backpressure applies the same way: adopted ordered-ahead
        epochs are bounded by Config.decrypt_lag_max."""
        adopted = False
        while True:
            if (
                self.epoch - len(self.committed_batches)
                >= self.config.decrypt_lag_max
            ):
                break  # the settler must drain before we order ahead
            tally = self._catchup_ord_tallies.get(self.epoch)
            if not tally:
                break
            won = self._tally_winner(
                tally, self.epoch, decode_ordered_body
            )
            if won is None:
                break
            output, body = won
            self._adopt_ordered(self.epoch, output, body)
            adopted = True
        if adopted:
            # chase the rest (plaintext AND ordered) from the peers.
            # Forced: COrd adoption advances the ORDERED frontier only,
            # and the non-forced dedup keys on the settled frontier —
            # without force this chase would be a no-op until
            # settlement moves (peers' counted repeat budgets still
            # bound a stuck requester)
            self._request_catchup(force=True)

    def _adopt_ordered(
        self, epoch: int, output: Dict[str, bytes], body: bytes
    ) -> None:
        """One ordering adopted: durable COrd record, bookkeeping,
        frontier advance.  The settler decrypts it like any locally
        ordered epoch — our own dec share re-issues at the next idle
        boundary; the plaintext typically completes via the share
        exchange or CLOG catch-up once peers settle."""
        self.log.info("adopted catch-up ordering", epoch=epoch)
        if self.trace is not None:
            self.trace.instant("catchup", "adopt_ordered", epoch=epoch)
        es = self._epochs.get(epoch)
        if es is None:
            es = _EpochState(None, self.roster_for(epoch))
            es.proposed = True
            self._epochs[epoch] = es
        if es.output is None:
            es.output = output
        self._record_ordered(epoch, es, body)
        self._catchup_ord_tallies.pop(epoch, None)
        self._advance_epoch()

    def _maybe_log_checkpoint(self, epoch: int) -> None:
        """Every Config.ledger_checkpoint_every commits, snapshot the
        dedup window into the WAL (call AFTER _remember_committed so
        the checkpoint covers ``epoch`` itself)."""
        every = self.config.ledger_checkpoint_every
        if every <= 0:
            return
        self._commits_since_ckpt += 1
        if self._commits_since_ckpt >= every:
            self._commits_since_ckpt = 0
            self.batch_log.append_checkpoint(
                epoch, self._committed_history
            )

    # -- commit (the consensused batch of honeybadger.go:20-21) ------------

    def _maybe_commit(self, epoch: int, es: _EpochState) -> None:
        if self._two_frontier:
            # decryption progress feeds the SETTLED frontier; the
            # ordered frontier advanced at ACS output
            self._maybe_settle()
            return
        if es.committed or es.output is None or epoch != self.epoch:
            return
        if any(p not in es.decrypted for p in es.output):
            return
        self._commit_batch(epoch, es)
        self._advance_epoch()

    def _commit_batch(self, epoch: int, es: _EpochState) -> None:
        """Deliver one fully-decrypted epoch: build the deduped batch,
        append the plaintext CLOG record, fold the dedup filter, fire
        on_commit.  The coupled path runs this at the (single) commit
        frontier; two-frontier mode runs it at the settled frontier,
        strictly in epoch order."""
        es.committed = True
        seen: Set[bytes] = set()
        contributions: Dict[str, List[bytes]] = {}
        for proposer in sorted(es.output):
            txs = es.decrypted[proposer]
            if not txs:
                continue
            mine: List[bytes] = []
            for tx in txs:
                if tx not in seen:  # first contribution wins (dedupe)
                    seen.add(tx)
                    mine.append(tx)
            if mine:
                contributions[proposer] = mine
        batch = Batch(contributions=contributions)
        self.committed_batches.append(batch)
        self.metrics.epoch_committed(epoch, len(batch))
        if self.trace is not None:
            self.trace.instant(
                "epoch", "commit", epoch=epoch, txs=len(batch),
                **self._lane_kw,
            )
            if es.t_ordered:
                # the settle track made visible: one span from the
                # ciphertext-ordered commit to plaintext settlement —
                # the tpke mass that LEFT the open->ordered window
                self.trace.complete(
                    "settle", "decrypt_lag", es.t_ordered, epoch=epoch,
                    **self._lane_kw,
                )
        if self.batch_log is not None:
            self.batch_log.append(epoch, batch)
        self.log.debug("committed", epoch=epoch, txs=len(batch))
        # re-queue our own txs that did not make it into the set
        if es.proposed:
            for tx in es.my_txs:
                if tx not in seen:
                    self.que.push(tx)
        # remember what committed so duplicate local submissions are
        # dropped lazily at poll time (bounded memory)
        self._remember_committed(seen)
        if self.batch_log is not None:
            self._maybe_log_checkpoint(epoch)
        # the reconfig plane reads every settled batch (RECONFIG +
        # dealing transactions drive discovery / qualified-set /
        # finalize), and settlement crossing an activation boundary
        # releases the retirees
        self._reconfig.on_batch_settled(epoch, batch)
        self._maybe_teardown_retired()
        self._notify_commit(epoch, batch)
        self._serve_owed_plaintext()

    def _prune_epoch_states(self) -> None:
        """Drop epoch state that is BOTH outside the demux window
        (late frames for it are rejected by ``_epoch_state``, so the
        state can never be touched again) and — in two-frontier mode
        — settled (an ordered-but-unsettled epoch must stay live
        however far the ordered frontier runs; its share exchange and
        settlement are still pending).  Driven from ordering advances
        AND from settlement: a quiescing two-frontier node settles
        its last ``decrypt_lag_max`` epochs with no further ordering,
        and must not retain their ACS/share state indefinitely."""
        settled = len(self.committed_batches)
        for stale in [
            e
            for e in self._epochs
            if e < self.epoch - KEEP_BEHIND
            and (not self._two_frontier or e < settled)
        ]:
            del self._epochs[stale]
            self.hub.drop_scope((self._scope_id, stale))

    def _advance_epoch(self) -> None:
        """Advance the live-protocol frontier ``self.epoch``: at every
        commit on the coupled path, at every ORDERING in two-frontier
        mode (where commit = settle trails behind)."""
        self.epoch += 1
        # crossing a roster activation boundary swaps the ACTIVE view
        # (keys, batch policy) before anything proposes into the new
        # epoch
        self._maybe_activate_roster()
        settled = len(self.committed_batches)
        for stale in [  # tallies below the frontier can never adopt
            e for e in self._catchup_tallies if e < settled
        ]:
            del self._catchup_tallies[stale]
        for stale in [
            e for e in self._catchup_ord_tallies if e < self.epoch
        ]:
            del self._catchup_ord_tallies[stale]
        for stale in [
            # COrd catch-up only ever serves from the settled frontier
            # up; bodies further behind are diagnostic witnesses (the
            # fuzzer's cross-node byte-identity check), kept for one
            # serving window, never forever
            e
            for e in self._ordered_bodies
            if e < settled - CATCHUP_MAX_EPOCHS
        ]:
            del self._ordered_bodies[stale]
        # progress re-arms the catch-up serving budgets and the
        # far-ahead retry clock (both counted per frontier value)
        self._catchup_repeats.clear()
        self._farahead_sightings = 0
        self._prune_epoch_states()
        # propose into the new epoch if we have work, if peers already
        # started it (its state exists from buffered traffic), or if
        # an installed roster switch still lies ahead — the boundary
        # only activates when the frontier REACHES it, so the old
        # roster drives (possibly empty) epochs up to the switch
        # instead of letting a quiescent cluster wedge mid-transition
        if self.auto_propose and (
            self._queue_work()
            or self.epoch in self._epochs
            or self.epoch < self.rosters.latest().activation_epoch
        ):
            self.start_epoch()
        if self._two_frontier:
            # the _maybe_order loop picks up the next epoch's buffered
            # ACS output; settlement is the settler's business
            return
        # the new current epoch may have fully resolved while we were
        # still committing the previous one
        es = self._epochs.get(self.epoch)
        if es is not None and es.output is not None:
            self._maybe_commit(self.epoch, es)


__all__ = [
    "HoneyBadger",
    "NodeKeys",
    "setup_keys",
    "serialize_txs",
    "deserialize_txs",
    "serialize_ciphertext",
    "deserialize_ciphertext",
    "KEEP_BEHIND",
    "EPOCH_HORIZON",
    "CATCHUP_MAX_EPOCHS",
    "CATCHUP_WINDOW",
]
