"""EchoBank: vectorized ECHO/READY receipt state across RBC instances.

An epoch runs N concurrent RBC instances (one per proposer,
docs/HONEYBADGER-EN.md:85-89), and within one wave a sender emits one
ECHO and one READY per instance — the coalescer ships them as ONE
columnar payload each (transport.message EchoBatchPayload /
ReadyBatchPayload).  Per-instance scalar processing of such a wave
costs O(N) python set/dict operations per (sender, receiver) frame;
after PR 7 moved dispatch off the critical path, this per-payload
receipt mass is what the PR-3 critical-path reports attribute to the
delivery plane (ROADMAP "Async-path wall clock").

The bank is the VoteBank treatment applied to RBC: one
struct-of-arrays per ACS holding every instance's ECHO/READY receipt
state, so a columnar wave's dedup, membership, delivered-instance
filtering and quorum counting run as a handful of numpy row operations,
and only threshold CROSSINGS (f+1 READY relay, 2f+1 deliver probe, the
N-f echo-quorum flush request — a constant number per instance) fall
back to the per-instance protocol logic in RBC.

Array layouts put the wave's axis LAST: receipt state is indexed
``seen[sender, instance]`` so one frame's dedup probe is a contiguous
row, and delivered/halted instances fold into ONE ``state`` vector (a
huge sentinel — every later delivery for them drops in the same
vectorized filter, before any python-level dispatch).

Quorum counting is per (root, instance): distinct Merkle roots map to
rows of the counting matrices through a registry, so a Byzantine
proposer equivocating different roots to different receivers keeps
fully separate counters — the bank can never conflate two roots'
quorums (the PR-4 Equivocator coalition runs against exactly this).
Registry growth is bounded by the one-vote-per-(sender, instance)
claim discipline: at most senders x instances distinct roots can ever
be counted.

Pending (hub-unverified) ECHO proofs park per instance in contiguous
arrival-order lists — ``pending[instance]`` — which RBC.drain_pending
pops WHOLESALE into the hub wave's branch columns, replacing the old
per-root dict-of-dicts walk with one list handoff.

Consistency contract: the bank is the SINGLE source of truth for
ECHO/READY receipt state.  RBC's scalar path (per-payload deliveries,
unit tests, non-columnar transports) writes through the same arrays,
so columnar and scalar deliveries interleave freely and the
``Config.delivery_columnar`` transport arms cannot diverge here.

Quorum semantics mirrored from RBC (docs/RBC-EN.md:35-42): +1
increments under one-vote-per-sender dedup make exact-equality
crossing detection (cnt == f+1) equivalent to the
>=-with-idempotent-guard scalar form; the 2f+1 deliver probe stays >=
because decode completion re-probes ride later arrivals.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# Byzantine batches can mint unlimited distinct proposer tuples; the
# index cache clears wholesale at the cap (honest traffic reuses a
# handful of tuples per wave).
_PROP_CACHE_CAP = 4096

# state sentinel for delivered/halted instances: live instances sit at
# 0, so one vectorized compare drops every late vote for a terminal
# instance (same discipline as votebank._HALTED)
_HALTED = 1 << 62


class EchoBank:
    """Struct-of-arrays ECHO/READY receipt state for up to ``n_inst``
    RBC instances over a fixed roster."""

    def __init__(
        self,
        member_ids: Sequence[str],
        f: int,
        inst_ids: Optional[Sequence[str]] = None,
        metrics=None,
        quorum_large: Optional[int] = None,
    ) -> None:
        self.members: List[str] = sorted(member_ids)
        self.f = f
        # the READY deliver threshold: 2f+1 in the baseline trust
        # model, n-f under Config.reduced_quorum (identical whenever
        # n = 3f+1 exactly — see Config.quorum_large)
        self.q_large = 2 * f + 1 if quorum_large is None else quorum_large
        # owner-node metrics (None in standalone unit tests): only the
        # duplicate-vote absorption counter is touched here
        self.metrics = metrics
        self.sidx: Dict[str, int] = {
            m: i for i, m in enumerate(self.members)
        }
        insts = self.members if inst_ids is None else list(inst_ids)
        self.iidx: Dict[str, int] = {p: i for i, p in enumerate(insts)}
        ns, n_inst = len(self.members), len(insts)
        # [sender, instance]: one frame's dedup probe is a contiguous
        # row (wave axis last, like votebank.bval_seen)
        self.echo_seen = np.zeros((ns, n_inst), dtype=bool)
        self.ready_seen = np.zeros((ns, n_inst), dtype=bool)
        # 0 = live; _HALTED once the instance delivered — the
        # vectorized stale filter every batch entry applies first
        self.state = np.zeros(n_inst, dtype=np.int64)
        self.rbcs: List[object] = [None] * n_inst
        # pending (unverified) ECHO proofs per instance, contiguous
        # arrival order: (root, sender, shard, shard_index, branch).
        # RBC.drain_pending pops a slot wholesale into hub columns.
        self.pending: List[list] = [[] for _ in range(n_inst)]
        # root registry: distinct root bytes -> row of the counting
        # matrices.  Bounded by the claim discipline (a row is only
        # ever allocated for a vote that claimed its one
        # (sender, instance) slot), so <= senders x instances rows.
        self._root_rows: Dict[bytes, int] = {}
        cap0 = max(4, n_inst)
        # [root_row, instance] quorum counters, wave axis last:
        # echo_pot counts CLAIMED echoes (pending + verified — the
        # flush-trigger potential), ready_cnt distinct READY senders
        self.echo_pot = np.zeros((cap0, n_inst), dtype=np.int32)
        self.ready_cnt = np.zeros((cap0, n_inst), dtype=np.int32)
        self._prop_cache: "Dict[tuple, Tuple[np.ndarray, np.ndarray, bool]]" = {}

    # -- membership --------------------------------------------------------

    def attach(self, index: int, rbc) -> None:
        self.rbcs[index] = rbc

    def deactivate(self, index: int) -> None:
        """Delivered/halted instance: every later delivery for it
        drops in the vectorized state filter, and its pending slot is
        released (the instance is terminal — nothing will drain it)."""
        self.state[index] = _HALTED
        self.pending[index] = []

    # -- root registry -----------------------------------------------------

    def _row(self, root: bytes) -> int:
        row = self._root_rows.get(root)
        if row is None:
            row = len(self._root_rows)
            self._root_rows[root] = row
            if row >= self.echo_pot.shape[0]:
                grow = self.echo_pot.shape[0]
                self.echo_pot = np.vstack(
                    (self.echo_pot, np.zeros_like(self.echo_pot[:grow]))
                )
                self.ready_cnt = np.vstack(
                    (self.ready_cnt, np.zeros_like(self.ready_cnt[:grow]))
                )
        return row

    # -- scalar write-through (RBC's non-columnar path) --------------------

    def echo_claim(self, index: int, sender_idx: int, root: bytes) -> int:
        """Claim one sender's ECHO slot for ``index`` and count it
        against ``root``; returns the new echo potential (pending +
        verified claims) for the (root, instance).  The caller has
        already passed dedup + precheck — a claim is final (an invalid
        proof burns the sender's one slot, reference rbc semantics)."""
        self.echo_seen[sender_idx, index] = True
        row = self._row(root)
        self.echo_pot[row, index] += 1
        return int(self.echo_pot[row, index])

    def echo_drop(self, index: int, root: bytes) -> None:
        """A claimed ECHO failed hub verification (or carried a
        conflicting shard length): remove it from the quorum POTENTIAL
        so burned claims cannot keep triggering flush requests — the
        sender's claim bit stays burned (one vote, spent)."""
        row = self._root_rows.get(root)
        if row is not None and self.echo_pot[row, index] > 0:
            self.echo_pot[row, index] -= 1

    def ready_add(
        self, index: int, sender_idx: int, root: bytes
    ) -> Optional[int]:
        """Record one READY; returns the new distinct-sender count for
        (root, instance), or None on a duplicate sender."""
        if self.ready_seen[sender_idx, index]:
            if self.metrics is not None:
                self.metrics.dedup_absorbed.inc()
            return None
        self.ready_seen[sender_idx, index] = True
        row = self._row(root)
        self.ready_cnt[row, index] += 1
        return int(self.ready_cnt[row, index])

    def ready_count(self, index: int, root: bytes) -> int:
        row = self._root_rows.get(root)
        return 0 if row is None else int(self.ready_cnt[row, index])

    def echo_potential(self, index: int, root: bytes) -> int:
        row = self._root_rows.get(root)
        return 0 if row is None else int(self.echo_pot[row, index])

    def ready_roots(self, index: int) -> list:
        """Roots with at least one READY receipt for ``index``, in
        registry insertion order (deterministic: the registry is an
        insertion-ordered dict, never a set)."""
        cnt = self.ready_cnt
        return [
            root
            for root, row in self._root_rows.items()
            if cnt[row, index] > 0
        ]

    # -- columnar delivery (ACS batch path) --------------------------------

    def _indices(
        self, proposers: tuple
    ) -> "Tuple[np.ndarray, np.ndarray, bool]":
        """(instance index array, source position array, has_dups) —
        computed once per distinct proposers tuple (the codec's decode
        memo shares one tuple across a broadcast's receivers, so this
        builds once per wire payload).  Unknown proposers drop at
        cache build; positions keep the per-instance columns (roots,
        branches, shards) aligned after the drop."""
        ent = self._prop_cache.get(proposers)
        if ent is None:
            iidx = self.iidx
            pairs = [
                (iidx[p], k)
                for k, p in enumerate(proposers)
                if p in iidx
            ]
            arr = np.asarray([i for i, _k in pairs], dtype=np.int64)
            pos = np.asarray([k for _i, k in pairs], dtype=np.int64)
            dups = len(set(proposers)) != len(proposers)
            if len(self._prop_cache) >= _PROP_CACHE_CAP:
                self._prop_cache.clear()
            ent = (arr, pos, dups)
            self._prop_cache[proposers] = ent
        return ent

    def batch_ready(self, sender: str, proposers: tuple, roots: tuple) -> None:
        """One sender's READYs fanned across ``proposers``
        (ReadyBatchPayload): vectorized membership + delivered filter
        + dedup + per-(root, instance) counting; only threshold
        crossings reach RBC."""
        si = self.sidx.get(sender)
        if si is None:
            return
        pi, pos, dups = self._indices(proposers)
        if pi.size == 0:
            return
        rbcs = self.rbcs
        if dups:
            # only Byzantine batches repeat an instance: the scalar
            # gate preserves exact first-vote-wins semantics
            for i, k in zip(pi, pos):
                rbc = rbcs[i]
                if rbc is not None:
                    rbc.handle_ready_root(sender, roots[k])
            return
        live = self.state[pi] == 0
        if not live.all():
            pi, pos = pi[live], pos[live]
            if pi.size == 0:
                return
        # malformed roots drop before any slot claim or dedup tally,
        # exactly like the scalar length gate
        lens_ok = np.fromiter(
            (len(roots[k]) == 32 for k in pos), dtype=bool, count=pi.size
        )
        if not lens_ok.all():
            pi, pos = pi[lens_ok], pos[lens_ok]
            if pi.size == 0:
                return
        seen = self.ready_seen[si, pi]
        if seen.any():
            if self.metrics is not None:
                self.metrics.dedup_absorbed.inc(int(seen.sum()))
            fresh = ~seen
            pi, pos = pi[fresh], pos[fresh]
            if pi.size == 0:
                return
        self.ready_seen[si, pi] = True
        rows = np.fromiter(
            (self._row(roots[k]) for k in pos),
            dtype=np.int64,
            count=pi.size,
        )
        cnt = self.ready_cnt
        np.add.at(cnt, (rows, pi), 1)
        after = cnt[rows, pi]
        f = self.f
        # f+1 same READY -> relay once (exact crossing: dedup makes
        # counts advance in +1 steps, docs/RBC-EN.md:41)
        for k in np.nonzero(after == f + 1)[0]:
            rbc = rbcs[pi[k]]
            if (
                rbc is not None
                and not rbc.delivered
                and rbc._ready_root is None
            ):
                rbc._send_ready(roots[pos[k]])
        # q_large reached: deliver probe (>= — post-crossing READYs
        # re-probe a decode that completed since, like the scalar path)
        for k in np.nonzero(after >= self.q_large)[0]:
            rbc = rbcs[pi[k]]
            if rbc is not None and not rbc.delivered:
                rbc._maybe_deliver(roots[pos[k]])

    def batch_echo(
        self,
        sender: str,
        shard_index: int,
        proposers: tuple,
        roots: tuple,
        branches: tuple,
        shards: tuple,
    ) -> None:
        """One sender's ECHOes fanned across ``proposers``
        (EchoBatchPayload): membership, delivered-instance and dedup
        filtering vectorized; surviving items park their proofs in the
        bank's contiguous pending slots via RBC (precheck + quorum
        probes are per-item protocol logic)."""
        si = self.sidx.get(sender)
        if si is None:
            return
        pi, pos, dups = self._indices(proposers)
        if pi.size == 0:
            return
        rbcs = self.rbcs
        if dups:
            for i, k in zip(pi, pos):
                rbc = rbcs[i]
                if rbc is not None and not rbc.delivered:
                    rbc.handle_echo_fast(
                        sender, roots[k], branches[k], shards[k], shard_index
                    )
            return
        live = self.state[pi] == 0
        if not live.all():
            pi, pos = pi[live], pos[live]
            if pi.size == 0:
                return
        seen = self.echo_seen[si, pi]
        if seen.any():
            if self.metrics is not None:
                self.metrics.dedup_absorbed.inc(int(seen.sum()))
            fresh = ~seen
            pi, pos = pi[fresh], pos[fresh]
            if pi.size == 0:
                return
        for i, k in zip(pi, pos):
            rbc = rbcs[i]
            if rbc is not None:
                rbc._echo_item(
                    si, sender, roots[k], branches[k], shards[k], shard_index
                )


__all__ = ["EchoBank"]
