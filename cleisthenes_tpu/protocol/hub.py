"""CryptoHub: cross-instance batched crypto for the live protocol path.

The reference's cost model is N^2 ECHO-phase Merkle verifications and
~4N^2 threshold-share verifications per epoch (reference
docs/HONEYBADGER-EN.md:93-96), arriving one message at a time.  The
hub is the per-epoch accumulation buffer SURVEY.md §7 (hard part 3)
calls for: protocol instances never run device crypto directly on the
message path — they park work (unverified ECHO branches, undecoded
roots, unverified threshold shares) in their own state and the hub
pulls and executes it in BATCHED dispatches when some instance's
quorum threshold makes results necessary.

Why pull, not push: the work lives where the protocol state lives, so
an instance that becomes irrelevant mid-flight (delivered, halted,
epoch GC'd) simply stops offering work — no queue invalidation.  And
because EVERY registered instance's pending work is collected whenever
ANY instance needs a flush, one instance reaching quorum amortizes the
whole node's backlog into the same dispatch: under 'tpu', an epoch's
N instances' ECHO proofs verify in ~1 `verify_batch` call instead of
N^2 singleton calls, and all TPKE + coin shares fold into ONE
dual-exponentiation dispatch via tpke.verify_share_groups.

Client protocol (duck-typed; see RBC/BBA/HoneyBadger):

  hub.mark_dirty(client)
      REQUIRED whenever pending crypto work appears or becomes
      unblocked (parked branch, staged decode, pooled share); a flush
      round polls only dirty clients
  collect_crypto_work(branches, decodes, shares) -> None
      append pending work items; pending state moves to in-flight
  after_crypto_flush() -> None
      verdicts have been applied via item callbacks; run quorum logic

Work item shapes:
  branches: (root: bytes32, leaf: bytes, branch: tuple[bytes32,...],
             index: int, client, ctx) -- verdicts deliver in bulk via
             client.on_branch_verdicts(ctxs, oks), one call per client
             per flush (a per-item closure was ~5% of an N=64 epoch)
  decodes:  (idxs: tuple[int,...], shards: (k, L) uint8 ndarray,
             root: bytes32, cb(data: Optional[ndarray]))
             -- decode + re-encode + Merkle-root recheck
             (docs/RBC-EN.md:37-39) batched across instances
  shares:   (pub, base: int, context: bytes, senders: list[str],
             shares: list[DhShare], cb(verdicts: list[bool]))

The flush loop iterates because verdicts unlock follow-on work (ECHO
verifies add shards -> a root becomes decodable -> decode next pass);
it terminates when a collection round yields nothing.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple

import numpy as np

from cleisthenes_tpu.ops.backend import BatchCrypto
from cleisthenes_tpu.ops.tpke import verify_share_groups

# A flush settles in 2-3 collection rounds (verify -> decode -> quorum
# actions); the cap only guards against a pathological client that
# re-offers work forever.
MAX_FLUSH_ROUNDS = 64

# Verdict-memo capacities.  Primary eviction is epoch GC (drop_scope
# clears the memos — every key belongs to some epoch's traffic, and
# stale entries never pay their rent back); the caps are a second
# bound for pathological single-epoch volume, sized per entry weight:
# share keys are a few hundred bytes (big-int triples), branch keys
# carry a leaf + branch path (~KB), decode keys carry the full shard
# matrix (~10s of KB).
SHARE_MEMO_CAP = 1 << 16
BRANCH_MEMO_CAP = 1 << 15
DECODE_MEMO_CAP = 1 << 10


class _Memo:
    """Bounded memo of pure-function results (cleared on overflow)."""

    __slots__ = ("map", "cap")

    def __init__(self, cap: int):
        self.map: Dict = {}
        self.cap = cap

    def put(self, key, val) -> None:
        if len(self.map) >= self.cap:
            self.map.clear()
        self.map[key] = val


class CryptoHub:
    """Per-node batched-crypto service shared by all protocol instances.

    ``dedup=True`` (the cluster-shared simulation mode) memoizes
    verification VERDICTS across clients: a coin/TPKE CP check, an
    ECHO-branch Merkle proof, or an RS decode-recheck is a pure
    function of its math inputs, and in an N-node in-proc simulation
    every node receives — and would redundantly re-verify — the same
    N^2 shares and branches.  The memo executes each distinct check
    once and fans the verdict out, which is exactly what the N real
    hosts of a deployed cluster do in parallel wall-clock: per-node
    work stays honest, only the single-process serialization artifact
    (N x the same pure computation, run serially) is removed.  Memo
    keys bind every input the verdict depends on (group, public-key
    identity, base, context, share values / root, leaf, branch, index),
    so two different-content messages can never share a verdict.
    Per-node hubs in a real deployment leave this off: nothing repeats.
    """

    def __init__(self, crypto: BatchCrypto, dedup: bool = False):
        self.crypto = crypto
        self.dedup = dedup
        if dedup:
            self._share_memo = _Memo(SHARE_MEMO_CAP)
            self._branch_memo = _Memo(BRANCH_MEMO_CAP)
            self._decode_memo = _Memo(DECODE_MEMO_CAP)
            # id(pub) -> (pub, token): small ints stand in for the
            # (expensive-to-hash) public-key objects in memo keys; the
            # held reference pins the id against reuse
            self._pub_tokens: Dict[int, Tuple[object, int]] = {}
        # scope (epoch int, or any hashable) -> clients; scopes drop
        # wholesale when HoneyBadger GCs an epoch
        self._clients: Dict[object, List[object]] = {}
        # Clients with (possibly) pending work: every state change
        # that creates or unblocks crypto work calls mark_dirty, and a
        # flush round polls ONLY drained-dirty clients — at N
        # validators x N instances, polling every registered client
        # every round was a top-5 epoch cost.  A client that stages
        # work without marking itself dirty will stall: marking is
        # part of the client protocol (see class docstring).
        # An insertion-ordered dict-as-set, NOT a set: flush order
        # decides the order work items batch and verdict callbacks
        # fire, which decides outbound payload order — id()-hash set
        # order would let two runs of the same seeded schedule ship
        # waves in different orders (staticcheck DET002).
        self._dirty: Dict[object, None] = {}
        self._flushing = False
        # Deferred mode (HoneyBadger.transport_manages_idle sets
        # ``hub.defer = True`` when its transport promises an idle
        # callback): request_flush only
        # records the want; the actual flush runs at the transport's
        # quiescence point, so one flush absorbs the whole message
        # wave's pending work instead of firing per quorum event —
        # VERDICT round 2's dispatch-count lever (item 2).
        self.defer = False
        self.flush_wanted = False
        # observability (utils.metrics reads these)
        self.flushes = 0
        self.branch_items = 0
        self.decode_items = 0
        self.share_items = 0
        self.dispatches = 0
        # flight recorder (utils/trace.py).  Per-node hubs inherit
        # the owner's recorder; a cluster-SHARED hub gets its own
        # "hub" track (its flushes serve the whole roster and belong
        # to no single node's timeline).  None = tracing off.
        self.trace = None

    # -- membership --------------------------------------------------------

    def register(self, scope, client) -> None:
        self._clients.setdefault(scope, []).append(client)

    def mark_dirty(self, client) -> None:
        """Client protocol: call whenever pending crypto work appears
        or becomes unblocked (a parked branch, a staged decode, a
        pooled share).  Idempotent and O(1)."""
        self._dirty[client] = None

    def drop_scope(self, scope) -> None:
        dropped = self._clients.pop(scope, None)
        if dropped:
            for client in dropped:
                self._dirty.pop(client, None)
        if self.dedup:
            # epoch GC is the natural memo eviction point: all of a
            # completed epoch's keys are dead, and any live entry a
            # clear loses costs at most one re-verification
            self._share_memo.map.clear()
            self._branch_memo.map.clear()
            self._decode_memo.map.clear()
            # the memos keyed by these tokens are gone, so a held key
            # object has no remaining value — dropping the table stops
            # unbounded growth under epoch re-keying
            self._pub_tokens.clear()

    # -- flushing ----------------------------------------------------------

    def request_flush(self) -> None:
        """Run a flush now — unless one is already running (its
        collection loop will pick the new work up) or deferred mode
        parks the request for the transport's idle callback."""
        if self._flushing:
            return
        if self.defer:
            self.flush_wanted = True
            return
        self.flush()

    def run_deferred(self) -> None:
        """Idle-callback entry: run the flush the message wave asked
        for (no-op when nothing requested one)."""
        if self.flush_wanted and not self._flushing:
            self.flush_wanted = False
            self.flush()

    def flush(self) -> None:
        if self._flushing:
            return
        self._flushing = True
        self.flush_wanted = False  # any full flush satisfies the want
        self.flushes += 1
        tr = self.trace
        t0 = 0.0 if tr is None else tr.now()
        d0, b0, k0, s0 = (
            self.dispatches,
            self.branch_items,
            self.decode_items,
            self.share_items,
        )
        try:
            for _ in range(MAX_FLUSH_ROUNDS):
                if not self._dirty:
                    break
                clients = list(self._dirty)
                self._dirty.clear()
                branches: List[Tuple] = []
                decodes: List[Tuple] = []
                shares: List[Tuple] = []
                for c in clients:
                    c.collect_crypto_work(branches, decodes, shares)
                if not (branches or decodes or shares):
                    break
                if branches:
                    self._run_branches(branches)
                if decodes:
                    self._run_decodes(decodes)
                if shares:
                    self._run_shares(shares)
                # executor callbacks may re-mark clients (e.g. a
                # verified ECHO shard completes a staged decode); the
                # next loop round drains them
                for c in clients:
                    c.after_crypto_flush()
        finally:
            self._flushing = False
            if tr is not None:
                tr.complete(
                    "hub",
                    "flush",
                    t0,
                    dispatches=self.dispatches - d0,
                    branches=self.branch_items - b0,
                    decodes=self.decode_items - k0,
                    shares=self.share_items - s0,
                )

    # -- executors ---------------------------------------------------------

    def _run_branches(self, items: List[Tuple]) -> None:
        """Branch proofs grouped by (depth, leaf length) — one
        merkle.verify_batch per group (trees of one roster share a
        depth, so this is ~one group per epoch).  Verdicts deliver in
        BULK per client (``on_branch_verdicts(ctxs, oks)``): a wave's
        N^2 echoes cost one call per instance, not one closure each."""
        self.branch_items += len(items)
        verdict_of: Dict[Tuple, bool] = {}
        if self.dedup:
            memo = self._branch_memo.map
            fresh: List[Tuple] = []
            for item in items:
                key = (item[0], item[1], item[2], item[3])
                if key not in verdict_of:
                    hit = memo.get(key)
                    if hit is None:
                        fresh.append(
                            (item[0], item[1], item[2], item[3], key)
                        )
                        verdict_of[key] = False  # filled below
                    else:
                        verdict_of[key] = hit
            if fresh:

                def fill(it, good, local=verdict_of):
                    local[it[4]] = good
                    self._branch_memo.put(it[4], good)

                self._verify_branch_groups(fresh, fill)
        else:
            self._verify_branch_groups(
                [item[:4] + (item[:4],) for item in items],
                lambda it, good: verdict_of.__setitem__(it[4], good),
            )
        # bulk delivery, preserving per-client arrival order
        by_client: Dict[int, Tuple[object, List, List]] = {}
        for item in items:
            client, ctx = item[4], item[5]
            ent = by_client.get(id(client))
            if ent is None:
                ent = (client, [], [])
                by_client[id(client)] = ent
            ent[1].append(ctx)
            ent[2].append(
                verdict_of[(item[0], item[1], item[2], item[3])]
            )
        for client, ctxs, oks in by_client.values():
            client.on_branch_verdicts(ctxs, oks)

    def _verify_branch_groups(
        self, items: List[Tuple], deliver: Callable
    ) -> None:
        groups: Dict[Tuple[int, int], List[Tuple]] = {}
        for item in items:
            _root, leaf, branch, _index, _cb = item
            groups.setdefault((len(branch), len(leaf)), []).append(item)
        for group in groups.values():
            self.dispatches += 1
            b = len(group)
            leaf_len = len(group[0][1])
            # single join+frombuffer per column: per-item np.stack /
            # frombuffer assembly was ~5% of an N=64 epoch
            roots = np.frombuffer(
                b"".join(it[0] for it in group), dtype=np.uint8
            ).reshape(b, 32)
            leaves = np.frombuffer(
                b"".join(it[1] for it in group), dtype=np.uint8
            ).reshape(b, leaf_len)
            depth = len(group[0][2])
            if depth:
                branches_arr = np.frombuffer(
                    b"".join(s for it in group for s in it[2]),
                    dtype=np.uint8,
                ).reshape(b, depth, 32)
            else:  # single-leaf trees
                branches_arr = np.zeros((b, 0, 32), dtype=np.uint8)
            indices = np.asarray([it[3] for it in group])
            ok = self.crypto.merkle.verify_batch(
                roots, leaves, branches_arr, indices
            )
            for it, good in zip(group, ok):
                deliver(it, bool(good))

    def _run_decodes(self, items: List[Tuple]) -> None:
        """Interpolate + re-encode + root recheck (docs/RBC-EN.md:37-39)
        for many instances at once, grouped by shard length — ONE
        fused dispatch per group on the 'tpu' backend
        (BatchCrypto.decode_recheck_batch)."""
        self.decode_items += len(items)
        if self.dedup:
            memo = self._decode_memo.map
            local: Dict[Tuple, object] = {}
            _miss = object()
            fresh: List[Tuple] = []
            keys = []
            for item in items:
                key = (item[2], item[0], item[1].tobytes())
                keys.append(key)
                if key not in local:
                    hit = memo.get(key, _miss)
                    if hit is _miss:
                        fresh.append((item[0], item[1], item[2], key))
                        local[key] = None  # filled by decode below
                    else:
                        local[key] = hit
            if fresh:

                def fill(it, row, local=local):
                    local[it[3]] = row
                    self._decode_memo.put(it[3], row)

                self._decode_groups(fresh, fill)
            for item, key in zip(items, keys):
                row = local[key]
                # hand each client its own copy: decoded rows feed
                # straight into batch deserialization and must not
                # alias across nodes
                item[3](None if row is None else row.copy())
            return
        self._decode_groups(items, lambda it, row: it[3](row))

    def _decode_groups(self, items: List[Tuple], deliver: Callable) -> None:
        groups: Dict[Tuple[int, int], List[Tuple]] = {}
        for item in items:
            idxs, shards = item[0], item[1]
            groups.setdefault((shards.shape[0], shards.shape[1]), []).append(
                item
            )
        for group in groups.values():
            idx_arr = np.stack([np.asarray(it[0]) for it in group])
            shard_arr = np.stack([it[1] for it in group])
            data, roots, dispatches = self.crypto.decode_recheck_batch(
                idx_arr, shard_arr
            )
            self.dispatches += dispatches
            for it, row, root in zip(group, data, roots):
                deliver(it, row if root.tobytes() == it[2] else None)

    def _run_shares(self, items: List[Tuple]) -> None:
        """ALL pooled threshold shares (TPKE decryption + BBA coins,
        every instance) in ONE dual-exponentiation dispatch."""
        self.share_items += sum(len(it[4]) for it in items)
        if self.dedup:
            self._run_shares_dedup(items)
            return
        self.dispatches += 1
        verdicts = verify_share_groups(
            [(pub, base, shs, ctx) for pub, base, ctx, _snd, shs, _cb in items],
            backend=self.crypto.engine_backend,
            mesh=self.crypto.mesh,
        )
        for item, ok in zip(items, verdicts):
            item[5](item[3], ok)

    def _pub_token(self, pub) -> int:
        ent = self._pub_tokens.get(id(pub))
        if ent is None or ent[0] is not pub:
            ent = (pub, len(self._pub_tokens))
            self._pub_tokens[id(pub)] = ent
        return ent[1]

    def _run_shares_dedup(self, items: List[Tuple]) -> None:
        """Each distinct (pub, base, context, share) CP check verifies
        once; verdicts fan out to every client that pooled a copy."""
        memo = self._share_memo.map
        # local verdict view for THIS call: immune to a memo clear-on-
        # overflow racing between put and the fan-out read below
        local: Dict[Tuple, bool] = {}
        # (token, base, context) -> [(key, share)] of fresh checks
        fresh: Dict[Tuple, List[Tuple]] = {}
        fresh_groups: Dict[Tuple, Tuple] = {}
        item_keys: List[List[Tuple]] = []
        for pub, base, context, _snd, shares, _cb in items:
            tok = self._pub_token(pub)
            gkey = (tok, base, context)
            keys = []
            for sh in shares:
                key = (tok, base, context, sh.index, sh.d, sh.e, sh.z)
                keys.append(key)
                if key not in local:
                    hit = memo.get(key)
                    if hit is None:
                        fresh.setdefault(gkey, []).append((key, sh))
                        fresh_groups[gkey] = (pub, base, context)
                        local[key] = False  # placeholder, filled below
                    else:
                        local[key] = hit
            item_keys.append(keys)
        if fresh:
            self.dispatches += 1
            groups = []
            order = []
            for gkey, pairs in fresh.items():
                pub, base, context = fresh_groups[gkey]
                groups.append((pub, base, [sh for _k, sh in pairs], context))
                order.append(pairs)
            verdicts = verify_share_groups(
                groups,
                backend=self.crypto.engine_backend,
                mesh=self.crypto.mesh,
            )
            put = self._share_memo.put
            for pairs, oks in zip(order, verdicts):
                for (key, _sh), good in zip(pairs, oks):
                    local[key] = good
                    put(key, good)
        for (item, keys) in zip(items, item_keys):
            item[5](item[3], [local[k] for k in keys])

    # -- stats -------------------------------------------------------------

    def stats(self) -> Dict[str, int]:
        return {
            "flushes": self.flushes,
            "dispatches": self.dispatches,
            "branch_items": self.branch_items,
            "decode_items": self.decode_items,
            "share_items": self.share_items,
        }


__all__ = ["CryptoHub"]
