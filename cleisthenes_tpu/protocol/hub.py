"""CryptoHub: columnar wave-batched crypto for the live protocol path.

The reference's cost model is N^2 ECHO-phase Merkle verifications and
~4N^2 threshold-share verifications per epoch (reference
docs/HONEYBADGER-EN.md:93-96), arriving one message at a time.  The
hub is the per-epoch accumulation buffer SURVEY.md §7 (hard part 3)
calls for: protocol instances never run device crypto directly on the
message path — they park work (unverified ECHO branches, undecoded
roots, unverified threshold shares) in their own state and the hub
pulls and executes it in BATCHED dispatches when some instance's
quorum threshold makes results necessary.

Wave-columnar execution (the Thetacrypt "threshold crypto as a
service with request coalescing" shape, PAPERS.md 2502.03247): the
transport's idle callback is the only flush trigger on both
transports, and one flush drains EVERY dirty client of the wave into
a handful of wide typed columns — all pending ECHO-branch proofs,
all ready RS decode-rechecks, all pooled coin/TPKE shares — then
executes ONE batch call per work kind, in dependency order
(branches -> decodes -> shares), and fans verdicts back out via the
client callback protocol.  Branch verdicts can unlock decodes
(verified shards complete a staged matrix); the hub re-drains
verdict-marked clients *within the same wave round* so those decodes
ride the round's single decode dispatch instead of a follow-on one.

Why pull, not push: the work lives where the protocol state lives, so
an instance that becomes irrelevant mid-flight (delivered, halted,
epoch GC'd) simply stops offering work — no queue invalidation.  And
because EVERY dirty client's pending work drains whenever ANY client
needs a flush, one instance reaching quorum amortizes the whole
node's backlog into the same dispatch: under 'tpu', an epoch's N
instances' ECHO proofs verify in ~1 `verify_batch` call instead of
N^2 singleton calls, and all TPKE + coin shares fold into ONE
dual-exponentiation dispatch via tpke.verify_share_groups.

Client protocol (duck-typed; see RBC/BBA/HoneyBadger):

  hub.mark_dirty(client)
      REQUIRED whenever pending crypto work appears or becomes
      unblocked (parked branch, staged decode, pooled share); a flush
      round drains only dirty clients
  drain_pending(wave: HubWave) -> None
      move pending work out of client state into the wave's typed
      columns (wave.add_branch / add_decode / add_share); a client
      may be drained more than once per round and must only offer
      each work item once
  after_crypto_flush() -> None
      verdicts have been applied via item callbacks; run quorum logic

Work item shapes (the wave's typed columns):
  branches: add_branch(client, root: bytes32, leaf: bytes,
            branch: tuple[bytes32,...], index: int, ctx) — verdicts
            deliver in bulk via client.on_branch_verdicts(ctxs, oks),
            one call per client per dispatch (a per-item closure was
            ~5% of an N=64 epoch).  Duplicate work across clients
            dedups AT APPEND TIME by object identity (dedup mode):
            an in-proc cluster's N receivers share one decoded
            payload's root/leaf/branch objects, so the content-key
            memo is consulted once per distinct check, not once per
            (check, receiver).
  decodes:  add_decode(root: bytes32, idxs: tuple[int,...],
            shards: list[bytes] (k branch-verified shards, idxs
            order), cb(data: Optional[ndarray])) — decode + re-encode
            + Merkle-root recheck (docs/RBC-EN.md:37-39) batched
            across instances; the hub builds each unique matrix once.
  shares:   add_share(pub, base: int, context: bytes,
            senders: list[str], shares: list[DhShare],
            cb(senders, verdicts: list[bool]))
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from cleisthenes_tpu.ops.backend import BatchCrypto
from cleisthenes_tpu.ops.coin import share_batch as coin_share_batch
from cleisthenes_tpu.ops.tpke import (
    issue_shares_batch,
    verify_share_groups,
)
from cleisthenes_tpu.utils.determinism import guarded_by
from cleisthenes_tpu.utils.lockcheck import new_lock
from cleisthenes_tpu.utils.memo import BoundedFifoMemo

# A flush settles in 1-2 wave rounds (branch verdicts unlock decodes
# WITHIN a round; only share burns and quorum follow-ons need another);
# the cap only guards against a pathological client that re-offers
# work forever.
MAX_FLUSH_ROUNDS = 64

# Verdict-memo capacities.  Primary eviction is epoch GC (drop_scope
# clears the memos — every key belongs to some epoch's traffic, and
# stale entries never pay their rent back); the caps are a second
# bound for pathological single-epoch volume, sized per entry weight:
# share keys are a few hundred bytes (big-int triples), branch keys
# carry a leaf + branch path (~KB), decode keys are (root, idxs).
SHARE_MEMO_CAP = 1 << 16
BRANCH_MEMO_CAP = 1 << 15
DECODE_MEMO_CAP = 1 << 10

# wave-width samples kept for bench percentiles (protocol sections
# report wave_width_p50/p95); a run's flushes far exceed this only in
# pathological schedules, and old samples are as good as new ones
WAVE_WIDTH_CAP = 1 << 16


# Bounded memo with FIFO eviction (never clear-all): the ONE shared
# discipline, hoisted to utils.memo so the transport plane's frame-
# decode memo evicts identically without importing protocol code.
# The historical name is kept — hub call sites and the tx-parse memo
# (protocol/honeybadger.py) import it from here.
_Memo = BoundedFifoMemo


class HubWave:
    """One flush's typed work columns.

    Branch items are slotted: each append lands a (client, ctx, slot)
    row, where ``slot`` indexes the unique-work list.  In dedup mode
    (cluster-shared hub) uniqueness is established at APPEND time by
    object identity — the in-proc transport's payload memo hands every
    receiver the same root/leaf/branch objects, so id-keying collapses
    a wave's N copies of one check to a single slot without hashing
    any content.  Ids are only compared between live objects held by
    this wave (the columns pin them), so reuse-after-GC cannot alias.
    Decode and share items stay flat lists — their populations are
    ~N per wave, not ~N^2.
    """

    __slots__ = (
        "dedup",
        "b_slots",
        "b_items",
        "_b_ids",
        "decodes",
        "shares",
        "clients",
    )

    def __init__(self, dedup: bool) -> None:
        self.dedup = dedup
        self.b_slots: List[Tuple] = []  # unique (root, leaf, branch, idx)
        self.b_items: List[Tuple] = []  # (client, ctx, slot)
        self._b_ids: Dict[Tuple, int] = {}
        self.decodes: List[Tuple] = []  # (root, idxs, [shards], cb, n)
        self.shares: List[Tuple] = []  # (pub, base, ctx, senders, shs, cb)
        self.clients: List[object] = []  # drained clients, arrival order

    def add_branch(
        self, client, root: bytes, leaf: bytes, branch: tuple,
        index: int, ctx,
    ) -> None:
        slots = self.b_slots
        if self.dedup:
            key = (id(root), id(leaf), id(branch), index)
            slot = self._b_ids.get(key)
            if slot is None:
                slot = len(slots)
                self._b_ids[key] = slot
                slots.append((root, leaf, branch, index))
        else:
            slot = len(slots)
            slots.append((root, leaf, branch, index))
        self.b_items.append((client, ctx, slot))

    def add_decode(
        self, root: bytes, idxs: tuple, shards: list, cb, n=None
    ) -> None:
        # ``n`` is the requesting instance's roster width (dynamic
        # membership: epochs under different roster versions carry
        # different RS geometries; None = the hub's native width)
        self.decodes.append((root, idxs, shards, cb, n))

    def add_share(
        self, pub, base: int, context: bytes, senders: list, shares: list,
        cb,
    ) -> None:
        self.shares.append((pub, base, context, senders, shares, cb))

    def has_work(self) -> bool:
        return bool(self.b_items or self.decodes or self.shares)

    def take_branches(self) -> Tuple[List[Tuple], List[Tuple]]:
        slots, items = self.b_slots, self.b_items
        self.b_slots, self.b_items = [], []
        if self._b_ids:
            self._b_ids = {}
        return slots, items

    def take_decodes(self) -> List[Tuple]:
        out, self.decodes = self.decodes, []
        return out

    def take_shares(self) -> List[Tuple]:
        out, self.shares = self.shares, []
        return out


@guarded_by("_dec_lock", "_dec_pool", "_dec_results")
class CryptoHub:
    """Per-node batched-crypto service shared by all protocol instances.

    ``dedup=True`` (the cluster-shared simulation mode) memoizes
    verification VERDICTS across clients: a coin/TPKE CP check, an
    ECHO-branch Merkle proof, or an RS decode-recheck is a pure
    function of its math inputs, and in an N-node in-proc simulation
    every node receives — and would redundantly re-verify — the same
    N^2 shares and branches.  The memo executes each distinct check
    once and fans the verdict out, which is exactly what the N real
    hosts of a deployed cluster do in parallel wall-clock: per-node
    work stays honest, only the single-process serialization artifact
    (N x the same pure computation, run serially) is removed.  Memo
    keys bind every input the verdict depends on (group, public-key
    identity, base, context, share values / root, leaf, branch,
    index); decode keys bind (root, idxs) — sufficient because only
    BRANCH-VERIFIED shards ever reach a decode request, and two
    different shard byte-strings verifying at the same index under
    the same root would be a SHA-256 second preimage.  Per-node hubs
    in a real deployment leave dedup off: nothing repeats.
    """

    def __init__(self, crypto: BatchCrypto, dedup: bool = False):
        self.crypto = crypto
        self.dedup = dedup
        # (n, k) -> BatchCrypto for decode groups whose RS geometry
        # differs from the native one (dynamic membership: epochs
        # under a resized roster version)
        self._crypto_cache: Dict[Tuple[int, int], BatchCrypto] = {}
        if dedup:
            self._share_memo = _Memo(SHARE_MEMO_CAP)
            self._branch_memo = _Memo(BRANCH_MEMO_CAP)
            self._decode_memo = _Memo(DECODE_MEMO_CAP)
            # id(pub) -> (pub, token): small ints stand in for the
            # (expensive-to-hash) public-key objects in memo keys; the
            # held reference pins the id against reuse
            self._pub_tokens: Dict[int, Tuple[object, int]] = {}
        # scope (epoch int, or any hashable) -> clients; scopes drop
        # wholesale when HoneyBadger GCs an epoch
        self._clients: Dict[object, List[object]] = {}
        # Clients with (possibly) pending work: every state change
        # that creates or unblocks crypto work calls mark_dirty, and a
        # flush round drains ONLY dirty clients — at N validators x N
        # instances, polling every registered client every round was a
        # top-5 epoch cost.  A client that stages work without marking
        # itself dirty will stall: marking is part of the client
        # protocol (see module docstring).
        # An insertion-ordered dict-as-set, NOT a set: drain order
        # decides the order work items batch and verdict callbacks
        # fire, which decides outbound payload order — id()-hash set
        # order would let two runs of the same seeded schedule ship
        # waves in different orders (staticcheck DET002).
        self._dirty: Dict[object, None] = {}
        self._flushing = False
        # Deferred mode (HoneyBadger.transport_manages_idle sets
        # ``hub.defer = True`` when its transport promises an idle
        # callback): request_flush only records the want; the actual
        # flush runs at the transport's quiescence point — the ONLY
        # flush trigger on both transports — so one flush absorbs the
        # whole message wave's pending work instead of firing per
        # quorum event.
        self.defer = False
        self.flush_wanted = False
        # observability (utils.metrics reads these)
        self.flushes = 0
        self.branch_items = 0
        self.decode_items = 0
        self.share_items = 0
        self.dispatches = 0
        # Wave-batched coin-issue column (Config.egress_columnar,
        # ISSUE 13): owners park (secret, base, context, vk) issue
        # items at aux-quorum time (stage_coin_issue) and collect the
        # shares at their own drain point (take_coin_issues).  The
        # FIRST taker of a wave executes EVERY staged owner's pending
        # items in one ops.coin.share_batch dispatch — one native
        # multi-exponentiation and one CP-nonce draw for all BBA
        # instances and rounds the wave touched, across ALL nodes of
        # a shared-hub cluster — and parks each owner's shares until
        # its drain claims them, so broadcast order and timing stay
        # byte-identical to the scalar arm (one issue batch per node
        # per drain).  Counter semantics: coin_issue_batches counts
        # native coin-issue dispatches on BOTH arms (the scalar drain
        # increments it too), the number bench.py reports as
        # coin_dispatches_per_epoch and perfgate gates.
        self.coin_issue_batches = 0
        self.coin_issue_items = 0
        self._coin_pool: List[Tuple] = []  # (owner, meta, item, group)
        # owner -> [(meta, share)] awaiting the owner's drain.  A
        # restarted owner object abandons its parked rows (one stale
        # entry per crash — bounded by the run's restart count).
        self._coin_results: Dict[object, List[Tuple]] = {}
        # Eager dec-share issue column (K-deep pipelined frontiers,
        # Config.pipeline_depth > 1): the TPKE twin of the coin
        # column above.  Owners stage (share, base, context, vk)
        # issue items the moment an epoch ORDERS — mid-wave — and
        # collect the DhShares at the turn's piggyback drain
        # (take_dec_issues); the first taker executes the whole
        # staged pool in one ops.tpke.issue_shares_batch dispatch,
        # so a wave that orders epochs on several shared-hub nodes
        # (or K epochs back to back) pays one exponentiation
        # dispatch and one CP-nonce draw, not one per node per epoch.
        self.dec_issue_batches = 0
        self.dec_issue_items = 0
        # guarded: a cluster-SHARED hub serves every node's stage/
        # drain calls, and the ISSUE-17 sweep requires the column's
        # pool+results to move under one declared lock
        self._dec_lock = new_lock()
        self._dec_pool: List[Tuple] = []  # (owner, meta, item, group)
        self._dec_results: Dict[object, List[Tuple]] = {}
        # per-flush total column width (branch+decode+share items) of
        # every flush that carried work, for the bench's
        # wave_width_p50/p95 counters (bounded; see WAVE_WIDTH_CAP)
        self.wave_widths: List[int] = []
        # flight recorder (utils/trace.py).  Per-node hubs inherit
        # the owner's recorder; a cluster-SHARED hub gets its own
        # "hub" track (its flushes serve the whole roster and belong
        # to no single node's timeline).  None = tracing off.
        self.trace = None

    # -- membership --------------------------------------------------------

    def register(self, scope, client) -> None:
        self._clients.setdefault(scope, []).append(client)

    def mark_dirty(self, client) -> None:
        """Client protocol: call whenever pending crypto work appears
        or becomes unblocked (a parked branch, a staged decode, a
        pooled share).  Idempotent and O(1)."""
        self._dirty[client] = None

    def drop_scope(self, scope) -> None:
        dropped = self._clients.pop(scope, None)
        if dropped:
            for client in dropped:
                self._dirty.pop(client, None)
        if self.dedup:
            # epoch GC is the natural memo eviction point: all of a
            # completed epoch's keys are dead, and any live entry a
            # clear loses costs at most one re-verification
            self._share_memo.map.clear()
            self._branch_memo.map.clear()
            self._decode_memo.map.clear()
            # the memos keyed by these tokens are gone, so a held key
            # object has no remaining value — dropping the table stops
            # unbounded growth under epoch re-keying
            self._pub_tokens.clear()

    # -- flushing ----------------------------------------------------------

    def request_flush(self) -> None:
        """Run a flush now — unless one is already running (its wave
        loop will pick the new work up) or deferred mode parks the
        request for the transport's idle callback."""
        if self._flushing:
            return
        if self.defer:
            self.flush_wanted = True
            return
        self.flush()

    def run_deferred(self) -> None:
        """Idle-callback entry: run the flush the message wave asked
        for (no-op when nothing requested one)."""
        if self.flush_wanted and not self._flushing:
            self.flush_wanted = False
            self.flush()

    def _drain_dirty(self, wave: HubWave) -> None:
        clients = list(self._dirty)
        self._dirty.clear()
        for c in clients:
            c.drain_pending(wave)
        wave.clients.extend(clients)

    def flush(self) -> None:
        """Drain every dirty client into typed columns and execute one
        batch dispatch per work kind, in dependency order.  Branch
        verdicts that unlock decodes re-mark their client; the
        mid-round re-drain folds those decodes into the SAME round's
        decode dispatch.  The loop iterates only when verdicts create
        genuinely new work (a share burn pulling parked replacements,
        quorum logic staging follow-ons); it terminates when a round
        neither executed work nor left dirty clients."""
        if self._flushing:
            return
        self._flushing = True
        self.flush_wanted = False  # any full flush satisfies the want
        self.flushes += 1
        tr = self.trace
        t0 = 0.0 if tr is None else tr.now()
        d0, b0, k0, s0 = (
            self.dispatches,
            self.branch_items,
            self.decode_items,
            self.share_items,
        )
        rounds = 0
        try:
            wave = HubWave(self.dedup)
            for _ in range(MAX_FLUSH_ROUNDS):
                if self._dirty:
                    self._drain_dirty(wave)
                if not wave.has_work():
                    break
                rounds += 1
                if wave.b_items:
                    self._run_branches(*wave.take_branches())
                    if self._dirty:
                        # verdicts unlocked work (a completed decode
                        # matrix): drain it into THIS round's columns
                        self._drain_dirty(wave)
                if wave.decodes:
                    self._run_decodes(wave.take_decodes())
                if wave.shares:
                    self._run_shares(wave.take_shares())
                # executor callbacks may re-mark clients (e.g. a share
                # burn with parked replacements); quorum logic runs on
                # every client drained this round, in drain order
                clients, wave.clients = wave.clients, []
                for c in dict.fromkeys(clients):
                    c.after_crypto_flush()
        finally:
            self._flushing = False
            width = (
                (self.branch_items - b0)
                + (self.decode_items - k0)
                + (self.share_items - s0)
            )
            if width and len(self.wave_widths) < WAVE_WIDTH_CAP:
                self.wave_widths.append(width)
            if tr is not None:
                tr.complete(
                    "hub",
                    "flush",
                    t0,
                    dispatches=self.dispatches - d0,
                    branches=self.branch_items - b0,
                    decodes=self.decode_items - k0,
                    shares=self.share_items - s0,
                    wave_width=width,
                    rounds=rounds,
                )

    # -- executors ---------------------------------------------------------

    def _run_branches(
        self, slots: List[Tuple], items: List[Tuple]
    ) -> None:
        """Branch proofs grouped by (depth, leaf length) — one
        merkle.verify_batch per group (trees of one roster share a
        depth, so this is ~one group per wave).  Content-key memo
        lookups run per unique SLOT (the wave already id-deduped the
        N-receiver copies), and verdicts deliver in BULK per client
        (``on_branch_verdicts(ctxs, oks)``): a wave's N^2 echoes cost
        one call per instance, not one closure each."""
        self.branch_items += len(items)
        verdicts: List[bool] = [False] * len(slots)
        if self.dedup:
            memo = self._branch_memo.map
            fresh: List[Tuple] = []
            for si, (root, leaf, branch, index) in enumerate(slots):
                key = (root, leaf, branch, index)
                hit = memo.get(key)
                if hit is None:
                    fresh.append((root, leaf, branch, index, si, key))
                else:
                    verdicts[si] = hit
            if fresh:
                put = self._branch_memo.put

                def fill(it, good, local=verdicts, put=put):
                    local[it[4]] = good
                    put(it[5], good)

                self._verify_branch_groups(fresh, fill)
        elif slots:
            self._verify_branch_groups(
                [
                    slot + (si, None)
                    for si, slot in enumerate(slots)
                ],
                lambda it, good: verdicts.__setitem__(it[4], good),
            )
        # bulk delivery, preserving per-client arrival order
        by_client: Dict[int, Tuple[object, List, List]] = {}
        for client, ctx, slot in items:
            ent = by_client.get(id(client))
            if ent is None:
                ent = (client, [], [])
                by_client[id(client)] = ent
            ent[1].append(ctx)
            ent[2].append(verdicts[slot])
        for client, ctxs, oks in by_client.values():
            client.on_branch_verdicts(ctxs, oks)

    def _verify_branch_groups(
        self, items: List[Tuple], deliver: Callable
    ) -> None:
        groups: Dict[Tuple[int, int], List[Tuple]] = {}
        for item in items:
            _root, leaf, branch = item[0], item[1], item[2]
            groups.setdefault((len(branch), len(leaf)), []).append(item)
        for group in groups.values():
            self.dispatches += 1
            b = len(group)
            leaf_len = len(group[0][1])
            # single join+frombuffer per column: per-item np.stack /
            # frombuffer assembly was ~5% of an N=64 epoch
            roots = np.frombuffer(
                b"".join(it[0] for it in group), dtype=np.uint8
            ).reshape(b, 32)
            leaves = np.frombuffer(
                b"".join(it[1] for it in group), dtype=np.uint8
            ).reshape(b, leaf_len)
            depth = len(group[0][2])
            if depth:
                branches_arr = np.frombuffer(
                    b"".join(s for it in group for s in it[2]),
                    dtype=np.uint8,
                ).reshape(b, depth, 32)
            else:  # single-leaf trees
                branches_arr = np.zeros((b, 0, 32), dtype=np.uint8)
            indices = np.asarray([it[3] for it in group])
            ok = self.crypto.merkle.verify_batch(
                roots, leaves, branches_arr, indices
            )
            for it, good in zip(group, ok):
                deliver(it, bool(good))

    def _run_decodes(self, items: List[Tuple]) -> None:
        """Interpolate + re-encode + root recheck (docs/RBC-EN.md:37-39)
        for many instances at once, grouped by shard shape — ONE fused
        dispatch per group on the 'tpu' backend
        (BatchCrypto.decode_recheck_batch).  Item shape:
        (root, idxs, [shard bytes], cb); the hub builds each unique
        matrix exactly once (dedup key (root, idxs): decode inputs are
        branch-verified, see class docstring)."""
        self.decode_items += len(items)
        if self.dedup:
            memo = self._decode_memo.map
            local: Dict[Tuple, object] = {}
            _miss = object()
            fresh: List[Tuple] = []
            keys = []
            for root, idxs, shards, _cb, n in items:
                key = (root, idxs)
                keys.append(key)
                if key not in local:
                    hit = memo.get(key, _miss)
                    if hit is _miss:
                        fresh.append((root, idxs, shards, key, n))
                        local[key] = None  # filled by decode below
                    else:
                        local[key] = hit
            if fresh:

                def fill(it, row, local=local):
                    local[it[3]] = row
                    self._decode_memo.put(it[3], row)

                self._decode_groups(fresh, fill)
            for item, key in zip(items, keys):
                row = local[key]
                # hand each client its own copy: decoded rows feed
                # straight into batch deserialization and must not
                # alias across nodes
                item[3](None if row is None else row.copy())
            return
        self._decode_groups(items, lambda it, row: it[3](row))

    def _decode_groups(self, items: List[Tuple], deliver: Callable) -> None:
        # grouped by (roster width, k, shard length): epochs under
        # different roster versions (dynamic membership) carry
        # different RS geometries and must not share a coder dispatch
        groups: Dict[Tuple[int, int, int], List[Tuple]] = {}
        for item in items:
            idxs, shards = item[1], item[2]
            n = item[4] if len(item) > 4 else None
            groups.setdefault(
                (n, len(idxs), len(shards[0])), []
            ).append(item)
        for (n, _k, _length), group in groups.items():
            k, length = len(group[0][1]), len(group[0][2][0])
            idx_arr = np.asarray([it[1] for it in group])
            # one join+frombuffer for the whole group's matrices (the
            # per-client np.stack of per-shard frombuffers was ~3% of
            # an N=64 epoch)
            shard_arr = np.frombuffer(
                b"".join(s for it in group for s in it[2]),
                dtype=np.uint8,
            ).reshape(len(group), k, length)
            data, roots, dispatches = self._crypto_for(
                n, k
            ).decode_recheck_batch(idx_arr, shard_arr)
            self.dispatches += dispatches
            for it, row, root in zip(group, data, roots):
                deliver(it, row if root.tobytes() == it[0] else None)

    def _crypto_for(self, n, k):
        """The BatchCrypto whose erasure geometry matches one decode
        group: the hub's native one when (n, k) agree (every request
        before a reconfig, and all of them on fixed rosters), else a
        cached per-geometry sibling on the same backend."""
        c = self.crypto
        if n is None or (n == c.n and k == c.k):
            return c
        hit = self._crypto_cache.get((n, k))
        if hit is None:
            hit = BatchCrypto(
                c.backend, n, (n - k) // 2, k,
                mesh_shape=c.mesh_shape,
            )
            self._crypto_cache[(n, k)] = hit
        return hit

    def _run_shares(self, items: List[Tuple]) -> None:
        """ALL pooled threshold shares (TPKE decryption + BBA coins,
        every instance) in ONE dual-exponentiation dispatch."""
        self.share_items += sum(len(it[4]) for it in items)
        if self.dedup:
            self._run_shares_dedup(items)
            return
        self.dispatches += 1
        verdicts = verify_share_groups(
            [(pub, base, shs, ctx) for pub, base, ctx, _snd, shs, _cb in items],
            backend=self.crypto.engine_backend,
            mesh=self.crypto.mesh,
        )
        for item, ok in zip(items, verdicts):
            item[5](item[3], ok)

    def _pub_token(self, pub) -> int:
        ent = self._pub_tokens.get(id(pub))
        if ent is None or ent[0] is not pub:
            ent = (pub, len(self._pub_tokens))
            self._pub_tokens[id(pub)] = ent
        return ent[1]

    def _run_shares_dedup(self, items: List[Tuple]) -> None:
        """Each distinct (pub, base, context, share) CP check verifies
        once; verdicts fan out to every client that pooled a copy."""
        memo = self._share_memo.map
        # local verdict view for THIS call: immune to memo eviction
        # racing between put and the fan-out read below
        local: Dict[Tuple, bool] = {}
        # (token, base, context) -> [(key, share)] of fresh checks
        fresh: Dict[Tuple, List[Tuple]] = {}
        fresh_groups: Dict[Tuple, Tuple] = {}
        item_keys: List[List[Tuple]] = []
        for pub, base, context, _snd, shares, _cb in items:
            tok = self._pub_token(pub)
            gkey = (tok, base, context)
            keys = []
            for sh in shares:
                key = (tok, base, context, sh.index, sh.d, sh.e, sh.z)
                keys.append(key)
                if key not in local:
                    hit = memo.get(key)
                    if hit is None:
                        fresh.setdefault(gkey, []).append((key, sh))
                        fresh_groups[gkey] = (pub, base, context)
                        local[key] = False  # placeholder, filled below
                    else:
                        local[key] = hit
            item_keys.append(keys)
        if fresh:
            self.dispatches += 1
            groups = []
            order = []
            for gkey, pairs in fresh.items():
                pub, base, context = fresh_groups[gkey]
                groups.append((pub, base, [sh for _k, sh in pairs], context))
                order.append(pairs)
            verdicts = verify_share_groups(
                groups,
                backend=self.crypto.engine_backend,
                mesh=self.crypto.mesh,
            )
            put = self._share_memo.put
            for pairs, oks in zip(order, verdicts):
                for (key, _sh), good in zip(pairs, oks):
                    local[key] = good
                    put(key, good)
        for (item, keys) in zip(items, item_keys):
            item[5](item[3], [local[k] for k in keys])

    # -- coin-issue column (Config.egress_columnar) ------------------------

    def stage_coin_issue(self, owner, meta, item, group) -> None:
        """Park one coin-share issue want: ``item`` is the
        ``(secret, base, context, vk)`` tuple ``ops.coin.share_batch``
        takes, ``meta`` the owner's own handle (returned with the
        share), ``group`` the issue's GroupParams.  Staging happens at
        aux-quorum time — during the message wave — so by the first
        drain of the idle phase the whole roster's wants are pooled."""
        self._coin_pool.append((owner, meta, item, group))

    def take_coin_issues(self, owner) -> List[Tuple]:
        """``(meta, share)`` rows for ``owner``, in stage order.  If
        any of the owner's staged items are still pending, the WHOLE
        pool — every staged owner — executes first in one native
        dispatch per distinct group (one group in practice: the coin
        group is deployment-wide), so a wave's coin issues across all
        instances, rounds, and in-proc nodes cost one
        multi-exponentiation and one CP-nonce draw."""
        if any(row[0] is owner for row in self._coin_pool):
            self._run_coin_pool()
        return self._coin_results.pop(owner, [])

    def _run_coin_pool(self) -> None:
        pool, self._coin_pool = self._coin_pool, []

        def tally(n: int) -> None:
            self.coin_issue_batches += 1
            self.coin_issue_items += n

        self._run_owner_pool(
            pool, coin_share_batch, "coin", "share_batch",
            self._coin_results, tally,
        )

    def _run_owner_pool(
        self, pool, kernel, trace_cat, trace_name, results, tally
    ) -> None:
        """The shared discipline of the owner-staged issue columns
        (coin shares and — K-deep eager mode — TPKE dec shares):
        insertion-ordered grouping by group object (DET002: dispatch
        and result order must not depend on hash order), ONE native
        ``kernel`` dispatch per distinct group over the pool's
        ``(secret/share, base, context, vk)`` items, results parked
        per owner in stage order.  ``tally(n_rows)`` bumps the
        column's batch/item counters."""
        groups: Dict[int, List[Tuple]] = {}
        group_objs: Dict[int, object] = {}
        for row in pool:
            gid = id(row[3])
            groups.setdefault(gid, []).append(row)
            group_objs[gid] = row[3]
        tr = self.trace
        for gid, rows in groups.items():
            t0 = 0.0 if tr is None else tr.now()
            tally(len(rows))
            shares = kernel(
                [row[2] for row in rows],
                group=group_objs[gid],
                backend=self.crypto.engine_backend,
                mesh=self.crypto.mesh,
            )
            if tr is not None:
                tr.complete(
                    trace_cat,
                    trace_name,
                    t0,
                    n=len(rows),
                    owners=len({id(row[0]) for row in rows}),
                )
            for row, share in zip(rows, shares):
                results.setdefault(row[0], []).append(
                    (row[1], share)
                )

    # -- dec-share issue column (Config.pipeline_depth > 1) ----------------

    def stage_dec_issue(self, owner, meta, item, group) -> None:
        """Park one TPKE dec-share issue want (the K-deep eager
        piggyback path): ``item`` is the ``(share, base, context,
        vk)`` tuple ``ops.tpke.issue_shares_batch`` takes, ``meta``
        the owner's own handle (returned with the share), ``group``
        the issue's GroupParams.  Staging happens the moment an
        epoch ORDERS — during the message wave — so by the turn's
        piggyback drain every node's (and every freshly ordered
        epoch's) wants are pooled."""
        with self._dec_lock:
            self._dec_pool.append((owner, meta, item, group))

    def take_dec_issues(self, owner) -> List[Tuple]:
        """``(meta, DhShare)`` rows for ``owner``, in stage order.
        If any of the owner's staged items are still pending, the
        WHOLE pool — every staged owner — executes first in one
        native dispatch per distinct group (one in practice: the
        TPKE group is deployment-wide), and each other owner's
        shares park until its own drain claims them, so broadcast
        site and order stay per-node deterministic."""
        with self._dec_lock:
            if any(row[0] is owner for row in self._dec_pool):
                self._run_dec_pool_locked()
            return self._dec_results.pop(owner, [])

    def _run_dec_pool_locked(self) -> None:
        pool, self._dec_pool = self._dec_pool, []

        def tally(n: int) -> None:
            self.dec_issue_batches += 1
            self.dec_issue_items += n

        self._run_owner_pool(
            pool, issue_shares_batch, "settle", "dec_share_batch",
            self._dec_results, tally,
        )

    # -- stats -------------------------------------------------------------

    def stats(self) -> Dict[str, int]:
        return {
            "flushes": self.flushes,
            "dispatches": self.dispatches,
            "branch_items": self.branch_items,
            "decode_items": self.decode_items,
            "share_items": self.share_items,
            "coin_issue_batches": self.coin_issue_batches,
            "coin_issue_items": self.coin_issue_items,
            "dec_issue_batches": self.dec_issue_batches,
            "dec_issue_items": self.dec_issue_items,
        }


__all__ = ["CryptoHub", "HubWave"]
