"""CryptoHub: cross-instance batched crypto for the live protocol path.

The reference's cost model is N^2 ECHO-phase Merkle verifications and
~4N^2 threshold-share verifications per epoch (reference
docs/HONEYBADGER-EN.md:93-96), arriving one message at a time.  The
hub is the per-epoch accumulation buffer SURVEY.md §7 (hard part 3)
calls for: protocol instances never run device crypto directly on the
message path — they park work (unverified ECHO branches, undecoded
roots, unverified threshold shares) in their own state and the hub
pulls and executes it in BATCHED dispatches when some instance's
quorum threshold makes results necessary.

Why pull, not push: the work lives where the protocol state lives, so
an instance that becomes irrelevant mid-flight (delivered, halted,
epoch GC'd) simply stops offering work — no queue invalidation.  And
because EVERY registered instance's pending work is collected whenever
ANY instance needs a flush, one instance reaching quorum amortizes the
whole node's backlog into the same dispatch: under 'tpu', an epoch's
N instances' ECHO proofs verify in ~1 `verify_batch` call instead of
N^2 singleton calls, and all TPKE + coin shares fold into ONE
dual-exponentiation dispatch via tpke.verify_share_groups.

Client protocol (duck-typed; see RBC/BBA/HoneyBadger):

  collect_crypto_work(branches, decodes, shares) -> None
      append pending work items; pending state moves to in-flight
  after_crypto_flush() -> None
      verdicts have been applied via item callbacks; run quorum logic

Work item shapes:
  branches: (root: bytes32, leaf: bytes, branch: tuple[bytes32,...],
             index: int, cb(ok: bool))
  decodes:  (idxs: tuple[int,...], shards: (k, L) uint8 ndarray,
             root: bytes32, cb(data: Optional[ndarray]))
             -- decode + re-encode + Merkle-root recheck
             (docs/RBC-EN.md:37-39) batched across instances
  shares:   (pub, base: int, context: bytes, senders: list[str],
             shares: list[DhShare], cb(verdicts: list[bool]))

The flush loop iterates because verdicts unlock follow-on work (ECHO
verifies add shards -> a root becomes decodable -> decode next pass);
it terminates when a collection round yields nothing.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple

import numpy as np

from cleisthenes_tpu.ops.backend import BatchCrypto
from cleisthenes_tpu.ops.tpke import verify_share_groups

# A flush settles in 2-3 collection rounds (verify -> decode -> quorum
# actions); the cap only guards against a pathological client that
# re-offers work forever.
MAX_FLUSH_ROUNDS = 64


class CryptoHub:
    """Per-node batched-crypto service shared by all protocol instances."""

    def __init__(self, crypto: BatchCrypto):
        self.crypto = crypto
        # scope (epoch int, or any hashable) -> clients; scopes drop
        # wholesale when HoneyBadger GCs an epoch
        self._clients: Dict[object, List[object]] = {}
        self._flushing = False
        # Deferred mode (HoneyBadger.transport_manages_idle sets
        # ``hub.defer = True`` when its transport promises an idle
        # callback): request_flush only
        # records the want; the actual flush runs at the transport's
        # quiescence point, so one flush absorbs the whole message
        # wave's pending work instead of firing per quorum event —
        # VERDICT round 2's dispatch-count lever (item 2).
        self.defer = False
        self.flush_wanted = False
        # observability (utils.metrics reads these)
        self.flushes = 0
        self.branch_items = 0
        self.decode_items = 0
        self.share_items = 0
        self.dispatches = 0

    # -- membership --------------------------------------------------------

    def register(self, scope, client) -> None:
        self._clients.setdefault(scope, []).append(client)

    def drop_scope(self, scope) -> None:
        self._clients.pop(scope, None)

    # -- flushing ----------------------------------------------------------

    def request_flush(self) -> None:
        """Run a flush now — unless one is already running (its
        collection loop will pick the new work up) or deferred mode
        parks the request for the transport's idle callback."""
        if self._flushing:
            return
        if self.defer:
            self.flush_wanted = True
            return
        self.flush()

    def run_deferred(self) -> None:
        """Idle-callback entry: run the flush the message wave asked
        for (no-op when nothing requested one)."""
        if self.flush_wanted and not self._flushing:
            self.flush_wanted = False
            self.flush()

    def flush(self) -> None:
        if self._flushing:
            return
        self._flushing = True
        self.flush_wanted = False  # any full flush satisfies the want
        self.flushes += 1
        try:
            for _ in range(MAX_FLUSH_ROUNDS):
                branches: List[Tuple] = []
                decodes: List[Tuple] = []
                shares: List[Tuple] = []
                clients = [
                    c for cs in self._clients.values() for c in cs
                ]
                for c in clients:
                    c.collect_crypto_work(branches, decodes, shares)
                if not (branches or decodes or shares):
                    break
                if branches:
                    self._run_branches(branches)
                if decodes:
                    self._run_decodes(decodes)
                if shares:
                    self._run_shares(shares)
                for c in clients:
                    c.after_crypto_flush()
        finally:
            self._flushing = False

    # -- executors ---------------------------------------------------------

    def _run_branches(self, items: List[Tuple]) -> None:
        """Branch proofs grouped by (depth, leaf length) — one
        merkle.verify_batch per group (trees of one roster share a
        depth, so this is ~one group per epoch)."""
        self.branch_items += len(items)
        groups: Dict[Tuple[int, int], List[Tuple]] = {}
        for item in items:
            _root, leaf, branch, _index, _cb = item
            groups.setdefault((len(branch), len(leaf)), []).append(item)
        for group in groups.values():
            self.dispatches += 1
            b = len(group)
            leaf_len = len(group[0][1])
            # single join+frombuffer per column: per-item np.stack /
            # frombuffer assembly was ~5% of an N=64 epoch
            roots = np.frombuffer(
                b"".join(it[0] for it in group), dtype=np.uint8
            ).reshape(b, 32)
            leaves = np.frombuffer(
                b"".join(it[1] for it in group), dtype=np.uint8
            ).reshape(b, leaf_len)
            depth = len(group[0][2])
            if depth:
                branches_arr = np.frombuffer(
                    b"".join(s for it in group for s in it[2]),
                    dtype=np.uint8,
                ).reshape(b, depth, 32)
            else:  # single-leaf trees
                branches_arr = np.zeros((b, 0, 32), dtype=np.uint8)
            indices = np.asarray([it[3] for it in group])
            ok = self.crypto.merkle.verify_batch(
                roots, leaves, branches_arr, indices
            )
            for it, good in zip(group, ok):
                it[4](bool(good))

    def _run_decodes(self, items: List[Tuple]) -> None:
        """Interpolate + re-encode + root recheck (docs/RBC-EN.md:37-39)
        for many instances at once, grouped by shard length — ONE
        fused dispatch per group on the 'tpu' backend
        (BatchCrypto.decode_recheck_batch)."""
        self.decode_items += len(items)
        groups: Dict[Tuple[int, int], List[Tuple]] = {}
        for item in items:
            idxs, shards, _root, _cb = item
            groups.setdefault((shards.shape[0], shards.shape[1]), []).append(
                item
            )
        for group in groups.values():
            idx_arr = np.stack([np.asarray(it[0]) for it in group])
            shard_arr = np.stack([it[1] for it in group])
            data, roots, dispatches = self.crypto.decode_recheck_batch(
                idx_arr, shard_arr
            )
            self.dispatches += dispatches
            for it, row, root in zip(group, data, roots):
                it[3](row if root.tobytes() == it[2] else None)

    def _run_shares(self, items: List[Tuple]) -> None:
        """ALL pooled threshold shares (TPKE decryption + BBA coins,
        every instance) in ONE dual-exponentiation dispatch."""
        self.share_items += sum(len(it[4]) for it in items)
        self.dispatches += 1
        verdicts = verify_share_groups(
            [(pub, base, shs, ctx) for pub, base, ctx, _snd, shs, _cb in items],
            backend=self.crypto.engine_backend,
            mesh=self.crypto.mesh,
        )
        for item, ok in zip(items, verdicts):
            item[5](item[3], ok)

    # -- stats -------------------------------------------------------------

    def stats(self) -> Dict[str, int]:
        return {
            "flushes": self.flushes,
            "dispatches": self.dispatches,
            "branch_items": self.branch_items,
            "decode_items": self.decode_items,
            "share_items": self.share_items,
        }


__all__ = ["CryptoHub"]
