"""BBA: randomized binary Byzantine agreement with a threshold coin.

Completes the reference's skeleton (reference bba/bba.go:63-107,
bba/binary_set.go:7-11) per its own spec (reference docs/BBA-EN.md):

  round r, estimate est:
    broadcast BVAL(est)                              (docs/BBA-EN.md:39-44)
    on f+1  BVAL(v): relay BVAL(v) once              (docs/BBA-EN.md:47-52)
    on 2f+1 BVAL(v): bin_values U= {v}               (docs/BBA-EN.md:53-58,
                                                      bba/binary_set.go union)
    when bin_values first non-empty: broadcast AUX(w), w in bin_values
                                                     (docs/BBA-EN.md:134-139)
    await n-f AUX whose values are in bin_values -> vals
                                                     (docs/BBA-EN.md:140-156)
    s = common_coin(r)                               (docs/BBA-EN.md:163-177)
    vals == {b}: est = b; decide b if b == s
    else:        est = s; next round

The common coin is the threshold VUF of ops.coin: each node broadcasts
one share per (instance, round); f+1 verified shares combine to the
network-global bit.  Share verification is batched through the
BatchCrypto seam (one TPU dispatch per reveal under 'tpu').

Termination (the part docs/BBA-EN.md leaves open): deciding alone must
not stop a node — rounds need n-f live participants, so a decided node
keeps participating with its estimate pinned to the decision, and a
Bracha-style TERM gadget provides the actual exit: broadcast TERM(b)
on decision; adopt-decide on f+1 TERM(b); halt on 2f+1 TERM(b)
(>= f+1 of those are correct, so every correct node eventually adopts
and halts too).

The epoch/round bookkeeping mirrors the reference struct
(bba/bba.go:27-61): n, f, proposer, epoch + internal round,
sentBvalSet, est/dec binaries, per-type repos, and the future-message
buffer (bba/request.go:28-32 semantics, here applied to rounds within
the instance; epochs are buffered one level up by HoneyBadger).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set, Tuple

from cleisthenes_tpu.config import Config
from cleisthenes_tpu.ops.coin import CommonCoin
from cleisthenes_tpu.ops.tpke import (
    DhShare,
    SharePool,
    ThresholdSecretShare,
)
from cleisthenes_tpu.transport.message import (
    BbaPayload,
    BbaType,
    CoinPayload,
)

# A Byzantine peer must not park unbounded state for distant rounds.
ROUND_HORIZON = 8
MAX_BUFFERED_PER_SENDER = 4 * ROUND_HORIZON
# Probabilistic termination: P(not done) halves per round; 1000 rounds
# is unreachable in practice and bounds state against pathology.
MAX_ROUNDS = 1000


class _Round:
    """Per-round SEND + coin state (the reference keeps one flat set
    because it never finished multi-round flow; bba/bba.go:44-51).
    BVAL/AUX RECEIPT state lives in the shared VoteBank row — single
    source of truth for both the columnar and the scalar delivery
    paths (protocol.votebank)."""

    __slots__ = (
        "bval_sent",
        "aux_sent",
        "coin_share_sent",
        "coin_shares",
        "coin_value",
        "advanced",
        "rows_pulled",
    )

    def __init__(self, coin_threshold: int) -> None:
        self.bval_sent: Set[bool] = set()
        self.aux_sent: Optional[bool] = None
        self.coin_share_sent = False
        # sender-keyed with burned-slot tracking: a Byzantine peer can
        # only ever occupy (and burn) its own slot, never censor an
        # honest node's share or force repeated re-verification
        self.coin_shares = SharePool(coin_threshold)
        self.coin_value: Optional[bool] = None
        self.advanced = False
        # cursor into the ACS CoinRowStore's row list for this round
        # (lazy columnar ingestion; see acs.CoinRowStore)
        self.rows_pulled = 0


class BBA:
    """One binary-agreement instance: (epoch, proposer)."""

    def __init__(
        self,
        *,
        config: Config,
        epoch: int,
        proposer: str,
        owner: str,
        member_ids,
        coin: CommonCoin,
        coin_secret: ThresholdSecretShare,
        out,
        hub=None,
        bank=None,
        index: Optional[int] = None,
        coin_issue_sink: Optional[Callable] = None,
        trace=None,
        metrics=None,
        scope=None,
    ) -> None:
        self.n = config.n
        self.f = config.f
        # bin_values / TERM-halt threshold: 2f+1 baseline, n-f
        # under Config.reduced_quorum (Config.quorum_large)
        self.q_large = config.quorum_large
        self.epoch = epoch
        self.proposer = proposer
        self.owner = owner
        self.members = sorted(member_ids)
        self._member_set = frozenset(self.members)
        if bank is None:  # standalone use (unit tests): private row
            from cleisthenes_tpu.protocol.votebank import VoteBank

            bank = VoteBank(
                self.members, config.f, inst_ids=[proposer],
                metrics=metrics, quorum_large=config.quorum_large,
            )
            index = 0
        self.bank = bank
        self.index = index
        bank.attach(index, self)
        self.coin = coin
        self.coin_secret = coin_secret
        self.out = out
        # when set, coin-share issuance defers to the owner's
        # per-drain batch (one exponentiation dispatch for a whole
        # wave of instances) instead of 4 scalar host exps here
        self.coin_issue_sink = coin_issue_sink
        if hub is None:  # standalone use (unit tests): private hub
            from cleisthenes_tpu.ops.backend import BatchCrypto
            from cleisthenes_tpu.protocol.hub import CryptoHub

            hub = CryptoHub(
                BatchCrypto(
                    coin.backend, config.n, config.f, config.data_shards
                )
            )
        self.hub = hub
        # see rbc.py note: lane shard-out qualifies scope per lane
        self.hub.register((owner if scope is None else scope, epoch), self)
        # flight recorder (None = tracing off; utils/trace.py)
        self.trace = trace
        # owner-node metrics (None in standalone unit tests): only the
        # duplicate-vote absorption counter is touched here
        self.metrics = metrics

        self.round = 0
        self.est: Optional[bool] = None
        self.decided: Optional[bool] = None  # dec (bba/bba.go:50)
        self.halted = False
        self.on_decide: Optional[Callable[[str, bool], None]] = None

        self._coin_threshold = coin.pub.threshold
        # set by ACS after construction: the epoch's shared columnar
        # coin-row store (None in standalone/unit-test use, where the
        # scalar per-share path below carries everything)
        self.coin_rows = None
        self._rounds: Dict[int, _Round] = {0: _Round(coin.pub.threshold)}
        self._term_sent = False
        self._term_recv: Dict[bool, Set[str]] = {True: set(), False: set()}
        self._term_voted: Set[str] = set()
        # (round -> [(sender, payload)]) future-round parking
        self._future: Dict[int, List[Tuple[str, object]]] = {}
        self._buffered_per_sender: Dict[str, int] = {}

    # -- public API (reference bba/bba.go:63-87) ---------------------------

    def result(self) -> Optional[bool]:
        """Reference bba/bba.go:78-80."""
        return self.decided

    @property
    def done(self) -> bool:
        return self.decided is not None

    def input(self, est: bool) -> None:
        """Reference bba/bba.go:69-71 HandleInput: set the initial
        estimate and open round 0.  Ignored if the instance already
        derived an estimate (it advanced rounds passively before the
        caller got around to providing input — ACS inputs 0 late)."""
        if self.halted or self.est is not None:
            return
        self.est = bool(est)
        self._broadcast_bval(self.round, self.est)

    def handle_message(self, sender: str, payload) -> None:
        """Reference bba/bba.go:74-76 HandleMessage + :89-99 muxRequest."""
        if self.halted or sender not in self._member_set:
            return
        if isinstance(payload, BbaPayload):
            if payload.type == BbaType.TERM:
                self._handle_term(sender, payload.value)
                return
            self._gated(sender, payload, payload.round)
        elif isinstance(payload, CoinPayload):
            self._gated(sender, payload, payload.round)

    # -- scalar entry points (columnar wave payloads) ----------------------

    def handle_vote(self, sender: str, t, rnd: int, value: bool) -> None:
        """BVAL/AUX/TERM without a payload object: the columnar batch
        path's per-instance call.  Off-round votes fall back to the
        parking path (payload built lazily — parking is the rare
        case)."""
        if self.halted or sender not in self._member_set:
            return
        if t == BbaType.TERM:
            self._handle_term(sender, value)
            return
        if rnd == self.round:
            if t == BbaType.BVAL:
                self._handle_bval(sender, value)
            else:
                self._handle_aux(sender, value)
            return
        if rnd < self.round:
            return  # stale: skip even the payload allocation
        self._gated(
            sender,
            BbaPayload(t, self.proposer, self.epoch, rnd, value),
            rnd,
        )

    def handle_coin(
        self, sender: str, rnd: int, index: int, d: int, e: int, z: int
    ) -> None:
        """Coin share without a payload object (columnar batch path)."""
        if self.halted or sender not in self._member_set:
            return
        self.handle_coin_fast(sender, rnd, index, d, e, z)

    def handle_coin_fast(
        self, sender: str, rnd: int, index: int, d: int, e: int, z: int
    ) -> None:
        """handle_coin minus the halted/membership gate — for callers
        that already checked both (ACS.handle_coin_batch hoists them
        out of its per-instance loop)."""
        if rnd == self.round:
            self._handle_coin_share_scalar(sender, index, d, e, z)
            return
        if rnd < self.round:
            return  # stale: skip the payload allocation
        self._gated(
            sender,
            CoinPayload(self.proposer, self.epoch, rnd, index, d, e, z),
            rnd,
        )

    # -- round gating ------------------------------------------------------

    def _gated(self, sender: str, payload, rnd: int) -> None:
        """Process current-round messages; park future rounds within
        the horizon (bba/request.go:28-32 pattern, per-round)."""
        if rnd < self.round:
            return  # stale: quorums it could join are already closed
        if rnd >= MAX_ROUNDS:
            # Liveness cutoff: an instance that somehow reaches round
            # MAX_ROUNDS can never decide, because the messages that
            # would let it are dropped here.  Accepted deliberately:
            # each round ends with probability >= 1/2, so P(reaching
            # round 1000) ~ 2^-1000 — the bound exists only to cap
            # state against a pathological/Byzantine round counter.
            return
        if rnd > self.round:
            if rnd > self.round + ROUND_HORIZON:
                return
            count = self._buffered_per_sender.get(sender, 0)
            if count >= MAX_BUFFERED_PER_SENDER:
                return
            self._buffered_per_sender[sender] = count + 1
            self._future.setdefault(rnd, []).append((sender, payload))
            return
        self._dispatch(sender, payload)

    def _dispatch(self, sender: str, payload) -> None:
        if isinstance(payload, BbaPayload):
            if payload.type == BbaType.BVAL:
                self._handle_bval(sender, payload.value)
            elif payload.type == BbaType.AUX:
                self._handle_aux(sender, payload.value)
        elif isinstance(payload, CoinPayload):
            self._handle_coin_share(sender, payload)

    # -- BVAL / AUX (reference bba/bba.go:101-107, empty in skeleton) ------

    def _cur(self) -> _Round:
        return self._rounds[self.round]

    def _broadcast_bval(self, rnd: int, value: bool) -> None:
        r = self._rounds[rnd]
        if value in r.bval_sent:
            return
        r.bval_sent.add(value)
        self.out.broadcast(
            BbaPayload(
                type=BbaType.BVAL,
                proposer=self.proposer,
                epoch=self.epoch,
                round=rnd,
                value=value,
            )
        )

    def _handle_bval(self, sender: str, value: bool) -> None:
        si = self.bank.sidx.get(sender)
        if si is None:
            return
        cnt = self.bank.bval_add(self.index, si, value)
        if cnt is None:  # duplicate
            return
        # f+1 same bval -> relay once (docs/BBA-EN.md:47-52; the
        # sentBvalSet of bba/bba.go:48)
        if cnt >= self.f + 1:
            self.on_bval_relay(value)
        # q_large -> bin_values union (docs/BBA-EN.md:53-58)
        if cnt >= self.q_large:
            self.on_bval_bin(value)

    def on_bval_relay(self, value: bool) -> None:
        """f+1 BVAL crossing (idempotent: bval_sent dedups)."""
        self._broadcast_bval(self.round, value)

    def on_bval_bin(self, value: bool) -> None:
        """2f+1 BVAL crossing: bin_values growth (idempotent)."""
        vi = 1 if value else 0
        if self.bank.bin_flags[self.index, vi]:
            return
        self.bank.set_bin(self.index, value)
        r = self._cur()
        if r.aux_sent is None:
            r.aux_sent = value
            self.out.broadcast(
                BbaPayload(
                    type=BbaType.AUX,
                    proposer=self.proposer,
                    epoch=self.epoch,
                    round=self.round,
                    value=value,
                )
            )
        # bin_values growth can complete the AUX quorum
        self._maybe_request_coin()
        self._maybe_advance()

    def _handle_aux(self, sender: str, value: bool) -> None:
        si = self.bank.sidx.get(sender)
        if si is None:
            return
        if not self.bank.aux_add(self.index, si, value):
            return  # duplicate
        self._maybe_request_coin()
        self._maybe_advance()

    def on_aux_quorum(self) -> None:
        """Columnar-path trigger: the n-f AUX quorum became reachable."""
        self._maybe_request_coin()
        self._maybe_advance()

    def _aux_quorum(self) -> bool:
        """n-f AUX messages whose values are in bin_values
        (docs/BBA-EN.md:140-156)."""
        return self.bank.aux_good(self.index) >= self.n - self.f

    # -- common coin (docs/BBA-EN.md:163-181) ------------------------------

    def _coin_id(self, rnd: int) -> bytes:
        return b"%d|%s|%d" % (self.epoch, self.proposer.encode(), rnd)

    def _maybe_request_coin(self) -> None:
        """First AUX quorum -> contribute our coin share for this round."""
        r = self._cur()
        if r.coin_share_sent or not self._aux_quorum():
            return
        r.coin_share_sent = True
        if self.trace is not None:
            self.trace.instant(
                "coin",
                "share_issue",
                epoch=self.epoch,
                proposer=self.proposer,
                round=self.round,
            )
        if self.coin_issue_sink is not None:
            # the drain batches every queued instance's issue into one
            # dispatch and calls broadcast_coin_share back
            self.coin_issue_sink(self, self.round)
            return
        share = self.coin.share(self.coin_secret, self._coin_id(self.round))
        self.broadcast_coin_share(self.round, share)

    def broadcast_coin_share(self, rnd: int, share) -> None:
        # deliberately NOT gated on halted: the share is a deterministic
        # public VUF value, and a node that decides via TERM between
        # queueing a coin issue and draining it must still contribute —
        # slower peers may be one share short of the coin threshold
        # (advisor r4 finding on the deferred-issue drain)
        self.out.broadcast(
            CoinPayload(
                proposer=self.proposer,
                epoch=self.epoch,
                round=rnd,
                index=share.index,
                d=share.d,
                e=share.e,
                z=share.z,
            )
        )

    def _handle_coin_share(self, sender: str, p: CoinPayload) -> None:
        self._handle_coin_share_scalar(sender, p.index, p.d, p.e, p.z)

    def _handle_coin_share_scalar(
        self, sender: str, index: int, d: int, e: int, z: int
    ) -> None:
        r = self._cur()
        if r.coin_value is not None or not (1 <= index <= self.n):
            return
        if r.coin_shares.add_lazy(sender, index, d, e, z):
            # below the threshold there is nothing a hub flush could
            # usefully verify for this pool — defer the dirty mark
            # (and the DhShare materialization) until the coin can
            # actually reveal; the post-burn replacement path re-marks
            # explicitly in _on_coin_verdicts
            if len(r.coin_shares) >= self._coin_threshold:
                self.hub.mark_dirty(self)
                self._maybe_reveal_coin()
        elif self.metrics is not None:
            self.metrics.dedup_absorbed.inc()

    def _maybe_reveal_coin(self) -> None:
        """Threshold reached -> flush the hub: OUR shares verify in the
        same dispatch as every other concurrent instance's pooled
        shares (and the epoch's pending TPKE/branch work)."""
        r = self._cur()
        if r.coin_value is not None:
            return
        self._top_up_coin(r)
        if len(r.coin_shares) < self.coin.pub.threshold:
            return
        self.hub.request_flush()

    # -- columnar coin rows (acs.CoinRowStore) -----------------------------

    def _pull_coin_rows(self, rnd: int, r: "_Round", target: int) -> None:
        """Materialize this instance's shares from the ACS row store
        into the round's pool, up to ``target`` pool entries — the
        callers (_top_up_coin) pull only until the threshold is
        index-coverable; surplus rows stay parked in the store and
        never materialize."""
        store = self.coin_rows
        if store is None:
            return
        ent = store.by_round.get(rnd)
        if ent is None:
            return
        rows = ent[0]
        cur = r.rows_pulled
        if cur >= len(rows):
            return
        pool = r.coin_shares
        me = self.proposer
        col_of = store.col
        while cur < len(rows) and len(pool) < target:
            sender, index, proposers, d, e, z = rows[cur]
            cur += 1
            ci = col_of(proposers, me)
            if ci is not None:
                pool.add_lazy(sender, index, d[ci], e[ci], z[ci])
        r.rows_pulled = cur

    def _top_up_coin(self, r: "_Round") -> None:
        """Pull from the row store until the threshold is COVERABLE
        (distinct Shamir indices) or the store has no more rows for
        this round; arm the store's re-notify watch when a replayed
        index leaves a threshold-size pool under-covered (the coin
        analog of the round-4 dec-share crossing-stall fix)."""
        pool = r.coin_shares
        while pool.covered() < pool.threshold:
            before = len(pool)
            self._pull_coin_rows(
                self.round,
                r,
                before + (pool.threshold - pool.covered()),
            )
            if len(pool) == before:
                break  # store exhausted for this round
        store = self.coin_rows
        if store is not None and self.index is not None:
            if pool.covered() < pool.threshold:
                store.watch_on(self.index, self.round)
            else:
                store.watch_off(self.index)

    def on_coin_rows(self, rnd: int) -> None:
        """ACS notification: the store's round-``rnd`` rows reached
        the coin threshold for this instance (or this instance just
        entered a round whose rows already had, or it is watched and
        a fresh row arrived)."""
        if self.halted or rnd != self.round:
            return
        r = self._rounds.get(rnd)
        if r is None or r.coin_value is not None:
            return
        self._top_up_coin(r)
        if len(r.coin_shares) >= self._coin_threshold:
            self.hub.mark_dirty(self)
            self.hub.request_flush()

    # -- hub client protocol (protocol.hub.CryptoHub) ----------------------

    def drain_pending(self, wave) -> None:
        if self.halted:
            return
        r = self._rounds.get(self.round)
        if r is None or r.coin_value is not None:
            return
        # flush boundary: top the pool up until the threshold is
        # COVERABLE (distinct Shamir indices), not until the store is
        # empty — surplus rows stay parked and never materialize
        # (burns recompute coverage, so deficits re-pull here on the
        # re-marked flush round)
        self._top_up_coin(r)
        pool = r.coin_shares
        senders, shs = pool.collect_pending(pool.need_more())
        if not senders:
            return
        pub, base, context = self.coin.group_params(
            self._coin_id(self.round)
        )
        rnd = self.round
        wave.add_share(
            pub,
            base,
            context,
            senders,
            shs,
            lambda snd, ok, rnd=rnd: self._on_coin_verdicts(rnd, snd, ok),
        )

    def _on_coin_verdicts(self, rnd: int, senders, ok) -> None:
        r = self._rounds.get(rnd)
        if r is None:
            return
        r.coin_shares.apply_verdicts(senders, ok)
        if not all(ok) and r.coin_shares.need_more():
            # an invalid share burned a collected slot: the surplus
            # shares already PARKED in the pool are the replacements,
            # and under dirty-set flushing nothing else would re-offer
            # them (no new arrival is coming — every share may already
            # be here).  Re-mark so the flush loop's next collection
            # round pulls them; without this the coin stays unrevealed
            # forever (liveness break found by round-3 review).
            self.hub.mark_dirty(self)

    def after_crypto_flush(self) -> None:
        if self.halted:
            return
        r = self._rounds.get(self.round)
        if r is None or r.coin_value is not None:
            return
        valid = r.coin_shares.ready()
        if valid is None:
            return
        r.coin_value = self.coin.toss(self._coin_id(self.round), valid)
        if self.trace is not None:
            self.trace.instant(
                "coin",
                "reveal",
                epoch=self.epoch,
                proposer=self.proposer,
                round=self.round,
                value=bool(r.coin_value),
            )
        if self.coin_rows is not None and self.index is not None:
            self.coin_rows.watch_off(self.index)
        self._maybe_advance()

    # -- round transition --------------------------------------------------

    def _maybe_advance(self) -> None:
        r = self._cur()
        if r.advanced or r.coin_value is None or not self._aux_quorum():
            return
        vals = self.bank.aux_vals(self.index)  # docs/BBA-EN.md:140-156
        coin = r.coin_value
        r.advanced = True
        if len(vals) == 1:
            (b,) = vals
            next_est = b
            if b == coin and self.decided is None:
                self._decide(b)
        else:
            next_est = coin
        if self.decided is not None:
            # decided nodes keep participating, estimate pinned, so
            # laggards' rounds retain n-f live members
            next_est = self.decided
        self.round += 1
        self.est = next_est
        if self.trace is not None:
            self.trace.instant(
                "bba",
                "round",
                epoch=self.epoch,
                proposer=self.proposer,
                round=self.round,
            )
        self._rounds[self.round] = _Round(self.coin.pub.threshold)
        self.bank.reset_row(self.index, self.round)
        self._broadcast_bval(self.round, next_est)
        # late entry: the store may already hold a coin quorum for the
        # new round (its crossing notification fired before we got
        # here and skipped us — round mismatch); any watch armed for
        # the finished round is stale now
        store = self.coin_rows
        if store is not None and self.index is not None:
            store.watch_off(self.index)
            if store.count(self.round, self.index) >= self._coin_threshold:
                self.on_coin_rows(self.round)
        # GC old round, replay parked messages for the new one
        self._rounds.pop(self.round - 1, None)
        replay_round = self.round
        for sender, payload in self._future.pop(replay_round, []):
            cnt = self._buffered_per_sender.get(sender, 0)
            if cnt > 0:
                self._buffered_per_sender[sender] = cnt - 1
            if self.halted:
                break
            # re-gate instead of dispatching blindly: a nested advance
            # during this replay moves self.round past replay_round,
            # and these parked votes must then be dropped as stale, not
            # counted into a later round's quorums
            self._gated(sender, payload, replay_round)

    # -- decision & termination --------------------------------------------

    def _decide(self, b: bool) -> None:
        self.decided = b
        if self.trace is not None:
            self.trace.instant(
                "bba",
                "decide",
                epoch=self.epoch,
                proposer=self.proposer,
                round=self.round,
                value=bool(b),
            )
        if not self._term_sent:
            self._term_sent = True
            self.out.broadcast(
                BbaPayload(
                    type=BbaType.TERM,
                    proposer=self.proposer,
                    epoch=self.epoch,
                    round=self.round,
                    value=b,
                )
            )
        if self.on_decide is not None:
            self.on_decide(self.proposer, b)

    def _handle_term(self, sender: str, value: bool) -> None:
        if sender in self._term_voted:
            if self.metrics is not None:
                self.metrics.dedup_absorbed.inc()
            return
        self._term_voted.add(sender)
        self._term_recv[value].add(sender)
        n_votes = len(self._term_recv[value])
        if n_votes >= self.f + 1 and self.decided is None:
            self._decide(value)  # adopt: f+1 guarantees a correct voter
        if n_votes >= self.q_large:
            # enough correct nodes have decided and broadcast TERM that
            # every correct node will adopt+halt without our help
            self.halted = True
            self._rounds.clear()
            self._future.clear()
            self.bank.deactivate(self.index)
            if self.coin_rows is not None and self.index is not None:
                self.coin_rows.watch_off(self.index)


__all__ = ["BBA", "ROUND_HORIZON", "MAX_ROUNDS"]
