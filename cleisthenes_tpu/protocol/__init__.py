"""Protocol plane: RBC, BBA (+ common coin), ACS, HoneyBadger.

The asynchronous, data-dependent control flow of HBBFT — the part XLA
cannot host — lives here as host-side message-driven state machines,
mirroring the reference's actor design (reference rbc/rbc.go,
bba/bba.go, honeybadger.go).  All O(N^2) crypto math is delegated to
the batched ops plane (cleisthenes_tpu.ops) through the BatchCrypto
seam.  The plane's own adversary — semantic Byzantine behaviors under
valid MACs — lives in protocol.byzantine (docs/FAULTS.md).
"""

from cleisthenes_tpu.protocol.acs import ACS
from cleisthenes_tpu.protocol.bba import BBA
from cleisthenes_tpu.protocol.byzantine import Behavior, make_behavior
from cleisthenes_tpu.protocol.cluster import SimulatedCluster
from cleisthenes_tpu.protocol.honeybadger import (
    HoneyBadger,
    NodeKeys,
    setup_keys,
)
from cleisthenes_tpu.protocol.rbc import RBC
from cleisthenes_tpu.protocol.reconfig import (
    ReconfigManager,
    encode_reconfig_tx,
)
from cleisthenes_tpu.protocol.spmd import LockstepCluster

__all__ = [
    "RBC",
    "BBA",
    "ACS",
    "HoneyBadger",
    "NodeKeys",
    "setup_keys",
    "SimulatedCluster",
    "LockstepCluster",
    "Behavior",
    "make_behavior",
    "ReconfigManager",
    "encode_reconfig_tx",
]
