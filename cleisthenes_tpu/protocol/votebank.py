"""VoteBank: vectorized BVAL/AUX bookkeeping across BBA instances.

An epoch runs N concurrent BBA instances (one per proposer,
docs/HONEYBADGER-EN.md:85-89), and within one wave a sender emits the
same logical vote across most of them — the coalescer ships it as ONE
columnar payload (transport.message BbaBatchPayload).  Per-instance
scalar processing of such a wave costs O(N) python set/dict operations
per (sender, receiver) frame, which at N=64 is ~1.8M handler calls per
epoch — the single largest protocol-plane cost after crypto.

The bank is the TPU-framework answer applied to the host plane: one
struct-of-arrays per ACS holding every instance's current-round vote
state, so a columnar wave updates a [n_instances] slice in a handful
of numpy operations, and only threshold CROSSINGS (f+1 relay, 2f+1
bin_values growth, n-f AUX quorum — a constant number per instance
per round) fall back to the per-instance protocol logic in BBA.

Array layouts put the wave's axis LAST: receipt state is indexed
``seen[sender, value, instance]`` so one frame's update touches a
contiguous row, and activity + round fold into ONE ``round_state``
vector (the instance's current round, or a huge sentinel once halted
— every later vote for it compares stale and drops in the same
vectorized filter).  At n=64 the fixed per-numpy-op cost dominates
this function, so the layout exists to minimize op COUNT (measured
~40% off batch_vote at N=64), not element traffic.

Consistency contract: the bank is the SINGLE source of truth for
BVAL/AUX receipt state of each instance's current round.  BBA's
scalar path (off-round replays, unit tests, non-columnar transports)
writes through the same arrays, so columnar and scalar deliveries can
interleave freely.  When an instance advances a round, its row resets;
when it halts, its row deactivates and every later delivery for it is
dropped vectorized, before any python-level dispatch.

Quorum semantics mirrored from BBA (reference docs/BBA-EN.md:39-58,
134-156): +1 increments make exact-equality crossing detection
(cnt == f+1, cnt == 2f+1) equivalent to the >=-with-idempotent-guard
scalar form.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# Byzantine batches can mint unlimited distinct proposer tuples; the
# index cache clears wholesale at the cap (honest traffic reuses a
# handful of tuples per wave).
_PROP_CACHE_CAP = 4096

# round_state sentinel for halted instances: any real vote round
# (< bba.MAX_ROUNDS = 1000) compares STALE against it, so halted rows
# drop in the same vectorized stale filter as old-round votes
_HALTED = 1 << 62


class VoteBank:
    """Struct-of-arrays vote state for up to ``n_inst`` BBA instances
    over a fixed roster."""

    def __init__(
        self,
        member_ids: Sequence[str],
        f: int,
        inst_ids: Optional[Sequence[str]] = None,
        metrics=None,
        quorum_large: Optional[int] = None,
    ) -> None:
        self.members: List[str] = sorted(member_ids)
        self.f = f
        # the bin_values growth threshold: 2f+1 in the baseline trust
        # model, n-f under Config.reduced_quorum (identical whenever
        # n = 3f+1 exactly — see Config.quorum_large)
        self.q_large = 2 * f + 1 if quorum_large is None else quorum_large
        # owner-node metrics (None in standalone unit tests): only the
        # duplicate-vote absorption counter is touched here
        self.metrics = metrics
        self.sidx: Dict[str, int] = {
            m: i for i, m in enumerate(self.members)
        }
        insts = self.members if inst_ids is None else list(inst_ids)
        self.iidx: Dict[str, int] = {p: i for i, p in enumerate(insts)}
        n_inst, ns = len(insts), len(self.members)
        # [sender, value, instance]: one frame's dedup probe is a
        # contiguous-row fancy index
        self.bval_seen = np.zeros((ns, 2, n_inst), dtype=bool)
        self.bval_cnt = np.zeros((2, n_inst), dtype=np.int32)
        self.aux_seen = np.zeros((ns, n_inst), dtype=bool)
        self.aux_cnt = np.zeros((2, n_inst), dtype=np.int32)
        # bin_flags[i, v]: v in instance i's current-round bin_values
        # (instance-major: BBA reads bin_flags[self.index, vi] scalar)
        self.bin_flags = np.zeros((n_inst, 2), dtype=bool)
        # edge-trigger memory: on_aux_quorum fires once per row (the
        # post-quorum AUX stream at N=64 was ~220k redundant probes
        # per epoch); bin_values growth re-probes via BBA directly
        self.aux_fired = np.zeros(n_inst, dtype=bool)
        # current round per instance; _HALTED once deactivated
        self.round_state = np.zeros(n_inst, dtype=np.int64)
        self.bbas: List[object] = [None] * n_inst
        self._prop_cache: "Dict[tuple, Tuple[np.ndarray, bool]]" = {}

    # -- membership --------------------------------------------------------

    def attach(self, index: int, bba) -> None:
        self.bbas[index] = bba

    def reset_row(self, index: int, rnd: int) -> None:
        """New round for one instance: receipt state starts empty."""
        self.bval_seen[:, :, index] = False
        self.bval_cnt[:, index] = 0
        self.aux_seen[:, index] = False
        self.aux_cnt[:, index] = 0
        self.bin_flags[index] = False
        self.aux_fired[index] = False
        self.round_state[index] = rnd

    def deactivate(self, index: int) -> None:
        """Halted instance: every later delivery drops vectorized (the
        sentinel makes any real round number compare stale)."""
        self.round_state[index] = _HALTED

    # -- scalar write-through (BBA's non-columnar path) --------------------

    def bval_add(self, index: int, sender_idx: int, value: bool):
        """Record one BVAL; returns the new count, or None if duplicate."""
        vi = 1 if value else 0
        row = self.bval_seen[sender_idx, vi]
        if row[index]:
            if self.metrics is not None:
                self.metrics.dedup_absorbed.inc()
            return None
        row[index] = True
        self.bval_cnt[vi, index] += 1
        return int(self.bval_cnt[vi, index])

    def aux_add(self, index: int, sender_idx: int, value: bool) -> bool:
        """Record one AUX; returns False on duplicate sender."""
        row = self.aux_seen[sender_idx]
        if row[index]:
            if self.metrics is not None:
                self.metrics.dedup_absorbed.inc()
            return False
        row[index] = True
        self.aux_cnt[1 if value else 0, index] += 1
        return True

    def set_bin(self, index: int, value: bool) -> None:
        self.bin_flags[index, 1 if value else 0] = True

    def aux_good(self, index: int) -> int:
        """AUX receipts whose value is in bin_values (the n-f quorum
        basis, docs/BBA-EN.md:140-156) — O(1) from the counters."""
        g = 0
        if self.bin_flags[index, 1]:
            g += int(self.aux_cnt[1, index])
        if self.bin_flags[index, 0]:
            g += int(self.aux_cnt[0, index])
        return g

    def aux_vals(self, index: int) -> set:
        """Distinct received-AUX values that are in bin_values."""
        vals = set()
        if self.bin_flags[index, 1] and self.aux_cnt[1, index] > 0:
            vals.add(True)
        if self.bin_flags[index, 0] and self.aux_cnt[0, index] > 0:
            vals.add(False)
        return vals

    # -- columnar delivery (ACS batch path) --------------------------------

    def _indices(self, proposers: tuple) -> "Tuple[np.ndarray, bool]":
        """(known-instance index array, has_duplicates) — computed once
        per distinct proposers tuple: unknown proposers drop at cache
        build (membership is fixed), and honest batches never repeat
        an instance, so batch_vote's dedup (np.unique, ~30% of its
        cost) runs only for flagged Byzantine payloads."""
        ent = self._prop_cache.get(proposers)
        if ent is None:
            iidx = self.iidx
            arr = np.asarray(
                [iidx[p] for p in proposers if p in iidx],
                dtype=np.int64,
            )
            dups = len(set(proposers)) != len(proposers)
            if len(self._prop_cache) >= _PROP_CACHE_CAP:
                self._prop_cache.clear()
            ent = (arr, dups)
            self._prop_cache[proposers] = ent
        return ent

    def wave_vote(
        self,
        is_bval: bool,
        rnd: int,
        value: bool,
        rows,
    ) -> None:
        """One delivery wave's same-(type, round, value) votes across
        MANY senders (wave routing, protocol.router): dedup + counting
        run as ONE concatenated fancy-index pass over the
        [sender, instance] arrays, and threshold crossings are
        detected by before/after comparison — counts may advance by
        more than +1 within a wave, so the exact-equality crossing of
        the per-payload paths generalizes to interval containment
        (before < thr <= after), which fires exactly once per
        (instance, threshold) under the same one-vote dedup.

        ``rows`` is a list of (sender_index, sender, proposers).  Rows
        with duplicate-instance proposers (only Byzantine batches) or
        any off-round instance fall back to the per-row batch_vote
        path AFTER the vectorized pass, which re-reads the round state
        and preserves the exact parking/stale semantics."""
        rs = self.round_state
        si_parts: list = []
        pi_parts: list = []
        fallback: list = []
        for si, sender, proposers in rows:
            pi, dups = self._indices(proposers)
            if pi.size == 0:
                continue
            if dups or (rs[pi] != rnd).any():
                fallback.append((sender, proposers))
                continue
            si_parts.append(np.full(pi.size, si, dtype=np.int64))
            pi_parts.append(pi)
        if si_parts:
            self._wave_apply(
                is_bval,
                value,
                np.concatenate(si_parts),
                np.concatenate(pi_parts),
            )
        for sender, proposers in fallback:
            self.batch_vote(sender, is_bval, rnd, value, proposers)

    def _wave_apply(
        self, is_bval: bool, value: bool, si_all, pi_all
    ) -> None:
        """The vectorized heart of wave_vote: every (sender, instance)
        pair is in-round and instance-unique per row; intra-wave
        duplicate pairs (replayed frames) dedup here, exactly like the
        seen-bit dedup absorbs them on the per-payload paths."""
        metrics = self.metrics
        n_inst = self.round_state.size
        key = si_all * n_inst + pi_all
        uniq_k, first_idx = np.unique(key, return_index=True)
        if uniq_k.size != key.size:
            if metrics is not None:
                metrics.dedup_absorbed.inc(int(key.size - uniq_k.size))
            first_idx.sort()
            si_all, pi_all = si_all[first_idx], pi_all[first_idx]
        vi = 1 if value else 0
        f = self.f
        q_large = self.q_large
        bbas = self.bbas
        if is_bval:
            seen_plane = self.bval_seen[:, vi]
        else:
            seen_plane = self.aux_seen
        seen = seen_plane[si_all, pi_all]
        if seen.any():
            if metrics is not None:
                metrics.dedup_absorbed.inc(int(seen.sum()))
            fresh = ~seen
            si_all, pi_all = si_all[fresh], pi_all[fresh]
            if pi_all.size == 0:
                return
        seen_plane[si_all, pi_all] = True
        uniq, adds = np.unique(pi_all, return_counts=True)
        if is_bval:
            cnt = self.bval_cnt[vi]
            before = cnt[uniq]
            cnt[uniq] = after = before + adds.astype(np.int32)
            # f+1 same bval -> relay once; q_large -> bin_values union
            # (docs/BBA-EN.md:47-58) — interval crossings, fired after
            # ALL of the wave's adds landed
            for i in uniq[(before < f + 1) & (after >= f + 1)]:
                bba = bbas[i]
                if bba is not None and not bba.halted:
                    bba.on_bval_relay(value)
            for i in uniq[(before < q_large) & (after >= q_large)]:
                bba = bbas[i]
                if bba is not None and not bba.halted:
                    bba.on_bval_bin(value)
        else:
            cnt = self.aux_cnt[vi]
            cnt[uniq] += adds.astype(np.int32)
            binf = self.bin_flags[uniq]
            good = self.aux_cnt[1][uniq] * binf[:, 1] + (
                self.aux_cnt[0][uniq] * binf[:, 0]
            )
            n = len(self.members)
            trig = uniq[(good >= n - f) & ~self.aux_fired[uniq]]
            if trig.size == 0:
                return
            self.aux_fired[trig] = True
            for i in trig:
                bba = bbas[i]
                if bba is not None and not bba.halted:
                    bba.on_aux_quorum()

    def batch_vote(
        self,
        sender: str,
        is_bval: bool,
        rnd: int,
        value: bool,
        proposers: tuple,
    ) -> None:
        """One sender's vote fanned across ``proposers``: vectorized
        dedup + counting for in-round instances; off-round instances
        fall back to BBA's scalar gate (parking / stale-drop).  The
        hot path (every instance in-round, vote fresh — the honest
        wave shape) runs a minimal op count; `.all()`/`.any()` probes
        divert the rare mixed cases onto slower branches."""
        si = self.sidx.get(sender)
        if si is None:
            return
        pi, dups = self._indices(proposers)
        if pi.size == 0:
            return
        rs = self.round_state[pi]
        on = rs == rnd
        if on.all():
            sel = pi
        else:
            # future rounds: scalar fallback (rare — round-horizon
            # parking; replay order is preserved by BBA._future).
            # Stale (rnd < current round, or halted at the sentinel)
            # drops vectorized — same as _gated's stale return,
            # without N python calls per frame.
            fut = pi[rs < rnd]
            if fut.size:
                from cleisthenes_tpu.transport.message import BbaType

                t = BbaType.BVAL if is_bval else BbaType.AUX
                for i in fut:
                    bba = self.bbas[i]
                    if bba is not None:
                        bba.handle_vote(sender, t, rnd, value)
            sel = pi[on]
            if sel.size == 0:
                return
        if dups:  # only Byzantine batches repeat instances
            sel = np.unique(sel)
        vi = 1 if value else 0
        metrics = self.metrics
        if is_bval:
            row = self.bval_seen[si, vi]
            seen = row[sel]
            if seen.any():
                new = sel[~seen]
                if metrics is not None:
                    metrics.dedup_absorbed.inc(int(sel.size - new.size))
                if new.size == 0:
                    return
            else:
                new = sel
            row[new] = True
            cnt = self.bval_cnt[vi]
            cnt[new] += 1
            cnts = cnt[new]
            relay = new[cnts == self.f + 1]
            grow = new[cnts == self.q_large]
            bbas = self.bbas
            # f+1 same bval -> relay once; q_large -> bin_values union
            # (docs/BBA-EN.md:47-58)
            for i in relay:
                bba = bbas[i]
                if bba is not None and not bba.halted:
                    bba.on_bval_relay(value)
            for i in grow:
                bba = bbas[i]
                if bba is not None and not bba.halted:
                    bba.on_bval_bin(value)
        else:
            row = self.aux_seen[si]
            seen = row[sel]
            if seen.any():
                new = sel[~seen]
                if metrics is not None:
                    metrics.dedup_absorbed.inc(int(sel.size - new.size))
                if new.size == 0:
                    return
            else:
                new = sel
            row[new] = True
            cnt = self.aux_cnt[vi]
            cnt[new] += 1
            # quorum trigger: good >= n-f (>=, not ==: bin_values
            # growth also moves `good`, so equality could be skipped;
            # post-quorum extras are cheap idempotent no-ops in BBA)
            binf = self.bin_flags[new]
            good = self.aux_cnt[1][new] * binf[:, 1] + (
                self.aux_cnt[0][new] * binf[:, 0]
            )
            n = len(self.members)
            trig = new[(good >= n - self.f) & ~self.aux_fired[new]]
            if trig.size == 0:
                return
            # fire ONCE per row: post-quorum receipts change nothing
            # the quorum path reads (advancement re-probes happen on
            # coin reveal and bin growth, which have their own
            # triggers); vals are read at advance time either way
            self.aux_fired[trig] = True
            bbas = self.bbas
            for i in trig:
                bba = bbas[i]
                if bba is not None and not bba.halted:
                    bba.on_aux_quorum()


__all__ = ["VoteBank"]
