"""Dynamic membership: RECONFIG transactions, in-band key resharing,
and the epoch-boundary roster switch.

The roster stops being a construction-time constant here.  A RECONFIG
transaction — ordinary opaque bytes to the consensus core — names the
next roster version: the full member table (ids + dial addresses) and
one enrollment public key per JOINER (operator-provisioned: the same
trusted channel that hands a new validator its identity today).  Once
it settles, the machinery in this module runs a reshare ceremony and
activates the new roster + fresh TPKE/coin/MAC key material at an
epoch boundary anchored on the PR-8 ordered frontier:

  1. DISCOVERY — every node sees the RECONFIG tx at the same log
     position (settlement is byte-identical across honest nodes).
     Old-roster nodes install pair keys for the joiners (derived
     below), widen their broadcast set to old ∪ new, and start
     serving the joiners CATCHUP from epoch 0.
  2. DEALING — each old-roster member deals a fresh Feldman sharing
     of a random secret over the NEW roster (ops/dkg.py primitives),
     for TPKE and the coin: t' commitments each, plus one encrypted
     share blob per new member.  The dealing broadcasts eagerly as a
     ``ResharePayload`` (the new message kind riding the existing
     transports) AND is submitted as a dealing transaction.
  3. QUALIFIED SET — the first ``f_old + 1`` structurally valid
     dealings in committed-log order form Q.  Log order is agreed, so
     every honest node picks the identical Q with no complaint
     rounds; f+1 dealers guarantee at least one honest dealing, so
     the summed secret is unknown to any f-coalition.  Validity is a
     pure function of the dealing bytes (commitment shape + subgroup
     membership + a blob per new member), so admission never splits.
  4. FINALIZE — when Q completes at the settlement of epoch e, the
     activation epoch is ``e + Config.reconfig_lead`` (strictly more
     than decrypt_lag_max: no epoch at or past the boundary can have
     been ordered under the old roster).  New members decrypt their
     blobs, verify each share against the dealer's commitments, and
     sum; everyone derives the public keys from the commitments alone
     (identical by construction).  An RCFG WAL record makes the
     switch replayable; crash recovery re-derives the whole ceremony
     from the replayed batches and cross-checks it.
  5. ACTIVATION — epochs >= activation_epoch resolve n/f/keys through
     the new ``RosterVersion``.  Joiners participate from there
     (having adopted the log via CATCHUP); retiring nodes order their
     last epoch at the boundary and park.  Once the SETTLED frontier
     crosses the boundary, retired peers' pair keys drop and their
     dial-health state tears down (transport.health.retire).

Share confidentiality and the MAC re-key ride one static-DH
construction with no extra round trips: an old member's DH identity
is its coin share (secret x_i, public vk_i = g^{x_i} — already in the
coin key's verification table); a joiner's is its enrollment keypair
from the RECONFIG tx.  Any pair (a, b) of the new roster derives
k_ab = H(version || g^{x_a x_b} || a || b) — both ends compute it
locally, nothing secret crosses the wire.  EVERY pair of the new
roster gets a fresh version-keyed MAC key — surviving pairs included:
survivors STAGE the next key at discovery (inbound frames verify
under either key), PROMOTE it to the signing key at the activation
boundary, and DROP the old one at retirement teardown (the rotation
half of ``transport.base.HmacAuthenticator``), so a pair key captured
before a reconfig stops authenticating anything once the reconfig
settles.

Reshare blobs are PUBLICLY verifiable (PVSS): each share is encrypted
chunk-wise to the receiver's static-DH key (ElGamal in the exponent,
16-bit chunks) with an aggregated Chaum-Pedersen DLEQ proof binding
the ciphertext to the dealer's OWN Feldman commitments.  Every node —
receiver of the blob or not — verifies every blob before admitting a
dealing to the qualified set, so a dealer that encrypts garbage to
one targeted receiver is excluded deterministically by ALL honest
nodes at the same log position: no complaint round, no divergence,
the ceremony completes from the remaining dealers.  Residual
(documented in docs/FAULTS.md): the DLEQ binds the weighted SUM of
the chunks, not each chunk's 16-bit range, so a malicious dealer can
still emit non-canonical chunks that verify publicly but fail the
receiver's table decode — the receiver fails loudly exactly as
before, but the attack surface narrows from "any garbage bytes" to
that single malformation.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import struct
from typing import Dict, List, Optional, Sequence, Tuple

from cleisthenes_tpu.core.member import Address, Member, RosterVersion
from cleisthenes_tpu.ops.dkg import DkgDealing, validate_commitments
from cleisthenes_tpu.ops.modmath import GroupParams, get_engine_degraded
from cleisthenes_tpu.ops import tpke as tpke_mod
from cleisthenes_tpu.ops.tpke import (
    ThresholdPublicKey,
    ThresholdSecretShare,
)

# Transaction-space tags: a leading NUL byte keeps protocol-internal
# transactions out of any sane application tx namespace, and the
# version digit hard-partitions future format changes.
RECONFIG_TX_PREFIX = b"\x00RCFG1|"
# RDEAL1 -> RDEAL2: the share blobs became PVSS (chunked ElGamal +
# DLEQ) — a different byte format, hard-partitioned by the version
# digit exactly as the tag comment above promises.
DEAL_TX_PREFIX = b"\x00RDEAL2|"

# DoS bounds on decoded tables (mirrors transport.message's caps)
MAX_ROSTER = 4096


def is_protocol_tx(tx: bytes) -> bool:
    """True for reconfig-machinery transactions (RECONFIG + dealing):
    they are node-originated, so invariants like the fuzzer's
    no-foreign-tx exempt them explicitly."""
    return tx.startswith(RECONFIG_TX_PREFIX) or tx.startswith(
        DEAL_TX_PREFIX
    )


# ---------------------------------------------------------------------------
# codecs
# ---------------------------------------------------------------------------


def _pack_bytes(out: List[bytes], b: bytes) -> None:
    out.append(struct.pack(">I", len(b)))
    out.append(b)


def _pack_str(out: List[bytes], s: str) -> None:
    _pack_bytes(out, s.encode("utf-8"))


class _Reader:
    __slots__ = ("d", "o")

    def __init__(self, data: bytes, offset: int = 0) -> None:
        self.d = data
        self.o = offset

    def u32(self) -> int:
        if self.o + 4 > len(self.d):
            raise ValueError("truncated reconfig blob")
        (v,) = struct.unpack_from(">I", self.d, self.o)
        self.o += 4
        return v

    def bytes_(self) -> bytes:
        n = self.u32()
        if self.o + n > len(self.d):
            raise ValueError("truncated reconfig blob")
        out = self.d[self.o : self.o + n]
        self.o += n
        return out

    def str_(self) -> str:
        return self.bytes_().decode("utf-8")

    def done(self) -> None:
        if self.o != len(self.d):
            raise ValueError("trailing bytes in reconfig blob")


@dataclasses.dataclass(frozen=True)
class ReconfigSpec:
    """A decoded RECONFIG transaction: the next roster version."""

    version: int
    members: Tuple[Tuple[str, str, int], ...]  # (id, ip, port), sorted
    enroll_pubs: Dict[str, int]  # joiner id -> enrollment public key

    @property
    def member_ids(self) -> Tuple[str, ...]:
        return tuple(m[0] for m in self.members)

    @property
    def n(self) -> int:
        return len(self.members)

    @property
    def f(self) -> int:
        return (len(self.members) - 1) // 3

    @property
    def threshold(self) -> int:
        """Both the TPKE decryption threshold and the coin threshold
        under the new roster (f' + 1, matching setup_keys)."""
        return self.f + 1

    def member_tuple(self) -> Tuple[Member, ...]:
        return tuple(
            Member(id=mid, addr=Address(ip, port))
            for mid, ip, port in self.members
        )


def encode_reconfig_tx(
    version: int,
    members: Sequence[Tuple[str, str, int]],
    enroll_pubs: Dict[str, int],
    group: Optional[GroupParams] = None,
) -> bytes:
    """Build the operator-submitted RECONFIG transaction bytes."""
    group = group or tpke_mod.DEFAULT_GROUP
    out: List[bytes] = [RECONFIG_TX_PREFIX, struct.pack(">I", version)]
    ordered = sorted(members)
    out.append(struct.pack(">I", len(ordered)))
    for mid, ip, port in ordered:
        _pack_str(out, mid)
        _pack_str(out, ip)
        out.append(struct.pack(">I", port))
    out.append(struct.pack(">I", len(enroll_pubs)))
    for mid in sorted(enroll_pubs):
        _pack_str(out, mid)
        _pack_bytes(out, enroll_pubs[mid].to_bytes(group.nbytes, "big"))
    return b"".join(out)


def decode_reconfig_tx(
    tx: bytes, group: Optional[GroupParams] = None
) -> ReconfigSpec:
    """Parse + structurally validate a RECONFIG transaction.  Raises
    ValueError on any malformation — validity is a pure function of
    the bytes, so every honest node accepts or rejects identically."""
    group = group or tpke_mod.DEFAULT_GROUP
    if not tx.startswith(RECONFIG_TX_PREFIX):
        raise ValueError("not a RECONFIG transaction")
    r = _Reader(tx, len(RECONFIG_TX_PREFIX))
    version = r.u32()
    n = r.u32()
    if not (1 <= n <= MAX_ROSTER):
        raise ValueError(f"roster size {n} out of range")
    members: List[Tuple[str, str, int]] = []
    for _ in range(n):
        mid = r.str_()
        ip = r.str_()
        port = r.u32()
        members.append((mid, ip, port))
    if members != sorted(members) or len(
        {m[0] for m in members}
    ) != len(members):
        raise ValueError("member table not sorted/unique")
    enroll: Dict[str, int] = {}
    for _ in range(r.u32()):
        mid = r.str_()
        pub = int.from_bytes(r.bytes_(), "big")
        enroll[mid] = pub
    r.done()
    ids = {m[0] for m in members}
    for mid in sorted(enroll):
        if mid not in ids:
            raise ValueError(f"enrollment key for non-member {mid!r}")
        if not tpke_mod.is_group_element(enroll[mid], group):
            raise ValueError(f"enrollment key for {mid!r} not in group")
    return ReconfigSpec(
        version=version,
        members=tuple(members),
        enroll_pubs=enroll,
    )


@dataclasses.dataclass(frozen=True)
class Dealing:
    """A decoded dealing transaction: one dealer's Feldman sharings
    (TPKE + coin) over the new roster."""

    version: int
    dealer: str
    tpke_commits: Tuple[int, ...]
    coin_commits: Tuple[int, ...]
    blobs: Dict[str, bytes]  # receiver id -> encrypted share pair


def encode_dealing_tx(
    version: int,
    dealer: str,
    tpke_commits: Sequence[int],
    coin_commits: Sequence[int],
    blobs: Dict[str, bytes],
    group: Optional[GroupParams] = None,
) -> bytes:
    group = group or tpke_mod.DEFAULT_GROUP
    nb = group.nbytes
    out: List[bytes] = [DEAL_TX_PREFIX, struct.pack(">I", version)]
    _pack_str(out, dealer)
    out.append(struct.pack(">I", len(tpke_commits)))
    for c in tpke_commits:
        _pack_bytes(out, c.to_bytes(nb, "big"))
    for c in coin_commits:
        _pack_bytes(out, c.to_bytes(nb, "big"))
    out.append(struct.pack(">I", len(blobs)))
    for rid in sorted(blobs):
        _pack_str(out, rid)
        _pack_bytes(out, blobs[rid])
    return b"".join(out)


def decode_dealing_tx(tx: bytes) -> Dealing:
    if not tx.startswith(DEAL_TX_PREFIX):
        raise ValueError("not a dealing transaction")
    r = _Reader(tx, len(DEAL_TX_PREFIX))
    version = r.u32()
    dealer = r.str_()
    t = r.u32()
    if not (1 <= t <= MAX_ROSTER):
        raise ValueError(f"commitment count {t} out of range")
    tpke_commits = tuple(
        int.from_bytes(r.bytes_(), "big") for _ in range(t)
    )
    coin_commits = tuple(
        int.from_bytes(r.bytes_(), "big") for _ in range(t)
    )
    blobs: Dict[str, bytes] = {}
    n = r.u32()
    if n > MAX_ROSTER:
        raise ValueError(f"receiver count {n} out of range")
    for _ in range(n):
        rid = r.str_()
        blobs[rid] = r.bytes_()
    r.done()
    return Dealing(
        version=version,
        dealer=dealer,
        tpke_commits=tpke_commits,
        coin_commits=coin_commits,
        blobs=blobs,
    )


# ---------------------------------------------------------------------------
# pairwise-DH key schedule + share blob cipher
# ---------------------------------------------------------------------------


def enrollment_keypair(
    seed: Optional[int] = None, group: Optional[GroupParams] = None
) -> Tuple[int, int]:
    """A joiner's (secret, public) enrollment pair.  Unseeded draws
    the OS CSPRNG (operator provisioning, not protocol scheduling);
    seeded is for tests/fuzz replays only."""
    group = group or tpke_mod.DEFAULT_GROUP
    if seed is None:
        import secrets

        raw = secrets.token_bytes(group.nbytes + 8)  # staticcheck: allow[DET001] enrollment keygen
    else:
        raw = hashlib.sha256(b"rcfg-enroll|%d" % seed).digest() + (
            hashlib.sha256(b"rcfg-enroll2|%d" % seed).digest()
        )
    x = int.from_bytes(raw, "big") % group.q
    if x == 0:
        x = 1
    return x, pow(group.g, x, group.p)


def dh_point(secret: int, peer_pub: int, group: GroupParams) -> int:
    """g^{x_a x_b} from one side's secret and the other's public."""
    return pow(peer_pub, secret, group.p)


def pair_mac_key(
    version: int, dh: int, a: str, b: str, group: GroupParams
) -> bytes:
    """The new pair's envelope-MAC key: both ends derive it locally
    from the shared DH point (unordered pair, like the dealer's
    ``HmacAuthenticator.pair_key`` schedule)."""
    lo, hi = sorted((a.encode("utf-8"), b.encode("utf-8")))
    return hashlib.sha256(
        b"rcfgmac|%d|" % version
        + dh.to_bytes(group.nbytes, "big")
        + b"|" + lo + b"|" + hi
    ).digest()


# ---------------------------------------------------------------------------
# PVSS share blobs: chunked ElGamal-in-the-exponent + aggregated DLEQ
# ---------------------------------------------------------------------------
#
# A share s in Z_q splits into m big-endian 16-bit chunks s_k.  Each
# chunk encrypts to the receiver's static-DH key y as an ElGamal pair
# in the exponent: (A_k, E_k) = (g^{rho_k}, y^{rho_k} * g^{s_k}).
# With weights w_k = 2^{16(m-1-k)}, the products Abar = prod A_k^{w_k}
# and Ebar = prod E_k^{w_k} satisfy Abar = g^{rho}, Ebar = y^{rho} *
# g^{s} for rho = sum rho_k w_k — so a single Chaum-Pedersen DLEQ
# proof over (g, y) for the pair (Abar, Ebar / X_j), where X_j =
# prod C_i^{j^i} is the share's Feldman image, PUBLICLY proves the
# blob decrypts (under the receiver's secret) to the exact share the
# dealer committed to — mod q, up to the non-canonical-chunk residual
# the module docstring describes.  The receiver recovers each g^{s_k}
# as E_k * A_k^{-x} and inverts it through a 2^16-entry table.

PVSS_CHUNK_BITS = 16
PVSS_CHUNK_BASE = 1 << PVSS_CHUNK_BITS


def _pvss_chunk_count(group: GroupParams) -> int:
    return -(-group.q.bit_length() // PVSS_CHUNK_BITS)


def pvss_blob_len(group: GroupParams) -> int:
    """Exact byte length of one receiver's blob: two share sections
    (tpke then coin), each m ciphertext pairs (A_k, E_k) of one group
    element apiece plus the compact DLEQ proof (c: 32 bytes, z: one
    scalar) — a pure function of the group, so malformed lengths
    reject before any group math."""
    nb = group.nbytes
    m = _pvss_chunk_count(group)
    return 2 * (2 * m * nb + 32 + nb)


@functools.lru_cache(maxsize=4)
def _pvss_tables(group: GroupParams):
    """(powers, dlog): g^v for v in [0, 2^16) and the inverse map —
    the chunk codec.  Built once per group (~20 ms, ~4 MB for the
    default 256-bit group)."""
    size = min(PVSS_CHUNK_BASE, group.q)
    powers: List[int] = [0] * size
    dlog: Dict[int, int] = {}
    acc = 1
    for v in range(size):
        powers[v] = acc
        dlog[acc] = v
        acc = acc * group.g % group.p
    return powers, dlog


@functools.lru_cache(maxsize=4)
def _pvss_weights(group: GroupParams) -> Tuple[int, ...]:
    m = _pvss_chunk_count(group)
    return tuple(
        pow(PVSS_CHUNK_BASE, m - 1 - k, group.q) for k in range(m)
    )


def _pvss_engine(group: GroupParams):
    """The batched-modexp engine for PVSS hot loops (the native cpu
    kernel is ~8x builtin pow; the tpu path batches further)."""
    return get_engine_degraded("cpu", None, group)


def _jacobi(a: int, n: int) -> int:
    """Jacobi symbol (a/n), n odd positive.  For the safe-prime groups
    here (p = 2q + 1) membership in the order-q QR subgroup is exactly
    (a/p) == 1 — a gcd-speed screen, vs a full modexp per element."""
    a %= n
    result = 1
    while a:
        while a % 2 == 0:
            a //= 2
            if n % 8 in (3, 5):
                result = -result
        a, n = n, a
        if a % 4 == 3 and n % 4 == 3:
            result = -result
        a %= n
    return result if n == 1 else 0


def _pvss_scalar(seed: bytes, k: int, q: int) -> int:
    """Deterministic scalar expansion: 512 hash bits mod q (bias
    negligible at these widths); index m doubles as the DLEQ witness
    slot."""
    tag = seed + struct.pack(">I", k)
    v = int.from_bytes(
        hashlib.sha256(tag + b"|a").digest()
        + hashlib.sha256(tag + b"|b").digest(),
        "big",
    ) % q
    return v or 1


def _pvss_ctx(
    version: int,
    dealer: str,
    receiver: str,
    kind: int,
    commits: Sequence[int],
    group: GroupParams,
) -> bytes:
    """The Fiat-Shamir statement prefix: binds the proof to this
    (version, dealer, receiver, share-kind) slot AND the dealer's
    commitment vector, so a proof cannot be replayed across slots or
    against substituted commitments."""
    nb = group.nbytes
    h = hashlib.sha256(
        b"rcfgpvss|%d|" % version
        + dealer.encode("utf-8")
        + b"|"
        + receiver.encode("utf-8")
        + b"|%d|" % kind
    )
    for c in commits:
        h.update(c.to_bytes(nb, "big"))
    return h.digest()


def _pvss_challenge(
    ctx: bytes,
    y: int,
    cipher: Sequence[int],
    t1: int,
    t2: int,
    group: GroupParams,
) -> int:
    nb = group.nbytes
    h = hashlib.sha256(ctx)
    h.update(y.to_bytes(nb, "big"))
    for v in cipher:
        h.update(v.to_bytes(nb, "big"))
    h.update(t1.to_bytes(nb, "big"))
    h.update(t2.to_bytes(nb, "big"))
    return int.from_bytes(h.digest(), "big") % group.q


def pvss_encrypt_share(
    s: int,
    y: int,
    rho_seed: bytes,
    ctx: bytes,
    group: GroupParams,
    eng=None,
) -> bytes:
    """One share -> one blob section: m chunk ciphertexts followed by
    the compact DLEQ proof (c, z).  ``rho_seed`` expands to the m
    chunk randomizers and the proof witness (CSPRNG-derived in
    production, seed-derived in fuzz replays)."""
    p, q, g = group.p, group.q, group.g
    nb = group.nbytes
    m = _pvss_chunk_count(group)
    powers, _ = _pvss_tables(group)
    weights = _pvss_weights(group)
    eng = eng if eng is not None else _pvss_engine(group)
    s %= q
    chunks = [
        (s >> (PVSS_CHUNK_BITS * (m - 1 - k))) & (PVSS_CHUNK_BASE - 1)
        for k in range(m)
    ]
    rhos = [_pvss_scalar(rho_seed, k, q) for k in range(m)]
    w = _pvss_scalar(rho_seed, m, q)
    out = eng.pow_batch(
        [g] * m + [y] * m + [g, y], rhos + rhos + [w, w]
    )
    cipher: List[int] = []
    for k in range(m):
        cipher.append(out[k])  # A_k = g^{rho_k}
        cipher.append(out[m + k] * powers[chunks[k]] % p)  # E_k
    t1, t2 = out[2 * m], out[2 * m + 1]
    rho = sum(r * wk for r, wk in zip(rhos, weights)) % q
    c = _pvss_challenge(ctx, y, cipher, t1, t2, group)
    z = (w + c * rho) % q
    return (
        b"".join(v.to_bytes(nb, "big") for v in cipher)
        + c.to_bytes(32, "big")
        + z.to_bytes(nb, "big")
    )


def _pvss_parse_section(
    blob: bytes, kind: int, group: GroupParams
) -> Tuple[List[int], int, int]:
    nb = group.nbytes
    m = _pvss_chunk_count(group)
    half = pvss_blob_len(group) // 2
    sec = blob[kind * half : (kind + 1) * half]
    cipher = [
        int.from_bytes(sec[i * nb : (i + 1) * nb], "big")
        for i in range(2 * m)
    ]
    off = 2 * m * nb
    c = int.from_bytes(sec[off : off + 32], "big")
    z = int.from_bytes(sec[off + 32 :], "big")
    return cipher, c, z


def pvss_verify_dealing(
    dealing: "Dealing",
    pubs: Dict[str, int],
    group: GroupParams,
    eng=None,
) -> bool:
    """PUBLIC verification of every receiver blob in a dealing against
    the dealer's own commitments — a pure function of (dealing bytes,
    receiver DH public keys), so every honest node admits or excludes
    the dealer identically.  ``pubs`` maps receiver id -> static-DH
    public key in ``sorted(dealing.blobs)`` == new-roster order (the
    1-based Shamir index is the position in that order)."""
    p, q, g = group.p, group.q, group.g
    m = _pvss_chunk_count(group)
    blob_len = pvss_blob_len(group)
    eng = eng if eng is not None else _pvss_engine(group)
    ids = sorted(dealing.blobs)
    entries = []  # (y, X_j, cipher, c, z)
    for j, rid in enumerate(ids, start=1):
        blob = dealing.blobs[rid]
        y = pubs.get(rid)
        if y is None or len(blob) != blob_len:
            return False
        for kind, commits in (
            (0, dealing.tpke_commits),
            (1, dealing.coin_commits),
        ):
            cipher, c, z = _pvss_parse_section(blob, kind, group)
            if not (0 <= c < q and 0 <= z < q):
                return False
            for v in cipher:
                # subgroup screen: QR test at gcd speed
                if not (0 < v < p) or _jacobi(v, p) != 1:
                    return False
            # X_j = prod C_i^{j^i}, Horner in the (small) exponent j
            x_j = commits[-1]
            for cm in reversed(commits[:-1]):
                x_j = pow(x_j, j, p) * cm % p
            ctx = _pvss_ctx(
                dealing.version, dealing.dealer, rid, kind, commits,
                group,
            )
            entries.append((y, x_j, cipher, c, z, ctx))
    # Abar/Ebar via Horner in the chunk base (16 squarings/step beats
    # a full modexp per weight), X^{-1} and the DLEQ legs batched
    aggs = []
    for y, x_j, cipher, c, z, ctx in entries:
        abar, ebar = cipher[0], cipher[1]
        for k in range(1, m):
            abar = pow(abar, PVSS_CHUNK_BASE, p) * cipher[2 * k] % p
            ebar = (
                pow(ebar, PVSS_CHUNK_BASE, p) * cipher[2 * k + 1] % p
            )
        aggs.append((abar, ebar))
    x_invs = eng.pow_batch(
        [e[1] for e in entries], [p - 2] * len(entries)
    )
    bases: List[int] = []
    exps: List[int] = []
    for (y, x_j, cipher, c, z, ctx), (abar, ebar), x_inv in zip(
        entries, aggs, x_invs
    ):
        u = ebar * x_inv % p
        neg_c = (q - c) % q
        bases.extend((g, y, abar, u))
        exps.extend((z, z, neg_c, neg_c))
    legs = eng.pow_batch(bases, exps)
    for i, (y, x_j, cipher, c, z, ctx) in enumerate(entries):
        g_z, y_z, a_negc, u_negc = legs[4 * i : 4 * i + 4]
        t1 = g_z * a_negc % p
        t2 = y_z * u_negc % p
        if _pvss_challenge(ctx, y, cipher, t1, t2, group) != c:
            return False
    return True


def pvss_decrypt_share(
    blob: bytes, kind: int, x: int, group: GroupParams, eng=None
) -> int:
    """Receiver-side decode of one share section under the receiver's
    static-DH secret ``x``.  Raises ValueError when a chunk falls
    outside the canonical 16-bit range — the documented residual a
    publicly-verified dealing can still hit; the caller fails loudly
    rather than diverging."""
    p, q = group.p, group.q
    m = _pvss_chunk_count(group)
    weights = _pvss_weights(group)
    _, dlog = _pvss_tables(group)
    eng = eng if eng is not None else _pvss_engine(group)
    cipher, _c, _z = _pvss_parse_section(blob, kind, group)
    a_inv = eng.pow_batch(cipher[0::2], [(q - x % q) % q] * m)
    s = 0
    for k in range(m):
        v = dlog.get(cipher[2 * k + 1] * a_inv[k] % p)
        if v is None:
            raise ValueError(
                f"PVSS chunk {k} outside the canonical range"
            )
        s = (s + v * weights[k]) % q
    return s


# memo for the (pure) public verification: settled dealing txs are
# re-scanned on WAL replay and by every node of an in-process cluster;
# the verdict is a function of the tx bytes + the (agreed) receiver
# key table, so one computation serves them all
_PVSS_VERDICTS: Dict[bytes, bool] = {}
_PVSS_VERDICT_CAP = 512


def key_material_digest(
    tpke_pub: ThresholdPublicKey, coin_pub: ThresholdPublicKey
) -> bytes:
    """Commitment to a version's public threshold key material — a
    pure function of the agreed ceremony, so byte-identical across
    honest nodes (the fuzzer's key-agreement invariant)."""
    h = hashlib.sha256(b"rcfgkeys|")
    nb = tpke_pub.group.nbytes
    for pub in (tpke_pub, coin_pub):
        h.update(struct.pack(">II", pub.n, pub.threshold))
        h.update(pub.master.to_bytes(nb, "big"))
        for vk in pub.verification_keys:
            h.update(vk.to_bytes(nb, "big"))
    return h.digest()


def finalize_public(
    commit_sets: Sequence[Sequence[int]],
    n: int,
    threshold: int,
    group: GroupParams,
    backend: str = "cpu",
) -> ThresholdPublicKey:
    """The public half of ops.dkg.finalize — master key and the full
    verification-key table from the qualified dealers' commitments
    alone.  Every node (member or not, joiner or retiree) derives the
    identical key because the inputs are committed-log bytes."""
    from cleisthenes_tpu.ops.dkg import finalize

    commits = {i + 1: list(c) for i, c in enumerate(commit_sets)}
    # dkg.finalize computes exactly the public table we need; the
    # zero "shares" exist only to satisfy its signature and the
    # returned (meaningless) share is discarded
    pub, _zero = finalize(
        commits,
        1,
        {i: 0 for i in commits},
        n,
        threshold,
        group=group,
        backend=backend,
    )
    return pub


# ---------------------------------------------------------------------------
# the ceremony state machine
# ---------------------------------------------------------------------------


class _PendingCeremony:
    __slots__ = (
        "spec",
        "discovered_epoch",
        "need",
        "dealings",
        "staged",
        "should_deal",
        "dealt",
        "t0",
    )

    def __init__(
        self, spec: ReconfigSpec, discovered_epoch: int, need: int
    ) -> None:
        self.spec = spec
        self.discovered_epoch = discovered_epoch
        self.need = need  # f_old + 1 qualified dealers
        # dealer id -> Dealing, admission (= committed-log) order
        self.dealings: Dict[str, Dealing] = {}
        # eager gossip staging: dealer id -> dealing tx bytes
        self.staged: Dict[str, bytes] = {}
        self.should_deal = False
        self.dealt = False
        self.t0 = 0.0  # ceremony trace-span start (0 when tracing off)


class ReconfigManager:
    """One node's reconfig plane: discovery, dealing, qualified-set
    tracking, finalize — driven entirely from settled batches (plus
    the eager ``ResharePayload`` gossip), so it is deterministic given
    the committed log.

    Owned by (and coupled to) one HoneyBadger, same pattern as the
    WaveRouter: it never touches the wire or the WAL directly except
    through its owner's seams.
    """

    def __init__(self, hb) -> None:
        self._hb = hb
        self._pending: Optional[_PendingCeremony] = None
        # versions whose gossip already nudged our catch-up chase
        self._nudged: set = set()
        # True while the constructor replays the WAL: suppresses
        # re-broadcasting / re-submitting / re-writing what the log
        # already proves happened
        self.replaying = False

    # -- membership over time ---------------------------------------------

    def known_member(self, sender: str) -> bool:
        """Epoch-unscoped membership (CATCHUP, reshare gossip): any
        version's member — past, active, or pending — is a legitimate
        correspondent during the transition window."""
        hb = self._hb
        if sender in hb.rosters.known_member_ids():
            return True
        p = self._pending
        return p is not None and sender in p.spec.member_ids

    @property
    def pending_version(self) -> Optional[int]:
        p = self._pending
        return None if p is None else p.spec.version

    # -- settled-batch scan (the only consensus-coupled entry) --------------

    def on_batch_settled(self, epoch: int, batch) -> None:
        """Called by the owner for EVERY settled batch, in epoch
        order (live commits, catch-up adoptions, and WAL replay all
        funnel here) — the reconfig plane's whole view of time."""
        for tx in batch.tx_list():
            if tx.startswith(DEAL_TX_PREFIX):
                self._on_deal_tx(epoch, tx)
            elif tx.startswith(RECONFIG_TX_PREFIX):
                self._on_reconfig_tx(epoch, tx)

    def _on_reconfig_tx(self, epoch: int, tx: bytes) -> None:
        hb = self._hb
        if self._pending is not None:
            return  # one ceremony at a time; extras ignored identically
        latest = hb.rosters.latest()
        if epoch < latest.activation_epoch:
            # settled under an older roster than the one already
            # switched to (replay of history): a RECONFIG here was
            # consumed by a ceremony the schedule already carries
            return
        try:
            spec = decode_reconfig_tx(tx, hb.group)
        except ValueError:
            return  # malformed: every honest node drops it identically
        if spec.version != latest.version + 1:
            return
        old_ids = set(latest.member_ids)
        joiners = [m for m in spec.member_ids if m not in old_ids]
        if any(j not in spec.enroll_pubs for j in joiners):
            return  # joiner without an enrollment key cannot be keyed
        pending = _PendingCeremony(
            spec, epoch, need=latest.f + 1
        )
        self._pending = pending
        tr = hb.trace
        if tr is not None:
            pending.t0 = tr.now()
            tr.instant(
                "reconfig",
                "discovered",
                version=spec.version,
                epoch=epoch,
                joiners=len(joiners),
                retiring=len(old_ids - set(spec.member_ids)),
            )
        hb.on_reconfig_discovered(pending.spec, joiners)
        if hb.node_id in old_ids:
            pending.should_deal = True
            if not self.replaying:
                self._deal_now()

    def after_replay(self) -> None:
        """WAL replay finished: re-enter the live protocol.  A dealer
        that crashed mid-ceremony re-deals (its un-committed dealing
        tx died with its mempool; a fresh dealing is just as good —
        the qualified set takes the first f+1 in log order), and the
        re-derived roster schedule is cross-checked against the RCFG
        records the crashed process wrote."""
        self.replaying = False
        hb = self._hb
        if hb.batch_log is not None:
            for (
                version,
                activation,
                _members,
                key_digest,
            ) in hb.batch_log.replay_reconfigs():
                for rv in hb.rosters:
                    if rv.version == version:
                        if (
                            rv.activation_epoch != activation
                            or rv.key_material_digest != key_digest
                        ):
                            raise RuntimeError(
                                f"WAL RCFG v{version} disagrees with "
                                "the ceremony re-derived from the "
                                "replayed log"
                            )
                        break
        p = self._pending
        if (
            p is not None
            and p.should_deal
            and not p.dealt
            and p.dealings.get(hb.node_id) is None
        ):
            self._deal_now()

    # -- dealing ------------------------------------------------------------

    def _dealing_seed(self, kind_offset: int) -> Optional[int]:
        """Deterministic dealing polynomials for seeded runs (fuzz
        replays); None (CSPRNG inside DkgDealing) in production."""
        hb = self._hb
        if hb.config.seed is None:
            return None
        p = self._pending
        h = hashlib.sha256(
            b"rcfgdeal|%d|%d|%d|" % (hb.config.seed, p.spec.version,
                                     kind_offset)
            + hb.node_id.encode("utf-8")
        ).digest()
        return int.from_bytes(h[:8], "big")

    def _blob_seed(self, rid: str, kind: int) -> bytes:
        """Expansion seed for one blob's chunk randomizers + DLEQ
        witness: CSPRNG in production, config-seed-derived in fuzz
        replays (same policy as ``_dealing_seed``)."""
        hb = self._hb
        if hb.config.seed is None:
            import secrets

            return secrets.token_bytes(32)  # staticcheck: allow[DET001] PVSS randomizers
        p = self._pending
        return hashlib.sha256(
            b"rcfgpvssrho|%d|%d|%d|"
            % (hb.config.seed, p.spec.version, kind)
            + hb.node_id.encode("utf-8")
            + b"|"
            + rid.encode("utf-8")
        ).digest()

    def _deal_now(self) -> None:
        hb = self._hb
        p = self._pending
        spec = p.spec
        p.dealt = True
        group = hb.group
        t_new = spec.threshold
        old_view = hb.active_view
        old_index = old_view.member_ids.index(hb.node_id) + 1
        deal_t = DkgDealing(
            old_index, spec.n, t_new, group, seed=self._dealing_seed(0)
        )
        deal_c = DkgDealing(
            old_index, spec.n, t_new, group, seed=self._dealing_seed(1)
        )
        tpke_commits = deal_t.commitments(backend="cpu")
        coin_commits = deal_c.commitments(backend="cpu")
        eng = _pvss_engine(group)
        blobs: Dict[str, bytes] = {}
        for j, rid in enumerate(spec.member_ids, start=1):
            y = self._dh_pub_for(rid)
            parts: List[bytes] = []
            for kind, (deal, commits) in enumerate(
                ((deal_t, tpke_commits), (deal_c, coin_commits))
            ):
                parts.append(
                    pvss_encrypt_share(
                        deal.share_for(j),
                        y,
                        self._blob_seed(rid, kind),
                        _pvss_ctx(
                            spec.version, hb.node_id, rid, kind,
                            commits, group,
                        ),
                        group,
                        eng,
                    )
                )
            blobs[rid] = b"".join(parts)
        tx = encode_dealing_tx(
            spec.version,
            hb.node_id,
            tpke_commits,
            coin_commits,
            blobs,
            group,
        )
        tr = hb.trace
        if tr is not None:
            tr.instant(
                "reconfig", "deal", version=spec.version, bytes=len(tx)
            )
        from cleisthenes_tpu.transport.message import ResharePayload

        hb.out.broadcast(
            ResharePayload(spec.version, hb.node_id, tx)
        )
        hb.add_transaction(tx)
        if hb.auto_propose:
            # the dealing rides the normal tx path, but the epoch
            # drive may have gone quiescent before it was queued (the
            # settle that discovered the RECONFIG postdates the last
            # ordering): kick a proposal so the ceremony makes
            # progress without waiting for client traffic
            hb.start_epoch()

    def _dh_pub_for(self, member_id: str) -> int:
        """A new-roster member's static-DH public key: its enrollment
        key (joiner) or its OLD coin verification key (survivor)."""
        p = self._pending
        pub = p.spec.enroll_pubs.get(member_id)
        if pub is not None:
            return pub
        hb = self._hb
        old_view = hb.active_view
        idx = old_view.member_ids.index(member_id)
        # an old member's view carries its key set; a JOINER's view of
        # the old roster is non-local, but its bootstrap NodeKeys hold
        # the same (public) coin key the operator provisioned
        coin_pub = (
            old_view.keys.coin_pub
            if old_view.keys is not None
            else hb.keys.coin_pub
        )
        return coin_pub.verification_keys[idx]

    def _dh_secret(self) -> int:
        """This node's static-DH secret: its old coin share
        (survivor/retiree) or its enrollment secret (joiner)."""
        hb = self._hb
        old_view = hb.active_view
        if hb.node_id in old_view.member_ids:
            return old_view.keys.coin_share.value
        if hb.keys.enroll_secret is None:
            raise RuntimeError(
                f"{hb.node_id}: joiner without an enrollment secret"
            )
        return hb.keys.enroll_secret

    def joiner_pair_keys(self, spec: ReconfigSpec) -> Dict[str, bytes]:
        """Pair keys between THIS node and every ceremony
        counterparty it does not already share one with (the joiner
        pairs) — installed at discovery on both sides so pre-
        activation CATCHUP authenticates."""
        hb = self._hb
        group = hb.group
        old_ids = set(hb.active_view.member_ids)
        if (
            hb.node_id not in old_ids
            and hb.node_id not in spec.member_ids
        ):
            # pure observer (e.g. a later joiner replaying history
            # from before its own enrollment): no pairs to derive
            return {}
        mine = self._dh_secret()
        out: Dict[str, bytes] = {}
        for rid in spec.member_ids:
            if rid == hb.node_id:
                continue
            if rid in old_ids and hb.node_id in old_ids:
                # surviving pair: its fresh key is STAGED, not
                # installed — see rotation_pair_keys
                continue
            dh = dh_point(mine, self._dh_pub_for(rid), group)
            out[rid] = pair_mac_key(
                spec.version, dh, hb.node_id, rid, group
            )
        return out

    def rotation_pair_keys(self, spec: ReconfigSpec) -> Dict[str, bytes]:
        """Fresh version-keyed MAC keys for this node's SURVIVING
        pairs (both ends in the old AND the new roster) — the MAC
        rotation's key schedule.  Installed via ``stage_peer_key`` at
        discovery (verify-either), promoted to the signing key at the
        activation boundary, with the old key dropped at teardown; a
        hard swap instead would reject every in-flight frame
        straddling the boundary."""
        hb = self._hb
        group = hb.group
        old_ids = set(hb.active_view.member_ids)
        if (
            hb.node_id not in old_ids
            or hb.node_id not in spec.member_ids
        ):
            return {}  # joiners and retirees have no surviving pairs
        mine = self._dh_secret()
        out: Dict[str, bytes] = {}
        for rid in spec.member_ids:
            if rid not in old_ids:
                continue  # joiner pair: installed, not staged
            # the self pair rotates too (loopback frames must track
            # the version's NodeKeys)
            dh = dh_point(mine, self._dh_pub_for(rid), group)
            out[rid] = pair_mac_key(
                spec.version, dh, hb.node_id, rid, group
            )
        return out

    # -- gossip (the ResharePayload message kind) ----------------------------

    def on_reshare_payload(self, sender: str, payload) -> None:
        """Eager dealing distribution + the joiner's bootstrap nudge.
        Staging is best-effort: the committed dealing tx is
        authoritative, so a dropped/forged gossip frame costs nothing
        but latency."""
        hb = self._hb
        p = self._pending
        if p is None or payload.version != p.spec.version:
            latest = hb.rosters.latest().version
            if (
                payload.version > latest
                and payload.version not in self._nudged
            ):
                # a ceremony we have not discovered yet is underway:
                # we are behind the log (the joiner's very first
                # signal) — chase it
                self._nudged.add(payload.version)
                hb._request_catchup(force=True)
            return
        if sender != payload.dealer or sender in p.staged:
            return
        try:
            dealing = decode_dealing_tx(payload.body)
        except ValueError:
            return
        if (
            dealing.version != p.spec.version
            or dealing.dealer != sender
        ):
            return
        p.staged[sender] = payload.body
        tr = hb.trace
        if tr is not None:
            tr.instant(
                "reconfig",
                "staged",
                version=p.spec.version,
                dealer=sender,
            )

    # -- qualified set + finalize -------------------------------------------

    def _on_deal_tx(self, epoch: int, tx: bytes) -> None:
        hb = self._hb
        p = self._pending
        if p is None:
            return
        try:
            dealing = decode_dealing_tx(tx)
        except ValueError:
            return
        spec = p.spec
        if dealing.version != spec.version:
            return
        old_view = hb.active_view
        if dealing.dealer not in old_view.member_ids:
            return
        if dealing.dealer in p.dealings:
            return  # first dealing per dealer wins (log order)
        t_new = spec.threshold
        if (
            len(dealing.tpke_commits) != t_new
            or len(dealing.coin_commits) != t_new
        ):
            return
        if sorted(dealing.blobs) != list(spec.member_ids):
            return  # must key every new member
        blen = pvss_blob_len(hb.group)
        if any(len(b) != blen for b in dealing.blobs.values()):
            return
        ok = validate_commitments(
            [dealing.tpke_commits, dealing.coin_commits],
            group=hb.group,
            backend="cpu",
            threshold=t_new,
        )
        if not all(ok):
            return  # commitment outside the prime-order subgroup
        if not self._pvss_check(tx, dealing, spec):
            # a blob fails public verification (e.g. targeted garbage
            # to one receiver): EVERY honest node rejects this dealing
            # at this log position — the dealer is excluded from Q
            # deterministically, no complaint round needed
            tr = hb.trace
            if tr is not None:
                tr.instant(
                    "reconfig",
                    "pvss_reject",
                    version=dealing.version,
                    dealer=dealing.dealer,
                )
            return
        p.dealings[dealing.dealer] = dealing
        if len(p.dealings) >= p.need:
            self._finalize(epoch)

    def _pvss_check(self, tx: bytes, dealing: Dealing, spec) -> bool:
        """Memoized ``pvss_verify_dealing``: the verdict is a pure
        function of the tx bytes + the version's (agreed) receiver key
        table, and the same settled tx is re-scanned on WAL replay and
        by every node of an in-process cluster."""
        digest = hashlib.sha256(tx).digest()
        verdict = _PVSS_VERDICTS.get(digest)
        if verdict is None:
            group = self._hb.group
            pubs = {
                rid: self._dh_pub_for(rid)
                for rid in spec.member_ids
            }
            verdict = pvss_verify_dealing(
                dealing, pubs, group, _pvss_engine(group)
            )
            while len(_PVSS_VERDICTS) >= _PVSS_VERDICT_CAP:
                _PVSS_VERDICTS.pop(next(iter(_PVSS_VERDICTS)))
            _PVSS_VERDICTS[digest] = verdict
        return verdict

    def _finalize(self, epoch: int) -> None:
        """Q is complete at the settlement of ``epoch``: derive the
        new key material, pick the activation boundary, and install
        the roster version."""
        hb = self._hb
        p = self._pending
        spec = p.spec
        group = hb.group
        t_new = spec.threshold
        activation = epoch + hb.config.reconfig_lead
        dealers = list(p.dealings)  # admission (log) order
        tpke_pub = finalize_public(
            [p.dealings[d].tpke_commits for d in dealers],
            spec.n,
            t_new,
            group,
        )
        coin_pub = finalize_public(
            [p.dealings[d].coin_commits for d in dealers],
            spec.n,
            t_new,
            group,
        )
        digest = key_material_digest(tpke_pub, coin_pub)
        keys = None
        if hb.node_id in spec.member_ids:
            keys = self._derive_member_keys(
                spec, dealers, tpke_pub, coin_pub
            )
        rv = RosterVersion(
            version=spec.version,
            activation_epoch=activation,
            members=spec.member_tuple(),
            key_material_digest=digest,
        )
        tr = hb.trace
        if tr is not None:
            tr.complete(
                "reconfig",
                "ceremony",
                p.t0,
                version=spec.version,
                dealers=len(dealers),
                activation_epoch=activation,
            )
        self._pending = None
        hb.install_roster_version(rv, keys, spec)

    def _derive_member_keys(
        self,
        spec: ReconfigSpec,
        dealers: Sequence[str],
        tpke_pub: ThresholdPublicKey,
        coin_pub: ThresholdPublicKey,
    ):
        """Decrypt, verify and fold this member's shares from every
        qualified dealing, and assemble the version's NodeKeys (MAC
        schedule included)."""
        from cleisthenes_tpu.protocol.honeybadger import NodeKeys
        from cleisthenes_tpu.ops.dkg import verify_dealer_shares

        hb = self._hb
        p = self._pending
        group = hb.group
        my_index = spec.member_ids.index(hb.node_id) + 1
        mine = self._dh_secret()
        eng = _pvss_engine(group)
        s_tpke_total = 0
        s_coin_total = 0
        check_items = []
        for d in dealers:
            dealing = p.dealings[d]
            blob = dealing.blobs[hb.node_id]
            try:
                s_t = pvss_decrypt_share(blob, 0, mine, group, eng)
                s_c = pvss_decrypt_share(blob, 1, mine, group, eng)
            except ValueError as exc:
                # a PUBLICLY verified dealing can only fail here via
                # the non-canonical-chunk residual (module docstring):
                # fail LOUDLY — diverging silently would fork the
                # roster
                raise RuntimeError(
                    f"{hb.node_id}: reshare v{spec.version} blob from "
                    f"dealer {d} failed chunk decode ({exc})"
                ) from exc
            check_items.append((dealing.tpke_commits, my_index, s_t))
            check_items.append((dealing.coin_commits, my_index, s_c))
            s_tpke_total = (s_tpke_total + s_t) % group.q
            s_coin_total = (s_coin_total + s_c) % group.q
        # defense-in-depth sanity: with canonical chunks the DLEQ
        # already pins g^s == X_j, so this can only fire on a bug
        verdicts = verify_dealer_shares(
            check_items, group=group, backend="cpu"
        )
        if not all(verdicts):
            bad = sorted(
                {
                    dealers[i // 2]
                    for i, ok in enumerate(verdicts)
                    if not ok
                }
            )
            raise RuntimeError(
                f"{hb.node_id}: reshare v{spec.version} shares from "
                f"dealers {bad} fail commitment verification"
            )
        # MAC rotation: EVERY pair of the new roster gets a fresh
        # version-keyed MAC key — surviving pairs included (they stage
        # it at discovery and promote at activation; see the module
        # docstring and HmacAuthenticator's rotation half)
        mac_keys: Dict[str, bytes] = {}
        for rid in spec.member_ids:
            dh = dh_point(mine, self._dh_pub_for(rid), group)
            mac_keys[rid] = pair_mac_key(
                spec.version, dh, hb.node_id, rid, group
            )
        return NodeKeys(
            tpke_pub=tpke_pub,
            tpke_share=ThresholdSecretShare(
                index=my_index, value=s_tpke_total
            ),
            coin_pub=coin_pub,
            coin_share=ThresholdSecretShare(
                index=my_index, value=s_coin_total
            ),
            mac_keys=mac_keys,
            enroll_secret=hb.keys.enroll_secret,
        )


def joiner_bootstrap_keys(
    enroll_secret: int,
    version: int,
    old_coin_pub: ThresholdPublicKey,
    old_member_ids: Sequence[str],
    self_id: str,
) -> Dict[str, bytes]:
    """The pair-key map a JOINER boots with: one DH-derived key per
    old-roster member (the counterpart of ``joiner_pair_keys`` on the
    old side), plus its self-pair — enough to authenticate CATCHUP
    before activation.  The operator provisions the joiner with the
    old roster's public coin key; nothing here is secret to the
    operator beyond the joiner's own enrollment secret."""
    group = old_coin_pub.group
    ordered = sorted(old_member_ids)
    out: Dict[str, bytes] = {}
    for i, mid in enumerate(ordered):
        if mid == self_id:
            continue
        dh = dh_point(
            enroll_secret, old_coin_pub.verification_keys[i], group
        )
        out[mid] = pair_mac_key(version, dh, self_id, mid, group)
    self_pub = pow(group.g, enroll_secret, group.p)
    out[self_id] = pair_mac_key(
        version,
        dh_point(enroll_secret, self_pub, group),
        self_id,
        self_id,
        group,
    )
    return out


__all__ = [
    "RECONFIG_TX_PREFIX",
    "DEAL_TX_PREFIX",
    "ReconfigSpec",
    "Dealing",
    "ReconfigManager",
    "is_protocol_tx",
    "encode_reconfig_tx",
    "decode_reconfig_tx",
    "encode_dealing_tx",
    "decode_dealing_tx",
    "enrollment_keypair",
    "joiner_bootstrap_keys",
    "pair_mac_key",
    "dh_point",
    "key_material_digest",
    "finalize_public",
    "pvss_blob_len",
    "pvss_encrypt_share",
    "pvss_verify_dealing",
    "pvss_decrypt_share",
]
