"""Semantic Byzantine adversaries: protocol-level lies under valid MACs.

The wire-level toolkit (``utils.adversary.Coalition``) attacks below
the MAC line — drop/tamper/duplicate/replay/delay/reorder of frames —
and everything it does is absorbed by envelope MACs and per-sender
dedup.  The one attack class the MAC layer explicitly does NOT cover
is a KEY-HOLDING node that "lies to each peer separately"
(transport/base.py HmacAuthenticator docstring): every frame it emits
verifies, yet the protocol content is malicious.  That is the
canonical BFT adversary (HBBFT's threat model is f *arbitrary* nodes),
and this module is its library:

  - ``Equivocator``    conflicting RBC VAL/ECHO proposals per receiver
  - ``SplitVoter``     conflicting BVAL/AUX votes per receiver per round
  - ``BadDealer``      structurally-valid wrong shards / Merkle branches
  - ``ShareForger``    well-formed but wrong TPKE / coin shares
  - ``SelectiveMute``  per-receiver silence (lying by omission)
  - ``EpochSprayer``   far-future epoch spam against the demux window

Injection point: a ``Behavior`` plugs into one node via the
``behavior=`` seam on ``HoneyBadger`` (and through it
``SimulatedCluster`` / ``ValidatorHost``).  The seam sits BETWEEN the
protocol instances and the outbound coalescer — every payload the node
emits is offered to the behavior once per receiver, so a lie can
differ per peer while still riding the normal envelope/MAC/bundling
path.  Behaviors compose with each other (``CompositeBehavior``) and
with wire-level ``Coalition`` filters on the same run.

All behaviors are seeded: a seeded cluster + seeded behaviors + seeded
scheduler replays the identical adversarial run (the property
``tools/fuzz.py`` builds its shrinking repros on).
"""

from __future__ import annotations

import random
from typing import Dict, List, Sequence

from cleisthenes_tpu.transport.message import (
    BbaPayload,
    BbaType,
    CatchupRespPayload,
    CoinPayload,
    DecSharePayload,
    RbcPayload,
    RbcType,
)

# how many per-epoch alternate proposals an Equivocator keeps alive
_ALT_EPOCH_CAP = 8


class Behavior:
    """One node's seeded malicious payload rewriter.

    Subclasses override ``rewrite(receiver, payload)`` and may return:
      - the payload unchanged (honest for this receiver),
      - a DIFFERENT payload (the lie),
      - ``None`` (suppress — lie by omission),
      - a list of payloads (inject extras alongside the original).

    ``attach(node)`` is called once by the HoneyBadger that hosts the
    behavior, giving it the node's identity, roster, config and crypto
    backend (an insider adversary holds all of those by definition).
    """

    def __init__(self, seed: int = 0) -> None:
        self.seed = seed
        self.rng = random.Random(seed)
        self.node = None
        self.rewrites = 0  # observability: lies actually told

    def attach(self, node) -> None:
        self.node = node
        self._attached()

    def _attached(self) -> None:
        """Subclass hook: runs once self.node is set."""

    def rewrite(self, receiver: str, payload):
        return payload

    # -- helpers -----------------------------------------------------------

    def split(self, fraction: float = 0.5) -> frozenset:
        """Seeded peer subset — the side the node lies TO.  Never
        includes the node itself (its self-delivery stays honest, as a
        real equivocator would keep its own state consistent)."""
        peers = [m for m in self.node.members if m != self.node.node_id]
        k = min(len(peers), max(1, round(len(peers) * fraction)))
        return frozenset(self.rng.sample(peers, k))


class BehaviorBroadcaster:
    """The seam: sits between one Byzantine node's protocol plane and
    its outbound coalescer, offering every payload to the behavior once
    per receiver.  Receivers are visited in sorted-roster order, so a
    seeded behavior's rng stream is deterministic."""

    def __init__(self, inner, member_ids: Sequence[str], behavior) -> None:
        self._inner = inner
        self._members: List[str] = sorted(member_ids)
        self._behavior = behavior

    def set_members(self, member_ids: Sequence[str]) -> None:
        """Roster-activation support (dynamic membership): the
        behavior keeps lying to whatever the CURRENT fan-out set is."""
        self._members = sorted(member_ids)

    def broadcast(self, payload) -> None:
        for member in self._members:
            self._send(member, payload)

    def send_to(self, member_id: str, payload) -> None:
        self._send(member_id, payload)

    def _send(self, member_id: str, payload) -> None:
        out = self._behavior.rewrite(member_id, payload)
        if out is None:
            return
        if isinstance(out, list):
            for p in out:
                self._inner.send_to(member_id, p)
        else:
            self._inner.send_to(member_id, out)


class Equivocator(Behavior):
    """Propose value A to one half of the roster and value B to the
    other — the textbook equivocation RBC exists to neutralize.

    For the node's OWN RBC instance, VAL and ECHO payloads to the
    seeded "B side" are rebuilt from a second, fully valid proposal:
    real RS shards, real Merkle tree, correct branch for the receiver's
    shard index.  Every frame verifies; the two sides just see
    irreconcilable roots.  A correct RBC must then either deliver ONE
    of the values everywhere or deliver nowhere (and ACS votes the
    proposer out) — never fork.
    """

    def _attached(self) -> None:
        self.side_b = self.split()
        self._alt: Dict[int, tuple] = {}  # epoch -> (tree, shards)

    def _alt_tree(self, epoch: int):
        ent = self._alt.get(epoch)
        if ent is None:
            from cleisthenes_tpu.ops.payload import split_payload

            node = self.node
            value = b"equivocation|%d|" % epoch + bytes(
                self.rng.randrange(256) for _ in range(64)
            )
            data = split_payload(value, node.config.data_shards)
            shards = node.crypto.erasure.encode(data)
            tree = node.crypto.merkle.build(shards)
            ent = (tree, shards)
            self._alt[epoch] = ent
            while len(self._alt) > _ALT_EPOCH_CAP:
                del self._alt[min(self._alt)]
        return ent

    def rewrite(self, receiver: str, payload):
        if (
            payload.__class__ is RbcPayload
            and payload.type in (RbcType.VAL, RbcType.ECHO)
            and payload.proposer == self.node.node_id
            and receiver in self.side_b
        ):
            tree, shards = self._alt_tree(payload.epoch)
            j = payload.shard_index
            self.rewrites += 1
            return RbcPayload(
                type=payload.type,
                proposer=payload.proposer,
                epoch=payload.epoch,
                root_hash=tree.root,
                branch=tuple(tree.branch(j)),
                shard=shards[j].tobytes(),
                shard_index=j,
            )
        return payload


class SplitVoter(Behavior):
    """Vote BVAL/AUX(v) to one half of the roster and (not v) to the
    other, every BBA round of every instance — the agreement-splitting
    attack the 2f+1 thresholds and the common coin exist for."""

    def _attached(self) -> None:
        self.side_b = self.split()

    def rewrite(self, receiver: str, payload):
        if (
            payload.__class__ is BbaPayload
            and payload.type in (BbaType.BVAL, BbaType.AUX)
            and receiver in self.side_b
        ):
            self.rewrites += 1
            return payload._replace(value=not payload.value)
        return payload


class BadDealer(Behavior):
    """A proposer that deals STRUCTURALLY valid but cryptographically
    wrong erasure shards / Merkle branches for its own instance:
    correct lengths, correct branch shape, correct root — the shard
    bytes or one branch sibling are garbage.  The receiver's batched
    branch verification must burn the slot (one vote per sender) and
    the roster must still converge on the honest echoes."""

    def _attached(self) -> None:
        self.side_b = self.split()

    def rewrite(self, receiver: str, payload):
        if (
            payload.__class__ is RbcPayload
            and payload.type in (RbcType.VAL, RbcType.ECHO)
            and payload.proposer == self.node.node_id
            and receiver in self.side_b
        ):
            self.rewrites += 1
            if payload.branch and self.rng.random() < 0.5:
                # corrupt one sibling hash: right shape, wrong proof
                i = self.rng.randrange(len(payload.branch))
                branch = tuple(
                    bytes(32) if k == i else b
                    for k, b in enumerate(payload.branch)
                )
                return payload._replace(branch=branch)
            shard = bytes(b ^ 0xA5 for b in payload.shard)
            return payload._replace(shard=shard)
        return payload


class ShareForger(Behavior):
    """Broadcast well-formed but WRONG threshold shares: valid Shamir
    index, in-range field elements, garbage value.  Coin shares attack
    BBA liveness (a forged share in the f+1 subset fails the batched
    CP verification and must burn without wedging the reveal); TPKE
    decryption shares attack the optimistic combine (bad tag must flip
    the proposer onto the CP-verified path)."""

    def __init__(
        self, seed: int = 0, kinds: Sequence[str] = ("coin", "dec")
    ) -> None:
        super().__init__(seed)
        self.kinds = tuple(kinds)

    def _attached(self) -> None:
        self.side_b = self.split()

    def _forge(self, d: int) -> int:
        forged = d ^ self.rng.randrange(2, 1 << 64)
        return forged if forged > 1 else 12345

    def rewrite(self, receiver: str, payload):
        cls = payload.__class__
        if (
            (cls is CoinPayload and "coin" in self.kinds)
            or (cls is DecSharePayload and "dec" in self.kinds)
        ) and receiver in self.side_b:
            self.rewrites += 1
            return payload._replace(d=self._forge(payload.d))
        return payload


class SelectiveMute(Behavior):
    """Silence toward a seeded peer subset only: the node looks live to
    most of the roster while starving a few members of its votes and
    shards — per-link omission, which no MAC can see and no global
    liveness counter flags."""

    def __init__(self, seed: int = 0, fraction: float = 0.34) -> None:
        super().__init__(seed)
        self.fraction = fraction
        self.muted: frozenset = frozenset()

    def _attached(self) -> None:
        self.muted = self.split(self.fraction)

    def rewrite(self, receiver: str, payload):
        if receiver in self.muted:
            self.rewrites += 1
            return None
        return payload


class EpochSprayer(Behavior):
    """Spam the epoch demux window: alongside honest traffic, inject
    payloads for far-future epochs (forcing receivers through the
    far-ahead CATCHUP sighting path) and junk CatchupResp bodies inside
    the tally window (attacking the f+1 adoption quorum's memory).
    Every sprayed frame is validly MAC'd — the sliding window, the
    tally bounds and the f+1 body quorum are the only defenses."""

    def __init__(
        self, seed: int = 0, every: int = 16, max_ahead: int = 1000
    ) -> None:
        super().__init__(seed)
        from cleisthenes_tpu.protocol.honeybadger import EPOCH_HORIZON

        self.every = max(1, every)
        # a spray must land BEYOND the demux horizon or it is just a
        # normal future-epoch payload; clamp so repro-file args can
        # never turn the spray range empty
        self.max_ahead = max(max_ahead, EPOCH_HORIZON + 2)
        self._count = 0

    def rewrite(self, receiver: str, payload):
        self._count += 1
        if self._count % self.every:
            return payload
        from cleisthenes_tpu.protocol.honeybadger import EPOCH_HORIZON

        self.rewrites += 1
        node = self.node
        if self.rng.random() < 0.5:
            ahead = self.rng.randrange(EPOCH_HORIZON + 1, self.max_ahead)
            spray = BbaPayload(
                type=BbaType.BVAL,
                proposer=node.node_id,
                epoch=node.epoch + ahead,
                round=0,
                value=True,
            )
        else:
            spray = CatchupRespPayload(
                epoch=node.epoch + self.rng.randrange(1, 64),
                body=b"sprayed-junk-%d" % self._count,
            )
        return [payload, spray]


class TxInjector(Behavior):
    """A Byzantine proposer that slips its OWN transactions into its
    proposals.  Perfectly legal under HBBFT — any proposer may propose
    any bytes — which is exactly what makes it the fuzzer's PLANTED
    violation: the harness knows every submitted tx, so a committed
    foreign one trips the ``no_foreign_tx`` invariant with certainty,
    deterministically, on every replay (tools/fuzz.py shrinker
    self-test)."""

    def __init__(self, seed: int = 0, count: int = 1) -> None:
        super().__init__(seed)
        self.count = count

    def _attached(self) -> None:
        for i in range(self.count):
            self.node.add_transaction(
                b"injected|%d|%d" % (self.seed, i)
            )


class CompositeBehavior:
    """Chain several behaviors on one node: each payload flows through
    every behavior in order (suppressions and injections included), so
    e.g. an Equivocator can ride with an EpochSprayer."""

    def __init__(self, behaviors: Sequence[Behavior]) -> None:
        self.behaviors = list(behaviors)
        self.node = None

    @property
    def rewrites(self) -> int:
        return sum(b.rewrites for b in self.behaviors)

    def attach(self, node) -> None:
        self.node = node
        for b in self.behaviors:
            b.attach(node)

    def rewrite(self, receiver: str, payload):
        items = [payload]
        for b in self.behaviors:
            nxt: List = []
            for p in items:
                out = b.rewrite(receiver, p)
                if out is None:
                    continue
                if isinstance(out, list):
                    nxt.extend(out)
                else:
                    nxt.append(out)
            items = nxt
            if not items:
                return None
        return items[0] if len(items) == 1 else items


# -- registry (the fuzzer's construction surface) ---------------------------

BEHAVIOR_KINDS = {
    "equivocator": Equivocator,
    "split_voter": SplitVoter,
    "bad_dealer": BadDealer,
    "share_forger": ShareForger,
    "selective_mute": SelectiveMute,
    "epoch_sprayer": EpochSprayer,
    "tx_injector": TxInjector,
}


def make_behavior(kind: str, seed: int = 0, **args) -> Behavior:
    """Build one behavior from its registry name — the JSON-schedule
    construction path ``tools/fuzz.py`` uses for replayable repros."""
    cls = BEHAVIOR_KINDS.get(kind)
    if cls is None:
        raise ValueError(
            f"unknown behavior kind {kind!r}; "
            f"known: {sorted(BEHAVIOR_KINDS)}"
        )
    return cls(seed=seed, **args)


__all__ = [
    "Behavior",
    "BehaviorBroadcaster",
    "Equivocator",
    "SplitVoter",
    "BadDealer",
    "ShareForger",
    "SelectiveMute",
    "EpochSprayer",
    "TxInjector",
    "CompositeBehavior",
    "BEHAVIOR_KINDS",
    "make_behavior",
]
