"""RBC: Bracha reliable broadcast with erasure coding + Merkle proofs.

Completes the reference's all-panics skeleton (reference rbc/rbc.go:38-100)
per its own spec (reference docs/RBC-EN.md:28-45):

  propose:  split value into K = N-2f data shards, RS-encode to N
            shards, build a Merkle tree over them, send VAL_j =
            (root h, branch b(j), shard s(j)) to node j
            (rbc/rbc.go:98-100 `shard`; docs/RBC-EN.md:28-33).
  VAL:      (from the proposer only) verify the branch, multicast
            ECHO with the same (h, b(j), s(j)) (docs/RBC-EN.md:34).
  ECHO:     verify branch (rbc/rbc.go:93-95 `validateMessage`); on
            N-f valid ECHOs interpolate from N-2f shards, *recompute
            the root* to catch a Byzantine proposer, then send
            READY(h) (rbc/rbc.go:88-90 `interpolate`;
            docs/RBC-EN.md:35-39).
  READY:    f+1 READY(h) -> send READY(h) if not yet sent; 2f+1
            READY(h) + N-2f verified shards -> decode and deliver
            (docs/RBC-EN.md:41-42).

The RS encode/decode and Merkle build/verify are delegated to the
BatchCrypto seam (ops.backend) so they run batched on TPU under
``crypto_backend='tpu'`` — this module is pure control flow.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from cleisthenes_tpu.config import Config
from cleisthenes_tpu.ops.backend import BatchCrypto
from cleisthenes_tpu.ops.payload import join_payload, split_payload
from cleisthenes_tpu.transport.message import RbcPayload, RbcType

# Per-root shard length sanity cap (a Byzantine proposer must not make
# honest nodes buffer huge shards; envelopes are separately capped by
# transport.message.MAX_FIELD_BYTES).
MAX_SHARD_BYTES = 16 * 1024 * 1024


class RBC:
    """One reliable-broadcast instance: (epoch, proposer).

    Mirrors the reference struct (rbc/rbc.go:9-36): n, f, proposer, the
    erasure codec, per-type bookkeeping, and a broadcaster — with the
    request repositories realized as per-root dicts enforcing
    one-vote-per-sender.
    """

    def __init__(
        self,
        *,
        config: Config,
        crypto: BatchCrypto,
        epoch: int,
        proposer: str,
        owner: str,
        member_ids: Sequence[str],
        out,
    ) -> None:
        self.n = config.n
        self.f = config.f
        self.k = config.data_shards
        self.epoch = epoch
        self.proposer = proposer
        self.owner = owner
        self.members: List[str] = sorted(member_ids)
        if len(self.members) != self.n:
            raise ValueError(
                f"roster size {len(self.members)} != n={self.n}"
            )
        self.crypto = crypto
        self.out = out  # PayloadBroadcaster: broadcast / send_to

        # hook set by ACS: fn(proposer_id, value_bytes)
        self.on_deliver: Optional[Callable[[str, bytes], None]] = None

        self._member_set = frozenset(self.members)
        self._echo_sent = False
        self._ready_root: Optional[bytes] = None  # root we READY'd
        # One ECHO and one READY per sender per *instance* (a correct
        # node sends exactly one of each; reference rbc/request.go:30-42
        # repositories are keyed by ConnId).  This also bounds the
        # number of distinct roots an instance ever tracks to n.
        self._echo_voted: Set[str] = set()
        self._ready_voted: Set[str] = set()
        # root -> set of ECHO senders
        self._echo_senders: Dict[bytes, Set[str]] = {}
        # root -> shard_index -> shard bytes (branch-verified)
        self._shards: Dict[bytes, Dict[int, bytes]] = {}
        self._shard_len: Dict[bytes, int] = {}
        # root -> set of READY senders (rbc/request.go ReadyReqRepository)
        self._ready_senders: Dict[bytes, Set[str]] = {}
        self._bad_roots: Set[bytes] = set()  # failed interpolation recheck
        self._decoded: Dict[bytes, bytes] = {}  # successful decode cache
        self._value: Optional[bytes] = None

    # -- public API (reference rbc/rbc.go:38-76) ---------------------------

    def value(self) -> Optional[bytes]:
        """The delivered value, or None (reference rbc/rbc.go:69-71)."""
        return self._value

    @property
    def delivered(self) -> bool:
        return self._value is not None

    def propose(self, value: bytes) -> None:
        """Shard, build the Merkle tree, send VAL_j to each node j
        (reference rbc/rbc.go:42-44 `broadcast` + :98-100 `shard`)."""
        if self.owner != self.proposer:
            raise ValueError(
                f"{self.owner!r} cannot propose in {self.proposer!r}'s RBC"
            )
        if len(value) > self.k * MAX_SHARD_BYTES - 4 - self.k * 128:
            # shards receivers would reject in _check_proof: fail fast
            raise ValueError(
                f"value of {len(value)} bytes exceeds the "
                f"{self.k} x {MAX_SHARD_BYTES}-byte shard capacity"
            )
        data = split_payload(value, self.k)
        shards = self.crypto.erasure.encode(data)  # (n, L)
        tree = self.crypto.merkle.build(shards)
        root = tree.root
        for j, member in enumerate(self.members):
            payload = RbcPayload(
                type=RbcType.VAL,
                proposer=self.proposer,
                epoch=self.epoch,
                root_hash=root,
                branch=tuple(tree.branch(j)),
                shard=shards[j].tobytes(),
                shard_index=j,
            )
            self.out.send_to(member, payload)

    def handle_message(self, sender: str, payload: RbcPayload) -> None:
        """Public entry (reference rbc/rbc.go:46-54)."""
        if not isinstance(payload, RbcPayload):
            return
        if self.delivered or sender not in self._member_set:
            return
        if payload.type == RbcType.VAL:
            self._handle_val(sender, payload)
        elif payload.type == RbcType.ECHO:
            self._handle_echo(sender, payload)
        elif payload.type == RbcType.READY:
            self._handle_ready(sender, payload)

    # -- handlers ----------------------------------------------------------

    def _check_proof(self, payload: RbcPayload) -> bool:
        """Branch verification (reference rbc/rbc.go:93-95
        `validateMessage`, docs/RBC-EN.md:35)."""
        if not (0 <= payload.shard_index < self.n):
            return False
        if not (0 < len(payload.shard) <= MAX_SHARD_BYTES):
            return False
        if len(payload.root_hash) != 32:
            return False
        # depth of the padded tree the proposer must have built
        p = 1
        depth = 0
        while p < self.n:
            p <<= 1
            depth += 1
        if len(payload.branch) != depth:
            return False
        if any(len(b) != 32 for b in payload.branch):
            return False
        # shards of one root must agree on length (RS needs a matrix)
        want_len = self._shard_len.get(payload.root_hash)
        if want_len is not None and len(payload.shard) != want_len:
            return False
        return self.crypto.merkle.verify_branch(
            payload.root_hash,
            payload.shard,
            list(payload.branch),
            payload.shard_index,
        )

    def _handle_val(self, sender: str, payload: RbcPayload) -> None:
        """docs/RBC-EN.md:34 — echo the received (h, b(j), s(j)) to all.

        Only the proposer may send VAL, and only the first one counts
        (reference rbc/rbc.go:56-58)."""
        if sender != self.proposer or self._echo_sent:
            return
        if not self._check_proof(payload):
            return
        self._echo_sent = True
        self.out.broadcast(
            RbcPayload(
                type=RbcType.ECHO,
                proposer=self.proposer,
                epoch=self.epoch,
                root_hash=payload.root_hash,
                branch=payload.branch,
                shard=payload.shard,
                shard_index=payload.shard_index,
            )
        )

    def _handle_echo(self, sender: str, payload: RbcPayload) -> None:
        """docs/RBC-EN.md:35-39 (reference rbc/rbc.go:60-62)."""
        root = payload.root_hash
        if sender in self._echo_voted:  # one ECHO per sender
            return
        if not self._check_proof(payload):
            return
        self._echo_voted.add(sender)
        senders = self._echo_senders.setdefault(root, set())
        senders.add(sender)
        self._shard_len.setdefault(root, len(payload.shard))
        self._shards.setdefault(root, {})[payload.shard_index] = payload.shard
        # N-f valid ECHOs -> interpolate, recheck root, READY
        if (
            len(senders) >= self.n - self.f
            and self._ready_root is None
            and root not in self._bad_roots
        ):
            if self._decode(root) is not None:
                self._send_ready(root)
        self._maybe_deliver(root)

    def _handle_ready(self, sender: str, payload: RbcPayload) -> None:
        """docs/RBC-EN.md:41-42 (reference rbc/rbc.go:64-66)."""
        root = payload.root_hash
        if len(root) != 32:
            return
        if sender in self._ready_voted:  # one READY per sender
            return
        self._ready_voted.add(sender)
        senders = self._ready_senders.setdefault(root, set())
        senders.add(sender)
        # f+1 READY(h) -> relay READY(h) once (amplification step)
        if len(senders) >= self.f + 1 and self._ready_root is None:
            self._send_ready(root)
        self._maybe_deliver(root)

    # -- quorum actions ----------------------------------------------------

    def _send_ready(self, root: bytes) -> None:
        self._ready_root = root
        self.out.broadcast(
            RbcPayload(
                type=RbcType.READY,
                proposer=self.proposer,
                epoch=self.epoch,
                root_hash=root,
            )
        )

    def _decode(self, root: bytes) -> Optional[bytes]:
        """Interpolate K shards, re-encode, recompute the Merkle root
        (the Byzantine-proposer check of docs/RBC-EN.md:37-39;
        reference rbc/rbc.go:88-90's '< N-2f shards -> error').

        Returns the decoded value or None (insufficient / bad root).
        """
        if root in self._decoded:
            return self._decoded[root]
        if root in self._bad_roots:
            return None
        shards = self._shards.get(root, {})
        if len(shards) < self.k:
            return None
        idxs = sorted(shards)[: self.k]
        mat = np.stack(
            [np.frombuffer(shards[i], dtype=np.uint8) for i in idxs]
        )
        data = self.crypto.erasure.decode(idxs, mat)
        full = self.crypto.erasure.encode(data)
        tree = self.crypto.merkle.build(full)
        if tree.root != root:
            self._bad_roots.add(root)
            return None
        try:
            value = join_payload(data)
        except ValueError:  # corrupt length framing from the proposer
            self._bad_roots.add(root)
            return None
        self._decoded[root] = value
        return value

    def _maybe_deliver(self, root: bytes) -> None:
        """2f+1 READY(h) + N-2f verified shards -> deliver
        (docs/RBC-EN.md:41-42)."""
        if self.delivered:
            return
        if len(self._ready_senders.get(root, ())) < 2 * self.f + 1:
            return
        value = self._decode(root)
        if value is None:
            return
        self._value = value
        # free per-root buffers; the instance is terminal now
        self._shards.clear()
        self._echo_senders.clear()
        if self.on_deliver is not None:
            self.on_deliver(self.proposer, value)


__all__ = ["RBC", "MAX_SHARD_BYTES"]
