"""RBC: Bracha reliable broadcast with erasure coding + Merkle proofs.

Completes the reference's all-panics skeleton (reference rbc/rbc.go:38-100)
per its own spec (reference docs/RBC-EN.md:28-45):

  propose:  split value into K = N-2f data shards, RS-encode to N
            shards, build a Merkle tree over them, send VAL_j =
            (root h, branch b(j), shard s(j)) to node j
            (rbc/rbc.go:98-100 `shard`; docs/RBC-EN.md:28-33).
  VAL:      (from the proposer only) verify the branch, multicast
            ECHO with the same (h, b(j), s(j)) (docs/RBC-EN.md:34).
  ECHO:     verify branch (rbc/rbc.go:93-95 `validateMessage`); on
            N-f valid ECHOs interpolate from N-2f shards, *recompute
            the root* to catch a Byzantine proposer, then send
            READY(h) (rbc/rbc.go:88-90 `interpolate`;
            docs/RBC-EN.md:35-39).
  READY:    f+1 READY(h) -> send READY(h) if not yet sent; 2f+1
            READY(h) + N-2f verified shards -> decode and deliver
            (docs/RBC-EN.md:41-42).

Crypto never runs on the message path: inbound ECHO proofs park in a
pending pool (one slot per sender) and the decode+root-recheck parks
as a request; the shared ``protocol.hub.CryptoHub`` pulls all pending
work — across every concurrent RBC instance of the epoch — into
batched device dispatches when some instance's quorum threshold makes
results necessary (SURVEY.md §7 hard part 3's per-epoch accumulation
buffers; the reference's N^2-branch-hash cost model is
docs/HONEYBADGER-EN.md:96).  Only the single VAL proof is verified
inline: our own ECHO must go out immediately and nothing else would
trigger a flush that early.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from cleisthenes_tpu.config import Config
from cleisthenes_tpu.ops.backend import BatchCrypto
from cleisthenes_tpu.ops.payload import join_payload, split_payload
from cleisthenes_tpu.transport.message import RbcPayload, RbcType

# Per-root shard length sanity cap (a Byzantine proposer must not make
# honest nodes buffer huge shards; envelopes are separately capped by
# transport.message.MAX_FIELD_BYTES).
MAX_SHARD_BYTES = 16 * 1024 * 1024

# id-keyed branch-shape memo: entries hold the branch TUPLE (a few
# hundred bytes — pinning the id against recycling, same discipline as
# the hub's token table) rather than the whole payload, whose shard
# bytes would otherwise keep dead epochs' data resident until the
# wholesale clear at the cap
_BRANCH_SHAPE_MEMO: dict = {}
_BRANCH_SHAPE_MEMO_CAP = 1 << 14


class RBC:
    """One reliable-broadcast instance: (epoch, proposer).

    Mirrors the reference struct (rbc/rbc.go:9-36): n, f, proposer, the
    erasure codec, per-type bookkeeping, and a broadcaster — with the
    request repositories realized as per-root dicts enforcing
    one-vote-per-sender.
    """

    def __init__(
        self,
        *,
        config: Config,
        crypto: BatchCrypto,
        epoch: int,
        proposer: str,
        owner: str,
        member_ids: Sequence[str],
        out,
        hub=None,
        bank=None,
        index=None,
        trace=None,
        metrics=None,
        scope=None,
    ) -> None:
        self.n = config.n
        self.f = config.f
        # READY deliver threshold: 2f+1 baseline, n-f under
        # Config.reduced_quorum (Config.quorum_large)
        self.q_large = config.quorum_large
        self.k = config.data_shards
        self.epoch = epoch
        self.proposer = proposer
        self.owner = owner
        self.members: List[str] = sorted(member_ids)
        if len(self.members) != self.n:
            raise ValueError(
                f"roster size {len(self.members)} != n={self.n}"
            )
        self.crypto = crypto
        self.out = out  # PayloadBroadcaster: broadcast / send_to
        if hub is None:  # standalone use (unit tests): private hub
            from cleisthenes_tpu.protocol.hub import CryptoHub

            hub = CryptoHub(crypto)
        self.hub = hub
        # ECHO/READY receipt state lives in the roster-wide EchoBank
        # (protocol.echobank): ACS shares ONE bank across the epoch's
        # N instances so columnar waves update struct-of-arrays slices;
        # standalone use (unit tests) gets a private single-instance
        # bank — the same arrays, width 1.
        if bank is None:
            from cleisthenes_tpu.protocol.echobank import EchoBank

            bank = EchoBank(
                member_ids, config.f, inst_ids=[proposer], metrics=metrics,
                quorum_large=config.quorum_large,
            )
            index = 0
        self.bank = bank
        self.index = index
        bank.attach(index, self)
        # scope is (owner, epoch): a hub may be SHARED by many
        # in-proc validators (cluster-batched dispatches), and one
        # node advancing epochs must only drop ITS clients.  Lane
        # shard-out (Config.lanes) further qualifies ``scope`` with
        # the lane id — sibling lanes of one node share the hub and
        # run the same epoch numbers concurrently, so epoch GC must
        # be lane-scoped too; at lanes=1, scope == owner.
        self.hub.register((owner if scope is None else scope, epoch), self)
        # flight recorder (None = tracing off; utils/trace.py)
        self.trace = trace
        # owner-node metrics (None in standalone unit tests): only the
        # duplicate-vote absorption counter is touched here
        self.metrics = metrics

        # hook set by ACS: fn(proposer_id, value_bytes)
        self.on_deliver: Optional[Callable[[str, bytes], None]] = None

        self._member_set = frozenset(self.members)
        self._echo_sent = False
        self._ready_root: Optional[bytes] = None  # root we READY'd
        # One ECHO and one READY per sender per *instance* (a correct
        # node sends exactly one of each; reference rbc/request.go:30-42
        # repositories are keyed by ConnId) — the claim/dedup state
        # lives in the EchoBank's [sender, instance] arrays, which also
        # bound the distinct roots an instance ever counts to n.  The
        # slot is claimed at arrival; a sender whose proof later fails
        # verification has burned its one vote.
        # depth of the padded tree the proposer must have built
        # (precomputed: _precheck runs once per delivered ECHO)
        p = 1
        self._depth = 0
        while p < self.n:
            p <<= 1
            self._depth += 1
        # root -> set of verified ECHO senders
        self._echo_senders: Dict[bytes, Set[str]] = {}
        # root -> shard_index -> shard bytes (branch-verified)
        self._shards: Dict[bytes, Dict[int, bytes]] = {}
        self._shard_len: Dict[bytes, int] = {}
        # roots whose decode+recheck is wanted (ready/echo quorum hit)
        self._decode_req: Set[bytes] = set()
        self._bad_roots: Set[bytes] = set()  # failed interpolation recheck
        self._decoded: Dict[bytes, bytes] = {}  # successful decode cache
        self._value: Optional[bytes] = None

    # -- public API (reference rbc/rbc.go:38-76) ---------------------------

    def value(self) -> Optional[bytes]:
        """The delivered value, or None (reference rbc/rbc.go:69-71)."""
        return self._value

    @property
    def delivered(self) -> bool:
        return self._value is not None

    def propose(self, value: bytes) -> None:
        """Shard, build the Merkle tree, send VAL_j to each node j
        (reference rbc/rbc.go:42-44 `broadcast` + :98-100 `shard`)."""
        if self.owner != self.proposer:
            raise ValueError(
                f"{self.owner!r} cannot propose in {self.proposer!r}'s RBC"
            )
        if len(value) > self.k * MAX_SHARD_BYTES - 4 - self.k * 128:
            # shards receivers would reject in _check_proof: fail fast
            raise ValueError(
                f"value of {len(value)} bytes exceeds the "
                f"{self.k} x {MAX_SHARD_BYTES}-byte shard capacity"
            )
        tr = self.trace
        t0 = 0.0 if tr is None else tr.now()
        data = split_payload(value, self.k)
        shards = self.crypto.erasure.encode(data)  # (n, L)
        tree = self.crypto.merkle.build(shards)
        root = tree.root
        if tr is not None:
            tr.complete(
                "rbc", "propose", t0, epoch=self.epoch, bytes=len(value)
            )
        for j, member in enumerate(self.members):
            payload = RbcPayload(
                type=RbcType.VAL,
                proposer=self.proposer,
                epoch=self.epoch,
                root_hash=root,
                branch=tuple(tree.branch(j)),
                shard=shards[j].tobytes(),
                shard_index=j,
            )
            self.out.send_to(member, payload)

    def handle_message(self, sender: str, payload: RbcPayload) -> None:
        """Public entry (reference rbc/rbc.go:46-54)."""
        if not isinstance(payload, RbcPayload):
            return
        if self.delivered or sender not in self._member_set:
            return
        if payload.type == RbcType.VAL:
            self._handle_val(sender, payload)
        elif payload.type == RbcType.ECHO:
            self._handle_echo(sender, payload)
        elif payload.type == RbcType.READY:
            self._handle_ready(sender, payload)

    # -- handlers ----------------------------------------------------------

    def _precheck(self, payload: RbcPayload) -> bool:
        return self._precheck_fields(
            payload.root_hash,
            payload.branch,
            payload.shard,
            payload.shard_index,
        )

    def _precheck_fields(
        self, root: bytes, branch: tuple, shard: bytes, shard_index: int
    ) -> bool:
        """Structural validation — everything except the branch hash
        check itself (reference rbc/rbc.go:93-95 `validateMessage`
        minus the crypto, which the hub batches).

        The branch-shape walk memoizes ON OBJECT IDENTITY: the codec's
        payload memo shares one branch tuple across a broadcast's N
        receivers, so the per-sibling length walk runs once per wire
        payload, not once per delivery (the held tuple pins the id);
        the remaining checks are a handful of scalar compares."""
        if not (0 <= shard_index < self.n):
            return False
        if not (0 < len(shard) <= MAX_SHARD_BYTES):
            return False
        if len(root) != 32:
            return False
        if len(branch) != self._depth:
            return False
        ent = _BRANCH_SHAPE_MEMO.get(id(branch))
        if ent is not None and ent[0] is branch:
            ok = ent[1]
        else:
            ok = all(len(b) == 32 for b in branch)
            if len(_BRANCH_SHAPE_MEMO) >= _BRANCH_SHAPE_MEMO_CAP:
                _BRANCH_SHAPE_MEMO.clear()
            _BRANCH_SHAPE_MEMO[id(branch)] = (branch, ok)
        if not ok:
            return False
        # Shards of one root must agree on length (RS needs a matrix).
        # _shard_len only ever holds BRANCH-VERIFIED lengths (set in
        # _handle_val after _check_proof and in _make_echo_cb), so an
        # unverified Byzantine ECHO cannot poison the expectation and
        # wedge honest traffic (ADVICE.md round-2 high finding).
        want_len = self._shard_len.get(root)
        if want_len is not None and len(shard) != want_len:
            return False
        return True

    def _check_proof(self, payload: RbcPayload) -> bool:
        """Full inline verification (VAL only — ECHO proofs batch
        through the hub).  The one sanctioned direct crypto call in
        protocol/: a single proposer branch per instance, and the ECHO
        reply cannot wait for a wave."""
        if not self._precheck(payload):
            return False
        return self.crypto.merkle.verify_branch(  # staticcheck: allow[DET003] inline VAL check
            payload.root_hash,
            payload.shard,
            list(payload.branch),
            payload.shard_index,
        )

    def _handle_val(self, sender: str, payload: RbcPayload) -> None:
        """docs/RBC-EN.md:34 — echo the received (h, b(j), s(j)) to all.

        Only the proposer may send VAL, and only the first one counts
        (reference rbc/rbc.go:56-58)."""
        if sender != self.proposer or self._echo_sent:
            return
        if not self._check_proof(payload):
            return
        # verified: this length is now the root's authoritative one
        self._shard_len.setdefault(payload.root_hash, len(payload.shard))
        self._echo_sent = True
        if self.trace is not None:
            self.trace.instant(
                "rbc", "val", epoch=self.epoch, proposer=self.proposer
            )
        self.out.broadcast(
            RbcPayload(
                type=RbcType.ECHO,
                proposer=self.proposer,
                epoch=self.epoch,
                root_hash=payload.root_hash,
                branch=payload.branch,
                shard=payload.shard,
                shard_index=payload.shard_index,
            )
        )

    def _handle_echo(self, sender: str, payload: RbcPayload) -> None:
        self.handle_echo_fast(
            sender,
            payload.root_hash,
            payload.branch,
            payload.shard,
            payload.shard_index,
        )

    def handle_echo_fast(
        self,
        sender: str,
        root: bytes,
        branch: tuple,
        shard: bytes,
        shard_index: int,
    ) -> None:
        """docs/RBC-EN.md:35-39 (reference rbc/rbc.go:60-62) — the
        field-level scalar entry; the columnar EchoBatchPayload path
        runs the same claim through EchoBank.batch_echo, which hoists
        the dedup/delivered/membership filters into vectorized row
        operations and calls ``_echo_item`` per surviving item."""
        bank = self.bank
        si = bank.sidx.get(sender)
        if si is None:
            return
        if bank.echo_seen[si, self.index]:  # one ECHO per sender
            if self.metrics is not None:
                self.metrics.dedup_absorbed.inc()
            return
        self._echo_item(si, sender, root, branch, shard, shard_index)

    def _echo_item(
        self,
        si: int,
        sender: str,
        root: bytes,
        branch: tuple,
        shard: bytes,
        shard_index: int,
    ) -> None:
        """Claim + park one deduped ECHO (the per-item protocol logic
        under both delivery paths).  The branch proof is NOT verified
        here: the proof parks in the bank's contiguous pending slot
        and verifies in the hub's next batched dispatch — triggered
        below the moment this root could reach its N-f quorum."""
        if not self._precheck_fields(root, branch, shard, shard_index):
            return
        bank = self.bank
        # slot claimed; burns if the proof later fails verification
        pot = bank.echo_claim(self.index, si, root)
        bank.pending[self.index].append(
            (root, sender, shard, shard_index, branch)
        )
        self.hub.mark_dirty(self)
        if (
            pot >= self.n - self.f
            and self._ready_root is None
            and root not in self._bad_roots
        ):
            self.hub.request_flush()
        self._maybe_deliver(root)

    def handle_ready_root(self, sender: str, root: bytes) -> None:
        """READY without a payload object (columnar batch path) —
        guards mirror handle_message's."""
        if self.delivered or sender not in self._member_set:
            return
        self._handle_ready_root(sender, root)

    def _handle_ready(self, sender: str, payload: RbcPayload) -> None:
        """docs/RBC-EN.md:41-42 (reference rbc/rbc.go:64-66)."""
        self._handle_ready_root(sender, payload.root_hash)

    def _handle_ready_root(self, sender: str, root: bytes) -> None:
        if len(root) != 32:
            return
        bank = self.bank
        si = bank.sidx.get(sender)
        if si is None:
            return
        cnt = bank.ready_add(self.index, si, root)
        if cnt is None:  # one READY per sender (dedup counted in bank)
            return
        # f+1 READY(h) -> relay READY(h) once (amplification step)
        if cnt >= self.f + 1 and self._ready_root is None:
            self._send_ready(root)
        self._maybe_deliver(root)

    # -- quorum actions ----------------------------------------------------

    def _send_ready(self, root: bytes) -> None:
        self._ready_root = root
        if self.trace is not None:
            # fires at most once per instance (_ready_root gates every
            # caller): the READY quorum-crossing marker
            self.trace.instant(
                "rbc", "ready", epoch=self.epoch, proposer=self.proposer
            )
        self.out.broadcast(
            RbcPayload(
                type=RbcType.READY,
                proposer=self.proposer,
                epoch=self.epoch,
                root_hash=root,
            )
        )

    def _request_decode(self, root: bytes) -> None:
        """Ask the hub for interpolate + re-encode + root recheck
        (docs/RBC-EN.md:37-39) at its next flush."""
        if (
            root in self._decoded
            or root in self._bad_roots
            or root in self._decode_req
        ):
            return
        self._decode_req.add(root)
        if self.trace is not None:
            # the ECHO-quorum crossing: a decode+recheck became wanted
            self.trace.instant(
                "rbc",
                "echo_quorum",
                epoch=self.epoch,
                proposer=self.proposer,
            )
        self.hub.mark_dirty(self)

    def _maybe_deliver(self, root: bytes) -> None:
        """q_large READY(h) + N-2f verified shards -> deliver
        (docs/RBC-EN.md:41-42; q_large = 2f+1 baseline, n-f reduced)."""
        if self.delivered:
            return
        if self.bank.ready_count(self.index, root) < self.q_large:
            return
        value = self._decoded.get(root)
        if value is None:
            # decode (or the shard verifications feeding it) is still
            # pending: stage the request and flush if work exists
            self._request_decode(root)
            if root in self._decode_req or self.bank.pending[self.index]:
                self.hub.request_flush()
            if self.delivered:
                return  # the flush's quorum pass delivered already
            value = self._decoded.get(root)
            if value is None:
                return
        self._value = value
        if self.trace is not None:
            self.trace.instant(
                "rbc",
                "deliver",
                epoch=self.epoch,
                proposer=self.proposer,
                bytes=len(value),
            )
        # free per-root buffers; the instance is terminal now — the
        # bank's sentinel row drops every later vote vectorized
        self._shards.clear()
        self._echo_senders.clear()
        self._decode_req.clear()
        self.bank.deactivate(self.index)
        if self.on_deliver is not None:
            self.on_deliver(self.proposer, value)

    # -- hub client protocol (protocol.hub.CryptoHub) ----------------------

    def drain_pending(self, wave) -> None:
        """Move pending crypto work into the wave's typed columns
        (protocol.hub.HubWave): every parked ECHO proof as a branch
        item, every staged decode whose matrix is complete as a decode
        item (shard BYTES in index order — the hub builds each unique
        matrix once instead of one np.stack per client)."""
        pend = self.bank.pending[self.index]
        if self.delivered or not (pend or self._decode_req):
            return  # fast path: the hub may drain a client twice/round
        # pending ECHO proofs -> batched branch verification: the
        # bank's contiguous arrival-order slot pops WHOLESALE into the
        # wave's branch columns (no per-root dict walk)
        if pend:
            self.bank.pending[self.index] = []
            add = wave.add_branch
            for root, sender, shard, sidx, branch in pend:
                add(
                    self,
                    root,
                    shard,
                    branch,
                    sidx,
                    (root, sender, shard, sidx),
                )
        # staged decode requests with enough verified shards; sorted:
        # _decode_req is a set of 32-byte roots, and its hash order
        # (PYTHONHASHSEED-dependent) would otherwise decide decode
        # batching and READY emission order across instances
        for root in sorted(self._decode_req):
            if root in self._decoded or root in self._bad_roots:
                self._decode_req.discard(root)
                continue
            shards_map = self._shards.get(root, {})
            if len(shards_map) < self.k:
                continue  # stays staged until shards verify
            self._decode_req.discard(root)
            idxs = tuple(sorted(shards_map)[: self.k])
            wave.add_decode(
                root,
                idxs,
                [shards_map[i] for i in idxs],
                self._make_decode_cb(root),
                n=self.n,
            )

    def on_branch_verdicts(self, ctxs, oks) -> None:
        """Bulk ECHO-branch verdicts from the hub (one call per flush
        instead of a per-echo closure — at N=64 the closures alone
        were ~1.8 s of an epoch).  ctx = (root, sender, shard, sidx).

        A root crossing its N-f echo quorum here stages its decode
        request IMMEDIATELY (not in after_crypto_flush): the hub
        re-drains verdict-marked clients before running the round's
        decode column, so the decode rides THIS wave's single decode
        dispatch instead of a follow-on round's."""
        if self.delivered:
            return
        shard_len = self._shard_len
        echo_senders = self._echo_senders
        shards = self._shards
        re_mark = False
        for (root, sender, shard, sidx), ok in zip(ctxs, oks):
            if not ok:
                # invalid: the sender's one slot stays burned, but the
                # claim leaves the bank's quorum POTENTIAL — otherwise
                # f parked forgeries would push pot past n-f forever
                # and every later honest echo would request a flush
                self.bank.echo_drop(self.index, root)
                continue
            # length authority comes only from verified shards; a
            # verified shard conflicting with the established length
            # is a Byzantine proposer mixing lengths under one tree —
            # drop it, RS needs a rectangular matrix
            want = shard_len.setdefault(root, len(shard))
            if len(shard) != want:
                self.bank.echo_drop(self.index, root)
                continue
            echo_senders.setdefault(root, set()).add(sender)
            shards.setdefault(root, {})[sidx] = shard
            re_mark = True
        if not re_mark:
            return
        # stage any echo-quorum decode now (same guards as
        # after_crypto_flush; _request_decode dedups staged roots)
        if self._ready_root is None:
            quorum = self.n - self.f
            for root, senders in echo_senders.items():
                if len(senders) >= quorum and root not in self._bad_roots:
                    self._request_decode(root)
        # a staged decode may just have reached k shards — stay on
        # the hub's dirty list so this wave round (or the next)
        # collects it (no decode staged -> nothing new to offer)
        if self._decode_req:
            self.hub.mark_dirty(self)

    def _make_decode_cb(self, root: bytes):
        def cb(data) -> None:
            if data is None:
                self._bad_roots.add(root)
                return
            try:
                self._decoded[root] = join_payload(data)
            except ValueError:  # corrupt length framing from proposer
                self._bad_roots.add(root)

        return cb

    def after_crypto_flush(self) -> None:
        """Quorum logic over freshly-verified state; new decode
        requests staged here are picked up by the flush loop's next
        collection round."""
        if self.delivered:
            return
        # N-f verified ECHOs -> stage decode (READY follows a
        # successful root recheck, docs/RBC-EN.md:35-39)
        for root, senders in list(self._echo_senders.items()):
            if (
                len(senders) >= self.n - self.f
                and self._ready_root is None
                and root not in self._bad_roots
            ):
                self._request_decode(root)
                if root in self._decoded:
                    self._send_ready(root)
        for root in list(self._decoded):
            if (
                self._ready_root is None
                and len(self._echo_senders.get(root, ())) >= self.n - self.f
            ):
                self._send_ready(root)
        for root in self.bank.ready_roots(self.index):
            if self.delivered:
                break
            self._maybe_deliver(root)


__all__ = ["RBC", "MAX_SHARD_BYTES"]
