"""Simulated-TEE attestation plane: the attested sender log.

"Efficient BFT using TEE" (arxiv 2102.01970) and "Proof of Trusted
Execution" (arxiv 2512.09409) reduce asynchronous BFT's roster
requirement from n >= 3f+1 to n >= 2f+1 by removing ONE capability
from the adversary: equivocation.  A trusted component that binds a
strictly monotonic counter + a sealed MAC to every outbound message
makes "say A to half the roster, B to the other half" produce
cryptographic evidence instead of a fork, and with equivocation gone,
any two (n-f)-quorums of an n >= 2f+1 roster intersect in at least
one NON-EQUIVOCATING node — which is all the quorum-intersection
arguments in RBC/BBA ever needed from the 2f+1-of-3f+1 arithmetic.

This module is that trusted component, SIMULATED:

- ``AttestationVault`` — one per node, the "TEE".  It keeps the
  monotonic (incarnation, sequence) counter pair and a registry of
  protocol SLOTS it has already attested: (epoch, instance, message
  type) -> digest.  Asked to attest a payload whose slot it has seen
  with a DIFFERENT digest, it REFUSES — the stamp it issues carries a
  ``refused`` flag it cannot be talked out of.  An equivocating
  sender therefore ships self-incriminating frames: honest receivers
  record the counter-fork evidence and reject exactly those frames,
  so at most one variant per slot is ever accepted network-wide and
  equivocation degrades to omission OF THE FORKED STATEMENTS ONLY.
  The sender's non-equivocated traffic (refused=0) keeps flowing on
  purpose: at n = 2f+1 the quorum arithmetic needs every vote the
  adversary did not actually lie about, and dropping a caught
  equivocator's honest frames wholesale starves the very receivers
  that detected it of quorum (observed as a liveness stall in the
  reduced-quorum fuzz band).  Roster-level eviction from the
  accumulated evidence is a reconfig-plane decision, not an ingress
  filter.
- ``AttestationDirectory`` — the cluster-held "TEE NVRAM": vault
  state (counters + slot registry) survives process restarts, so a
  crash-restart cannot launder a second dealing of an already
  attested slot under a fresh counter; restarts bump the incarnation
  instead.  It also aggregates the fork evidence receivers report —
  the surface the fuzzer's reduced-quorum invariants inspect.
- ``AttestingAuthenticator`` — the egress/ingress seam.  It extends
  the pairwise-MAC ``HmacAuthenticator``: every frame leaving
  ``sign``/``sign_wire_many``/``sign_wire_wave`` gains an attestation
  trailer (incarnation, seq, refused, MAC over the frame's signing
  prefix under a key derived from — and rotating with — the pair MAC
  key), one vault pass per egress flush on the columnar wave path;
  every frame entering ``verify_wire``/``verify_wire_many`` must
  carry a valid trailer, with counter regressions (old incarnations,
  replayed or below-window sequence numbers) and refused stamps
  rejected loudly.

What the simulation does and does not model (docs/FAULTS.md "Trust
models"): the seal is STRUCTURAL, not physical.  The semantic-
adversary seam (``protocol.byzantine.Behavior``) rewrites payloads
between the protocol plane and the coalescer — BELOW it, the vault
sees every variant at sign time and the behavior API simply has no
handle on the authenticator, which is exactly the interposition a
hardware TEE enforces.  A fully compromised process that bypasses
its own authenticator is out of model here, as a compromised TEE is
out of model in the papers.
"""

from __future__ import annotations

import hashlib
import hmac
import struct
from typing import Callable, Dict, List, Optional, Set, Tuple

from cleisthenes_tpu.transport.base import (
    HmacAuthenticator,
    _hmac_sha256_fn,
)
from cleisthenes_tpu.transport.message import (
    ATTEST_TAG,
    BbaBatchPayload,
    BbaPayload,
    BbaType,
    BundlePayload,
    EchoBatchPayload,
    Message,
    RbcPayload,
    RbcType,
    ReadyBatchPayload,
    attach_signature,
    signing_bytes,
    signing_bytes_shared,
)

# attestation trailer body: ">IQB" header (incarnation u32, seq u64,
# refused u8) + 32-byte HMAC-SHA256
_ATT_HEADER = struct.Struct(">IQB")
ATTEST_LEN = _ATT_HEADER.size + 32

# Bounded per-link seen-sequence window: within it, out-of-order
# delivery (the fuzzer's reorder/delay/WAN faults are honest-path
# behavior) is accepted and exact duplicates (replay) are rejected;
# below it, everything is rejected as a counter regression.
SEQ_WINDOW = 4096

# domain tag separating attestation MACs from envelope MACs
_ATT_DOMAIN = b"att|"


def attest_key(pair_mac_key: bytes) -> bytes:
    """The sealed attestation key for one (sender, receiver) pair,
    derived from — never equal to — the pair's envelope MAC key.
    Deriving keeps the attestation plane on the existing key schedule
    (reconfig MAC rotation rotates attestation keys for free) while
    the domain tag keeps a valid envelope MAC useless as an
    attestation MAC and vice versa."""
    return hashlib.sha256(b"attest|" + pair_mac_key).digest()


# -- slot extraction --------------------------------------------------------
#
# A SLOT names one protocol statement a correct node makes at most
# once; the digest is the statement's content.  Equivocation == two
# digests for one slot.  Slot choice is deliberately conservative:
#
# - RBC VAL/ECHO/READY bind the Merkle ROOT per (epoch, proposer,
#   type): the per-receiver branch/shard legitimately differ across
#   receivers of one honest broadcast, the root never does.  The type
#   lives IN the slot because a node's READY may legally amplify a
#   quorum root different from the VAL/ECHO root it relayed.
# - BBA AUX/TERM bind the vote value per (epoch, proposer, round,
#   type).  BVAL is deliberately NOT slotted: broadcasting BVAL(0)
#   and BVAL(1) in one round is honest Bracha behavior (both values
#   enter bin_values), so there is no single-statement slot to bind.
# - Coin and decryption shares carry Chaum-Pedersen validity proofs;
#   a forged share is rejected by the proof, and the share value per
#   (instance, index) is deterministic — nothing to equivocate.
# - Catchup/reshare/ingress bodies are either quorum-validated
#   (f+1 byte-identical copies) or anchored by the committed log, so
#   the attested log adds nothing there.


def payload_slots(
    payload, out: List[Tuple[tuple, bytes]]
) -> None:
    """Append the (slot, digest) statements ``payload`` makes."""
    t = type(payload)
    if t is RbcPayload:
        out.append(
            (
                ("rbc", payload.epoch, payload.proposer, int(payload.type)),
                payload.root_hash,
            )
        )
    elif t is BbaPayload:
        if payload.type is not BbaType.BVAL:
            out.append(
                (
                    (
                        "bba",
                        payload.epoch,
                        payload.proposer,
                        payload.round,
                        int(payload.type),
                    ),
                    b"\x01" if payload.value else b"\x00",
                )
            )
    elif t is ReadyBatchPayload:
        for proposer, root in zip(payload.proposers, payload.roots):
            out.append(
                (
                    ("rbc", payload.epoch, proposer, int(RbcType.READY)),
                    root,
                )
            )
    elif t is EchoBatchPayload:
        for proposer, root in zip(payload.proposers, payload.roots):
            out.append(
                (
                    ("rbc", payload.epoch, proposer, int(RbcType.ECHO)),
                    root,
                )
            )
    elif t is BbaBatchPayload:
        if payload.type is not BbaType.BVAL:
            digest = b"\x01" if payload.value else b"\x00"
            for proposer in payload.proposers:
                out.append(
                    (
                        (
                            "bba",
                            payload.epoch,
                            proposer,
                            payload.round,
                            int(payload.type),
                        ),
                        digest,
                    )
                )
    elif t is BundlePayload:
        for item in payload.items:
            payload_slots(item, out)
    # every other payload kind: no attested slots (see block comment)


class _VaultState:
    """One node's persistent TEE state (lives in the directory)."""

    __slots__ = ("incarnation", "seq", "slots", "refusals")

    def __init__(self) -> None:
        self.incarnation = 0
        self.seq = 0
        self.slots: Dict[tuple, bytes] = {}
        self.refusals = 0


class AttestationDirectory:
    """The simulated TEE NVRAM + evidence aggregator (cluster-held).

    ``attach(node_id)`` hands out the node's vault state, bumping the
    incarnation — a restarted process resumes the same slot registry
    under a fresh incarnation, so replays of its pre-crash frames are
    recognizably old and re-attesting a forked slot stays refused.
    ``fork_reports`` maps accused sender -> [(reporter, incarnation,
    seq)] — the counter-fork evidence honest receivers recorded."""

    def __init__(self) -> None:
        self._states: Dict[str, _VaultState] = {}
        self.fork_reports: Dict[str, List[Tuple[str, int, int]]] = {}

    def attach(self, node_id: str) -> "AttestationVault":
        st = self._states.get(node_id)
        if st is None:
            st = _VaultState()
            self._states[node_id] = st
        st.incarnation += 1
        return AttestationVault(node_id, st, self)

    def report_fork(
        self, accused: str, reporter: str, incarnation: int, seq: int
    ) -> None:
        self.fork_reports.setdefault(accused, []).append(
            (reporter, incarnation, seq)
        )

    @property
    def accused(self) -> Set[str]:
        """Senders any honest receiver holds fork evidence against."""
        return set(self.fork_reports)


class AttestationVault:
    """The per-node simulated TEE: monotonic counters + the attested
    slot registry.  ``observe`` registers a payload's statements and
    returns whether ANY of them forks an already attested slot (the
    first digest per slot wins and is never overwritten); ``stamp``
    issues the next (incarnation, seq) pair.  The vault never blocks
    a send — it marks it.  Refusing to emit at all would turn the
    attestation plane into a crash fault injector; emitting with
    ``refused=1`` makes the equivocation attempt self-evident to every
    receiver, which is the detectable-and-excludable contract."""

    __slots__ = ("node_id", "_st", "_dir")

    def __init__(
        self, node_id: str, state: _VaultState, directory: AttestationDirectory
    ) -> None:
        self.node_id = node_id
        self._st = state
        self._dir = directory

    @property
    def incarnation(self) -> int:
        return self._st.incarnation

    @property
    def refusals(self) -> int:
        return self._st.refusals

    def observe(self, payload) -> bool:
        """Register ``payload``'s slots; True iff attestation is
        REFUSED (some slot already holds a different digest)."""
        slots: List[Tuple[tuple, bytes]] = []
        payload_slots(payload, slots)
        st = self._st
        refused = False
        for slot, digest in slots:
            prev = st.slots.get(slot)
            if prev is None:
                st.slots[slot] = digest
            elif prev != digest:
                refused = True
        if refused:
            st.refusals += 1
        return refused

    def stamp(self, refused: bool) -> bytes:
        """Issue the next attestation header (the MAC is appended by
        the authenticator, which holds the per-pair sealed keys)."""
        st = self._st
        st.seq += 1
        return _ATT_HEADER.pack(st.incarnation, st.seq, 1 if refused else 0)

    def report_fork(self, accused: str, incarnation: int, seq: int) -> None:
        self._dir.report_fork(accused, self.node_id, incarnation, seq)


class _LinkState:
    """Per-(sender -> this receiver) counter state: highest sequence
    seen, a bounded recent-sequence set (replay rejection that still
    admits honest reordering), and the gap tally."""

    __slots__ = ("incarnation", "max_seq", "seen")

    def __init__(self) -> None:
        self.incarnation = 0
        self.max_seq = 0
        self.seen: Set[int] = set()


class AttestingAuthenticator(HmacAuthenticator):
    """HmacAuthenticator + the attested sender log (Config.attested_log).

    Outbound: every frame gains the tagged attestation trailer —
    ``header(incarnation, seq, refused) || HMAC(attest_key(pair_key),
    "att|" || header || sha256(signing_prefix))`` — one vault pass per
    payload per egress flush on the columnar ``sign_wire_wave`` path.
    Inbound: frames without a valid trailer are rejected exactly like
    bad envelope MACs; a ``refused`` stamp is counter-fork evidence —
    the receiver reports it to the directory, accuses the sender, and
    rejects THAT frame (the sender's refused=0 traffic still verifies:
    per-statement omission preserves quorum liveness at n = 2f+1, and
    eviction from evidence is the reconfig plane's call, not the
    ingress filter's).  Counter policy per link: old
    incarnations rejected, duplicate sequences rejected (anti-replay),
    sequences older than ``SEQ_WINDOW`` below the high-water mark
    rejected, out-of-order arrivals inside the window accepted (the
    transports legitimately reorder), gaps tallied loudly in
    ``attest_stats``."""

    def __init__(
        self,
        self_id: str,
        peer_keys: "Dict[str, bytes]",
        vault: AttestationVault,
    ):
        super().__init__(self_id, peer_keys)
        if vault.node_id != self_id:
            raise ValueError(
                f"vault of {vault.node_id!r} cannot attest for {self_id!r}"
            )
        self.vault = vault
        self._links: Dict[str, _LinkState] = {}
        self._accused: Set[str] = set()
        # attestation-MAC schedules, cached per pair KEY BYTES so the
        # rotation machinery (primary/alt swaps in the base class)
        # needs no mirroring here
        self._att_fns: Dict[bytes, Callable[[bytes], bytes]] = {}
        # loud-rejection tallies (surfaced by transports' debug dumps
        # and the fuzzer's invariant checks)
        self.attest_stats = {
            "missing": 0,       # frame without a trailer
            "bad_mac": 0,       # trailer MAC failed both pair keys
            "regressions": 0,   # old incarnation / replay / below window
            "gaps": 0,          # sequence holes (dropped frames upstream)
            "forks": 0,         # refused stamps seen (fork evidence);
                                # every one is rejected, never delivered
        }

    # -- key plumbing ------------------------------------------------

    def _att_fn(self, pair_key: bytes) -> Callable[[bytes], bytes]:
        fn = self._att_fns.get(pair_key)
        if fn is None:
            if len(self._att_fns) > 4 * (len(self._peer_keys) + 1):
                self._att_fns.clear()  # bound: rotations retire keys
            fn = _hmac_sha256_fn(attest_key(pair_key))
            self._att_fns[pair_key] = fn
        return fn

    # -- egress ------------------------------------------------------

    def _attestation_for(
        self, header: bytes, prefix_digest: bytes, pair_key: bytes
    ) -> bytes:
        mac = self._att_fn(pair_key)(_ATT_DOMAIN + header + prefix_digest)
        return header + mac

    def sign(self, msg: Message, receiver_id: Optional[str] = None) -> Message:
        signed = super().sign(msg, receiver_id)
        refused = self.vault.observe(msg.payload)
        header = self.vault.stamp(refused)
        digest = hashlib.sha256(signing_bytes(msg)).digest()
        return Message(
            sender_id=signed.sender_id,
            timestamp=signed.timestamp,
            payload=signed.payload,
            signature=signed.signature,
            attestation=self._attestation_for(
                header, digest, self._peer_keys[receiver_id]
            ),
        )

    def sign_wire_many(self, msg: Message, receiver_ids) -> "Dict[str, bytes]":
        frames = super().sign_wire_many(  # staticcheck: allow[DET006] scalar arm
            msg, receiver_ids
        )
        refused = self.vault.observe(msg.payload)
        digest = hashlib.sha256(signing_bytes(msg)).digest()
        out: Dict[str, bytes] = {}
        for rid, frame in frames.items():
            att = self._attestation_for(
                self.vault.stamp(refused), digest, self._peer_keys[rid]
            )
            out[rid] = frame + struct.pack(">BI", ATTEST_TAG, len(att)) + att
        return out

    def sign_wire_wave(self, items, memo=None) -> "List[Dict[str, bytes]]":
        """One attestation pass per egress flush: the wave's envelope
        bodies encode once through the shared memo (unchanged), the
        vault observes each item's payload once, and every receiver
        frame gets its own (seq, MAC) stamp."""
        vault = self.vault
        self_id = self._self_id
        macs = self._macs
        keys = self._peer_keys
        out: "List[Dict[str, bytes]]" = []
        for msg, rids in items:
            if msg.sender_id != self_id:
                raise ValueError(
                    f"cannot sign as {msg.sender_id!r}: this "
                    f"authenticator holds the keys of {self_id!r}"
                )
            sb = (
                signing_bytes_shared(msg, memo)
                if memo is not None
                else signing_bytes(msg)
            )
            digest = hashlib.sha256(sb).digest()
            refused = vault.observe(msg.payload)
            frames: Dict[str, bytes] = {}
            for rid in rids:
                mac_fn = macs.get(rid)
                if mac_fn is None:
                    raise ValueError(f"no pair key with {rid!r}")
                att = self._attestation_for(
                    vault.stamp(refused), digest, keys[rid]
                )
                frames[rid] = attach_signature(sb, mac_fn(sb), att)
            out.append(frames)
        return out

    # -- ingress -----------------------------------------------------

    def _check_attestation(self, msg: Message, prefix_digest: bytes) -> bool:
        sender = msg.sender_id
        stats = self.attest_stats
        att = msg.attestation
        if len(att) != ATTEST_LEN:
            stats["missing"] += 1
            return False
        header, mac = att[: _ATT_HEADER.size], att[_ATT_HEADER.size :]
        body = _ATT_DOMAIN + header + prefix_digest
        key = self._peer_keys.get(sender)
        ok = key is not None and hmac.compare_digest(
            self._att_fn(key)(body), mac
        )
        if not ok:
            alt = self._alt_keys.get(sender)
            ok = alt is not None and hmac.compare_digest(
                self._att_fn(alt)(body), mac
            )
        if not ok:
            stats["bad_mac"] += 1
            return False
        incarnation, seq, refused = _ATT_HEADER.unpack(header)
        if refused:
            # counter-fork evidence: the sender's own vault refused to
            # attest this statement.  Record the accusation and reject
            # the lied statement — and ONLY it.  Dropping the sender's
            # refused=0 traffic too would starve the detecting
            # receivers of quorum at n = 2f+1 (the equivocator's
            # honest votes — its READY relays, coin shares — are load-
            # bearing there), turning detection into a self-inflicted
            # liveness failure.
            stats["forks"] += 1
            self._accused.add(sender)
            self.vault.report_fork(sender, incarnation, seq)
            return False
        link = self._links.get(sender)
        if link is None:
            link = self._links[sender] = _LinkState()
        if incarnation < link.incarnation:
            stats["regressions"] += 1  # pre-restart replay
            return False
        if incarnation > link.incarnation:
            link.incarnation = incarnation
            link.max_seq = 0
            link.seen.clear()
        if seq in link.seen or seq + SEQ_WINDOW <= link.max_seq:
            stats["regressions"] += 1  # replay or below-window
            return False
        link.seen.add(seq)
        if seq > link.max_seq:
            if link.max_seq and seq > link.max_seq + 1:
                stats["gaps"] += seq - link.max_seq - 1
            link.max_seq = seq
            if len(link.seen) > SEQ_WINDOW:
                floor = link.max_seq - SEQ_WINDOW
                link.seen = {s for s in link.seen if s > floor}
        return True

    def accused_senders(self) -> Set[str]:
        """Senders this node holds counter-fork evidence against.
        Evidence, not a frame filter: their refused=0 traffic still
        verifies (test/fuzz inspection surface; roster eviction from
        this evidence belongs to the reconfig plane)."""
        return set(self._accused)

    def verify(self, msg: Message) -> bool:
        if not super().verify(msg):
            return False
        return self._check_attestation(
            msg, hashlib.sha256(signing_bytes(msg)).digest()
        )

    def verify_wire(self, msg: Message, signing_prefix: bytes) -> bool:
        if not super().verify_wire(msg, signing_prefix):
            return False
        return self._check_attestation(
            msg, hashlib.sha256(signing_prefix).digest()
        )

    def verify_wire_many(self, msgs, signing_prefixes) -> "List[bool]":
        base = super().verify_wire_many(msgs, signing_prefixes)
        return [
            ok
            and self._check_attestation(
                msg, hashlib.sha256(prefix).digest()
            )
            for ok, msg, prefix in zip(base, msgs, signing_prefixes)
        ]


__all__ = [
    "ATTEST_LEN",
    "SEQ_WINDOW",
    "attest_key",
    "payload_slots",
    "AttestationDirectory",
    "AttestationVault",
    "AttestingAuthenticator",
]
