"""ACS: asynchronous common subset = N x RBC + N x BBA.

The component the reference names as required but never started
("TODO : HoneyBadger must have ACS", reference honeybadger.go:19;
composition depicted in img/acs.png and described at
docs/HONEYBADGER-EN.md:85-89):

  - input v        -> RBC_self.propose(v)
  - RBC_j delivers -> input 1 to BBA_j (if BBA_j has no input yet)
  - n-f BBAs output 1 -> input 0 to every BBA without input
  - all N BBAs decided -> wait for RBC_j delivery for every j with
    BBA_j = 1 (guaranteed by RBC totality: some correct node delivered
    RBC_j, or no correct node would have voted 1) -> output the union
    {j: value_j} for BBA_j = 1

Properties (docs/HONEYBADGER-EN.md:34-37): Validity (output contains
the inputs of >= n-2f correct nodes), Agreement (all correct nodes
output the same set), Totality (all correct nodes eventually output).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Set

import numpy as np

from cleisthenes_tpu.config import Config
from cleisthenes_tpu.ops.backend import BatchCrypto
from cleisthenes_tpu.ops.coin import CommonCoin
from cleisthenes_tpu.ops.tpke import ThresholdSecretShare
from cleisthenes_tpu.protocol.bba import BBA
from cleisthenes_tpu.protocol.rbc import RBC
from cleisthenes_tpu.transport.message import (
    BbaPayload,
    BbaType,
    CoinPayload,
    RbcPayload,
)


class CoinRowStore:
    """Round-keyed columnar coin-share rows for one epoch's N BBAs.

    The round-5 profile showed the per-share coin ingestion chain
    (batch handler -> per-instance dispatch -> pool add, ~573k scalar
    calls per N=64 epoch) as the largest protocol cost after echoes.
    This store replaces it with ROW semantics: one sender's whole
    share fan-out (a CoinBatchPayload, or a width-1 single) is ONE
    append here, and per-instance pools materialize shares lazily —
    bounded to the f+1 the threshold needs on the fast path, and
    completely at every hub-flush boundary, where pools therefore
    hold exactly what the eager path would have held (the burn/
    replacement/verdict logic is untouched).

    Pools are NOT fully materialized at flush time: BBA._top_up_coin
    pulls only until the threshold is index-coverable, and surplus
    rows stay parked here; the burn/replacement logic re-pulls on the
    re-marked flush round, and the per-instance ``watch`` re-notifies
    when a replayed index leaves a threshold-size pool under-covered.

    DoS bounds: rounds are capped at bba.MAX_ROUNDS (bounding the
    by_round table); per-sender FRESH rows are capped per round at
    2n (an honest sender emits at most one share per instance per
    round — n width-1 singles in the worst schedule); replayed frames
    are fresh-filtered before any cap or count is touched; and
    per-instance dedup stays in SharePool (first share per sender
    wins), so a Byzantine sender still only ever burns its own slot.
    """

    __slots__ = (
        "members",
        "threshold",
        "_iidx",
        "by_round",
        "_col_memo",
        "_watch_rnd",
    )
    _COL_MEMO_CAP = 4096
    MAX_COIN_ROW_ROUNDS = 256

    def __init__(self, members: Sequence[str], threshold: int) -> None:
        self.members = list(members)
        self.threshold = threshold
        self._iidx = {p: i for i, p in enumerate(self.members)}
        # rnd -> [rows, counts, notified, (sender,inst) seen,
        #         per-sender fresh-row counts]
        self.by_round: Dict[int, list] = {}
        # id(proposers) -> (proposers, {proposer: column}, idx array) —
        # the codec payload memo shares one proposers tuple across a
        # broadcast's receivers, so these build once per wire payload
        # (width-1 singles bypass the memo entirely: each single is a
        # fresh tuple that could never hit and would churn the table)
        self._col_memo: dict = {}
        # per-instance watched ROUND (-1 = off): re-notify arrivals
        # for exactly the round whose pool is threshold-size but
        # index-under-covered — the coin analog of the round-4
        # dec-share crossing-stall fix.  Round-scoped, so a watch can
        # never burn a DIFFERENT round's one-shot crossing flag.
        self._watch_rnd = np.full(len(self.members), -1, dtype=np.int64)

    def watch_on(self, proposer_index: int, rnd: int) -> None:
        self._watch_rnd[proposer_index] = rnd

    def watch_off(self, proposer_index: int) -> None:
        self._watch_rnd[proposer_index] = -1

    def add(
        self, sender: str, rnd: int, index: int, proposers, d, e, z
    ) -> list:
        """Append one sender row; returns the member names whose
        DISTINCT-SENDER share count just crossed the threshold (fires
        at most once per (round, instance)) plus any round-watched
        instances the row contains.

        Counting must be per (sender, instance) — exactly the dedup
        SharePool applies — or a replayed/duplicated frame inflates a
        count past the threshold with too few distinct senders, burns
        the one-shot crossing, and the real quorum later arrives
        unannounced (liveness stall found by the n=7 coalition test)."""
        n = len(self.members)
        if not (1 <= index <= n):
            return []  # a bad Shamir index must not inflate counts
        if not (0 <= rnd < self.MAX_COIN_ROW_ROUNDS):
            return []  # bounds the by_round table (DoS): ~4KB+n^2
            # bits of state per allocated round, and a coin decides
            # each round w.p. 1/2 — P(honest round >= 256) ~ 2^-256
        si = self._iidx.get(sender)
        if si is None:
            return []
        ent = self.by_round.get(rnd)
        if ent is None:
            ent = self.by_round[rnd] = [
                [],
                np.zeros(n, dtype=np.int32),
                np.zeros(n, dtype=bool),
                np.zeros((n, n), dtype=bool),  # (sender, inst) seen
                {},  # sender -> fresh rows this round
            ]
        rows, counts, notified, seen, sender_rows = ent
        if len(proposers) == 1:
            ci = self._iidx.get(proposers[0])
            idx = (
                np.asarray([ci], dtype=np.int64)
                if ci is not None
                else np.empty(0, dtype=np.int64)
            )
        else:
            idx = self._memo(proposers)[2]
        fresh = idx[~seen[si, idx]]
        if fresh.size == 0:
            return []  # pure replay: consumes no cap, changes nothing
        # freshness-gated per-round cap: an honest sender emits at
        # most one share per instance per round, i.e. <= n fresh rows
        # even in the all-singles worst schedule
        nrows = sender_rows.get(sender, 0)
        if nrows >= 2 * n:
            return []
        sender_rows[sender] = nrows + 1
        rows.append((sender, index, proposers, d, e, z))
        seen[si, fresh] = True
        counts[fresh] += 1
        after = counts[fresh]
        crossed_thr = fresh[(after >= self.threshold) & ~notified[fresh]]
        notified[crossed_thr] = True  # the one-shot flag: thresholds only
        watched = fresh[self._watch_rnd[fresh] == rnd]
        if crossed_thr.size == 0 and watched.size == 0:
            return []
        members = self.members
        out = [members[i] for i in crossed_thr]
        for i in watched:
            if i not in crossed_thr:
                out.append(members[i])
        return out

    def count(self, rnd: int, proposer_index: int) -> int:
        ent = self.by_round.get(rnd)
        return int(ent[1][proposer_index]) if ent is not None else 0

    def _memo(self, proposers):
        ent = self._col_memo.get(id(proposers))
        if ent is None or ent[0] is not proposers:
            m = {p: i for i, p in enumerate(proposers)}
            iidx = self._iidx
            idx = np.asarray(
                [iidx[p] for p in proposers if p in iidx],
                dtype=np.int64,
            )
            if len(self._col_memo) >= self._COL_MEMO_CAP:
                self._col_memo.clear()
            ent = (proposers, m, idx)
            self._col_memo[id(proposers)] = ent
        return ent

    def col(self, proposers, me: str):
        """Column of ``me`` in a row's proposers tuple (id-memoized;
        width-1 rows bypass the memo — see __init__)."""
        if len(proposers) == 1:
            return 0 if proposers[0] == me else None
        return self._memo(proposers)[1].get(me)


class ACS:
    """One common-subset instance (one per epoch)."""

    def __init__(
        self,
        *,
        config: Config,
        crypto: BatchCrypto,
        epoch: int,
        owner: str,
        member_ids: Sequence[str],
        coin: CommonCoin,
        coin_secret: ThresholdSecretShare,
        out,
        hub=None,
        coin_issue_sink=None,
        trace=None,
        metrics=None,
        scope=None,
    ) -> None:
        self.n = config.n
        self.f = config.f
        self.epoch = epoch
        self.owner = owner
        # the hub-scope owner key (defaults to ``owner``): lane
        # shard-out (Config.lanes) runs S sibling HoneyBadger
        # instances per node against ONE shared hub, and each lane's
        # epoch GC must only drop ITS OWN epoch's clients — so lanes
        # > 0 qualify the scope with the lane id while ``owner``
        # keeps its protocol meaning (the member id this ACS
        # proposes under).  Lane 0 passes scope == owner, keeping
        # the single-lane scope keys byte-identical.
        self.scope = owner if scope is None else scope
        self.members: List[str] = sorted(member_ids)
        self._member_set = frozenset(self.members)
        # fn(epoch, {proposer: value}) fired exactly once
        self.on_output: Optional[Callable[[int, Dict[str, bytes]], None]] = None

        if hub is None:  # standalone use: one shared hub per ACS so
            # the epoch's 2N instances still batch together
            from cleisthenes_tpu.protocol.hub import CryptoHub

            hub = CryptoHub(crypto)
        self.hub = hub
        # one vote bank per epoch: every BBA instance's BVAL/AUX state
        # as struct-of-arrays, so columnar waves update vectorized
        # (protocol.votebank)
        from cleisthenes_tpu.protocol.votebank import VoteBank

        self.bank = VoteBank(
            self.members, config.f, metrics=metrics,
            quorum_large=config.quorum_large,
        )
        # the RBC twin of the vote bank: ECHO/READY receipt state for
        # every instance as struct-of-arrays (protocol.echobank), so
        # columnar echo/ready waves update vectorized too
        from cleisthenes_tpu.protocol.echobank import EchoBank

        self.echo_bank = EchoBank(
            self.members, config.f, metrics=metrics,
            quorum_large=config.quorum_large,
        )
        self.rbcs: Dict[str, RBC] = {}
        self.bbas: Dict[str, BBA] = {}
        for index, proposer in enumerate(self.members):
            rbc = RBC(
                config=config,
                crypto=crypto,
                epoch=epoch,
                proposer=proposer,
                owner=owner,
                member_ids=self.members,
                out=out,
                hub=hub,
                bank=self.echo_bank,
                index=index,
                trace=trace,
                metrics=metrics,
                scope=self.scope,
            )
            rbc.on_deliver = self._on_rbc_deliver
            self.rbcs[proposer] = rbc
            bba = BBA(
                config=config,
                epoch=epoch,
                proposer=proposer,
                owner=owner,
                member_ids=self.members,
                coin=coin,
                coin_secret=coin_secret,
                out=out,
                hub=hub,
                bank=self.bank,
                index=index,
                coin_issue_sink=coin_issue_sink,
                trace=trace,
                metrics=metrics,
                scope=self.scope,
            )
            bba.on_decide = self._on_bba_decide
            self.bbas[proposer] = bba

        self._input_given: Set[str] = set()  # BBAs we provided input to
        self._zero_phase = False  # n-f ones seen, 0s injected
        self._output: Optional[Dict[str, bytes]] = None
        # columnar coin ingestion: every coin share (batch or single)
        # lands here as a row; BBAs pull lazily (see CoinRowStore)
        self._coin_rows = CoinRowStore(self.members, coin.pub.threshold)
        self._coin_threshold = coin.pub.threshold
        for bba in self.bbas.values():
            bba.coin_rows = self._coin_rows

    # -- public API --------------------------------------------------------

    def input(self, value: bytes) -> None:
        """Propose this node's value (the HoneyBadger TPKE ciphertext,
        docs/HONEYBADGER-EN.md:58-61)."""
        self.rbcs[self.owner].propose(value)

    def output(self) -> Optional[Dict[str, bytes]]:
        return self._output

    @property
    def done(self) -> bool:
        return self._output is not None

    def handle_message(self, sender: str, payload) -> None:
        """Route by payload kind + instance (proposer)."""
        proposer = getattr(payload, "proposer", None)
        if proposer not in self.rbcs:
            return
        if isinstance(payload, RbcPayload):
            self.rbcs[proposer].handle_message(sender, payload)
        elif isinstance(payload, CoinPayload):
            # width-1 row: singles and batches share ONE ingestion
            # path, so threshold crossing is purely row-count based
            if sender in self._member_set:
                self._coin_row(
                    sender,
                    payload.round,
                    payload.index,
                    (proposer,),
                    (payload.d,),
                    (payload.e,),
                    (payload.z,),
                )
        elif isinstance(payload, BbaPayload):
            self.bbas[proposer].handle_message(sender, payload)

    def _coin_row(
        self, sender: str, rnd: int, index: int, proposers, d, e, z
    ) -> None:
        crossed = self._coin_rows.add(
            sender, rnd, index, proposers, d, e, z
        )
        for proposer in crossed:
            bba = self.bbas.get(proposer)
            if bba is not None and not bba.halted and bba.round == rnd:
                bba.on_coin_rows(rnd)

    # -- columnar wave payloads (transport.message batch kinds) ------------

    def handle_bba_batch(self, sender: str, p) -> None:
        """One vote fanned across many instances: BVAL/AUX go through
        the vectorized bank; TERM (a handful per instance, ever) stays
        scalar (transport._columnarize)."""
        t, rnd, value = p.type, p.round, p.value
        if t == BbaType.TERM:
            bbas = self.bbas
            for proposer in p.proposers:
                bba = bbas.get(proposer)
                if bba is not None:
                    bba.handle_vote(sender, t, rnd, value)
            return
        self.bank.batch_vote(
            sender, t == BbaType.BVAL, rnd, value, p.proposers
        )

    def handle_coin_batch(self, sender: str, p) -> None:
        """One sender's coin shares fanned across instances: ONE row
        append in the CoinRowStore — per-instance pools pull lazily
        (replacing the per-share dispatch chain the round-5 profile
        put at ~573k scalar calls per N=64 epoch)."""
        if sender not in self.bank.sidx:
            return
        self._coin_row(
            sender, p.round, p.index, p.proposers, p.d, p.e, p.z
        )

    def handle_ready_batch(self, sender: str, p) -> None:
        """One sender's READYs fanned across instances
        (ReadyBatchPayload): membership, delivered-instance filtering,
        dedup and per-(root, instance) counting all run vectorized in
        the EchoBank; only threshold crossings reach RBC logic."""
        self.echo_bank.batch_ready(sender, p.proposers, p.roots)

    def handle_echo_batch(self, sender: str, p) -> None:
        """One sender's ECHOes fanned across instances
        (EchoBatchPayload): the membership + delivered + dedup gates
        hoist into the EchoBank's vectorized row filters; surviving
        items park per instance via RBC's claim logic."""
        self.echo_bank.batch_echo(
            sender, p.shard_index, p.proposers, p.roots, p.branches, p.shards
        )

    # -- wave-routed ingest columns (protocol.router.WaveRouter) -----------

    def handle_vote_wave(self, items) -> None:
        """One delivery wave's BVAL/AUX/TERM votes across ALL senders
        and instances (wave routing: one handler dispatch for the
        whole column).  Non-TERM votes group by (type, round, value)
        — one sender's columnar batch and a width-1 scalar vote are
        the same row shape — and each group updates the VoteBank
        wholesale in a single vectorized pass (VoteBank.wave_vote).
        TERM stays scalar (a handful per instance, ever)."""
        bank = self.bank
        sidx = bank.sidx
        bbas = self.bbas
        groups: Dict[tuple, list] = {}
        for sender, t, rnd, value, proposers in items:
            if t == BbaType.TERM:
                for proposer in proposers:
                    bba = bbas.get(proposer)
                    if bba is not None:
                        bba.handle_vote(sender, t, rnd, value)
                continue
            si = sidx.get(sender)
            if si is None:
                continue
            key = (t, rnd, value)
            rows = groups.get(key)
            if rows is None:
                groups[key] = [(si, sender, proposers)]
            else:
                rows.append((si, sender, proposers))
        for (t, rnd, value), rows in groups.items():
            bank.wave_vote(t == BbaType.BVAL, rnd, value, rows)

    def handle_echo_wave(self, items) -> None:
        """One delivery wave's ECHOes across ALL senders: each row is
        one sender's fan-out (columnar batch, or a width-1 scalar
        ECHO) and runs the EchoBank's vectorized membership/delivered/
        dedup filters — one handler dispatch instead of one per
        payload."""
        batch_echo = self.echo_bank.batch_echo
        for sender, shard_index, proposers, roots, branches, shards in items:
            batch_echo(
                sender, shard_index, proposers, roots, branches, shards
            )

    def handle_ready_wave(self, items) -> None:
        """One delivery wave's READYs across ALL senders (row shape as
        in handle_echo_wave)."""
        batch_ready = self.echo_bank.batch_ready
        for sender, proposers, roots in items:
            batch_ready(sender, proposers, roots)

    def handle_coin_wave(self, items) -> None:
        """One delivery wave's coin shares across ALL senders: each
        row is one (sender, round) share fan-out and lands as ONE
        CoinRowStore append (per-instance pools pull lazily)."""
        sidx = self.bank.sidx
        for sender, rnd, index, proposers, d, e, z in items:
            if sender in sidx:
                self._coin_row(sender, rnd, index, proposers, d, e, z)

    # -- composition rules (img/acs.png) -----------------------------------

    def _on_rbc_deliver(self, proposer: str, value: bytes) -> None:
        # deliver_j -> BBA_j(1), unless we already voted (possibly 0)
        if proposer not in self._input_given:
            self._input_given.add(proposer)
            self.bbas[proposer].input(True)
        self._maybe_output()

    def _on_bba_decide(self, proposer: str, decision: bool) -> None:
        ones = sum(1 for b in self.bbas.values() if b.result() is True)
        if ones >= self.n - self.f and not self._zero_phase:
            # n-f BBAs delivered 1: vote 0 on everything still open
            self._zero_phase = True
            for p in self.members:
                if p not in self._input_given:
                    self._input_given.add(p)
                    self.bbas[p].input(False)
        self._maybe_output()

    def _maybe_output(self) -> None:
        if self._output is not None:
            return
        if any(not b.done for b in self.bbas.values()):
            return
        accepted = [p for p in self.members if self.bbas[p].result() is True]
        # totality: every 1-decided RBC will deliver; wait for them
        if any(not self.rbcs[p].delivered for p in accepted):
            return
        self._output = {p: self.rbcs[p].value() for p in accepted}
        if self.on_output is not None:
            self.on_output(self.epoch, dict(self._output))


__all__ = ["ACS"]
