"""ACS: asynchronous common subset = N x RBC + N x BBA.

The component the reference names as required but never started
("TODO : HoneyBadger must have ACS", reference honeybadger.go:19;
composition depicted in img/acs.png and described at
docs/HONEYBADGER-EN.md:85-89):

  - input v        -> RBC_self.propose(v)
  - RBC_j delivers -> input 1 to BBA_j (if BBA_j has no input yet)
  - n-f BBAs output 1 -> input 0 to every BBA without input
  - all N BBAs decided -> wait for RBC_j delivery for every j with
    BBA_j = 1 (guaranteed by RBC totality: some correct node delivered
    RBC_j, or no correct node would have voted 1) -> output the union
    {j: value_j} for BBA_j = 1

Properties (docs/HONEYBADGER-EN.md:34-37): Validity (output contains
the inputs of >= n-2f correct nodes), Agreement (all correct nodes
output the same set), Totality (all correct nodes eventually output).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Set

from cleisthenes_tpu.config import Config
from cleisthenes_tpu.ops.backend import BatchCrypto
from cleisthenes_tpu.ops.coin import CommonCoin
from cleisthenes_tpu.ops.tpke import ThresholdSecretShare
from cleisthenes_tpu.protocol.bba import BBA
from cleisthenes_tpu.protocol.rbc import RBC
from cleisthenes_tpu.transport.message import (
    BbaPayload,
    BbaType,
    CoinPayload,
    RbcPayload,
)


class ACS:
    """One common-subset instance (one per epoch)."""

    def __init__(
        self,
        *,
        config: Config,
        crypto: BatchCrypto,
        epoch: int,
        owner: str,
        member_ids: Sequence[str],
        coin: CommonCoin,
        coin_secret: ThresholdSecretShare,
        out,
        hub=None,
        coin_issue_sink=None,
    ) -> None:
        self.n = config.n
        self.f = config.f
        self.epoch = epoch
        self.owner = owner
        self.members: List[str] = sorted(member_ids)
        # fn(epoch, {proposer: value}) fired exactly once
        self.on_output: Optional[Callable[[int, Dict[str, bytes]], None]] = None

        if hub is None:  # standalone use: one shared hub per ACS so
            # the epoch's 2N instances still batch together
            from cleisthenes_tpu.protocol.hub import CryptoHub

            hub = CryptoHub(crypto)
        self.hub = hub
        # one vote bank per epoch: every BBA instance's BVAL/AUX state
        # as struct-of-arrays, so columnar waves update vectorized
        # (protocol.votebank)
        from cleisthenes_tpu.protocol.votebank import VoteBank

        self.bank = VoteBank(self.members, config.f)
        self.rbcs: Dict[str, RBC] = {}
        self.bbas: Dict[str, BBA] = {}
        for index, proposer in enumerate(self.members):
            rbc = RBC(
                config=config,
                crypto=crypto,
                epoch=epoch,
                proposer=proposer,
                owner=owner,
                member_ids=self.members,
                out=out,
                hub=hub,
            )
            rbc.on_deliver = self._on_rbc_deliver
            self.rbcs[proposer] = rbc
            bba = BBA(
                config=config,
                epoch=epoch,
                proposer=proposer,
                owner=owner,
                member_ids=self.members,
                coin=coin,
                coin_secret=coin_secret,
                out=out,
                hub=hub,
                bank=self.bank,
                index=index,
                coin_issue_sink=coin_issue_sink,
            )
            bba.on_decide = self._on_bba_decide
            self.bbas[proposer] = bba

        self._input_given: Set[str] = set()  # BBAs we provided input to
        self._zero_phase = False  # n-f ones seen, 0s injected
        self._output: Optional[Dict[str, bytes]] = None

    # -- public API --------------------------------------------------------

    def input(self, value: bytes) -> None:
        """Propose this node's value (the HoneyBadger TPKE ciphertext,
        docs/HONEYBADGER-EN.md:58-61)."""
        self.rbcs[self.owner].propose(value)

    def output(self) -> Optional[Dict[str, bytes]]:
        return self._output

    @property
    def done(self) -> bool:
        return self._output is not None

    def handle_message(self, sender: str, payload) -> None:
        """Route by payload kind + instance (proposer)."""
        proposer = getattr(payload, "proposer", None)
        if proposer not in self.rbcs:
            return
        if isinstance(payload, RbcPayload):
            self.rbcs[proposer].handle_message(sender, payload)
        elif isinstance(payload, (BbaPayload, CoinPayload)):
            self.bbas[proposer].handle_message(sender, payload)

    # -- columnar wave payloads (transport.message batch kinds) ------------

    def handle_bba_batch(self, sender: str, p) -> None:
        """One vote fanned across many instances: BVAL/AUX go through
        the vectorized bank; TERM (a handful per instance, ever) stays
        scalar (transport._columnarize)."""
        t, rnd, value = p.type, p.round, p.value
        if t == BbaType.TERM:
            bbas = self.bbas
            for proposer in p.proposers:
                bba = bbas.get(proposer)
                if bba is not None:
                    bba.handle_vote(sender, t, rnd, value)
            return
        self.bank.batch_vote(
            sender, t == BbaType.BVAL, rnd, value, p.proposers
        )

    def handle_coin_batch(self, sender: str, p) -> None:
        """One sender's coin shares fanned across instances: the
        roster-membership check hoists out of the loop (handle_coin
        re-checks per call; at N=64 the per-share frozenset probe and
        the halted re-check were ~5% of an epoch).

        A vectorized bank-row pre-filter (drop post-reveal/stale rows
        in numpy before the Python loop) was tried and measured NO
        BETTER (within this box's noise): ~8 small-array numpy ops
        per batch roughly cancel the ~2.5 us scalar early-returns
        they avoid at this batch width."""
        if sender not in self.bank.sidx:
            return
        bbas = self.bbas
        rnd, index = p.round, p.index
        d, e, z = p.d, p.e, p.z
        for i, proposer in enumerate(p.proposers):
            bba = bbas.get(proposer)
            if bba is not None and not bba.halted:
                bba.handle_coin_fast(sender, rnd, index, d[i], e[i], z[i])

    def handle_ready_batch(self, sender: str, p) -> None:
        rbcs = self.rbcs
        for i, proposer in enumerate(p.proposers):
            rbc = rbcs.get(proposer)
            if rbc is not None:
                rbc.handle_ready_root(sender, p.roots[i])

    # -- composition rules (img/acs.png) -----------------------------------

    def _on_rbc_deliver(self, proposer: str, value: bytes) -> None:
        # deliver_j -> BBA_j(1), unless we already voted (possibly 0)
        if proposer not in self._input_given:
            self._input_given.add(proposer)
            self.bbas[proposer].input(True)
        self._maybe_output()

    def _on_bba_decide(self, proposer: str, decision: bool) -> None:
        ones = sum(1 for b in self.bbas.values() if b.result() is True)
        if ones >= self.n - self.f and not self._zero_phase:
            # n-f BBAs delivered 1: vote 0 on everything still open
            self._zero_phase = True
            for p in self.members:
                if p not in self._input_given:
                    self._input_given.add(p)
                    self.bbas[p].input(False)
        self._maybe_output()

    def _maybe_output(self) -> None:
        if self._output is not None:
            return
        if any(not b.done for b in self.bbas.values()):
            return
        accepted = [p for p in self.members if self.bbas[p].result() is True]
        # totality: every 1-decided RBC will deliver; wait for them
        if any(not self.rbcs[p].delivered for p in accepted):
            return
        self._output = {p: self.rbcs[p].value() for p in accepted}
        if self.on_output is not None:
            self.on_output(self.epoch, dict(self._output))


__all__ = ["ACS"]
