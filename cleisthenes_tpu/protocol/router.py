"""WaveRouter: one batch handler dispatch per message kind per wave.

PR 9 columnarized the delivery plane's decode+MAC work (4688->533
frames, 4688->308 verifies per seeded n16 epoch) and the transport
stage share did not move — because the remaining mass is per-payload
handler dispatch: each decoded frame still walked the
``HoneyBadger.serve_request -> _serve_payload -> ACS.handle_message ->
RBC/BBA.handle_message`` Python call chain one payload at a time.

This module is the inbound twin of the PR-7 outbound wave work at the
ROUTING layer.  A transport in wave mode hands the router one delivery
wave's already-decoded, already-MAC-verified frames in a single
``serve_wave`` call; the router demuxes every payload in one pass into
typed ingest columns keyed by ``(message kind, epoch)`` and then makes
ONE batch handler invocation per (kind, wave) into the ``*_wave()``
entry points on ACS (which write EchoBank/VoteBank slots wholesale)
and the dec-share wave handler on HoneyBadger.  Stale/future-epoch
filtering happens once per column against the demux window instead of
once per payload; far-ahead traffic still feeds the CATCHUP renudge
counter payload-by-payload, so the traffic-clocked retry cadence is
identical to the scalar arm's.

The scalar ``handle_message`` chain stays live behind
``Config.wave_routing=False`` as the byte-equivalence comparison arm
(tests/test_delivery_equivalence.py): same seeded schedule, either
routing discipline, byte-identical committed ledgers.

Ordering contract: within a wave, columns dispatch in first-occurrence
order of their (kind, epoch) key — deterministic given the transport's
(seeded or FIFO) delivery order, independent of PYTHONHASHSEED.
CATCHUP payloads are order-sensitive barriers: the router flushes the
columns accumulated so far, dispatches the catch-up payload through
the scalar chain, and keeps demuxing — catch-up traffic is rare, so a
steady-state wave is one flush.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from cleisthenes_tpu.protocol.honeybadger import (
    _logical_count as _logical,
)
from cleisthenes_tpu.transport.message import (
    BbaBatchPayload,
    BbaPayload,
    BundlePayload,
    CatchupOrdPayload,
    CatchupReqPayload,
    CatchupRespPayload,
    CoinBatchPayload,
    CoinPayload,
    DecShareBatchPayload,
    DecSharePayload,
    EchoBatchPayload,
    LanePayload,
    RbcPayload,
    RbcType,
    ReadyBatchPayload,
    ResharePayload,
)

# the scalar chain handles these outside the epoch demux entirely
# (CATCHUP state transfer + reconfig gossip: epoch-unscoped, rare,
# and order-sensitive relative to the columns around them)
_CATCHUP_PAYLOADS = (
    CatchupReqPayload,
    CatchupRespPayload,
    CatchupOrdPayload,
    ResharePayload,
)

# kind tags (the router's column vocabulary); dispatch happens in
# first-occurrence order of (kind, epoch), never in tag order
_K_VAL = "val"
_K_ECHO = "echo"
_K_READY = "ready"
_K_VOTE = "vote"
_K_COIN = "coin"
_K_DEC = "dec"


class WaveRouter:
    """Per-node demux of delivery waves into typed ingest columns.

    Owned by (and coupled to) one HoneyBadger: the router reads the
    node's epoch window through ``_epoch_state`` and dispatches into
    the same protocol objects the scalar chain reaches — it changes
    HOW MANY Python calls carry a wave, never what state they write.

    Lock audit (ISSUE 17): deliberately unlocked.  The router holds no
    mutable state of its own (``__slots__`` is one back-pointer) and
    ``serve_wave``/``route`` run only on the dispatcher thread that
    serializes ALL protocol mutation; a ``@guarded_by`` here would
    declare a lock no second thread can ever contend.  The
    interprocedural sweep (CONC003/CONC004) confirms: no ``*_locked``
    callee and no blocking call is reachable from ``route``.
    """

    __slots__ = ("_hb",)

    def __init__(self, hb) -> None:
        self._hb = hb

    def route(self, msgs) -> None:
        """Demux one wave of verified Messages and dispatch each
        (kind, epoch) column once."""
        hb = self._hb
        metrics = hb.metrics
        metrics.waves_routed.inc()
        tr = hb.trace
        t0 = 0.0 if tr is None else tr.now()
        d0 = metrics.handler_dispatches.value if tr is not None else 0
        # (kind, epoch) -> item column, first-occurrence order (dicts
        # preserve insertion order; keys are tuples of str/int, so the
        # composition is PYTHONHASHSEED-independent)
        cols: Dict[Tuple[str, int], List] = {}
        logical = 0
        n_payloads = 0
        for msg in msgs:
            sender = msg.sender_id
            payload = msg.payload
            if payload.__class__ is BundlePayload:
                items = payload.items
            else:
                items = (payload,)
            for p in items:
                n_payloads += 1
                logical += _logical(p)
                if not self._demux(cols, sender, p):
                    # order-sensitive barrier (CATCHUP): flush what
                    # accumulated, scalar-dispatch, keep demuxing
                    self._dispatch_all(cols)
                    cols = {}
                    hb._serve_payload(sender, p)
        metrics.msgs_in.inc(logical)
        self._dispatch_all(cols)
        if tr is not None:
            tr.complete(
                "router",
                "route",
                t0,
                frames=len(msgs),
                payloads=n_payloads,
                dispatches=metrics.handler_dispatches.value - d0,
            )

    # -- demux -------------------------------------------------------------

    def _demux(self, cols, sender: str, p) -> bool:
        """Append one payload to its (kind, epoch) column — or, for a
        lane-wrapped payload (Config.lanes > 1), to its
        (kind, epoch, lane) column; False when the payload is an
        ordering barrier the caller must flush for."""
        cls = p.__class__
        lane = 0
        if cls is LanePayload:
            lane = p.lane
            if not (0 < lane < len(self._hb.lanes)):
                return True  # unknown lane: drop, like the scalar arm
            p = p.inner
            cls = p.__class__
            if cls in _CATCHUP_PAYLOADS:
                # barrier: the scalar chain demuxes the WRAPPED
                # payload into the sibling (route() passes the
                # original payload object)
                return False
        if cls is BbaBatchPayload:
            item = (sender, p.type, p.round, p.value, p.proposers)
            key = (_K_VOTE, p.epoch)
        elif cls is CoinBatchPayload:
            item = (sender, p.round, p.index, p.proposers, p.d, p.e, p.z)
            key = (_K_COIN, p.epoch)
        elif cls is EchoBatchPayload:
            item = (
                sender, p.shard_index, p.proposers, p.roots,
                p.branches, p.shards,
            )
            key = (_K_ECHO, p.epoch)
        elif cls is ReadyBatchPayload:
            item = (sender, p.proposers, p.roots)
            key = (_K_READY, p.epoch)
        elif cls is DecShareBatchPayload or cls is DecSharePayload:
            item = (sender, p)
            key = (_K_DEC, p.epoch)
        elif cls is RbcPayload:
            t = p.type
            if t == RbcType.ECHO:
                item = (
                    sender, p.shard_index, (p.proposer,),
                    (p.root_hash,), (p.branch,), (p.shard,),
                )
                key = (_K_ECHO, p.epoch)
            elif t == RbcType.READY:
                item = (sender, (p.proposer,), (p.root_hash,))
                key = (_K_READY, p.epoch)
            else:  # VAL: bulky one-per-instance payloads stay scalar
                item = (sender, p)
                key = (_K_VAL, p.epoch)
        elif cls is BbaPayload:
            item = (sender, p.type, p.round, p.value, (p.proposer,))
            key = (_K_VOTE, p.epoch)
        elif cls is CoinPayload:
            item = (
                sender, p.round, p.index, (p.proposer,),
                (p.d,), (p.e,), (p.z,),
            )
            key = (_K_COIN, p.epoch)
        elif cls in _CATCHUP_PAYLOADS:
            return False
        else:  # unknown/epochless payloads drop, like the scalar arm
            return True
        if lane:
            # lane columns stay distinct but ride the SAME wave: one
            # route() pass, one _dispatch_all — S lanes' traffic per
            # wave without S× routing passes
            key = key + (lane,)
        col = cols.get(key)
        if col is None:
            cols[key] = [item]
        else:
            col.append(item)
        return True

    # -- dispatch ----------------------------------------------------------

    def _dispatch_all(self, cols) -> None:
        for key, items in cols.items():
            if len(key) == 3:  # (kind, epoch, lane): a sibling's column
                sib = self._hb.lanes[key[2]]
                sib._idle_rx += len(items)  # its stall-watchdog clock
                sib._router._dispatch(key[0], key[1], items)
            else:
                self._dispatch(key[0], key[1], items)

    def _dispatch(self, kind: str, epoch: int, items) -> None:
        """One column = one handler invocation (the counter perfgate
        gates).  The demux window is checked HERE — column granularity
        — because an earlier column's dispatch may advance the epoch
        frontier mid-wave, exactly like a handler turn does on the
        scalar arm."""
        hb = self._hb
        es = hb._epochs.get(epoch) or hb._epoch_state(epoch)
        if es is None:  # outside the sliding window, or not a member
            if epoch > hb.epoch + hb.EPOCH_HORIZON or (
                epoch > hb.epoch
                and not hb.roster_for(epoch).local
            ):
                # per-payload sightings: the CATCHUP renudge cadence
                # is counted in payloads, and must tick identically
                # under either routing arm (the second arm is the
                # dynamic-membership joiner watching epochs it cannot
                # participate in run ahead of its adopted frontier)
                for _ in items:
                    hb._note_farahead()
            return
        metrics = hb.metrics
        if kind == _K_DEC:
            metrics.handler_dispatches.inc()
            hb._handle_dec_share_wave(epoch, es, items)
            return
        acs = es.acs
        if acs is None:
            # settle-only state (two-frontier mode): consensus traffic
            # for it is stale by definition
            return
        # the K-deep follow window (== {hb.epoch} at depth 1); the
        # predicate and RNG-order discipline are the owner's, shared
        # with the scalar arm so the two can never drift apart
        hb.maybe_follow_epoch(epoch, es)
        metrics.handler_dispatches.inc()
        if kind == _K_VOTE:
            acs.handle_vote_wave(items)
        elif kind == _K_ECHO:
            acs.handle_echo_wave(items)
        elif kind == _K_READY:
            acs.handle_ready_wave(items)
        elif kind == _K_COIN:
            acs.handle_coin_wave(items)
        else:  # _K_VAL
            for sender, p in items:
                acs.handle_message(sender, p)


__all__ = ["WaveRouter"]
