"""SimulatedCluster: N in-proc validators in one call.

The reference tests multi-node behavior by hand-wiring mock streams
(its test/mock/stream.go pattern); this module packages the equivalent
— and everything this framework adds on top — as a first-class API:

    cluster = SimulatedCluster(n=16, batch_size=1024, seed=7)
    cluster.submit(b"tx-1"); cluster.submit(b"tx-2")
    cluster.run_epochs()                  # drive to quiescence
    batches = cluster.committed()         # identical on every node

One call builds the roster keys (trusted dealer), the deterministic
ChannelNetwork (optionally seeded = adversarial scheduler), pairwise
MAC authenticators, and — by default — a cluster-SHARED CryptoHub, so
every wave flush executes the whole roster's crypto in single batched
device dispatches (the north star's "vmaps them across all N
validators' shards at once"; essential under a remote TPU attachment
where per-dispatch round-trips dominate).  ``shared_hub=False``
reverts to per-node hubs, the shape of a real multi-host deployment.

Fault injection passes straight through to the network: ``crash``,
``partition``, ``fault_filter`` (utils.adversary.Coalition), plus the
SEMANTIC adversary seam: ``behaviors={node_id: Behavior}`` mounts
protocol-level malicious behaviors (protocol.byzantine — equivocation,
split voting, share forgery...) on chosen nodes, composable with the
wire-level filters on the same run.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

from cleisthenes_tpu.config import Config
from cleisthenes_tpu.core.batch import Batch
from cleisthenes_tpu.ops.backend import get_backend
from cleisthenes_tpu.protocol.honeybadger import HoneyBadger, setup_keys
from cleisthenes_tpu.protocol.hub import CryptoHub
from cleisthenes_tpu.transport.base import HmacAuthenticator
from cleisthenes_tpu.transport.broadcast import ChannelBroadcaster
from cleisthenes_tpu.transport.channel import ChannelNetwork


def run_until_drained(
    net,
    nodes: Dict[str, HoneyBadger],
    *,
    skip: Sequence[str] = (),
    max_rounds: int = 50,
    before_round: Optional[Callable[[int], None]] = None,
    on_quiescence: Optional[Callable[[int], None]] = None,
) -> int:
    """THE propose-and-drain loop: each round starts an epoch on every
    non-skipped node, drives the network to quiescence, and stops once
    every non-skipped queue is empty (or ``max_rounds`` pass).  Returns
    the rounds used.

    This is the quiescence helper that used to be copy-pasted across
    the Byzantine test modules; ``SimulatedCluster.run_until_drained``
    and ``tools/fuzz.py`` both drive through it.  ``before_round``
    (fault-timeline injection) runs before each round's proposals;
    ``on_quiescence`` (invariant checks) runs after each round's drain
    — both may raise to abort the run.
    """
    for r in range(max_rounds):
        if before_round is not None:
            before_round(r)
        for nid, hb in nodes.items():
            if nid not in skip:
                hb.start_epoch()
        net.run()
        if on_quiescence is not None:
            on_quiescence(r)
        if all(
            hb.pending_tx_count() == 0
            for nid, hb in nodes.items()
            if nid not in skip
        ):
            return r + 1
    return max_rounds


class SimulatedCluster:
    """N HoneyBadger validators over the deterministic in-proc
    transport, with cluster-batched crypto."""

    def __init__(
        self,
        n: int = 4,
        *,
        config: Optional[Config] = None,
        batch_size: int = 256,
        crypto_backend: str = "cpu",
        seed: Optional[int] = None,
        key_seed: int = 1,
        auto_propose: bool = True,
        shared_hub: bool = True,
        group=None,
        member_ids: Optional[Sequence[str]] = None,
        behaviors: Optional[Dict[str, object]] = None,
    ) -> None:
        if config is not None:
            if n != 4 and n != config.n:  # both given and conflicting
                raise ValueError(
                    f"n={n} conflicts with config.n={config.n}; pass one"
                )
            self.config = config
        else:
            self.config = Config(
                n=n, batch_size=batch_size, crypto_backend=crypto_backend
            )
        if member_ids is None:
            member_ids = [f"node{i:03d}" for i in range(self.config.n)]
        self.ids: List[str] = sorted(member_ids)
        self.keys = setup_keys(self.config, self.ids, seed=key_seed,
                               group=group)
        self.net = ChannelNetwork(
            seed=seed,
            delivery_columnar=self.config.delivery_columnar,
            wave_routing=self.config.wave_routing,
        )
        # dedup=True: the shared hub verifies each distinct pure crypto
        # check ONCE for the whole roster (see CryptoHub docstring) —
        # the in-proc stand-in for N real hosts verifying in parallel
        hub = (
            CryptoHub(get_backend(self.config), dedup=True)
            if shared_hub
            else None
        )
        # tracing (Config.trace): a cluster-shared hub's flushes serve
        # the whole roster, so they record on a dedicated "hub" track
        # rather than any one node's timeline; per-node hubs
        # (shared_hub=False) inherit their owner's recorder inside
        # HoneyBadger.  tools/tracetool.py merges all tracks.
        self.hub_trace = None
        if hub is not None and self.config.trace:
            from cleisthenes_tpu.utils.trace import maybe_recorder

            self.hub_trace = maybe_recorder(self.config, "hub")
            hub.trace = self.hub_trace
        # same rationale as dedup above: N in-proc nodes re-parse the
        # identical decrypted blobs; per-node deployments pass None.
        # Instance-scoped and shared across THIS cluster's nodes only
        # (dies with the cluster — never process-global state).
        from cleisthenes_tpu.protocol.honeybadger import make_tx_parse_memo

        tx_memo = make_tx_parse_memo() if shared_hub else None
        behaviors = behaviors or {}
        unknown = sorted(set(behaviors) - set(self.ids))
        if unknown:
            raise ValueError(f"behaviors for non-members: {unknown}")
        self.behaviors = behaviors
        self.nodes: Dict[str, HoneyBadger] = {}
        for nid in self.ids:
            hb = HoneyBadger(
                config=self.config,
                node_id=nid,
                member_ids=self.ids,
                keys=self.keys[nid],
                out=ChannelBroadcaster(self.net, nid, self.ids),
                auto_propose=auto_propose,
                hub=hub,
                tx_parse_memo=tx_memo,
                behavior=behaviors.get(nid),
            )
            self.nodes[nid] = hb
            self.net.join(
                nid, hb, HmacAuthenticator(nid, self.keys[nid].mac_keys)
            )
            # public route to MAC-rejection/delivery counts:
            # Metrics.snapshot()["transport"]
            hb.metrics.set_transport_stats(
                lambda nid=nid: self.net.endpoint_stats(nid)
            )
        self._rr = 0  # submit() round-robin cursor
        # SLO watchdog plane (utils/watchdog.py): one per node, peer
        # state from the channel network's fault view (crash/partition)
        # and peer LAG from the epoch frontiers the in-proc cluster can
        # see directly.  Alert counters fold into each node's
        # Metrics.snapshot()["alerts"]; cluster.health() is the
        # worst-of verdict.
        from cleisthenes_tpu.utils.watchdog import SloWatchdog

        self.watchdogs: Dict[str, SloWatchdog] = {}
        for nid in self.ids:
            wd = SloWatchdog(
                metrics=self.nodes[nid].metrics,
                pending_fn=self.nodes[nid].pending_tx_count,
                stall_factor=self.config.slo_stall_factor,
                stall_grace_s=self.config.slo_stall_grace_s,
                queue_depth_limit=self.config.slo_queue_depth,
                peer_lag_epochs=self.config.slo_peer_lag_epochs,
                peer_states_fn=lambda nid=nid: self.net.link_states(nid),
                peer_lag_fn=lambda nid=nid: self._peer_lag(nid),
                decrypt_lag_budget=self.config.decrypt_lag_max,
                trace=self.nodes[nid].trace,
            )
            self.nodes[nid].metrics.set_alerts(wd.alerts_block)
            self.watchdogs[nid] = wd
        # live telemetry endpoints (Config.obs_port): ONE server fronts
        # the whole roster, each sample labeled node="..." — started
        # eagerly (there is no listen() phase on the in-proc cluster).
        # Each node gets a bounded-ring sampler (utils/timeseries.py);
        # the sampler threads only READ thread-safe metrics, so the
        # deterministic scheduler is unaffected.
        self.obs = None
        self.samplers: Dict[str, object] = {}
        if self.config.obs_port is not None:
            from cleisthenes_tpu.transport.obs_http import (
                ObsServer,
                ObsTarget,
            )
            from cleisthenes_tpu.utils.timeseries import TimeSeriesSampler

            targets = []
            for nid in self.ids:
                sampler = TimeSeriesSampler(self.nodes[nid].metrics.snapshot)
                sampler.on_tick(self.watchdogs[nid].check)
                sampler.start(self.config.obs_sample_period_s)
                self.samplers[nid] = sampler
                targets.append(
                    ObsTarget(
                        nid,
                        self.nodes[nid].metrics,
                        self.watchdogs[nid],
                        sampler,
                    )
                )
            self.obs = ObsServer(targets, port=self.config.obs_port)
            self.obs.start()

    # -- application surface ----------------------------------------------

    def submit(self, tx: bytes, node_id: Optional[str] = None) -> None:
        """Queue a transaction at ``node_id`` (default: round-robin)."""
        if node_id is None:
            node_id = self.ids[self._rr % len(self.ids)]
            self._rr += 1
        self.nodes[node_id].add_transaction(tx)

    def pending(self) -> int:
        return sum(hb.pending_tx_count() for hb in self.nodes.values())

    def run_until_drained(
        self,
        max_rounds: int = 50,
        skip: Sequence[str] = (),
        before_round: Optional[Callable[[int], None]] = None,
        on_quiescence: Optional[Callable[[int], None]] = None,
    ) -> int:
        """Propose + drain until every live queue is empty (or
        ``max_rounds`` proposal rounds pass); returns rounds used.
        The module-level ``run_until_drained`` over this cluster's
        network and nodes (see its docstring for the callbacks)."""
        return run_until_drained(
            self.net,
            self.nodes,
            skip=skip,
            max_rounds=max_rounds,
            before_round=before_round,
            on_quiescence=on_quiescence,
        )

    # the historical name; both spellings are public API
    run_epochs = run_until_drained

    def committed(self, node_id: Optional[str] = None) -> List[Batch]:
        return list(self.nodes[node_id or self.ids[0]].committed_batches)

    def assert_agreement(self, skip: Sequence[str] = ()) -> int:
        """Every live node committed the identical batch history;
        returns the common depth."""
        live = {
            nid: hb for nid, hb in self.nodes.items() if nid not in skip
        }
        depth = min(len(hb.committed_batches) for hb in live.values())
        assert depth > 0, "no common committed epoch"
        for e in range(depth):
            lists = {
                tuple(hb.committed_batches[e].tx_list())
                for hb in live.values()
            }
            assert len(lists) == 1, f"fork at epoch {e}"
        return depth

    # -- observability (telemetry + SLO surface) ---------------------------

    def _peer_lag(self, node_id: str) -> Dict[str, int]:
        """``node_id``'s view of peers trailing its epoch frontier
        (positive gaps only) — the in-proc peer-lag signal: a crashed
        or starved node stops advancing and shows up here on every
        healthy node's watchdog."""
        own = self.nodes[node_id].epoch
        return {
            nid: own - hb.epoch
            for nid, hb in self.nodes.items()
            if nid != node_id and own - hb.epoch > 0
        }

    def health(self) -> Dict[str, object]:
        """Run every node's SLO watchdog checks and return the
        /healthz-shaped verdict: ``{"status": worst, "nodes": {...}}``
        (the convenience accessor tests assert against — no HTTP
        round-trip needed)."""
        from cleisthenes_tpu.utils.watchdog import worst_health

        nodes = {
            nid: self.watchdogs[nid].check() for nid in self.ids
        }
        return {"status": worst_health(nodes.values()), "nodes": nodes}

    def stop(self) -> None:
        """Tear down background observers (the in-proc cluster itself
        has no threads; only the opt-in obs plane does)."""
        for sampler in self.samplers.values():
            sampler.stop()
        if self.obs is not None:
            self.obs.stop()

    # -- observability (the flight-recorder surface) -----------------------

    def trace_events(self) -> Dict[str, list]:
        """Every node's trace buffer (plus the shared hub's, under
        the key "hub"), for tools/tracetool.py merging.  Empty when
        Config.trace is off."""
        out: Dict[str, list] = {}
        for nid, hb in self.nodes.items():
            if hb.trace is not None:
                out[nid] = hb.trace.events()
        if self.hub_trace is not None:
            out["hub"] = self.hub_trace.events()
        return out

    def write_trace(self, path: str) -> None:
        """Write the merged Chrome-trace-event artifact (Perfetto-
        loadable; see docs/TRACING.md).  Raises if tracing is off —
        an empty artifact would silently hide a misconfiguration."""
        events = self.trace_events()
        if not events:
            raise ValueError(
                "no trace buffers: construct the cluster with "
                "Config(trace=True)"
            )
        from cleisthenes_tpu.utils.trace import write_chrome

        write_chrome(path, events)

    # -- fault injection (delegates to the network) ------------------------

    def crash(self, node_id: str) -> None:
        self.net.crash(node_id)

    def partition(self, a: str, b: str) -> None:
        self.net.partition(a, b)

    @property
    def fault_filter(self):
        return self.net.fault_filter

    @fault_filter.setter
    def fault_filter(self, f) -> None:
        self.net.fault_filter = f


__all__ = ["SimulatedCluster", "run_until_drained"]
