"""SimulatedCluster: N in-proc validators in one call.

The reference tests multi-node behavior by hand-wiring mock streams
(its test/mock/stream.go pattern); this module packages the equivalent
— and everything this framework adds on top — as a first-class API:

    cluster = SimulatedCluster(n=16, batch_size=1024, seed=7)
    cluster.submit(b"tx-1"); cluster.submit(b"tx-2")
    cluster.run_epochs()                  # drive to quiescence
    batches = cluster.committed()         # identical on every node

One call builds the roster keys (trusted dealer), the deterministic
ChannelNetwork (optionally seeded = adversarial scheduler), pairwise
MAC authenticators, and — by default — a cluster-SHARED CryptoHub, so
every wave flush executes the whole roster's crypto in single batched
device dispatches (the north star's "vmaps them across all N
validators' shards at once"; essential under a remote TPU attachment
where per-dispatch round-trips dominate).  ``shared_hub=False``
reverts to per-node hubs, the shape of a real multi-host deployment.

Fault injection passes straight through to the network: ``crash``,
``partition``, ``fault_filter`` (utils.adversary.Coalition), plus the
SEMANTIC adversary seam: ``behaviors={node_id: Behavior}`` mounts
protocol-level malicious behaviors (protocol.byzantine — equivocation,
split voting, share forgery...) on chosen nodes, composable with the
wire-level filters on the same run.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

from cleisthenes_tpu.config import Config
from cleisthenes_tpu.core.batch import Batch
from cleisthenes_tpu.ops.backend import get_backend
from cleisthenes_tpu.protocol.attest import (
    AttestationDirectory,
    AttestingAuthenticator,
)
from cleisthenes_tpu.protocol.honeybadger import HoneyBadger, setup_keys
from cleisthenes_tpu.protocol.hub import CryptoHub
from cleisthenes_tpu.transport.base import HmacAuthenticator
from cleisthenes_tpu.transport.broadcast import ChannelBroadcaster
from cleisthenes_tpu.transport.channel import ChannelNetwork


def run_until_drained(
    net,
    nodes: Dict[str, HoneyBadger],
    *,
    skip: Sequence[str] = (),
    max_rounds: int = 50,
    before_round: Optional[Callable[[int], None]] = None,
    on_quiescence: Optional[Callable[[int], None]] = None,
) -> int:
    """THE propose-and-drain loop: each round starts an epoch on every
    non-skipped node, drives the network to quiescence, and stops once
    every non-skipped queue is empty (or ``max_rounds`` pass).  Returns
    the rounds used.

    This is the quiescence helper that used to be copy-pasted across
    the Byzantine test modules; ``SimulatedCluster.run_until_drained``
    and ``tools/fuzz.py`` both drive through it.  ``before_round``
    (fault-timeline injection) runs before each round's proposals;
    ``on_quiescence`` (invariant checks) runs after each round's drain
    — both may raise to abort the run.
    """
    for r in range(max_rounds):
        if before_round is not None:
            before_round(r)
        for nid, hb in nodes.items():
            if nid not in skip:
                hb.start_epoch()
        net.run()
        if on_quiescence is not None:
            on_quiescence(r)
        if all(
            hb.pending_tx_count() == 0 and _lanes_merged(hb)
            for nid, hb in nodes.items()
            if nid not in skip
        ):
            return r + 1
    return max_rounds


def _lanes_merged(hb: HoneyBadger) -> bool:
    """Quiescence extension for lane shard-out: every settled lane
    epoch has also merge-emitted (no lane is epochs ahead of a
    sibling, parking merged slots).  Always True at lanes=1; the
    lockstep drive closes any gap within a few more rounds."""
    if hb._merge is None:
        return True
    return hb.merged_settled_frontier == sum(
        len(lane.committed_batches) for lane in hb.lanes
    )


class SimulatedCluster:
    """N HoneyBadger validators over the deterministic in-proc
    transport, with cluster-batched crypto."""

    def __init__(
        self,
        n: int = 4,
        *,
        config: Optional[Config] = None,
        batch_size: int = 256,
        crypto_backend: str = "cpu",
        seed: Optional[int] = None,
        key_seed: int = 1,
        auto_propose: bool = True,
        shared_hub: bool = True,
        group=None,
        member_ids: Optional[Sequence[str]] = None,
        behaviors: Optional[Dict[str, object]] = None,
        wal_dir: Optional[str] = None,
        wan_profile: Optional[object] = None,
    ) -> None:
        if config is not None:
            if n != 4 and n != config.n:  # both given and conflicting
                raise ValueError(
                    f"n={n} conflicts with config.n={config.n}; pass one"
                )
            self.config = config
        else:
            self.config = Config(
                n=n, batch_size=batch_size, crypto_backend=crypto_backend
            )
        if member_ids is None:
            member_ids = [f"node{i:03d}" for i in range(self.config.n)]
        self.ids: List[str] = sorted(member_ids)
        self._key_seed = key_seed
        self.keys = setup_keys(self.config, self.ids, seed=key_seed,
                               group=group)
        # wan_profile (ISSUE 16): a name from transport.wan.PROFILES
        # (or a WanProfile) mounts the seeded link-delay model on the
        # channel scheduler — geo-realistic delivery schedules priced
        # on a virtual clock, still byte-identical for a fixed seed
        self.net = ChannelNetwork(
            seed=seed,
            delivery_columnar=self.config.delivery_columnar,
            wave_routing=self.config.wave_routing,
            egress_columnar=self.config.egress_columnar,
            wan_profile=wan_profile,
        )
        # dedup=True: the shared hub verifies each distinct pure crypto
        # check ONCE for the whole roster (see CryptoHub docstring) —
        # the in-proc stand-in for N real hosts verifying in parallel
        hub = (
            CryptoHub(get_backend(self.config), dedup=True)
            if shared_hub
            else None
        )
        # tracing (Config.trace): a cluster-shared hub's flushes serve
        # the whole roster, so they record on a dedicated "hub" track
        # rather than any one node's timeline; per-node hubs
        # (shared_hub=False) inherit their owner's recorder inside
        # HoneyBadger.  tools/tracetool.py merges all tracks.
        self.hub_trace = None
        if hub is not None and self.config.trace:
            from cleisthenes_tpu.utils.trace import maybe_recorder

            self.hub_trace = maybe_recorder(self.config, "hub")
            hub.trace = self.hub_trace
        # same rationale as dedup above: N in-proc nodes re-parse the
        # identical decrypted blobs; per-node deployments pass None.
        # Instance-scoped and shared across THIS cluster's nodes only
        # (dies with the cluster — never process-global state).
        from cleisthenes_tpu.protocol.honeybadger import make_tx_parse_memo

        tx_memo = make_tx_parse_memo() if shared_hub else None
        behaviors = behaviors or {}
        unknown = sorted(set(behaviors) - set(self.ids))
        if unknown:
            raise ValueError(f"behaviors for non-members: {unknown}")
        self.behaviors = behaviors
        self.nodes: Dict[str, HoneyBadger] = {}
        self._hub = hub
        self._tx_memo = tx_memo
        self._auto_propose = auto_propose
        # authenticators are kept per node: dynamic membership
        # installs joiner pair keys / drops retirees through them
        self.auths: Dict[str, HmacAuthenticator] = {}
        # attested sender log (Config.attested_log): the cluster holds
        # the directory — the in-proc stand-in for each node's sealed
        # TEE NVRAM.  Vault state (counters, slots) survives
        # restart_node() with an incarnation bump, exactly the
        # monotonicity a real attested counter must keep across
        # process restarts; fork evidence aggregates here too.
        self.attest_dir = (
            AttestationDirectory()
            if self.config.attested_log
            else None
        )
        # optional per-node durable WALs (crash/restart tests):
        # wal_dir/<node>.log, restored by restart_node()
        self._wal_dir = wal_dir
        # per-node construction parameters, so restart_node() rebuilds
        # a process-restart-faithful node (same genesis view; the WAL
        # replay re-derives any roster versions it lived through)
        self._node_params: Dict[str, dict] = {}
        for nid in self.ids:
            auth = self._make_auth(nid, self.keys[nid].mac_keys)
            self.auths[nid] = auth
            self._node_params[nid] = {
                "config": self.config,
                "member_ids": list(self.ids),
                "joining": False,
                "roster_version_base": 0,
            }
            hb = HoneyBadger(
                config=self.config,
                node_id=nid,
                member_ids=self.ids,
                keys=self.keys[nid],
                out=ChannelBroadcaster(self.net, nid, self.ids),
                auto_propose=auto_propose,
                hub=hub,
                tx_parse_memo=tx_memo,
                behavior=behaviors.get(nid),
                authenticator=auth,
                batch_log=self._make_wal(nid),
            )
            self.nodes[nid] = hb
            self.net.join(nid, hb, auth)
            # public route to MAC-rejection/delivery counts:
            # Metrics.snapshot()["transport"]
            hb.metrics.set_transport_stats(
                lambda nid=nid: self.net.endpoint_stats(nid)
            )
            if self.net.wan is not None:
                hb.metrics.set_wan_stats(self.net.wan.stats)
        self._rr = 0  # submit() round-robin cursor
        # lazily-built per-node ingress planes (see ingress())
        self._ingress_planes: Dict[str, object] = {}
        # SLO watchdog plane (utils/watchdog.py): one per node, peer
        # state from the channel network's fault view (crash/partition)
        # and peer LAG from the epoch frontiers the in-proc cluster can
        # see directly.  Alert counters fold into each node's
        # Metrics.snapshot()["alerts"]; cluster.health() is the
        # worst-of verdict.
        from cleisthenes_tpu.utils.watchdog import SloWatchdog

        self.watchdogs: Dict[str, SloWatchdog] = {}
        for nid in self.ids:
            wd = SloWatchdog(
                metrics=self.nodes[nid].metrics,
                pending_fn=self.nodes[nid].outstanding_tx_count,
                stall_factor=self.config.slo_stall_factor,
                stall_grace_s=self.config.slo_stall_grace_s,
                queue_depth_limit=self.config.slo_queue_depth,
                peer_lag_epochs=self.config.slo_peer_lag_epochs,
                peer_states_fn=lambda nid=nid: self.net.link_states(nid),
                peer_lag_fn=lambda nid=nid: self._peer_lag(nid),
                decrypt_lag_budget=self.config.decrypt_lag_max,
                budget_floor_fn=self._wan_floor,
                trace=self.nodes[nid].trace,
            )
            self.nodes[nid].metrics.set_alerts(wd.alerts_block)
            self.watchdogs[nid] = wd
        # live telemetry endpoints (Config.obs_port): ONE server fronts
        # the whole roster, each sample labeled node="..." — started
        # eagerly (there is no listen() phase on the in-proc cluster).
        # Each node gets a bounded-ring sampler (utils/timeseries.py);
        # the sampler threads only READ thread-safe metrics, so the
        # deterministic scheduler is unaffected.
        self.obs = None
        self.samplers: Dict[str, object] = {}
        if self.config.obs_port is not None:
            from cleisthenes_tpu.transport.obs_http import (
                ObsServer,
                ObsTarget,
            )
            from cleisthenes_tpu.utils.timeseries import TimeSeriesSampler

            targets = []
            for nid in self.ids:
                sampler = TimeSeriesSampler(self.nodes[nid].metrics.snapshot)
                sampler.on_tick(self.watchdogs[nid].check)
                sampler.start(self.config.obs_sample_period_s)
                self.samplers[nid] = sampler
                targets.append(
                    ObsTarget(
                        nid,
                        self.nodes[nid].metrics,
                        self.watchdogs[nid],
                        sampler,
                    )
                )
            self.obs = ObsServer(targets, port=self.config.obs_port)
            self.obs.start()

    # -- application surface ----------------------------------------------

    def submit(self, tx: bytes, node_id: Optional[str] = None) -> None:
        """Queue a transaction at ``node_id`` (default: round-robin)."""
        if node_id is None:
            node_id = self.ids[self._rr % len(self.ids)]
            self._rr += 1
        self.nodes[node_id].add_transaction(tx)

    def pending(self) -> int:
        return sum(hb.pending_tx_count() for hb in self.nodes.values())

    def ingress(self, node_id: Optional[str] = None):
        """The in-process twin of the client gRPC surface: an
        ``InProcIngressClient`` over ``node_id``'s IngressPlane
        (transport/ingress.py), round-tripping the identical encoded
        client frames through the identical admission/subscription
        code — minus the sockets.  Needs a mounted mempool
        (Config.mempool_capacity > 0); the plane is built lazily and
        cached per node."""
        from cleisthenes_tpu.transport.ingress import (
            InProcIngressClient,
            IngressPlane,
        )

        nid = node_id or self.ids[0]
        plane = self._ingress_planes.get(nid)
        if plane is None:
            plane = IngressPlane(self.nodes[nid])
            self._ingress_planes[nid] = plane
        return InProcIngressClient(plane)

    def run_until_drained(
        self,
        max_rounds: int = 50,
        skip: Sequence[str] = (),
        before_round: Optional[Callable[[int], None]] = None,
        on_quiescence: Optional[Callable[[int], None]] = None,
    ) -> int:
        """Propose + drain until every live queue is empty (or
        ``max_rounds`` proposal rounds pass); returns rounds used.
        The module-level ``run_until_drained`` over this cluster's
        network and nodes (see its docstring for the callbacks)."""
        return run_until_drained(
            self.net,
            self.nodes,
            skip=skip,
            max_rounds=max_rounds,
            before_round=before_round,
            on_quiescence=on_quiescence,
        )

    # the historical name; both spellings are public API
    run_epochs = run_until_drained

    def committed(self, node_id: Optional[str] = None) -> List[Batch]:
        return list(self.nodes[node_id or self.ids[0]].committed_batches)

    def merged(self, node_id: Optional[str] = None) -> List[Batch]:
        """The MERGED total order (== committed() at lanes=1): the
        cross-lane deterministic ledger every client reads."""
        return list(self.nodes[node_id or self.ids[0]].merged_batches)

    def assert_agreement(self, skip: Sequence[str] = ()) -> int:
        """Every live node committed the identical batch history —
        compared over the MERGED total order, which IS the per-lane
        committed history at lanes=1; returns the common depth."""
        live = {
            nid: hb for nid, hb in self.nodes.items() if nid not in skip
        }
        depth = min(len(hb.merged_batches) for hb in live.values())
        assert depth > 0, "no common committed epoch"
        for e in range(depth):
            lists = {
                tuple(hb.merged_batches[e].tx_list())
                for hb in live.values()
            }
            assert len(lists) == 1, f"fork at merged slot {e}"
        return depth

    def _make_auth(self, nid: str, mac_keys) -> HmacAuthenticator:
        """Build one node's authenticator: plain pairwise-MAC, or —
        under Config.attested_log — the attesting subclass bound to
        the node's vault in the cluster-held directory.  attach()
        bumps the vault incarnation, so a restarted node resumes its
        sender log monotonically instead of re-using sequence
        numbers."""
        if self.attest_dir is None:
            return HmacAuthenticator(nid, mac_keys)
        return AttestingAuthenticator(
            nid, mac_keys, self.attest_dir.attach(nid)
        )

    def _make_wal(self, nid: str):
        if self._wal_dir is None:
            return None
        import os

        from cleisthenes_tpu.core.ledger import BatchLog

        return BatchLog(os.path.join(self._wal_dir, f"{nid}.log"))

    def restart_node(self, nid: str):
        """Process-restart one (crashed) node from its WAL: a FRESH
        HoneyBadger rebuilt with the node's ORIGINAL construction
        parameters replays the log — committed history, ordered-ahead
        window, and every roster version it lived through (the RCFG
        records cross-check the re-derivation) — then rejoins the
        network.  Requires ``wal_dir``."""
        if self._wal_dir is None:
            raise ValueError("restart_node() needs wal_dir")
        old = self.nodes[nid]
        if old.batch_log is not None:
            old.batch_log.close()
        # the old ingress plane (if any) holds the dead node; drop it
        # so the next ingress() call builds one over the restarted node
        stale_plane = self._ingress_planes.pop(nid, None)
        if stale_plane is not None:
            stale_plane.close()
        params = self._node_params[nid]
        auth = self._make_auth(nid, self.keys[nid].mac_keys)
        self.auths[nid] = auth
        hb = HoneyBadger(
            config=params["config"],
            node_id=nid,
            member_ids=params["member_ids"],
            keys=self.keys[nid],
            out=ChannelBroadcaster(
                self.net, nid, params["member_ids"]
            ),
            auto_propose=self._auto_propose,
            hub=self._hub,
            tx_parse_memo=self._tx_memo,
            authenticator=auth,
            joining=params["joining"],
            roster_version_base=params["roster_version_base"],
            batch_log=self._make_wal(nid),
        )
        self.nodes[nid] = hb
        self.net.restart(nid, hb, auth)
        hb.metrics.set_transport_stats(
            lambda nid=nid: self.net.endpoint_stats(nid)
        )
        if self.net.wan is not None:
            hb.metrics.set_wan_stats(self.net.wan.stats)
        # rewire the observability plane to the NEW instance: the old
        # watchdog/sampler closures hold the dead node's metrics and
        # would keep feeding frozen pre-crash state to SLO checks and
        # scrapes
        from cleisthenes_tpu.utils.watchdog import SloWatchdog

        wd = SloWatchdog(
            metrics=hb.metrics,
            pending_fn=hb.outstanding_tx_count,
            stall_factor=self.config.slo_stall_factor,
            stall_grace_s=self.config.slo_stall_grace_s,
            queue_depth_limit=self.config.slo_queue_depth,
            peer_lag_epochs=self.config.slo_peer_lag_epochs,
            peer_states_fn=lambda nid=nid: self.net.link_states(nid),
            peer_lag_fn=lambda nid=nid: self._peer_lag(nid),
            decrypt_lag_budget=self.config.decrypt_lag_max,
            budget_floor_fn=self._wan_floor,
            trace=hb.trace,
        )
        hb.metrics.set_alerts(wd.alerts_block)
        self.watchdogs[nid] = wd
        old_sampler = self.samplers.pop(nid, None)
        if old_sampler is not None:
            from cleisthenes_tpu.transport.obs_http import ObsTarget
            from cleisthenes_tpu.utils.timeseries import (
                TimeSeriesSampler,
            )

            old_sampler.stop()
            sampler = TimeSeriesSampler(hb.metrics.snapshot)
            sampler.on_tick(wd.check)
            sampler.start(self.config.obs_sample_period_s)
            self.samplers[nid] = sampler
            if self.obs is not None:
                fresh = ObsTarget(nid, hb.metrics, wd, sampler)
                for i, t in enumerate(self.obs.targets):
                    if t.node_id == nid:
                        self.obs.targets[i] = fresh
                        break
                else:
                    self.obs.add_target(fresh)
        return hb

    # -- dynamic membership (protocol.reconfig) ----------------------------

    def roster_versions(self) -> Dict[str, int]:
        """Every node's ACTIVE roster version (the convergence check
        reconfig tests assert against)."""
        return {
            nid: hb.roster_version for nid, hb in self.nodes.items()
        }

    def begin_reconfig(
        self,
        join: Sequence[str] = (),
        retire: Sequence[str] = (),
        submit_via: Optional[str] = None,
    ) -> int:
        """Operator surface: construct the joiner nodes, wire them to
        the network, and submit the RECONFIG transaction that starts
        the reshare ceremony.  Returns the new version number.  The
        ceremony itself runs in-band (protocol.reconfig) as the
        cluster keeps draining epochs; activation follows
        automatically once the qualified dealer set commits."""
        from cleisthenes_tpu.protocol import reconfig as rcfg

        # the authoritative current roster is any CURRENT member's
        # latest version (all agree by construction) — a parked
        # retiree from an earlier reconfig still sits in self.nodes
        # but carries no active key material, so it cannot be the
        # source of the roster's public keys
        any_node = None
        for nid in sorted(self.nodes):
            hb = self.nodes[nid]
            if hb.active_view.keys is not None and (
                any_node is None
                or hb.rosters.latest().version
                > any_node.rosters.latest().version
            ):
                any_node = hb
        if any_node is None:
            raise ValueError("no active member to anchor the reconfig")
        latest = any_node.rosters.latest()
        current = list(latest.member_ids)
        version = latest.version + 1
        unknown = sorted(set(retire) - set(current))
        if unknown:
            raise ValueError(f"cannot retire non-members: {unknown}")
        clash = sorted(set(join) & set(current))
        if clash:
            raise ValueError(f"cannot join existing members: {clash}")
        new_ids = sorted((set(current) - set(retire)) | set(join))
        old_view_keys = any_node.active_view.keys
        enroll_pubs: Dict[str, int] = {}
        for jid in sorted(join):
            secret, pub = self._add_joiner(
                jid, version, current, old_view_keys
            )
            enroll_pubs[jid] = pub
        tx = rcfg.encode_reconfig_tx(
            version,
            [(mid, "", 0) for mid in new_ids],
            enroll_pubs,
            any_node.group,
        )
        via = submit_via
        if via is None:  # first member surviving the change
            via = next(m for m in current if m not in set(retire))
        self.nodes[via].add_transaction(tx)
        return version

    def _add_joiner(
        self,
        jid: str,
        version: int,
        current_ids: Sequence[str],
        old_keys,
    ):
        """Construct + wire one JOINER: enrollment keypair (seeded
        off key_seed for replayable tests), bootstrap NodeKeys (public
        threshold keys + DH-derived pair keys, no shares), and a
        ``joining=True`` HoneyBadger attached to the live network."""
        import dataclasses as _dc
        import hashlib as _hashlib

        from cleisthenes_tpu.protocol import reconfig as rcfg
        from cleisthenes_tpu.protocol.honeybadger import NodeKeys
        from cleisthenes_tpu.utils.watchdog import SloWatchdog

        eseed = int.from_bytes(
            _hashlib.sha256(
                b"cluster-enroll|%d|%d|" % (self._key_seed, version)
                + jid.encode("utf-8")
            ).digest()[:8],
            "big",
        )
        secret, pub = rcfg.enrollment_keypair(
            eseed, old_keys.tpke_pub.group
        )
        mac_keys = rcfg.joiner_bootstrap_keys(
            secret, version, old_keys.coin_pub, current_ids, jid
        )
        keys = NodeKeys(
            tpke_pub=old_keys.tpke_pub,
            tpke_share=None,
            coin_pub=old_keys.coin_pub,
            coin_share=None,
            mac_keys=mac_keys,
            enroll_secret=secret,
        )
        jcfg = _dc.replace(self.config, n=len(current_ids), f=None)
        auth = self._make_auth(jid, mac_keys)
        self._node_params[jid] = {
            "config": jcfg,
            "member_ids": list(current_ids),
            "joining": True,
            "roster_version_base": version - 1,
        }
        hb = HoneyBadger(
            config=jcfg,
            node_id=jid,
            member_ids=current_ids,
            keys=keys,
            out=ChannelBroadcaster(self.net, jid, current_ids),
            auto_propose=self._auto_propose,
            hub=self._hub,
            tx_parse_memo=self._tx_memo,
            authenticator=auth,
            joining=True,
            roster_version_base=version - 1,
            batch_log=self._make_wal(jid),
        )
        self.nodes[jid] = hb
        self.auths[jid] = auth
        self.keys[jid] = keys
        self.net.join(jid, hb, auth)
        hb.metrics.set_transport_stats(
            lambda jid=jid: self.net.endpoint_stats(jid)
        )
        if self.net.wan is not None:
            hb.metrics.set_wan_stats(self.net.wan.stats)
        if jid not in self.ids:
            self.ids.append(jid)
            self.ids.sort()
        wd = SloWatchdog(
            metrics=hb.metrics,
            pending_fn=hb.outstanding_tx_count,
            stall_factor=self.config.slo_stall_factor,
            stall_grace_s=self.config.slo_stall_grace_s,
            queue_depth_limit=self.config.slo_queue_depth,
            peer_lag_epochs=self.config.slo_peer_lag_epochs,
            peer_states_fn=lambda jid=jid: self.net.link_states(jid),
            peer_lag_fn=lambda jid=jid: self._peer_lag(jid),
            decrypt_lag_budget=self.config.decrypt_lag_max,
            budget_floor_fn=self._wan_floor,
            trace=hb.trace,
        )
        hb.metrics.set_alerts(wd.alerts_block)
        self.watchdogs[jid] = wd
        if self.obs is not None:
            from cleisthenes_tpu.transport.obs_http import ObsTarget
            from cleisthenes_tpu.utils.timeseries import (
                TimeSeriesSampler,
            )

            sampler = TimeSeriesSampler(hb.metrics.snapshot)
            sampler.on_tick(wd.check)
            sampler.start(self.config.obs_sample_period_s)
            self.samplers[jid] = sampler
            self.obs.add_target(
                ObsTarget(jid, hb.metrics, wd, sampler)
            )
        return secret, pub

    # -- observability (telemetry + SLO surface) ---------------------------

    def _wan_floor(self) -> float:
        """The epoch-stall budget floor the mounted WAN profile needs
        (0 without one) — keeps a p50 self-calibrated on fast local
        epochs from flipping DOWN when the link model's delay tail
        lands (ISSUE 16 watchdog hardening)."""
        wan = self.net.wan
        return 0.0 if wan is None else wan.stall_floor_s()

    def _peer_lag(self, node_id: str) -> Dict[str, int]:
        """``node_id``'s view of peers trailing its epoch frontier
        (positive gaps only) — the in-proc peer-lag signal: a crashed
        or starved node stops advancing and shows up here on every
        healthy node's watchdog."""
        own = self.nodes[node_id].merged_ordered_frontier
        return {
            nid: own - hb.merged_ordered_frontier
            for nid, hb in self.nodes.items()
            if nid != node_id and own - hb.merged_ordered_frontier > 0
        }

    def health(self) -> Dict[str, object]:
        """Run every node's SLO watchdog checks and return the
        /healthz-shaped verdict: ``{"status": worst, "nodes": {...}}``
        (the convenience accessor tests assert against — no HTTP
        round-trip needed)."""
        from cleisthenes_tpu.utils.watchdog import worst_health

        nodes = {
            nid: self.watchdogs[nid].check() for nid in self.ids
        }
        return {"status": worst_health(nodes.values()), "nodes": nodes}

    def stop(self) -> None:
        """Tear down background observers (the in-proc cluster itself
        has no threads; only the opt-in obs plane does)."""
        for sampler in self.samplers.values():
            sampler.stop()
        if self.obs is not None:
            self.obs.stop()
        for hb in self.nodes.values():
            if hb.batch_log is not None:
                hb.batch_log.close()

    # -- observability (the flight-recorder surface) -----------------------

    def trace_events(self) -> Dict[str, list]:
        """Every node's trace buffer (plus the shared hub's, under
        the key "hub"), for tools/tracetool.py merging.  Empty when
        Config.trace is off."""
        out: Dict[str, list] = {}
        for nid, hb in self.nodes.items():
            if hb.trace is not None:
                out[nid] = hb.trace.events()
        if self.hub_trace is not None:
            out["hub"] = self.hub_trace.events()
        return out

    def write_trace(self, path: str) -> None:
        """Write the merged Chrome-trace-event artifact (Perfetto-
        loadable; see docs/TRACING.md).  Raises if tracing is off —
        an empty artifact would silently hide a misconfiguration."""
        events = self.trace_events()
        if not events:
            raise ValueError(
                "no trace buffers: construct the cluster with "
                "Config(trace=True)"
            )
        from cleisthenes_tpu.utils.trace import write_chrome

        write_chrome(path, events)

    # -- fault injection (delegates to the network) ------------------------

    def crash(self, node_id: str) -> None:
        self.net.crash(node_id)

    def partition(self, a: str, b: str) -> None:
        self.net.partition(a, b)

    @property
    def fault_filter(self):
        return self.net.fault_filter

    @fault_filter.setter
    def fault_filter(self, f) -> None:
        self.net.fault_filter = f


__all__ = ["SimulatedCluster", "run_until_drained"]
