"""Typed request repositories and the epoch catch-up buffer.

Reference request.go:3-17 defines ``Request`` (marker), a per-ConnId
``RequestRepository{Save, Find, FindAll}`` and an
``IncomingRequestRepository`` additionally keyed by epoch — the buffer
for messages "sent from a node that is already in a later epoch …
saved and handled in the next epoch" (reference bba/request.go:28-32,
wired at bba/bba.go:55).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from cleisthenes_tpu.utils.determinism import guarded_by
from cleisthenes_tpu.utils.lockcheck import new_lock

Request = Any  # marker interface (reference request.go:3-5)


class DuplicateRequestError(Exception):
    """A peer tried to save a second request for the same key.

    Protocol handlers rely on first-write-wins per (sender, type) to
    enforce the at-most-one-vote-per-peer rule in quorum counting.
    """


@guarded_by("_lock", "_reqs")
class RequestRepository:
    """Per-connection-id request store (reference request.go:7-11).

    First save wins; duplicates raise, which callers treat as "already
    counted this peer" (idempotent message delivery).
    """

    def __init__(self) -> None:
        self._reqs: Dict[str, Request] = {}
        self._lock = new_lock()

    def save(self, conn_id: str, req: Request) -> None:
        with self._lock:
            if conn_id in self._reqs:
                raise DuplicateRequestError(conn_id)
            self._reqs[conn_id] = req

    def find(self, conn_id: str) -> Request:
        with self._lock:
            return self._reqs.get(conn_id)

    def find_all(self) -> List[Tuple[str, Request]]:
        with self._lock:
            return list(self._reqs.items())

    def __len__(self) -> int:
        with self._lock:
            return len(self._reqs)

    def __contains__(self, conn_id: str) -> bool:
        with self._lock:
            return conn_id in self._reqs


@guarded_by("_lock", "_reqs")
class IncomingRequestRepository:
    """Epoch-keyed buffer for future-epoch messages
    (reference request.go:13-17, bba/request.go:28-32).

    Messages from nodes already in a later epoch are parked here and
    replayed when the local node advances.
    """

    def __init__(
        self, max_epoch_horizon: int = 8, max_per_sender: int = 256
    ) -> None:
        # DoS bounds (absent in the reference, which keeps one request
        # per sender in a bare map): a Byzantine peer must not be able
        # to park unbounded messages for arbitrarily-distant epochs.
        self._max_epoch_horizon = max_epoch_horizon
        self._max_per_sender = max_per_sender
        self._reqs: Dict[int, Dict[str, List[Request]]] = {}
        self._lock = new_lock()
        self.dropped = 0

    def save(
        self, epoch: int, conn_id: str, req: Request, current_epoch: int
    ) -> bool:
        """Buffer ``req`` for a future ``epoch``; returns False if dropped.

        Only strictly-future epochs within ``max_epoch_horizon`` are
        buffered (current-epoch messages are handled directly and
        past-epoch messages are useless), and at most
        ``max_per_sender`` per (epoch, sender) — a correct peer never
        needs more.
        """
        with self._lock:
            if not (
                current_epoch < epoch <= current_epoch + self._max_epoch_horizon
            ):
                self.dropped += 1
                return False
            bucket = self._reqs.setdefault(epoch, {}).setdefault(conn_id, [])
            if len(bucket) >= self._max_per_sender:
                self.dropped += 1
                return False
            bucket.append(req)
            return True

    def find_all(self, epoch: int) -> List[Tuple[str, Request]]:
        """All buffered (sender, request) pairs for ``epoch``."""
        with self._lock:
            out: List[Tuple[str, Request]] = []
            for conn_id, reqs in self._reqs.get(epoch, {}).items():
                out.extend((conn_id, r) for r in reqs)
            return out

    def pop_epoch(self, epoch: int) -> List[Tuple[str, Request]]:
        """Drain and return everything buffered for ``epoch``.

        Also garbage-collects anything parked for earlier epochs — a
        node draining epoch e will never revisit e' < e.
        """
        with self._lock:
            buf = self._reqs.pop(epoch, {})
            for stale in [e for e in self._reqs if e < epoch]:
                del self._reqs[stale]
        out: List[Tuple[str, Request]] = []
        for conn_id, reqs in buf.items():
            out.extend((conn_id, r) for r in reqs)
        return out
