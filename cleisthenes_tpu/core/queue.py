"""In-memory FIFO transaction queue.

Semantics mirror the reference's mutex-guarded ``memQueue``
(reference queue.go:15-94): Push appends, Poll pops the head, ``at``
indexes without removal, with typed errors for empty-queue and
index-out-of-bounds conditions (queue.go:21-47).  Transactions are
opaque to the framework (reference honeybadger.go:115
``Transaction interface{}``).
"""

from __future__ import annotations

import collections
from typing import Any, Deque

from cleisthenes_tpu.utils.determinism import guarded_by
from cleisthenes_tpu.utils.lockcheck import new_lock

# A transaction is opaque to the consensus core (honeybadger.go:115).
Transaction = Any


class EmptyQueueError(Exception):
    """Raised when polling/peeking an empty queue (reference queue.go:21-26)."""

    def __init__(self) -> None:
        super().__init__("empty queue")


class IndexBoundaryError(Exception):
    """Raised on out-of-range ``at`` access (reference queue.go:28-34)."""

    def __init__(self, index: int, size: int) -> None:
        super().__init__(f"index {index} out of bounds for queue of size {size}")
        self.index = index
        self.size = size


@guarded_by("_lock", "_txs")
class TxQueue:
    """Thread-safe FIFO of opaque transactions (reference queue.go:15-94)."""

    def __init__(self) -> None:
        self._txs: Deque[Transaction] = collections.deque()
        self._lock = new_lock()

    def push(self, tx: Transaction) -> None:
        """Append a transaction (reference queue.go:89-94)."""
        with self._lock:
            self._txs.append(tx)

    def poll(self) -> Transaction:
        """Pop and return the head (reference queue.go:59-76)."""
        with self._lock:
            if not self._txs:
                raise EmptyQueueError()
            return self._txs.popleft()

    def peek(self) -> Transaction:
        """Return the head without removing it (reference queue.go:50-57)."""
        with self._lock:
            if not self._txs:
                raise EmptyQueueError()
            return self._txs[0]

    def at(self, index: int) -> Transaction:
        """Return the item at ``index`` without removal (queue.go:78-87)."""
        with self._lock:
            if index < 0 or index >= len(self._txs):
                raise IndexBoundaryError(index, len(self._txs))
            return self._txs[index]

    def __len__(self) -> int:
        with self._lock:
            return len(self._txs)

    def len(self) -> int:
        """Go-style alias (reference queue.go uses Len())."""
        return len(self)
