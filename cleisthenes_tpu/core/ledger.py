"""Durable committed-batch log: crash/restart recovery.

SURVEY.md §5.4: the reference keeps everything in memory (its only
resume-adjacent mechanism is the future-epoch buffer) and the build
plan calls for "an optional committed-batch log for restart".  This is
that log: an append-only file of (epoch, Batch) records with per-record
CRCs, replayed at startup to restore the committed history, the epoch
counter, and the duplicate-filter — so a restarted validator rejoins at
the epoch after its last commit instead of epoch 0.

Record format (all big-endian, following transport.message's TLV
style):  magic | u32 record_len | body | u32 crc32(record body), with
three record magics:

  "CLOG" — committed batch: u64 epoch | u32 n_proposers | per
  proposer (u32 id_len | id | u32 n_txs | per tx (u32 len | bytes)).

  "CCKP" — dedup-set checkpoint: u64 epoch | u32 n_epoch_sets | per
  set, oldest first (u32 n_txs | per tx (u32 len | bytes)) — a
  snapshot of the node's bounded committed-tx duplicate filter
  (HoneyBadger._committed_history) as of ``epoch``.  On restart the
  filter seeds from the LAST checkpoint and folds only the batches
  logged after it, instead of re-deriving tx sets from every batch in
  the log.

  "COrd" — ciphertext-ordered commit (Config.order_then_settle): u64
  epoch | u32 n_proposers | per proposer, sorted (u32 id_len | id |
  u32 ct_len | ct_bytes) — the agreed ACS output as raw RBC values,
  durable BEFORE threshold decryption runs.  Epoch e's COrd precedes
  its CLOG in the file; a crash between them leaves an ordered-ahead
  epoch that a restart re-enters into the settler (the ordering is
  never re-run).  The body bytes are a pure function of the agreed
  output map, so honest nodes' ordered logs are byte-identical —
  the cross-frontier fuzz invariant.

  "RCFG" — roster switch (dynamic membership): u32 version | u64
  activation_epoch | u32 n_members | per member (u32 id_len | id |
  u32 ip_len | ip | u32 port) | u32 digest_len | key-material digest
  — the durable witness of a finalized reshare ceremony, written
  strictly before any epoch orders under the new roster.  Recovery
  re-derives the ceremony from the replayed CLOG batches (the
  RECONFIG and dealing transactions are ordinary committed txs) and
  cross-checks the result against these records.

A torn tail (crash mid-append) is detected by length/CRC and
truncated away on open.  The fsync-on-commit policy is
Config.ledger_fsync.
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from cleisthenes_tpu.core.batch import Batch
from cleisthenes_tpu.utils.determinism import guarded_by
from cleisthenes_tpu.utils.lockcheck import new_lock

_MAGIC = b"CLOG"
_MAGIC_CKPT = b"CCKP"
_MAGIC_ORD = b"COrd"
_MAGIC_RCFG = b"RCFG"
# Lane-tagged twins (horizontal shard-out, ISSUE 20): lanes > 0 of an
# S-lane node share ONE log file with lane 0, appending records whose
# body is ``u32 lane | <the lane-agnostic body>`` under these magics.
# Lane 0 keeps the bare magics above, so a lanes=1 log — and lane 0's
# stream inside an S-lane log — stays byte-identical to the pre-lane
# format, and the bare replay()/replay_ordered() iterators (which
# filter by magic) never see lane traffic.  Per-lane recovery goes
# through ``BatchLog.lane_view(lane)``.
_MAGIC_LANE = b"LCLG"
_MAGIC_LANE_CKPT = b"LCKP"
_MAGIC_LANE_ORD = b"LOrd"


def encode_batch_body(epoch: int, batch: Batch) -> bytes:
    """The CRC-covered record body: (epoch, contributions).  Also the
    payload of CATCHUP responses (transport.message
    CatchupRespPayload), so a caught-up batch round-trips through the
    exact bytes a local commit would have logged — and f+1 "identical
    bodies" means f+1 identical LOG RECORDS."""
    return _encode_body(epoch, batch)


def decode_batch_body(body: bytes) -> Tuple[int, Batch]:
    return _decode_body(body)


def encode_ordered_body(epoch: int, output: Dict[str, bytes]) -> bytes:
    """The COrd record body: the epoch's agreed {proposer: raw RBC
    value} map in sorted-proposer order.  Deterministic bytes for a
    given ACS output — also the payload of ordered CATCHUP responses
    (transport.message.CatchupOrdPayload), so f+1 "identical bodies"
    means f+1 identical ORDERING records."""
    out: List[bytes] = [struct.pack(">Q", epoch)]
    out.append(struct.pack(">I", len(output)))
    for proposer in sorted(output):
        pid = proposer.encode("utf-8")
        out.append(struct.pack(">I", len(pid)))
        out.append(pid)
        ct = output[proposer]
        out.append(struct.pack(">I", len(ct)))
        out.append(ct)
    return b"".join(out)


def decode_ordered_body(body: bytes) -> Tuple[int, Dict[str, bytes]]:
    off = 0

    def u32() -> int:
        nonlocal off
        (v,) = struct.unpack_from(">I", body, off)
        off += 4
        return v

    (epoch,) = struct.unpack_from(">Q", body, off)
    off += 8
    output: Dict[str, bytes] = {}
    for _ in range(u32()):
        id_len = u32()
        proposer = body[off : off + id_len].decode("utf-8")
        off += id_len
        ct_len = u32()
        output[proposer] = body[off : off + ct_len]
        off += ct_len
    if off != len(body):
        raise ValueError("trailing bytes in ordered record")
    return epoch, output


def encode_reconfig_body(
    version: int,
    activation_epoch: int,
    members: Sequence[Tuple[str, str, int]],
    key_digest: bytes,
) -> bytes:
    """The RCFG record body: a committed roster switch — version,
    activation epoch, the (id, ip, port) member table and the
    key-material digest.  Written when a reshare ceremony finalizes,
    BEFORE the first epoch ordered under the new roster, so crash
    recovery replays the switch deterministically (the ceremony
    re-derives from replayed CLOG batches; the RCFG record is the
    durable witness recovery cross-checks against)."""
    out: List[bytes] = [
        struct.pack(">IQ", version, activation_epoch),
        struct.pack(">I", len(members)),
    ]
    for mid, ip, port in members:
        b_id = mid.encode("utf-8")
        b_ip = ip.encode("utf-8")
        out.append(struct.pack(">I", len(b_id)))
        out.append(b_id)
        out.append(struct.pack(">I", len(b_ip)))
        out.append(b_ip)
        out.append(struct.pack(">I", port))
    out.append(struct.pack(">I", len(key_digest)))
    out.append(key_digest)
    return b"".join(out)


def decode_reconfig_body(
    body: bytes,
) -> Tuple[int, int, List[Tuple[str, str, int]], bytes]:
    off = 0
    version, activation = struct.unpack_from(">IQ", body, off)
    off += 12

    def u32() -> int:
        nonlocal off
        (v,) = struct.unpack_from(">I", body, off)
        off += 4
        return v

    members: List[Tuple[str, str, int]] = []
    for _ in range(u32()):
        id_len = u32()
        mid = body[off : off + id_len].decode("utf-8")
        off += id_len
        ip_len = u32()
        ip = body[off : off + ip_len].decode("utf-8")
        off += ip_len
        port = u32()
        members.append((mid, ip, port))
    dig_len = u32()
    key_digest = body[off : off + dig_len]
    off += dig_len
    if off != len(body):
        raise ValueError("trailing bytes in reconfig record")
    return version, activation, members, key_digest


def _encode_body(epoch: int, batch: Batch) -> bytes:
    out: List[bytes] = [struct.pack(">Q", epoch)]
    contributions = batch.contributions
    out.append(struct.pack(">I", len(contributions)))
    for proposer in sorted(contributions):
        pid = proposer.encode("utf-8")
        out.append(struct.pack(">I", len(pid)))
        out.append(pid)
        txs = contributions[proposer]
        out.append(struct.pack(">I", len(txs)))
        for tx in txs:
            out.append(struct.pack(">I", len(tx)))
            out.append(tx)
    return b"".join(out)


def _frame_record(magic: bytes, body: bytes) -> bytes:
    return (
        magic
        + struct.pack(">I", len(body))
        + body
        + struct.pack(">I", zlib.crc32(body))
    )


def _encode_record(epoch: int, batch: Batch) -> bytes:
    return _frame_record(_MAGIC, _encode_body(epoch, batch))


def _encode_checkpoint_body(
    epoch: int, history: Sequence[Set[bytes]]
) -> bytes:
    out: List[bytes] = [
        struct.pack(">Q", epoch),
        struct.pack(">I", len(history)),
    ]
    for seen in history:
        out.append(struct.pack(">I", len(seen)))
        for tx in sorted(seen):  # deterministic bytes for a given set
            out.append(struct.pack(">I", len(tx)))
            out.append(tx)
    return b"".join(out)


def _decode_checkpoint_body(body: bytes) -> Tuple[int, List[Set[bytes]]]:
    off = 0

    def u32() -> int:
        nonlocal off
        (v,) = struct.unpack_from(">I", body, off)
        off += 4
        return v

    (epoch,) = struct.unpack_from(">Q", body, off)
    off += 8
    history: List[Set[bytes]] = []
    for _ in range(u32()):
        seen: Set[bytes] = set()
        for _ in range(u32()):
            tx_len = u32()
            seen.add(body[off : off + tx_len])
            off += tx_len
        history.append(seen)
    if off != len(body):
        raise ValueError("trailing bytes in checkpoint record")
    return epoch, history


def _decode_body(body: bytes) -> Tuple[int, Batch]:
    off = 0

    def u32() -> int:
        nonlocal off
        (v,) = struct.unpack_from(">I", body, off)
        off += 4
        return v

    (epoch,) = struct.unpack_from(">Q", body, off)
    off += 8
    contributions: Dict[str, List[bytes]] = {}
    for _ in range(u32()):
        id_len = u32()
        proposer = body[off : off + id_len].decode("utf-8")
        off += id_len
        txs: List[bytes] = []
        for _ in range(u32()):
            tx_len = u32()
            txs.append(body[off : off + tx_len])
            off += tx_len
        contributions[proposer] = txs
    if off != len(body):
        raise ValueError("trailing bytes in ledger record")
    return epoch, Batch(contributions=contributions)


def _lane_body(lane: int, body: bytes) -> bytes:
    return struct.pack(">I", lane) + body


def _split_lane_body(body: bytes) -> Tuple[int, bytes]:
    if len(body) < 4:
        raise ValueError("lane record body too short")
    (lane,) = struct.unpack_from(">I", body, 0)
    return lane, body[4:]


@guarded_by(
    "_lock", "_fh", "_last_epoch", "_last_checkpoint",
    "_last_ordered_epoch", "_lane_last_epoch", "_lane_last_ordered",
    "_lane_last_checkpoint",
)
class BatchLog:
    """Append-only durable log of committed batches.

    One lock guards the file handle and the recovered-state fields
    (commit path and CATCHUP serving run on different threads under
    the gRPC transport); ``*_locked`` methods assume the caller —
    or single-threaded construction — already holds exclusivity."""

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self.fsync = fsync
        self._lock = new_lock()
        self._last_epoch: Optional[int] = None
        self._last_checkpoint: Optional[Tuple[int, List[Set[bytes]]]] = None
        self._last_ordered_epoch: Optional[int] = None
        # per-lane recovered state for lanes > 0 (lane 0 uses the bare
        # fields above); populated by _recover_locked and the lane
        # append paths, read through lane_view()
        self._lane_last_epoch: Dict[int, int] = {}
        self._lane_last_ordered: Dict[int, int] = {}
        self._lane_last_checkpoint: Dict[
            int, Tuple[int, List[Set[bytes]]]
        ] = {}
        # flight recorder (utils/trace.py), set by the owning node
        # when Config.trace is on: every append/checkpoint records a
        # "ledger" span (write+flush+fsync cost is a real commit-path
        # stage).  None = tracing off.
        self.trace = None
        # held even in __init__: the static rules exempt constructors,
        # but the runtime sanitizer (CLEISTHENES_LOCKCHECK=1) walks
        # into _recover_locked's own frame, which is not exempt
        with self._lock:
            self._recover_locked()
            self._fh = open(path, "ab")

    @staticmethod
    def _scan(data: bytes) -> Iterator[Tuple[int, bytes, bytes]]:
        """Walk validated records: yields (end_offset, magic, body) for
        every record whose framing, CRC and body parse check out,
        stopping at the first torn/corrupt one.  The single source of
        framing truth for both recovery and replay."""
        off = 0
        while off + 8 <= len(data):
            magic = data[off : off + 4]
            if (
                magic != _MAGIC
                and magic != _MAGIC_CKPT
                and magic != _MAGIC_ORD
                and magic != _MAGIC_RCFG
                and magic != _MAGIC_LANE
                and magic != _MAGIC_LANE_ORD
                and magic != _MAGIC_LANE_CKPT
            ):
                return
            (body_len,) = struct.unpack_from(">I", data, off + 4)
            end = off + 8 + body_len + 4
            if end > len(data):
                return
            body = data[off + 8 : off + 8 + body_len]
            (crc,) = struct.unpack_from(">I", data, off + 8 + body_len)
            if zlib.crc32(body) != crc:
                return
            try:
                if magic == _MAGIC:
                    _decode_body(body)
                elif magic == _MAGIC_ORD:
                    decode_ordered_body(body)
                elif magic == _MAGIC_RCFG:
                    decode_reconfig_body(body)
                elif magic == _MAGIC_LANE:
                    _decode_body(_split_lane_body(body)[1])
                elif magic == _MAGIC_LANE_ORD:
                    decode_ordered_body(_split_lane_body(body)[1])
                elif magic == _MAGIC_LANE_CKPT:
                    _decode_checkpoint_body(_split_lane_body(body)[1])
                else:
                    _decode_checkpoint_body(body)
            except (ValueError, struct.error, UnicodeDecodeError):
                return
            yield end, magic, body
            off = end

    def _recover_locked(self) -> None:
        """Scan the log, truncating any torn tail (construction-time:
        the instance is not shared yet)."""
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as fh:
            data = fh.read()
        good_end = 0
        for end, magic, body in self._scan(data):
            if magic == _MAGIC:
                self._last_epoch, _ = _decode_body(body)
            elif magic == _MAGIC_ORD:
                (self._last_ordered_epoch,) = struct.unpack_from(
                    ">Q", body, 0
                )
            elif magic == _MAGIC_CKPT:
                epoch, history = _decode_checkpoint_body(body)
                self._last_checkpoint = (epoch, history)
            elif magic == _MAGIC_LANE:
                lane, inner = _split_lane_body(body)
                self._lane_last_epoch[lane], _ = _decode_body(inner)
            elif magic == _MAGIC_LANE_ORD:
                lane, inner = _split_lane_body(body)
                (self._lane_last_ordered[lane],) = struct.unpack_from(
                    ">Q", inner, 0
                )
            elif magic == _MAGIC_LANE_CKPT:
                lane, inner = _split_lane_body(body)
                self._lane_last_checkpoint[lane] = _decode_checkpoint_body(
                    inner
                )
            # RCFG records are consumed via replay_reconfigs()
            good_end = end
        if good_end < len(data):  # torn/corrupt tail: drop it
            with open(self.path, "r+b") as fh:
                fh.truncate(good_end)

    def _append_record_locked(self, rec: bytes) -> None:
        self._fh.write(rec)
        self._fh.flush()
        if self.fsync:
            # fsync=True deployments opt into blocking the dispatcher
            # until the batch is on disk (crash recovery needs the
            # barrier); the cost is traced as a "ledger" span
            fd = self._fh.fileno()
            os.fsync(fd)  # staticcheck: allow[CONC004] durable-commit barrier, fsync=True opt-in

    def append(self, epoch: int, batch: Batch) -> None:
        rec = _encode_record(epoch, batch)
        tr = self.trace
        t0 = 0.0 if tr is None else tr.now()
        with self._lock:
            self._append_record_locked(rec)
            self._last_epoch = epoch
        if tr is not None:
            tr.complete(
                "ledger", "wal_append", t0, epoch=epoch, bytes=len(rec)
            )

    def append_ordered(self, epoch: int, output: Dict[str, bytes]) -> bytes:
        """Durably record ``epoch``'s ciphertext-ordered commit (the
        agreed ACS output) BEFORE threshold decryption runs — the
        ordered frontier's WAL write (Config.order_then_settle).
        Returns the encoded body (the bytes CATCHUP serves and the
        cross-node byte-identity invariant compares)."""
        body = encode_ordered_body(epoch, output)
        self.append_ordered_body(epoch, body)
        return body

    def append_ordered_body(self, epoch: int, body: bytes) -> None:
        """``append_ordered`` for a body already in hand (a COrd
        catch-up adoption): the WAL persists the EXACT bytes the
        quorum agreed on, so the durable record, the catch-up serving
        store, and the fuzzer's byte-identity witness can never
        diverge."""
        rec = _frame_record(_MAGIC_ORD, body)
        tr = self.trace
        t0 = 0.0 if tr is None else tr.now()
        with self._lock:
            self._append_record_locked(rec)
            self._last_ordered_epoch = epoch
        if tr is not None:
            tr.complete(
                "ledger", "wal_ordered", t0, epoch=epoch, bytes=len(rec)
            )

    def append_checkpoint(
        self, epoch: int, history: Sequence[Set[bytes]]
    ) -> None:
        """Snapshot the bounded dedup window (oldest epoch-set first)
        as of ``epoch``'s commit.  A torn checkpoint truncates away on
        the next open exactly like a torn batch record."""
        rec = _frame_record(
            _MAGIC_CKPT, _encode_checkpoint_body(epoch, history)
        )
        tr = self.trace
        t0 = 0.0 if tr is None else tr.now()
        with self._lock:
            self._append_record_locked(rec)
            self._last_checkpoint = (epoch, [set(s) for s in history])
        if tr is not None:
            tr.complete(
                "ledger", "wal_checkpoint", t0, epoch=epoch, bytes=len(rec)
            )

    def append_reconfig(
        self,
        version: int,
        activation_epoch: int,
        members: Sequence[Tuple[str, str, int]],
        key_digest: bytes,
    ) -> None:
        """Durably record a finalized roster switch (dynamic
        membership): written when the reshare ceremony completes,
        strictly BEFORE any epoch orders under the new roster."""
        rec = _frame_record(
            _MAGIC_RCFG,
            encode_reconfig_body(
                version, activation_epoch, members, key_digest
            ),
        )
        tr = self.trace
        t0 = 0.0 if tr is None else tr.now()
        with self._lock:
            self._append_record_locked(rec)
        if tr is not None:
            tr.complete(
                "ledger",
                "wal_reconfig",
                t0,
                version=version,
                activation_epoch=activation_epoch,
            )

    def replay_reconfigs(
        self,
    ) -> Iterator[Tuple[int, int, List[Tuple[str, str, int]], bytes]]:
        """All (version, activation_epoch, members, key_digest)
        reconfig records, oldest first — recovery's cross-check that
        the ceremony re-derived from the replayed batches matches what
        the crashed process had durably switched to."""
        with open(self.path, "rb") as fh:
            data = fh.read()
        for _end, magic, body in self._scan(data):
            if magic == _MAGIC_RCFG:
                yield decode_reconfig_body(body)

    def replay(self) -> Iterator[Tuple[int, Batch]]:
        """All committed (epoch, batch) records, oldest first
        (checkpoint records are skipped — see ``last_checkpoint``)."""
        with open(self.path, "rb") as fh:
            data = fh.read()
        for _end, magic, body in self._scan(data):
            if magic == _MAGIC:
                yield _decode_body(body)

    def replay_ordered(self) -> Iterator[Tuple[int, bytes]]:
        """All ciphertext-ordered (epoch, COrd body) records, oldest
        first.  A restart settles ordered-ahead epochs (COrd with no
        matching CLOG yet) from here — the ordering is never re-run."""
        with open(self.path, "rb") as fh:
            data = fh.read()
        for _end, magic, body in self._scan(data):
            if magic == _MAGIC_ORD:
                (epoch,) = struct.unpack_from(">Q", body, 0)
                yield epoch, body

    @property
    def last_epoch(self) -> Optional[int]:
        with self._lock:
            return self._last_epoch

    @property
    def last_ordered_epoch(self) -> Optional[int]:
        """Epoch of the newest COrd record, or None when the log holds
        no (intact) ordered record."""
        with self._lock:
            return self._last_ordered_epoch

    @property
    def last_checkpoint(self) -> Optional[Tuple[int, List[Set[bytes]]]]:
        """(epoch, dedup epoch-sets) of the newest checkpoint record,
        or None when the log holds no (intact) checkpoint."""
        with self._lock:
            return self._last_checkpoint

    def close(self) -> None:
        with self._lock:
            self._fh.close()

    def lane_view(self, lane: int) -> "_LaneLog":
        """The per-lane facade of this log (horizontal shard-out):
        lane 0 is the log itself — its records keep the bare magics,
        byte-identical to a single-lane build — and lanes > 0 get a
        delegating view that appends/replays ``u32 lane``-prefixed
        lane-magic records in the SAME file.  Restart recovery
        re-enters every lane's ordered-unsettled window independently
        by replaying its own view."""
        if lane == 0:
            return self
        return _LaneLog(self, lane)


class _LaneLog:
    """BatchLog facade for one lane > 0: the batch_log API surface the
    protocol plane consumes, with every record lane-tagged and every
    replay/last-* read filtered to this lane.  Shares the parent's
    file handle, lock and trace recorder; ``close()`` is a no-op (the
    lane-0 owner closes the file)."""

    __slots__ = ("_log", "lane")

    def __init__(self, log: BatchLog, lane: int):
        if lane < 1:
            raise ValueError(f"lane view lane={lane} must be >= 1")
        self._log = log
        self.lane = lane

    @property
    def path(self) -> str:
        return self._log.path

    @property
    def fsync(self) -> bool:
        return self._log.fsync

    @property
    def trace(self):
        return self._log.trace

    @trace.setter
    def trace(self, recorder) -> None:
        # lanes share the node's recorder; the primary installs it
        # once on the parent and lane installs are idempotent aliases
        self._log.trace = recorder

    def append(self, epoch: int, batch: Batch) -> None:
        log = self._log
        rec = _frame_record(
            _MAGIC_LANE, _lane_body(self.lane, _encode_body(epoch, batch))
        )
        tr = log.trace
        t0 = 0.0 if tr is None else tr.now()
        with log._lock:
            log._append_record_locked(rec)
            log._lane_last_epoch[self.lane] = epoch
        if tr is not None:
            tr.complete(
                "ledger", "wal_append", t0, epoch=epoch, bytes=len(rec),
                lane=self.lane,
            )

    def append_ordered(self, epoch: int, output: Dict[str, bytes]) -> bytes:
        body = encode_ordered_body(epoch, output)
        self.append_ordered_body(epoch, body)
        return body

    def append_ordered_body(self, epoch: int, body: bytes) -> None:
        log = self._log
        rec = _frame_record(_MAGIC_LANE_ORD, _lane_body(self.lane, body))
        tr = log.trace
        t0 = 0.0 if tr is None else tr.now()
        with log._lock:
            log._append_record_locked(rec)
            log._lane_last_ordered[self.lane] = epoch
        if tr is not None:
            tr.complete(
                "ledger", "wal_ordered", t0, epoch=epoch, bytes=len(rec),
                lane=self.lane,
            )

    def append_checkpoint(
        self, epoch: int, history: Sequence[Set[bytes]]
    ) -> None:
        log = self._log
        rec = _frame_record(
            _MAGIC_LANE_CKPT,
            _lane_body(self.lane, _encode_checkpoint_body(epoch, history)),
        )
        tr = log.trace
        t0 = 0.0 if tr is None else tr.now()
        with log._lock:
            log._append_record_locked(rec)
            log._lane_last_checkpoint[self.lane] = (
                epoch,
                [set(s) for s in history],
            )
        if tr is not None:
            tr.complete(
                "ledger", "wal_checkpoint", t0, epoch=epoch,
                bytes=len(rec), lane=self.lane,
            )

    def append_reconfig(self, *args, **kwargs) -> None:
        raise NotImplementedError(
            "dynamic membership is not supported at lanes > 1 "
            "(Config.lanes docs): no RCFG records in lane streams"
        )

    def replay(self) -> Iterator[Tuple[int, Batch]]:
        with open(self._log.path, "rb") as fh:
            data = fh.read()
        for _end, magic, body in self._log._scan(data):
            if magic == _MAGIC_LANE:
                lane, inner = _split_lane_body(body)
                if lane == self.lane:
                    yield _decode_body(inner)

    def replay_ordered(self) -> Iterator[Tuple[int, bytes]]:
        with open(self._log.path, "rb") as fh:
            data = fh.read()
        for _end, magic, body in self._log._scan(data):
            if magic == _MAGIC_LANE_ORD:
                lane, inner = _split_lane_body(body)
                if lane == self.lane:
                    (epoch,) = struct.unpack_from(">Q", inner, 0)
                    yield epoch, inner

    def replay_reconfigs(self):
        return iter(())  # lanes never carry roster switches

    @property
    def last_epoch(self) -> Optional[int]:
        log = self._log
        with log._lock:
            return log._lane_last_epoch.get(self.lane)

    @property
    def last_ordered_epoch(self) -> Optional[int]:
        log = self._log
        with log._lock:
            return log._lane_last_ordered.get(self.lane)

    @property
    def last_checkpoint(self) -> Optional[Tuple[int, List[Set[bytes]]]]:
        log = self._log
        with log._lock:
            return log._lane_last_checkpoint.get(self.lane)

    def close(self) -> None:
        pass  # the lane-0 owner closes the shared file


__all__ = [
    "BatchLog",
    "encode_batch_body",
    "decode_batch_body",
    "encode_ordered_body",
    "decode_ordered_body",
    "encode_reconfig_body",
    "decode_reconfig_body",
]
