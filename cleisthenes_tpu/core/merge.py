"""Deterministic cross-lane total-order merge (ISSUE 20).

Horizontal shard-out runs S independent HBBFT lane instances over one
roster; each lane commits its own settled epoch stream.  Two pure
functions turn those S streams into one system:

``lane_of``
    The admission partitioner: ``sha256(seed || digest) % S``.  A pure
    function of (seed, tx digest, S) — identical on every node and
    under every PYTHONHASHSEED, so all honest nodes admit a given
    transaction into the SAME lane and the per-lane ledgers stay
    disjoint by construction.

``MergeCursor``
    The settled-frontier merge: the merged total order enumerates
    slots epoch-major, lane-minor —

        (epoch 0, lane 0), (epoch 0, lane 1), ..., (epoch 0, lane S-1),
        (epoch 1, lane 0), ...

    A slot emits the moment its lane settles that epoch AND every
    earlier slot has emitted.  Because each lane settles strictly in
    epoch order and the slot sequence is fixed, the merged order is a
    pure function of the committed bytes: honest nodes that settled
    the same per-lane prefixes hold byte-identical merged prefixes,
    regardless of the wall-clock interleaving in which lanes settled.

The merge is deliberately NOT fee- or timestamp-aware: any dynamic
key would make the total order depend on per-node observation order.
Slot arithmetic only.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Tuple

__all__ = ["lane_of", "merge_order", "MergeCursor"]


def _seed_bytes(seed: Optional[int]) -> bytes:
    # the mempool tiebreak's packing (core.mempool): unseeded
    # configs partition with seed 0 — still deterministic, just not
    # operator-chosen
    return (seed or 0).to_bytes(8, "big", signed=True)


def lane_of(seed: Optional[int], digest: bytes, lanes: int) -> int:
    """Admission lane for a transaction digest: seeded
    ``sha256(seed || digest) % S``.  ``lanes=1`` maps everything to
    lane 0 (the single-lane build never calls this)."""
    if lanes <= 1:
        return 0
    h = hashlib.sha256(_seed_bytes(seed) + digest).digest()
    return int.from_bytes(h[:8], "big") % lanes


class MergeCursor:
    """Incremental epoch-major, lane-minor merge over S settled lane
    streams.

    ``push(lane, epoch, batch)`` records one lane settlement (epochs
    per lane must arrive in order — they do, lanes settle strictly in
    epoch order); ``drain()`` returns every newly emittable merged
    slot as ``(seq, lane, epoch, batch)`` rows, where ``seq`` is the
    global merged position ``epoch * S + lane``.  The emitted prefix
    is also kept in ``merged`` for subscription replay.
    """

    __slots__ = ("lanes", "_pending", "_next", "merged")

    def __init__(self, lanes: int) -> None:
        if lanes < 1:
            raise ValueError(f"lanes={lanes} must be >= 1")
        self.lanes = lanes
        # (epoch, lane) -> batch, settled but not yet merge-emitted
        self._pending: Dict[Tuple[int, int], object] = {}
        self._next = 0  # next merged seq = epoch * S + lane
        self.merged: List[object] = []  # emitted batches, seq order

    @property
    def frontier(self) -> int:
        """Number of merged slots emitted (the merged settled
        frontier)."""
        return self._next

    def push(self, lane: int, epoch: int, batch) -> None:
        if not (0 <= lane < self.lanes):
            raise ValueError(f"lane={lane} out of range 0..{self.lanes - 1}")
        self._pending[(epoch, lane)] = batch

    def drain(self) -> List[Tuple[int, int, int, object]]:
        out: List[Tuple[int, int, int, object]] = []
        pending = self._pending
        while True:
            epoch, lane = divmod(self._next, self.lanes)
            if (epoch, lane) not in pending:
                return out
            batch = pending.pop((epoch, lane))
            out.append((self._next, lane, epoch, batch))
            self.merged.append(batch)
            self._next += 1


def merge_order(settled: List[List[object]]) -> List[object]:
    """The batch merge rule applied wholesale: per-lane settled batch
    lists in, the emittable merged prefix out (fuzz oracle; the live
    path uses MergeCursor incrementally)."""
    cur = MergeCursor(max(1, len(settled)))
    for lane, batches in enumerate(settled):
        for epoch, batch in enumerate(batches):
            cur.push(lane, epoch, batch)
    cur.drain()
    return cur.merged
