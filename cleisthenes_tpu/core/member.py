"""Network roster: Address, Member, MemberMap — and, since the
dynamic-membership PR, the VERSIONED roster vocabulary.

Mirrors reference member_map.go: ``Address{Ip, Port}``
(member_map.go:12-19), ``Member{Id, Addr}`` (member_map.go:22-25), and
the RWMutex-guarded id->member ``MemberMap`` with Members/Member/Add/Del
(member_map.go:43-87).

``RosterVersion`` / ``RosterSchedule`` are the dynamic-membership
additions (docs/ARCHITECTURE.md "Dynamic membership"): a roster is no
longer a construction-time constant but a VERSIONED value activating
at an epoch boundary — every epoch-scoped structure resolves n/f/keys
through ``RosterSchedule.version_for(epoch)`` instead of reading the
construction-time ``Config``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from cleisthenes_tpu.utils.determinism import guarded_by
from cleisthenes_tpu.utils.lockcheck import new_rlock


@dataclasses.dataclass(frozen=True, order=True)
class Address:
    """Peer network address (reference member_map.go:12-19)."""

    ip: str
    port: int

    def __str__(self) -> str:
        return f"{self.ip}:{self.port}"


@dataclasses.dataclass(frozen=True, order=True)
class Member:
    """A validator identity: id + address (reference member_map.go:22-25).

    ``id`` is an opaque string (the reference uses uuid strings for
    connection ids, comm.go:46); for consensus we conventionally use
    stable validator names so Shamir share indices can be derived from
    roster order.
    """

    id: str
    addr: Address = Address("", 0)

    def address(self) -> Address:
        """Reference member_map.go:38."""
        return self.addr


@dataclasses.dataclass(frozen=True)
class RosterVersion:
    """One activated (or pending) roster configuration.

    ``activation_epoch``: the first epoch ORDERED under this roster —
    the PR-8 ordered frontier is the switch point, so the boundary is
    WAL-durable and identical at every honest node.  ``members`` is
    the sorted member tuple (sorted order defines Shamir share
    indices, exactly like the construction-time roster).
    ``key_material_digest`` commits to the version's public threshold
    key material (TPKE + coin master keys and verification-key
    tables): every honest node derives the identical digest from the
    committed ceremony, which makes key agreement a checkable
    cross-node invariant (tools/fuzz.py reconfig band).
    """

    version: int
    activation_epoch: int
    members: Tuple[Member, ...]
    key_material_digest: bytes = b""

    def __post_init__(self) -> None:
        ordered = tuple(sorted(self.members, key=lambda m: m.id))
        if ordered != self.members:
            object.__setattr__(self, "members", ordered)

    @property
    def member_ids(self) -> Tuple[str, ...]:
        return tuple(m.id for m in self.members)

    @property
    def n(self) -> int:
        return len(self.members)

    @property
    def f(self) -> int:
        """Maximum fault budget under the BASELINE (3f+1) trust model.
        Quorum-mode-aware consumers use ``fault_budget``."""
        return (len(self.members) - 1) // 3

    def fault_budget(self, reduced_quorum: bool = False) -> int:
        """Maximum tolerable f for this roster size under the given
        trust model: floor((n-1)/3) baseline, floor((n-1)/2) when the
        attested sender log enables the reduced (2f+1) quorum mode —
        the seam through which roster views carry the quorum mode
        (Config.reduced_quorum re-derives per-version f through the
        same arithmetic in HoneyBadger.install_roster_version)."""
        d = 2 if reduced_quorum else 3
        return (len(self.members) - 1) // d


class RosterSchedule:
    """The ordered sequence of roster versions one node knows about.

    Single-threaded (owned by the protocol actor); versions append in
    order and never retract — a version, once installed, is a durable
    fact of the log (the RCFG WAL record replays it).
    """

    def __init__(self, genesis: RosterVersion) -> None:
        # the base version is 0 for a dealer-provisioned deployment;
        # a JOINER boots with the cluster's CURRENT version as its
        # base (its view of history starts at the roster it dials)
        if genesis.activation_epoch != 0:
            raise ValueError(
                "genesis roster must activate at epoch 0"
            )
        self._versions: List[RosterVersion] = [genesis]

    def install(self, rv: RosterVersion) -> None:
        last = self._versions[-1]
        if rv.version != last.version + 1:
            raise ValueError(
                f"roster version {rv.version} does not extend "
                f"{last.version}"
            )
        if rv.activation_epoch <= last.activation_epoch:
            raise ValueError(
                f"activation epoch {rv.activation_epoch} does not "
                f"advance past {last.activation_epoch}"
            )
        self._versions.append(rv)

    def version_for(self, epoch: int) -> RosterVersion:
        """The roster an epoch runs under: the newest version with
        ``activation_epoch <= epoch`` (epochs below 0 resolve to
        genesis)."""
        for rv in reversed(self._versions):
            if rv.activation_epoch <= epoch:
                return rv
        return self._versions[0]

    def latest(self) -> RosterVersion:
        return self._versions[-1]

    def known_member_ids(self) -> frozenset:
        """Union of every version's member ids — the membership test
        for epoch-UNSCOPED traffic (CATCHUP, reshare gossip), where a
        joiner or a retiree is still a legitimate correspondent."""
        out: set = set()
        for rv in self._versions:
            out.update(rv.member_ids)
        return frozenset(out)

    def __len__(self) -> int:
        return len(self._versions)

    def __iter__(self):
        return iter(self._versions)


@guarded_by("_lock", "_members")
class MemberMap:
    """Lock-guarded id -> Member map (reference member_map.go:43-87)."""

    def __init__(self) -> None:
        self._members: Dict[str, Member] = {}
        self._lock = new_rlock()

    def add(self, member: Member) -> None:
        with self._lock:
            self._members[member.id] = member

    def delete(self, member_id: str) -> None:
        """Reference member_map.go:82-87 (Del)."""
        with self._lock:
            self._members.pop(member_id, None)

    def member(self, member_id: str) -> Optional[Member]:
        with self._lock:
            return self._members.get(member_id)

    def members(self) -> List[Member]:
        """Snapshot of all members, sorted by id for deterministic
        roster order (share indices, proposer ordering)."""
        with self._lock:
            return sorted(self._members.values(), key=lambda m: m.id)

    def __len__(self) -> int:
        with self._lock:
            return len(self._members)

    def __contains__(self, member_id: str) -> bool:
        with self._lock:
            return member_id in self._members
