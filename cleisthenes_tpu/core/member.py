"""Network roster: Address, Member, MemberMap.

Mirrors reference member_map.go: ``Address{Ip, Port}``
(member_map.go:12-19), ``Member{Id, Addr}`` (member_map.go:22-25), and
the RWMutex-guarded id->member ``MemberMap`` with Members/Member/Add/Del
(member_map.go:43-87).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional

from cleisthenes_tpu.utils.determinism import guarded_by


@dataclasses.dataclass(frozen=True, order=True)
class Address:
    """Peer network address (reference member_map.go:12-19)."""

    ip: str
    port: int

    def __str__(self) -> str:
        return f"{self.ip}:{self.port}"


@dataclasses.dataclass(frozen=True, order=True)
class Member:
    """A validator identity: id + address (reference member_map.go:22-25).

    ``id`` is an opaque string (the reference uses uuid strings for
    connection ids, comm.go:46); for consensus we conventionally use
    stable validator names so Shamir share indices can be derived from
    roster order.
    """

    id: str
    addr: Address = Address("", 0)

    def address(self) -> Address:
        """Reference member_map.go:38."""
        return self.addr


@guarded_by("_lock", "_members")
class MemberMap:
    """Lock-guarded id -> Member map (reference member_map.go:43-87)."""

    def __init__(self) -> None:
        self._members: Dict[str, Member] = {}
        self._lock = threading.RLock()

    def add(self, member: Member) -> None:
        with self._lock:
            self._members[member.id] = member

    def delete(self, member_id: str) -> None:
        """Reference member_map.go:82-87 (Del)."""
        with self._lock:
            self._members.pop(member_id, None)

    def member(self, member_id: str) -> Optional[Member]:
        with self._lock:
            return self._members.get(member_id)

    def members(self) -> List[Member]:
        """Snapshot of all members, sorted by id for deterministic
        roster order (share indices, proposer ordering)."""
        with self._lock:
            return sorted(self._members.values(), key=lambda m: m.id)

    def __len__(self) -> int:
        with self._lock:
            return len(self._members)

    def __contains__(self, member_id: str) -> bool:
        with self._lock:
            return member_id in self._members
