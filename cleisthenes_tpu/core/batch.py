"""Committed batch of transactions.

Reference honeybadger.go:10-16: ``Batch{txList []Transaction}`` with
``TxList()``.  Here a batch additionally remembers which proposer
contributed which transactions (the ACS output is a union of per-
proposer contributions, docs/HONEYBADGER-EN.md:85-89), which the
reference leaves implicit because its ACS is absent.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List

from cleisthenes_tpu.core.queue import Transaction


@dataclasses.dataclass
class Batch:
    """An ordered set of committed transactions (honeybadger.go:10-16)."""

    # proposer id -> that proposer's contributed transactions, in
    # proposal order.  Iteration over proposers is by sorted id so every
    # correct node derives the identical total order (Atomic Broadcast
    # "Total order", docs/HONEYBADGER-EN.md:24-25).
    contributions: Dict[str, List[Transaction]] = dataclasses.field(
        default_factory=dict
    )

    def tx_list(self) -> List[Transaction]:
        """Flattened, deterministically-ordered transactions
        (reference honeybadger.go:14)."""
        out: List[Transaction] = []
        for proposer in sorted(self.contributions):
            out.extend(self.contributions[proposer])
        return out

    def __len__(self) -> int:
        return sum(len(v) for v in self.contributions.values())
