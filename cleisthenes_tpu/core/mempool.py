"""Fee-priority mempool: the admission stage ahead of the TxQueue.

The ingress plane (transport/ingress.py) terminates untrusted client
submissions; this module is its policy core.  Where the FIFO TxQueue
(core/queue.py) trusts its callers — the node itself, protocol-internal
transactions — the mempool assumes an open-loop, adversarial client
population and makes three promises the ingress ack contract
(docs/ARCHITECTURE.md "Ingress plane") is built on:

1. **No silent drops.**  Every ``admit`` returns an explicit verdict:
   OK, DUPLICATE (the tx is already pending, in flight, or recently
   settled), RETRY_AFTER (per-client cap or global pressure — come
   back in ``retry_after_ms``), or REJECTED (malformed/oversized).
   An OK'd tx either settles or is *visibly* evicted (the ``evicted``
   counter + the on_evict hook), never lost in between — the fuzz
   band's settles-exactly-once invariant (tools/fuzz.py --ingress).

2. **Priority under pressure.**  Entries order by (fee desc, seeded
   tie-break, admission seq): batch selection drains highest-fee
   first, and when the pool is full a NEW submission bumps the
   lowest-priority *pending* entry only if it strictly outbids it —
   otherwise the newcomer waits.  In-flight entries (already drained
   into the TxQueue) are past the point of eviction.

3. **Determinism.**  The tie-break among equal fees is
   sha256(seed || digest) — a pure function of the config seed and
   the tx bytes — so two nodes (or two PYTHONHASHSEED arms) given the
   same submission stream admit, order, and evict identically.  No
   wall clock, no id(), no hash() anywhere in the policy.

Dedup layering: the mempool's bounded seen-ring is the cheap front
door (a resubmit never re-enters the pool); the committed-history
filter at batch selection (HoneyBadger._load_candidate_txs) remains
the authoritative settle-time dedup.  ``mark_settled`` is the
coordination point — settling a tx retires its in-flight accounting,
frees the client's cap slot, and leaves the digest in the seen-ring so
late resubmits still ack DUPLICATE.
"""

from __future__ import annotations

import collections
import hashlib
import heapq
from typing import Callable, Deque, Dict, Iterable, List, NamedTuple, Optional, Tuple

from cleisthenes_tpu.core.merge import lane_of
from cleisthenes_tpu.utils.determinism import guarded_by
from cleisthenes_tpu.utils.lockcheck import new_lock

# admission verdicts (mirrored onto the wire as
# transport.message.IngressStatus; core stays transport-free)
OK = "ok"
DUPLICATE = "duplicate"
REJECTED = "rejected"
RETRY_AFTER = "retry_after"

# a tx larger than this is rejected outright (same order as the wire
# field cap; a mempool must bound its per-entry memory)
MAX_TX_BYTES = 1 << 20


class Admission(NamedTuple):
    """One admit() verdict: ``status`` is OK/DUPLICATE/REJECTED/
    RETRY_AFTER, ``retry_after_ms`` is nonzero only for RETRY_AFTER,
    ``reason`` is a human-readable cause, ``digest`` names the tx."""

    status: str
    retry_after_ms: int
    reason: str
    digest: bytes


class _Entry:
    __slots__ = (
        "digest", "client_id", "fee", "tb", "seq", "tx", "drained", "lane",
    )

    def __init__(self, digest, client_id, fee, tb, seq, tx, lane=0):
        self.digest = digest
        self.client_id = client_id
        self.fee = fee
        self.tb = tb
        self.seq = seq
        self.tx = tx
        self.drained = False
        self.lane = lane


def tx_digest(tx: bytes) -> bytes:
    """The mempool's name for a transaction: sha256 of its bytes."""
    return hashlib.sha256(tx).digest()


@guarded_by(
    "_lock",
    "_live",
    "_seen",
    "_by_client",
    "_drain_heaps",
    "_evict_heap",
    "_seq",
    "_lane_pending",
)
class Mempool:
    """One node's fee-priority admission pool.  Thread-safe: admit()
    runs on gRPC ingress threads while drain_into()/mark_settled()
    run on the protocol dispatcher."""

    def __init__(
        self,
        *,
        capacity: int,
        client_cap: int = 64,
        seen_cap: int = 1 << 16,
        retry_after_ms: int = 100,
        seed: int = 0,
        on_evict: Optional[Callable[[bytes, str], None]] = None,
        lanes: int = 1,
    ) -> None:
        if capacity < 1:
            raise ValueError(f"capacity={capacity} must be >= 1")
        if client_cap < 1 or seen_cap < 1:
            raise ValueError(
                f"client_cap={client_cap} seen_cap={seen_cap}: both "
                "must be >= 1"
            )
        if lanes < 1:
            raise ValueError(f"lanes={lanes} must be >= 1")
        self.capacity = capacity
        self.client_cap = client_cap
        self.seen_cap = seen_cap
        self.retry_after_ms = retry_after_ms
        self.lanes = lanes
        self._seed = seed
        self._tb_seed = seed.to_bytes(8, "big", signed=True)
        self._on_evict = on_evict
        self._lock = new_lock()
        # digest -> entry, pending AND in-flight (drained, unsettled)
        self._live: Dict[bytes, _Entry] = {}
        # bounded FIFO dedup ring: admitted + settled digests
        self._seen: Deque[bytes] = collections.deque()
        self._seen_set: set = set()
        # client -> live (pending + in-flight) entry count
        self._by_client: Dict[str, int] = {}
        # lazy-deletion heaps over PENDING entries; stale slots are
        # skipped at pop when the digest is gone or already drained.
        # One drain heap PER LANE (horizontal shard-out): admission
        # routes each entry to lane_of(seed, digest, lanes) and each
        # lane's batch selection drains only its own heap, so the
        # per-lane ledgers stay disjoint.  lanes=1 keeps the single
        # heap and bit-identical drain order.  Eviction stays global
        # (the lowest-priority pending entry across all lanes).
        self._drain_heaps: List[List[Tuple[int, bytes, int, bytes]]] = [
            [] for _ in range(lanes)
        ]
        self._evict_heap: List[Tuple[int, bytes, int, bytes]] = []
        self._seq = 0
        # per-lane gauges/fill counters (partition-skew reporting)
        self._lane_pending: List[int] = [0] * lanes
        self.lane_admitted: List[int] = [0] * lanes
        # lifetime counters (the ingress metrics block reads these)
        self.submitted = 0
        self.admitted = 0
        self.deduped = 0
        self.rejected = 0
        self.retried = 0
        self.evicted = 0

    # -- policy helpers (pure; no lock needed) --------------------------

    def _tiebreak(self, digest: bytes) -> bytes:
        """Seeded, hash()-free order among equal fees: a pure function
        of (seed, digest), identical across nodes and interpreter
        hash randomization."""
        return hashlib.sha256(self._tb_seed + digest).digest()[:16]

    @staticmethod
    def _inv(tb: bytes) -> bytes:
        """Byte-wise complement: reverses the tb order so the eviction
        min-heap surfaces the entry the drain order ranks LAST."""
        return bytes(255 - b for b in tb)

    def _outranks(self, fee: int, tb: bytes, e: "_Entry") -> bool:
        """Does (fee, tb) strictly outbid entry ``e`` in drain order?"""
        return (fee, self._inv(tb)) > (e.fee, self._inv(e.tb))

    # -- admission ------------------------------------------------------

    def admit(self, tx: bytes, client_id: str, fee: int) -> Admission:
        """Admit one client transaction; returns an explicit verdict
        (promise 1 above: never a silent drop)."""
        digest = tx_digest(tx)
        with self._lock:
            self.submitted += 1
            if not tx or len(tx) > MAX_TX_BYTES or fee < 0:
                self.rejected += 1
                return Admission(
                    REJECTED, 0,
                    "empty tx" if not tx else (
                        f"tx of {len(tx)} bytes exceeds cap"
                        if len(tx) > MAX_TX_BYTES else "negative fee"
                    ),
                    digest,
                )
            if digest in self._seen_set:
                self.deduped += 1
                return Admission(
                    DUPLICATE, 0, "tx already pending or settled", digest
                )
            if self._by_client.get(client_id, 0) >= self.client_cap:
                self.retried += 1
                return Admission(
                    RETRY_AFTER, self.retry_after_ms,
                    f"client has {self.client_cap} txs in flight",
                    digest,
                )
            tb = self._tiebreak(digest)
            if len(self._live) >= self.capacity:
                victim = self._lowest_pending_locked()
                if victim is None or not self._outranks(fee, tb, victim):
                    # full of equal-or-better work: the newcomer waits
                    self.retried += 1
                    return Admission(
                        RETRY_AFTER, self.retry_after_ms,
                        "mempool at capacity", digest,
                    )
                self._evict_locked(victim)
            self._seq += 1
            lane = (
                lane_of(self._seed, digest, self.lanes)
                if self.lanes > 1
                else 0
            )
            e = _Entry(digest, client_id, fee, tb, self._seq, tx, lane)
            self._live[digest] = e
            self._by_client[client_id] = (
                self._by_client.get(client_id, 0) + 1
            )
            self._remember_locked(digest)
            heapq.heappush(
                self._drain_heaps[lane], (-fee, tb, e.seq, digest)
            )
            heapq.heappush(
                self._evict_heap, (fee, self._inv(tb), -e.seq, digest)
            )
            self.admitted += 1
            self.lane_admitted[lane] += 1
            self._lane_pending[lane] += 1
            return Admission(OK, 0, "", digest)

    def _remember_locked(self, digest: bytes) -> None:
        self._seen.append(digest)
        self._seen_set.add(digest)
        while len(self._seen) > self.seen_cap:
            old = self._seen.popleft()
            self._seen_set.discard(old)

    def _lowest_pending_locked(self) -> Optional[_Entry]:
        """The pending entry the drain order ranks last (lazy-deletion
        scan of the eviction heap; in-flight entries are skipped AND
        popped — they can never become eviction candidates again)."""
        while self._evict_heap:
            fee, inv_tb, neg_seq, digest = self._evict_heap[0]
            e = self._live.get(digest)
            if e is None or e.drained or e.seq != -neg_seq:
                heapq.heappop(self._evict_heap)
                continue
            return e
        return None

    def _evict_locked(self, e: "_Entry") -> None:
        heapq.heappop(self._evict_heap)
        del self._live[e.digest]
        self._lane_pending[e.lane] -= 1
        self._dec_client_locked(e.client_id)
        # an evicted digest stays in the seen-ring: a resubmit of it
        # acks DUPLICATE until the ring forgets it, which is the
        # documented cost of the bounded-memory front door
        self.evicted += 1
        if self._on_evict is not None:
            self._on_evict(e.digest, e.client_id)

    def _dec_client_locked(self, client_id: str) -> None:
        n = self._by_client.get(client_id, 0) - 1
        if n <= 0:
            self._by_client.pop(client_id, None)
        else:
            self._by_client[client_id] = n

    # -- the TxQueue seam ----------------------------------------------

    def drain_into(self, queue, max_n: int, lane: int = 0) -> int:
        """Move up to ``max_n`` highest-priority pending txs of
        ``lane`` into the FIFO TxQueue ahead of batch selection (the
        single-lane build always drains lane 0, the only heap).
        Drained entries stay live (in flight) for client-cap
        accounting and the settles-exactly-once ledger until
        mark_settled retires them."""
        moved = 0
        with self._lock:
            heap = self._drain_heaps[lane]
            while moved < max_n and heap:
                neg_fee, tb, seq, digest = heap[0]
                e = self._live.get(digest)
                if e is None or e.drained or e.seq != seq:
                    heapq.heappop(heap)
                    continue
                heapq.heappop(heap)
                e.drained = True
                self._lane_pending[e.lane] -= 1
                queue.push(e.tx)
                moved += 1
        return moved

    # -- settle-time coordination --------------------------------------

    def mark_settled(self, txs: Iterable[bytes]) -> None:
        """Retire settled txs: frees the client's cap slot and the
        entry's memory; the digest stays in the seen-ring so a late
        resubmit still acks DUPLICATE."""
        with self._lock:
            for tx in txs:
                digest = tx_digest(tx)
                e = self._live.pop(digest, None)
                if e is not None:
                    if not e.drained:
                        # settled from a PEER's proposal while still
                        # pending here: retire the lane gauge too
                        self._lane_pending[e.lane] -= 1
                    self._dec_client_locked(e.client_id)

    # -- introspection --------------------------------------------------

    def pending_count(self, lane: Optional[int] = None) -> int:
        """Entries admitted but not yet drained into the TxQueue
        (optionally of one lane only — the lane's propose gate)."""
        with self._lock:
            if lane is not None:
                return self._lane_pending[lane]
            return sum(1 for e in self._live.values() if not e.drained)

    def lane_fill(self) -> List[int]:
        """Lifetime admissions per lane — the partition-skew witness
        (loadgen reports max/min over this; snapshot()["lanes"]
        carries the spread)."""
        with self._lock:
            return list(self.lane_admitted)

    def inflight_count(self) -> int:
        """Entries drained into the TxQueue but not yet settled."""
        with self._lock:
            return sum(1 for e in self._live.values() if e.drained)

    def depth(self) -> int:
        """All live (pending + in-flight) entries — the gauge the
        queue-backpressure SLO watchdog reads."""
        with self._lock:
            return len(self._live)

    def __len__(self) -> int:
        return self.depth()

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "submitted": self.submitted,
                "admitted": self.admitted,
                "deduped": self.deduped,
                "rejected": self.rejected,
                "retried": self.retried,
                "evicted": self.evicted,
                "depth": len(self._live),
            }


__all__ = [
    "Admission",
    "Mempool",
    "MAX_TX_BYTES",
    "OK",
    "DUPLICATE",
    "REJECTED",
    "RETRY_AFTER",
    "tx_digest",
]
