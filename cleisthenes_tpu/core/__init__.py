"""Core data types: transaction queue, membership, batches, request repos."""

from cleisthenes_tpu.core.batch import Batch
from cleisthenes_tpu.core.member import Address, Member, MemberMap
from cleisthenes_tpu.core.queue import (
    EmptyQueueError,
    IndexBoundaryError,
    Transaction,
    TxQueue,
)
from cleisthenes_tpu.core.request import (
    IncomingRequestRepository,
    RequestRepository,
)

__all__ = [
    "Batch",
    "Address",
    "Member",
    "MemberMap",
    "TxQueue",
    "Transaction",
    "EmptyQueueError",
    "IndexBoundaryError",
    "RequestRepository",
    "IncomingRequestRepository",
]
