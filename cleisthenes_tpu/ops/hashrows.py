"""Batched host-side SHA-256: one native call per wave.

The lockstep executor and the live hub both end every crypto wave
with a host loop that hashes one short transcript per share (CP
challenges) or per Merkle node — at N=128 that is ~265k hashlib calls
per epoch, and the Python call overhead dwarfs the compression work.
``sha256_rows`` hashes a whole (m, stride) row-matrix in one ctypes
crossing via native/sha256rows.cpp, degrading to a hashlib loop when
the toolchain is unavailable (identical digests either way — the
native kernel is plain FIPS 180-4, selftested at load).
"""

from __future__ import annotations

import hashlib
from typing import List, Optional, Sequence

import numpy as np

from cleisthenes_tpu.native.build import load_sha256


def sha256_rows(
    rows: np.ndarray, lens: Optional[np.ndarray] = None
) -> np.ndarray:
    """Digest each row of a (m, stride) uint8 matrix -> (m, 32) uint8.

    ``lens`` gives per-row message lengths (defaults to the full
    stride for every row)."""
    rows = np.ascontiguousarray(rows, dtype=np.uint8)
    if rows.ndim != 2:
        raise ValueError(f"rows must be 2-D, got shape {rows.shape}")
    m, stride = rows.shape
    out = np.empty((m, 32), dtype=np.uint8)
    if m == 0:
        return out
    lens32 = None
    if lens is not None:
        lens32 = np.ascontiguousarray(lens, dtype=np.int32)
        if lens32.shape != (m,):
            raise ValueError("lens must be (m,)")
        if int(lens32.min()) < 0 or int(lens32.max()) > stride:
            # the native kernel casts straight to size_t: an
            # out-of-range length would read past the row (and the
            # fallback would silently truncate — reject in both)
            raise ValueError("lens values must be in [0, stride]")
    lib = load_sha256()
    if lib is not None:
        if lens32 is None:
            lib.sha256_rows_fixed(
                rows.ctypes.data, m, stride, stride, out.ctypes.data
            )
        else:
            lib.sha256_rows(
                rows.ctypes.data, m, stride, lens32.ctypes.data,
                out.ctypes.data,
            )
        return out
    # degraded path: identical digests, one hashlib call per row
    if lens32 is None:
        for i in range(m):
            out[i] = np.frombuffer(
                hashlib.sha256(rows[i].tobytes()).digest(), dtype=np.uint8
            )
    else:
        for i in range(m):
            out[i] = np.frombuffer(
                hashlib.sha256(rows[i, : int(lens32[i])].tobytes()).digest(),
                dtype=np.uint8,
            )
    return out


def ints_to_be_rows(values: Sequence[int], nbytes: int) -> np.ndarray:
    """(m, nbytes) big-endian byte matrix from Python ints — the
    transcript field encoder (same bytes as int.to_bytes per item)."""
    m = len(values)
    # one join + one frombuffer for the whole column: per-item
    # frombuffer assignments were a top-5 profile line at N=128
    buf = b"".join(v.to_bytes(nbytes, "big") for v in values)
    return np.frombuffer(buf, dtype=np.uint8).reshape(m, nbytes).copy()


__all__ = ["sha256_rows", "ints_to_be_rows"]
