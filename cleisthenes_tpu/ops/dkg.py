"""Distributed key generation: threshold keys without the dealer.

The one trust assumption this framework inherits from the reference's
design docs is the trusted dealer (reference
docs/THRESHOLD_ENCRYPTION-EN.md:33 assumes "SetUp" hands out shares;
ops/tpke.py's ``deal`` implements exactly that).  This module removes
it: Joint-Feldman DKG over the same prime-order group, where every
participant acts as a dealer of a random secret and the final key is
the sum of the QUALIFIED dealings.

Per participant i (threshold t, roster 1..n):

  1. sample f_i(x) = a_i0 + a_i1 x + ... + a_i,t-1 x^(t-1) over Z_q
  2. broadcast Feldman commitments C_ik = g^{a_ik}  (k < t)
  3. send s_ij = f_i(j) to participant j over a private channel
  4. j accepts iff g^{s_ij} == prod_k C_ik^{j^k}  (verify_dealer_share)
  5. dealers with any valid complaint are disqualified; the qualified
     set Q survives, and j's final share is x_j = sum_{i in Q} s_ij,
     the master key h = prod_{i in Q} C_i0, and every verification key
     h_j = prod_{i in Q} prod_k C_ik^{j^k} is PUBLICLY computable —
     so the output is a drop-in ``ThresholdPublicKey`` +
     ``ThresholdSecretShare`` pair for TPKE and the common coin.

Security note (documented, deliberate): plain Joint-Feldman lets a
rushing adversary bias the distribution of the final public key
(Gennaro, Jarecki, Krawczyk, Rabin 1999); the fix is their two-phase
variant with Pedersen commitments in phase one.  The bias does not
affect secrecy of the shares — only uniformity of the key — and the
phase structure here (deal -> verify -> complain -> finalize over the
same commitment algebra) is exactly the skeleton that variant slots
into.  The share transport must be private: this module produces and
verifies the protocol's VALUES and leaves carriage to the caller
(tests drive it in-process; a deployment would wrap shares in a
key-agreed channel).

All verification exponentiations batch through the ModEngine seam —
one ``pow_batch`` for a whole roster's share checks, one for the full
verification-key table — same as every other crypto plane in ops/.
"""

from __future__ import annotations

import hashlib
import secrets as _secrets
from typing import Dict, List, Optional, Sequence, Tuple

from cleisthenes_tpu.ops.modmath import (
    DEFAULT_GROUP,
    GroupParams,
    get_engine,
)
from cleisthenes_tpu.ops.tpke import (
    ThresholdPublicKey,
    ThresholdSecretShare,
)


class DkgDealing:
    """One participant's dealer role: polynomial + commitments + the
    per-receiver shares."""

    def __init__(
        self,
        dealer_index: int,
        n: int,
        threshold: int,
        group: GroupParams = DEFAULT_GROUP,
        seed: Optional[int] = None,
    ) -> None:
        if not (1 <= threshold <= n):
            raise ValueError(f"need 1 <= t <= n, got t={threshold} n={n}")
        self.dealer_index = dealer_index
        self.n = n
        self.threshold = threshold
        self.group = group
        q = group.q
        nb = group.nbytes + 8  # excess bytes: unbiased mod-q samples
        if seed is None:
            rnd = _secrets.token_bytes
        else:
            ctr = [0]

            def rnd(k: int, _s=seed, _d=dealer_index) -> bytes:
                out = b""
                while len(out) < k:
                    ctr[0] += 1
                    out += hashlib.sha256(
                        b"dkg|%d|%d|%d" % (_s, _d, ctr[0])
                    ).digest()
                return out[:k]

        self._coeffs = [
            int.from_bytes(rnd(nb), "big") % q for _ in range(threshold)
        ]

    def commitments(self, backend: str = "cpu", mesh=None) -> List[int]:
        """Feldman commitments C_k = g^{a_k} — broadcast publicly."""
        gp = self.group
        eng = get_engine(
            backend if gp.p.bit_length() <= 256 else "cpu", mesh, gp
        )
        return eng.pow_batch([gp.g] * len(self._coeffs), self._coeffs)

    def share_for(self, receiver_index: int) -> int:
        """s_ij = f_i(j) — send PRIVATELY to participant j (1-based)."""
        if not (1 <= receiver_index <= self.n):
            raise ValueError(f"receiver index {receiver_index} out of roster")
        q = self.group.q
        acc = 0
        for c in reversed(self._coeffs):
            acc = (acc * receiver_index + c) % q
        return acc


def _commit_eval_exps(
    j: int, threshold: int, q: int
) -> List[int]:
    """[j^k mod q for k < threshold] — the exponents of the commitment
    product at evaluation point j."""
    out = [1]
    for _ in range(threshold - 1):
        out.append(out[-1] * j % q)
    return out


def validate_commitments(
    commitment_sets: Sequence[Sequence[int]],
    group: GroupParams = DEFAULT_GROUP,
    backend: str = "cpu",
    mesh=None,
    threshold: Optional[int] = None,
) -> List[bool]:
    """Shape + subgroup membership for whole commitment vectors.

    REQUIRED before any exponent arithmetic on a dealer's broadcast:
    the verification equation reduces exponents mod q, which is sound
    only for order-q elements.  A malicious dealer broadcasting a
    commitment with an order-2 component would otherwise verify
    INCONSISTENTLY across receivers (the reduced exponent's parity
    differs per evaluation point), splitting honest nodes' qualified
    sets — an agreement break, not just a bad key.  Membership is a
    deterministic property of the broadcast bytes, so every honest
    node disqualifies the same dealers.

    ``threshold`` (when given) also pins the vector LENGTH: a wrong-
    length broadcast must disqualify its dealer here, not crash every
    honest verifier downstream (an empty vector is vacuously
    "all-member", and a t' != t vector desynchronizes the flattened
    exponent batches of verify/finalize)."""
    gp = group
    eng = get_engine(
        backend if gp.p.bit_length() <= 256 else "cpu", mesh, gp
    )
    flat: List[int] = []
    spans: List[int] = []
    for commits in commitment_sets:
        flat.extend(c % gp.p for c in commits)
        spans.append(len(commits))
    pows = eng.pow_batch(flat, [gp.q] * len(flat))
    out: List[bool] = []
    off = 0
    for (commits, span) in zip(commitment_sets, spans):
        ok = span > 0 and (threshold is None or span == threshold)
        ok = ok and all(
            1 < (c % gp.p) and pows[off + i] == 1
            for i, c in enumerate(commits)
        )
        off += span
        out.append(ok)
    return out


def verify_dealer_shares(
    items: Sequence[tuple],
    group: GroupParams = DEFAULT_GROUP,
    backend: str = "cpu",
    mesh=None,
) -> List[bool]:
    """Batched step-4 checks: ``items`` is a sequence of
    ``(commitments, receiver_index, share)`` and every
    g^{s} == prod_k C_k^{j^k} test runs from two batched dispatches.

    Callers must have validated the commitment vectors first
    (validate_commitments) — the j^k exponents here are reduced mod q,
    which assumes order-q elements."""
    if not items:
        return []
    gp = group
    eng = get_engine(
        backend if gp.p.bit_length() <= 256 else "cpu", mesh, gp
    )
    bases: List[int] = []
    exps: List[int] = []
    spans: List[int] = []
    for commitments, j, share in items:
        t = len(commitments)
        if t == 0:
            spans.append(0)  # malformed broadcast: verdict False below
            continue
        jk = _commit_eval_exps(j, t, gp.q)
        bases.extend(c % gp.p for c in commitments)
        exps.extend(jk)
        bases.append(gp.g)
        exps.append(share % gp.q)
        spans.append(t + 1)
    pows = eng.pow_batch(bases, exps)
    out: List[bool] = []
    off = 0
    for span in spans:
        if span == 0:
            out.append(False)
            continue
        prod = 1
        for v in pows[off : off + span - 1]:
            prod = prod * v % gp.p
        lhs = pows[off + span - 1]  # g^{share}
        off += span
        out.append(lhs == prod)
    return out


def finalize(
    all_commitments: Dict[int, Sequence[int]],
    my_index: int,
    my_shares: Dict[int, int],
    n: int,
    threshold: int,
    group: GroupParams = DEFAULT_GROUP,
    backend: str = "cpu",
    mesh=None,
) -> Tuple[ThresholdPublicKey, ThresholdSecretShare]:
    """Fold the qualified dealings into this node's final key pair.

    ``all_commitments``: dealer index -> its t commitments (the
    qualified set Q — callers exclude disqualified dealers from BOTH
    arguments).  ``my_shares``: dealer index -> s_{i,my_index}.  Every
    correct node derives the IDENTICAL public key because the inputs
    are the broadcast commitments alone."""
    if set(all_commitments) != set(my_shares):
        raise ValueError("commitment/share dealer sets differ")
    if not all_commitments:
        raise ValueError("empty qualified set")
    for i, commits in all_commitments.items():
        if len(commits) != threshold:
            # qualified dealers were length-validated; a mismatch here
            # is a caller bug and must fail loudly, not desync the
            # flattened exponent batches below
            raise ValueError(
                f"dealer {i}: {len(commits)} commitments != t={threshold}"
            )
    gp = group
    eng = get_engine(
        backend if gp.p.bit_length() <= 256 else "cpu", mesh, gp
    )
    x_j = sum(my_shares.values()) % gp.q
    master = 1
    for commits in all_commitments.values():
        master = master * (commits[0] % gp.p) % gp.p
    # the full verification-key table h_m = prod_{i,k} C_ik^{m^k},
    # one batched dispatch for all n receivers x |Q| dealers x t terms
    bases: List[int] = []
    exps: List[int] = []
    for m in range(1, n + 1):
        jk = _commit_eval_exps(m, threshold, gp.q)
        for commits in all_commitments.values():
            bases.extend(c % gp.p for c in commits)
            exps.extend(jk)
    pows = eng.pow_batch(bases, exps)
    vks: List[int] = []
    per_m = len(all_commitments) * threshold
    for m in range(n):
        prod = 1
        for v in pows[m * per_m : (m + 1) * per_m]:
            prod = prod * v % gp.p
        vks.append(prod)
    pub = ThresholdPublicKey(
        n=n,
        threshold=threshold,
        master=master,
        verification_keys=tuple(vks),
        group=gp,
    )
    return pub, ThresholdSecretShare(index=my_index, value=x_j)


def run_dkg(
    n: int,
    threshold: int,
    group: GroupParams = DEFAULT_GROUP,
    seed: Optional[int] = None,
    backend: str = "cpu",
    mesh=None,
    corrupt_dealers: Sequence[int] = (),
) -> Tuple[ThresholdPublicKey, List[ThresholdSecretShare], List[int]]:
    """Drive the whole protocol in-process (the test/simulation
    harness; a deployment pumps the same four steps over its own
    private channels).  ``corrupt_dealers`` hand out a tampered share
    to receiver 1 — the complaint flow must disqualify exactly them.

    Returns (pub, shares, qualified_dealer_indices)."""
    dealings = {
        i: DkgDealing(i, n, threshold, group, seed=seed)
        for i in range(1, n + 1)
    }
    commits = {
        i: d.commitments(backend=backend, mesh=mesh)
        for i, d in dealings.items()
    }
    # commitment subgroup validation first (see validate_commitments:
    # skipping it lets a crafted broadcast split honest qualified sets)
    commit_ok = validate_commitments(
        [commits[i] for i in range(1, n + 1)],
        group=group,
        backend=backend,
        mesh=mesh,
        threshold=threshold,
    )
    bad_commits = {
        i for i, ok in zip(range(1, n + 1), commit_ok) if not ok
    }
    # every (dealer, receiver) share, tampered for corrupt dealers
    shares: Dict[int, Dict[int, int]] = {}  # receiver -> dealer -> s
    for j in range(1, n + 1):
        shares[j] = {}
        for i, d in dealings.items():
            s = d.share_for(j)
            if i in corrupt_dealers and j == 1:
                s = (s + 1) % group.q
            shares[j][i] = s
    # batched verification of all n^2 shares; any failure = complaint
    items = []
    order = []
    for j in range(1, n + 1):
        for i in range(1, n + 1):
            items.append((commits[i], j, shares[j][i]))
            order.append((j, i))
    verdicts = verify_dealer_shares(
        items, group=group, backend=backend, mesh=mesh
    )
    disqualified = bad_commits | {
        i for (j, i), ok in zip(order, verdicts) if not ok
    }
    qualified = sorted(set(range(1, n + 1)) - disqualified)
    if len(qualified) < threshold:
        raise RuntimeError(
            f"only {len(qualified)} qualified dealers < t={threshold}"
        )
    q_commits = {i: commits[i] for i in qualified}
    pub = None
    out_shares: List[ThresholdSecretShare] = []
    for j in range(1, n + 1):
        p_j, sh_j = finalize(
            q_commits,
            j,
            {i: shares[j][i] for i in qualified},
            n,
            threshold,
            group=group,
            backend=backend,
            mesh=mesh,
        )
        if pub is None:
            pub = p_j
        else:
            # agreement on the public state is a THEOREM here (pure
            # function of broadcast commitments); assert it anyway
            assert p_j == pub
        out_shares.append(sh_j)
    return pub, out_shares, qualified


__all__ = [
    "DkgDealing",
    "verify_dealer_shares",
    "finalize",
    "run_dkg",
]
