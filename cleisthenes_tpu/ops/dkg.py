"""Distributed key generation: threshold keys without the dealer.

The one trust assumption this framework inherits from the reference's
design docs is the trusted dealer (reference
docs/THRESHOLD_ENCRYPTION-EN.md:33 assumes "SetUp" hands out shares;
ops/tpke.py's ``deal`` implements exactly that).  This module removes
it: GJKR DKG over the same prime-order group, where every participant
acts as a dealer of a random secret and the final key is the sum of
the QUALIFIED dealings.

Per participant i (threshold t, roster 1..n):

  phase 1 (hiding — fixes WHO contributes and hence the secret):
  1. sample f_i(x), f'_i(x) of degree t-1 over Z_q
  2. broadcast Pedersen commitments E_ik = g^{a_ik} h^{b_ik}  (k < t)
  3. send (s_ij, s'_ij) = (f_i(j), f'_i(j)) to j over a private channel
  4. j accepts iff g^{s_ij} h^{s'_ij} == prod_k E_ik^{j^k}; complaints
     are resolved by public dealer reveal (justified complaints); the
     qualified set Q — and therefore x = sum_{i in Q} a_i0 — is fixed

  phase 2 (extraction — reveals g^x without letting anyone change x):
  5. each i in Q opens Feldman commitments A_ik = g^{a_ik}, checked
     against the phase-1 shares; misbehavers are RECONSTRUCTED, not
     dropped.  j's final share is x_j = sum_{i in Q} s_ij, the master
     key y = prod_{i in Q} A_i0, and every verification key
     h_j = prod_{i in Q} prod_k A_ik^{j^k} is PUBLICLY computable —
     so the output is a drop-in ``ThresholdPublicKey`` +
     ``ThresholdSecretShare`` pair for TPKE and the common coin.

Security: ``run_dkg`` implements the GJKR two-phase variant (Gennaro,
Jarecki, Krawczyk, Rabin 1999), not plain Joint-Feldman.  Phase one
deals under PEDERSEN commitments E_k = g^{a_k} h^{b_k} (perfectly
hiding — no function of the secrets leaks), fixes the qualified set Q
through a justified-complaint round, and thereby pins the final secret
x = sum_{i in Q} a_i0 BEFORE any g^{a_i0} is revealed; a rushing
adversary who waits to move last learns nothing it can condition its
dealing on, so the key is uniform.  Phase two extracts y = g^x: each
qualified dealer opens Feldman commitments A_k = g^{a_k}, checked
against the phase-one shares; a dealer who misbehaves HERE is not
disqualified (that would let it bias the key by selective abort) —
its polynomial is reconstructed from the honest receivers' verified
phase-one shares and its contribution included regardless.

Complaints are JUSTIFIED: a complaint alone never disqualifies.  The
accused dealer reveals the disputed share pair publicly; every node
checks the reveal against the broadcast commitments and disqualifies
only on verifiable evidence (invalid reveal / silence), so all honest
nodes derive the IDENTICAL Q — a false accuser cannot split the
qualified set, and an honest-but-accused dealer survives.

The share transport must be private and the commitment/complaint
transport must be a broadcast channel: this module produces and
verifies the protocol's VALUES and leaves carriage to the caller
(tests drive it in-process; a deployment pumps the same steps over
RBC for broadcasts and key-agreed channels for shares).

All verification exponentiations batch through the ModEngine seam —
one ``pow_batch`` for a whole roster's share checks, one for the full
verification-key table — same as every other crypto plane in ops/.
"""

from __future__ import annotations

import hashlib
import secrets as _secrets
from typing import Dict, List, Optional, Sequence, Tuple

import functools

from cleisthenes_tpu.ops.modmath import (
    DEFAULT_GROUP,
    GroupParams,
    get_engine_degraded,
)
from cleisthenes_tpu.ops.tpke import (
    ThresholdPublicKey,
    ThresholdSecretShare,
)


def _sample_coeffs(
    group: GroupParams,
    threshold: int,
    seed: Optional[int],
    dealer_index: int,
    tag: bytes,
) -> List[int]:
    """t coefficients over Z_q: CSPRNG when unseeded, a domain-tagged
    SHA-256 counter stream when seeded (tests/replays).  Excess bytes
    keep the mod-q reduction unbiased."""
    q = group.q
    nb = group.nbytes + 8
    if seed is None:
        rnd = _secrets.token_bytes  # staticcheck: allow[DET001] unseeded DKG keygen
    else:
        ctr = [0]

        def rnd(k: int, _s=seed, _d=dealer_index) -> bytes:
            out = b""
            while len(out) < k:
                ctr[0] += 1
                out += hashlib.sha256(
                    tag + b"|%d|%d|%d" % (_s, _d, ctr[0])
                ).digest()
            return out[:k]

    return [
        int.from_bytes(rnd(nb), "big") % q for _ in range(threshold)
    ]


def _eval_poly(coeffs: Sequence[int], x: int, q: int) -> int:
    """Horner evaluation of sum_k coeffs[k] x^k over Z_q."""
    acc = 0
    for c in reversed(coeffs):
        acc = (acc * x + c) % q
    return acc


class DkgDealing:
    """One participant's dealer role: polynomial + commitments + the
    per-receiver shares."""

    def __init__(
        self,
        dealer_index: int,
        n: int,
        threshold: int,
        group: GroupParams = DEFAULT_GROUP,
        seed: Optional[int] = None,
    ) -> None:
        if not (1 <= threshold <= n):
            raise ValueError(f"need 1 <= t <= n, got t={threshold} n={n}")
        self.dealer_index = dealer_index
        self.n = n
        self.threshold = threshold
        self.group = group
        self._coeffs = _sample_coeffs(
            group, threshold, seed, dealer_index, b"dkg"
        )

    def commitments(self, backend: str = "cpu", mesh=None) -> List[int]:
        """Feldman commitments A_k = g^{a_k}.

        Under the GJKR flow these are the PHASE-2 opening: they must
        stay private until the qualified set Q is fixed — broadcasting
        them alongside the phase-1 Pedersen commitments reopens the
        Joint-Feldman rushing-bias channel the two-phase structure
        exists to close.  (Standalone Feldman-VSS uses, e.g. the unit
        tests, may broadcast them immediately.)"""
        gp = self.group
        eng = get_engine_degraded(backend, mesh, gp)
        return eng.pow_batch([gp.g] * len(self._coeffs), self._coeffs)

    def share_for(self, receiver_index: int) -> int:
        """s_ij = f_i(j) — send PRIVATELY to participant j (1-based)."""
        if not (1 <= receiver_index <= self.n):
            raise ValueError(f"receiver index {receiver_index} out of roster")
        return _eval_poly(self._coeffs, receiver_index, self.group.q)


class PedersenDealing(DkgDealing):
    """GJKR phase-one dealer role: a second blinding polynomial
    f'_i(x) alongside f_i(x), Pedersen commitments E_k = g^{a_k}
    h^{b_k}, and (s, s') share pairs.  The Feldman opening A_k =
    g^{a_k} (phase two) comes from the inherited ``commitments``."""

    def __init__(
        self,
        dealer_index: int,
        n: int,
        threshold: int,
        group: GroupParams = DEFAULT_GROUP,
        seed: Optional[int] = None,
    ) -> None:
        super().__init__(dealer_index, n, threshold, group, seed=seed)
        self._coeffs2 = _sample_coeffs(
            group, threshold, seed, dealer_index, b"dkg-blind"
        )

    def pedersen_commitments(
        self, backend: str = "cpu", mesh=None
    ) -> List[int]:
        """E_k = g^{a_k} h^{b_k} — the phase-one broadcast.  Perfectly
        hiding: reveals NOTHING about the a_k until phase two."""
        gp = self.group
        h = pedersen_generator(gp)
        eng = get_engine_degraded(backend, mesh, gp)
        t = len(self._coeffs)
        pows = eng.pow_batch(
            [gp.g] * t + [h] * t, self._coeffs + self._coeffs2
        )
        return [pows[k] * pows[t + k] % gp.p for k in range(t)]

    def share_pair_for(self, receiver_index: int) -> Tuple[int, int]:
        """(f_i(j), f'_i(j)) — send PRIVATELY to participant j."""
        if not (1 <= receiver_index <= self.n):
            raise ValueError(f"receiver index {receiver_index} out of roster")
        return self.share_for(receiver_index), _eval_poly(
            self._coeffs2, receiver_index, self.group.q
        )


def verify_pedersen_shares(
    items: Sequence[tuple],
    group: GroupParams = DEFAULT_GROUP,
    backend: str = "cpu",
    mesh=None,
) -> List[bool]:
    """Batched GJKR phase-one checks: ``items`` is a sequence of
    ``(pedersen_commitments, receiver_index, share, blind_share)`` and
    every g^{s} h^{s'} == prod_k E_k^{j^k} test runs from one batched
    dispatch.  Commitment vectors must be pre-validated
    (validate_commitments) for the same reason as the Feldman path."""
    if not items:
        return []
    gp = group
    h = pedersen_generator(gp)
    eng = get_engine_degraded(backend, mesh, gp)
    bases: List[int] = []
    exps: List[int] = []
    spans: List[int] = []
    for commitments, j, share, blind in items:
        t = len(commitments)
        if t == 0:
            spans.append(0)
            continue
        jk = _commit_eval_exps(j, t, gp.q)
        bases.extend(c % gp.p for c in commitments)
        exps.extend(jk)
        bases.append(gp.g)
        exps.append(share % gp.q)
        bases.append(h)
        exps.append(blind % gp.q)
        spans.append(t + 2)
    pows = eng.pow_batch(bases, exps)
    out: List[bool] = []
    off = 0
    for span in spans:
        if span == 0:
            out.append(False)
            continue
        prod = 1
        for v in pows[off : off + span - 2]:
            prod = prod * v % gp.p
        lhs = pows[off + span - 2] * pows[off + span - 1] % gp.p
        off += span
        out.append(lhs == prod)
    return out


def _interpolate_coeffs(
    points: Sequence[Tuple[int, int]], q: int
) -> List[int]:
    """Coefficients of the unique degree-(len(points)-1) polynomial
    through ``points`` over Z_q (Lagrange basis, expanded).  Phase-two
    reconstruction: t verified shares of a misbehaving-but-qualified
    dealer pin its whole polynomial, hence its Feldman opening."""
    t = len(points)
    coeffs = [0] * t
    for m, (xm, ym) in enumerate(points):
        # basis polynomial prod_{l != m} (x - x_l) / (x_m - x_l)
        basis = [1]
        denom = 1
        for l, (xl, _) in enumerate(points):
            if l == m:
                continue
            # multiply basis by (x - xl)
            nxt = [0] * (len(basis) + 1)
            for d, c in enumerate(basis):
                nxt[d] = (nxt[d] - c * xl) % q
                nxt[d + 1] = (nxt[d + 1] + c) % q
            basis = nxt
            denom = denom * (xm - xl) % q
        scale = ym * pow(denom, -1, q) % q
        for d, c in enumerate(basis):
            coeffs[d] = (coeffs[d] + c * scale) % q
    return coeffs


@functools.cache
def pedersen_generator(group: GroupParams = DEFAULT_GROUP) -> int:
    """Second generator h of the order-q subgroup with UNKNOWN dlog_g:
    hash-to-group (SHA-256 counter stream mod p, squared — p = 2q+1 so
    squares are exactly the QR subgroup).  Nothing-up-my-sleeve: anyone
    re-derives h from the group constants, and no one knows log_g(h),
    which is what makes E_k = g^{a_k} h^{b_k} perfectly hiding AND
    binding under DLOG."""
    ctr = 0
    while True:
        ctr += 1
        raw = int.from_bytes(
            hashlib.sha256(
                b"cleisthenes-pedersen-h|%d|%d" % (group.p, ctr)
            ).digest()
            + hashlib.sha256(
                b"cleisthenes-pedersen-h2|%d|%d" % (group.p, ctr)
            ).digest(),
            "big",
        ) % group.p
        h = pow(raw, 2, group.p)
        if h not in (0, 1, group.g, group.p - 1):
            return h


def _commit_eval_exps(
    j: int, threshold: int, q: int
) -> List[int]:
    """[j^k mod q for k < threshold] — the exponents of the commitment
    product at evaluation point j."""
    out = [1]
    for _ in range(threshold - 1):
        out.append(out[-1] * j % q)
    return out


def validate_commitments(
    commitment_sets: Sequence[Sequence[int]],
    group: GroupParams = DEFAULT_GROUP,
    backend: str = "cpu",
    mesh=None,
    threshold: Optional[int] = None,
) -> List[bool]:
    """Shape + subgroup membership for whole commitment vectors.

    REQUIRED before any exponent arithmetic on a dealer's broadcast:
    the verification equation reduces exponents mod q, which is sound
    only for order-q elements.  A malicious dealer broadcasting a
    commitment with an order-2 component would otherwise verify
    INCONSISTENTLY across receivers (the reduced exponent's parity
    differs per evaluation point), splitting honest nodes' qualified
    sets — an agreement break, not just a bad key.  Membership is a
    deterministic property of the broadcast bytes, so every honest
    node disqualifies the same dealers.

    ``threshold`` (when given) also pins the vector LENGTH: a wrong-
    length broadcast must disqualify its dealer here, not crash every
    honest verifier downstream (an empty vector is vacuously
    "all-member", and a t' != t vector desynchronizes the flattened
    exponent batches of verify/finalize)."""
    gp = group
    eng = get_engine_degraded(backend, mesh, gp)
    flat: List[int] = []
    spans: List[int] = []
    for commits in commitment_sets:
        flat.extend(c % gp.p for c in commits)
        spans.append(len(commits))
    pows = eng.pow_batch(flat, [gp.q] * len(flat))
    out: List[bool] = []
    off = 0
    for (commits, span) in zip(commitment_sets, spans):
        ok = span > 0 and (threshold is None or span == threshold)
        ok = ok and all(
            1 < (c % gp.p) and pows[off + i] == 1
            for i, c in enumerate(commits)
        )
        off += span
        out.append(ok)
    return out


def verify_dealer_shares(
    items: Sequence[tuple],
    group: GroupParams = DEFAULT_GROUP,
    backend: str = "cpu",
    mesh=None,
) -> List[bool]:
    """Batched step-4 checks: ``items`` is a sequence of
    ``(commitments, receiver_index, share)`` and every
    g^{s} == prod_k C_k^{j^k} test runs from two batched dispatches.

    Callers must have validated the commitment vectors first
    (validate_commitments) — the j^k exponents here are reduced mod q,
    which assumes order-q elements."""
    if not items:
        return []
    gp = group
    eng = get_engine_degraded(backend, mesh, gp)
    bases: List[int] = []
    exps: List[int] = []
    spans: List[int] = []
    for commitments, j, share in items:
        t = len(commitments)
        if t == 0:
            spans.append(0)  # malformed broadcast: verdict False below
            continue
        jk = _commit_eval_exps(j, t, gp.q)
        bases.extend(c % gp.p for c in commitments)
        exps.extend(jk)
        bases.append(gp.g)
        exps.append(share % gp.q)
        spans.append(t + 1)
    pows = eng.pow_batch(bases, exps)
    out: List[bool] = []
    off = 0
    for span in spans:
        if span == 0:
            out.append(False)
            continue
        prod = 1
        for v in pows[off : off + span - 1]:
            prod = prod * v % gp.p
        lhs = pows[off + span - 1]  # g^{share}
        off += span
        out.append(lhs == prod)
    return out


def finalize(
    all_commitments: Dict[int, Sequence[int]],
    my_index: int,
    my_shares: Dict[int, int],
    n: int,
    threshold: int,
    group: GroupParams = DEFAULT_GROUP,
    backend: str = "cpu",
    mesh=None,
) -> Tuple[ThresholdPublicKey, ThresholdSecretShare]:
    """Fold the qualified dealings into this node's final key pair.

    ``all_commitments``: dealer index -> its t commitments (the
    qualified set Q — callers exclude disqualified dealers from BOTH
    arguments).  ``my_shares``: dealer index -> s_{i,my_index}.  Every
    correct node derives the IDENTICAL public key because the inputs
    are the broadcast commitments alone."""
    if set(all_commitments) != set(my_shares):
        raise ValueError("commitment/share dealer sets differ")
    if not all_commitments:
        raise ValueError("empty qualified set")
    for i, commits in all_commitments.items():
        if len(commits) != threshold:
            # qualified dealers were length-validated; a mismatch here
            # is a caller bug and must fail loudly, not desync the
            # flattened exponent batches below
            raise ValueError(
                f"dealer {i}: {len(commits)} commitments != t={threshold}"
            )
    gp = group
    eng = get_engine_degraded(backend, mesh, gp)
    x_j = sum(my_shares.values()) % gp.q
    master = 1
    for commits in all_commitments.values():
        master = master * (commits[0] % gp.p) % gp.p
    # the full verification-key table h_m = prod_{i,k} C_ik^{m^k},
    # one batched dispatch for all n receivers x |Q| dealers x t terms
    bases: List[int] = []
    exps: List[int] = []
    for m in range(1, n + 1):
        jk = _commit_eval_exps(m, threshold, gp.q)
        for commits in all_commitments.values():
            bases.extend(c % gp.p for c in commits)
            exps.extend(jk)
    pows = eng.pow_batch(bases, exps)
    vks: List[int] = []
    per_m = len(all_commitments) * threshold
    for m in range(n):
        prod = 1
        for v in pows[m * per_m : (m + 1) * per_m]:
            prod = prod * v % gp.p
        vks.append(prod)
    pub = ThresholdPublicKey(
        n=n,
        threshold=threshold,
        master=master,
        verification_keys=tuple(vks),
        group=gp,
    )
    return pub, ThresholdSecretShare(index=my_index, value=x_j)


def run_dkg(
    n: int,
    threshold: int,
    group: GroupParams = DEFAULT_GROUP,
    seed: Optional[int] = None,
    backend: str = "cpu",
    mesh=None,
    corrupt_dealers: Sequence[int] = (),
    false_accusers: Sequence[int] = (),
    phase2_cheaters: Sequence[int] = (),
    phase2_short_openers: Sequence[int] = (),
) -> Tuple[ThresholdPublicKey, List[ThresholdSecretShare], List[int]]:
    """Drive the whole GJKR protocol in-process (the test/simulation
    harness; a deployment pumps the same steps over RBC broadcasts and
    private channels).  Fault knobs:

    - ``corrupt_dealers`` hand receiver 1 a tampered share AND double
      down when challenged (reveal the tampered pair) — the justified
      complaint flow must disqualify exactly them;
    - ``false_accusers`` are receivers who complain against every
      dealer regardless of evidence — honest dealers must reveal and
      SURVIVE (Q agreement holds against slander);
    - ``phase2_cheaters`` deal honestly in phase one but broadcast
      garbage Feldman openings in phase two — their contribution must
      be reconstructed, leaving the final key exactly what phase one
      fixed (the rushing-adversary regression);
    - ``phase2_short_openers`` broadcast a WRONG-LENGTH opening
      (t-1 entries) — the length guard must shunt them to the same
      reconstruction path instead of desynchronizing the batched
      exponent layouts (advisor r4 finding).

    Returns (pub, shares, qualified_dealer_indices)."""
    dealings = {
        i: PedersenDealing(i, n, threshold, group, seed=seed)
        for i in range(1, n + 1)
    }
    # -- phase one: Pedersen deal + justified complaints -> Q ---------
    ped = {
        i: d.pedersen_commitments(backend=backend, mesh=mesh)
        for i, d in dealings.items()
    }
    # commitment subgroup validation first (see validate_commitments:
    # skipping it lets a crafted broadcast split honest qualified sets)
    commit_ok = validate_commitments(
        [ped[i] for i in range(1, n + 1)],
        group=group,
        backend=backend,
        mesh=mesh,
        threshold=threshold,
    )
    bad_commits = {
        i for i, ok in zip(range(1, n + 1), commit_ok) if not ok
    }
    # every (dealer, receiver) share pair, tampered for corrupt dealers
    pairs: Dict[int, Dict[int, Tuple[int, int]]] = {}  # recv -> dealer
    for j in range(1, n + 1):
        pairs[j] = {}
        for i, d in dealings.items():
            s, s2 = d.share_pair_for(j)
            if i in corrupt_dealers and j == 1:
                s = (s + 1) % group.q
            pairs[j][i] = (s, s2)
    # batched verification of all n^2 pairs; any failure = a complaint
    items = []
    order = []
    for j in range(1, n + 1):
        for i in range(1, n + 1):
            if i in bad_commits:
                continue
            s, s2 = pairs[j][i]
            items.append((ped[i], j, s, s2))
            order.append((j, i))
    verdicts = verify_pedersen_shares(
        items, group=group, backend=backend, mesh=mesh
    )
    complaints = {(j, i) for (j, i), ok in zip(order, verdicts) if not ok}
    for j in false_accusers:
        complaints |= {
            (j, i) for i in range(1, n + 1) if i not in bad_commits
        }
    # justified resolution: the accused dealer reveals the disputed
    # pair PUBLICLY; everyone checks the reveal against the broadcast
    # commitments and disqualifies only on verifiable evidence.  A
    # corrupt dealer doubles down (reveals what it actually sent); an
    # honest-but-slandered dealer reveals the true pair and survives.
    reveal_items = []
    reveal_order = sorted(complaints)
    for (j, i) in reveal_order:
        s, s2 = pairs[j][i]  # what the dealer actually sent
        reveal_items.append((ped[i], j, s, s2))
    reveal_ok = verify_pedersen_shares(
        reveal_items, group=group, backend=backend, mesh=mesh
    )
    # (receiver, dealer) pairs proven consistent with the dealer's
    # phase-one Pedersen commitments — the ONLY shares phase two may
    # later interpolate from (a receiver lying about its share must
    # not be able to poison a reconstruction)
    ped_verified = {(j, i) for (j, i), ok in zip(order, verdicts) if ok}
    disqualified = set(bad_commits)
    for (j, i), item, ok in zip(reveal_order, reveal_items, reveal_ok):
        if ok:
            # valid reveal: the complaint was slander (or transport
            # corruption); receiver j adopts the now-public pair
            pairs[j][i] = item[2:4]
            ped_verified.add((j, i))
        else:
            disqualified.add(i)
    qualified = sorted(set(range(1, n + 1)) - disqualified)
    if len(qualified) < threshold:
        raise RuntimeError(
            f"only {len(qualified)} qualified dealers < t={threshold}"
        )
    # Q is FIXED here — so is x = sum_{i in Q} f_i(0), while every
    # broadcast so far is perfectly hiding.  Nothing an adversary does
    # from this point can change the key (only how we learn g^x).
    # -- phase two: Feldman opening, reconstruct cheaters -------------
    feld = {}
    for i in qualified:
        if i in phase2_short_openers:
            # wrong-length opening: parses element-wise but must be
            # caught by the length guard before any batch flattening
            feld[i] = [group.g] * (threshold - 1)
        elif i in phase2_cheaters:
            # garbage opening: right length, valid subgroup elements,
            # wrong values — the strongest cheat that still parses
            feld[i] = [group.g] * threshold
        else:
            feld[i] = dealings[i].commitments(backend=backend, mesh=mesh)
    # length guard BEFORE anything is flattened: a t' != t opening
    # from a real adversary would desynchronize the batched exponent
    # layouts below (see verify_dealer_shares' docstring); such a
    # dealer goes straight to the reconstruction path, mirroring
    # finalize's own guard
    wrong_len = {i for i in qualified if len(feld[i]) != threshold}
    p2_checked = [i for i in qualified if i not in wrong_len]
    feld_ok = validate_commitments(
        [feld[i] for i in p2_checked],
        group=group,
        backend=backend,
        mesh=mesh,
        threshold=threshold,
    )
    # consistency vs the phase-one shares every receiver holds
    p2_items = []
    p2_order = []
    for i in p2_checked:
        for j in range(1, n + 1):
            p2_items.append((feld[i], j, pairs[j][i][0]))
            p2_order.append((i, j))
    p2_verdicts = verify_dealer_shares(
        p2_items, group=group, backend=backend, mesh=mesh
    )
    bad_openings = (
        wrong_len
        | {i for i, ok in zip(p2_checked, feld_ok) if not ok}
        | {i for (i, j), ok in zip(p2_order, p2_verdicts) if not ok}
    )
    if bad_openings:
        # NOT disqualified: their secrets are already in x.
        # Reconstruct each f_i from t phase-one-verified shares and
        # open it ourselves — all dealers in ONE batched dispatch.
        eng = get_engine_degraded(backend, mesh, group)
        recon = sorted(bad_openings)
        all_coeffs: List[int] = []
        for i in recon:
            # interpolate ONLY from shares proven against dealer i's
            # phase-one Pedersen commitments: a Byzantine receiver
            # among the first t broadcasting a lie must not yield a
            # wrong opening that splits honest nodes' keys
            pts = [
                (j, pairs[j][i][0])
                for j in range(1, n + 1)
                if (j, i) in ped_verified
            ][:threshold]
            if len(pts) < threshold:
                raise RuntimeError(
                    f"dealer {i}: only {len(pts)} Pedersen-verified "
                    f"shares < t={threshold} for reconstruction"
                )
            all_coeffs.extend(_interpolate_coeffs(pts, group.q))
        pows = eng.pow_batch(
            [group.g] * len(all_coeffs), all_coeffs
        )
        for idx, i in enumerate(recon):
            feld[i] = pows[idx * threshold : (idx + 1) * threshold]
    q_commits = {i: feld[i] for i in qualified}
    pub = None
    out_shares: List[ThresholdSecretShare] = []
    for j in range(1, n + 1):
        p_j, sh_j = finalize(
            q_commits,
            j,
            {i: pairs[j][i][0] for i in qualified},
            n,
            threshold,
            group=group,
            backend=backend,
            mesh=mesh,
        )
        if pub is None:
            pub = p_j
        else:
            # agreement on the public state is a THEOREM here (pure
            # function of broadcast commitments); assert it anyway
            assert p_j == pub
        out_shares.append(sh_j)
    return pub, out_shares, qualified


__all__ = [
    "DkgDealing",
    "PedersenDealing",
    "pedersen_generator",
    "verify_dealer_shares",
    "verify_pedersen_shares",
    "finalize",
    "run_dkg",
]
