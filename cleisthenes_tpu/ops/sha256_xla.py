"""Batched SHA-256 as JAX uint32 vector ops.

The RBC ECHO phase costs N^2 log N hashes per epoch network-wide
(reference docs/HONEYBADGER-EN.md:96): every node verifies a Merkle
branch for each of N shards in each of N concurrent RBC instances
(docs/RBC-EN.md:35).  Those hashes are all independent, which is
exactly what the TPU VPU wants: this module computes SHA-256 over a
*batch* axis — every op is a (B,)-wide uint32 add/rotate/xor — so one
dispatch hashes thousands of messages.

Message lengths are static per call site (shard length, 65-byte
interior nodes), so padding is baked into the traced graph and each
distinct length compiles once.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_K = np.array(
    [
        0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5,
        0x3956C25B, 0x59F111F1, 0x923F82A4, 0xAB1C5ED5,
        0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
        0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174,
        0xE49B69C1, 0xEFBE4786, 0x0FC19DC6, 0x240CA1CC,
        0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
        0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7,
        0xC6E00BF3, 0xD5A79147, 0x06CA6351, 0x14292967,
        0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
        0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85,
        0xA2BFE8A1, 0xA81A664B, 0xC24B8B70, 0xC76C51A3,
        0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
        0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5,
        0x391C0CB3, 0x4ED8AA4A, 0x5B9CCA4F, 0x682E6FF3,
        0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
        0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
    ],
    dtype=np.uint32,
)

_H0 = np.array(
    [
        0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
        0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
    ],
    dtype=np.uint32,
)


def _rotr(x: jnp.ndarray, n: int) -> jnp.ndarray:
    return (x >> jnp.uint32(n)) | (x << jnp.uint32(32 - n))


def _compress_block(state: jnp.ndarray, block: jnp.ndarray) -> jnp.ndarray:
    """One SHA-256 compression: state (B, 8) u32, block (B, 16) u32.

    Both the 48-step message-schedule expansion and the 64 rounds run
    as fori_loops (not unrolled) so the traced graph stays small —
    compile time matters because each distinct message length is its
    own XLA program; runtime stays vectorized over the batch axis.
    """
    b = block.shape[0]
    w0 = jnp.concatenate(
        [jnp.swapaxes(block, 0, 1), jnp.zeros((48, b), dtype=jnp.uint32)]
    )  # (64, B)

    def expand(t, w):
        wm15 = w[t - 15]
        wm2 = w[t - 2]
        s0 = _rotr(wm15, 7) ^ _rotr(wm15, 18) ^ (wm15 >> jnp.uint32(3))
        s1 = _rotr(wm2, 17) ^ _rotr(wm2, 19) ^ (wm2 >> jnp.uint32(10))
        return w.at[t].set(w[t - 16] + s0 + w[t - 7] + s1)

    w = jax.lax.fori_loop(16, 64, expand, w0)
    k = jnp.asarray(_K)

    def round_fn(t, vs):
        a, b_, c, d, e, f, g, h = vs
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + k[t] + w[t]
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b_) ^ (a & c) ^ (b_ & c)
        return (t1 + s0 + maj, a, b_, c, d + t1, e, f, g)

    vs = jax.lax.fori_loop(
        0, 64, round_fn, tuple(state[:, i] for i in range(8))
    )
    return state + jnp.stack(vs, axis=1)


def _pad_to_blocks(msgs: jnp.ndarray) -> jnp.ndarray:
    """(B, L) uint8 -> (B, nblocks, 16) uint32 big-endian padded blocks."""
    b, l = msgs.shape
    nblocks = (l + 9 + 63) // 64
    padded = jnp.zeros((b, nblocks * 64), dtype=jnp.uint8)
    padded = padded.at[:, :l].set(msgs)
    padded = padded.at[:, l].set(jnp.uint8(0x80))
    bitlen = np.frombuffer(
        np.uint64(l * 8).byteswap().tobytes(), dtype=np.uint8
    )  # big-endian length, static
    padded = padded.at[:, nblocks * 64 - 8 :].set(
        jnp.asarray(bitlen, dtype=jnp.uint8)[None, :]
    )
    words = padded.reshape(b, nblocks, 16, 4).astype(jnp.uint32)
    return (
        (words[..., 0] << 24) | (words[..., 1] << 16)
        | (words[..., 2] << 8) | words[..., 3]
    )


def _digest_to_bytes(state: jnp.ndarray) -> jnp.ndarray:
    """(B, 8) u32 -> (B, 32) uint8 big-endian."""
    b = state.shape[0]
    shifts = jnp.asarray([24, 16, 8, 0], dtype=jnp.uint32)
    return (
        (state[:, :, None] >> shifts[None, None, :]) & jnp.uint32(0xFF)
    ).astype(jnp.uint8).reshape(b, 32)


@jax.jit
def sha256_batch(msgs: jnp.ndarray) -> jnp.ndarray:
    """SHA-256 of a batch of equal-length messages: (B, L) u8 -> (B, 32) u8."""
    blocks = _pad_to_blocks(msgs)
    state = jnp.broadcast_to(
        jnp.asarray(_H0), (msgs.shape[0], 8)
    ).astype(jnp.uint32)
    # scan over the (static) block count; body compiled once
    def step(st, blk):
        return _compress_block(st, blk), None
    state, _ = jax.lax.scan(step, state, jnp.swapaxes(blocks, 0, 1))
    return _digest_to_bytes(state)


@functools.cache
def _zero_digest() -> bytes:
    """Digest used to pad Merkle leaf sets to a power of two."""
    import hashlib

    return hashlib.sha256(b"cleisthenes-tpu:empty-leaf").digest()


# ---------------------------------------------------------------------------
# Device-resident Merkle kernels (consumed by ops.merkle.XlaMerkle)
# ---------------------------------------------------------------------------

_LEAF_PREFIX_BYTE = 0x00
_NODE_PREFIX_BYTE = 0x01


@jax.jit
def build_forest(shards: jnp.ndarray):
    """Build B Merkle trees in ONE XLA program.

    shards (B, n, L) uint8 -> (B, 2p-1, 32): all levels concatenated,
    leaf row first (width p = next power of two >= n), root digest
    last.  Leaf digest = SHA256(0x00 || shard), node =
    SHA256(0x01 || left || right) (ops.merkle convention).
    """
    b, n, l = shards.shape
    leaf_msgs = jnp.concatenate(
        [
            jnp.full((b * n, 1), _LEAF_PREFIX_BYTE, dtype=jnp.uint8),
            shards.reshape(b * n, l),
        ],
        axis=1,
    )
    cur = sha256_batch(leaf_msgs).reshape(b, n, 32)
    p = 1
    while p < n:
        p <<= 1
    if p != n:
        pad = jnp.broadcast_to(
            jnp.asarray(
                np.frombuffer(_zero_digest(), dtype=np.uint8)
            ),
            (b, p - n, 32),
        )
        cur = jnp.concatenate([cur, pad], axis=1)
    levels = [cur]
    width = p
    while width > 1:
        half = width // 2
        msgs = jnp.concatenate(
            [
                jnp.full((b * half, 1), _NODE_PREFIX_BYTE, dtype=jnp.uint8),
                cur.reshape(b * half, 64),
            ],
            axis=1,
        )
        cur = sha256_batch(msgs).reshape(b, half, 32)
        levels.append(cur)
        width = half
    # single (B, 2p-1, 32) output: ONE device->host transfer for the
    # whole forest instead of one per level (dispatch/transfer latency
    # dominates under remote-relay TPU attachment)
    return jnp.concatenate(levels, axis=1)


@jax.jit
def verify_branches(
    roots: jnp.ndarray,
    leaves: jnp.ndarray,
    branches: jnp.ndarray,
    indices: jnp.ndarray,
) -> jnp.ndarray:
    """Verify B Merkle branches in ONE XLA program.

    roots (B, 32) u8, leaves (B, L) u8 raw shard bytes, branches
    (B, D, 32) u8 sibling paths bottom-up, indices (B,) u32 -> (B,) bool.
    """
    b, l = leaves.shape
    d = branches.shape[1]
    msgs = jnp.concatenate(
        [jnp.full((b, 1), _LEAF_PREFIX_BYTE, dtype=jnp.uint8), leaves],
        axis=1,
    )
    cur = sha256_batch(msgs)
    idx = indices.astype(jnp.uint32)
    for lvl in range(d):  # d is static: unrolled into the one program
        sib = branches[:, lvl]
        bit = (idx & 1).astype(bool)[:, None]
        left = jnp.where(bit, sib, cur)
        right = jnp.where(bit, cur, sib)
        msgs = jnp.concatenate(
            [jnp.full((b, 1), _NODE_PREFIX_BYTE, dtype=jnp.uint8), left, right],
            axis=1,
        )
        cur = sha256_batch(msgs)
        idx = idx >> 1
    return (cur == roots).all(axis=1)


__all__ = ["sha256_batch", "build_forest", "verify_branches"]
