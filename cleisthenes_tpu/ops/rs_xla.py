"""TPU-native Reed-Solomon codec: GF(2^8) as one MXU matmul.

Design (SURVEY.md §7 step 3): multiplication by a GF(2^8) constant is
GF(2)-linear on bits, so the whole systematic encode
``parity = A_p (*) data`` lifts to ``parity_bits = (G @ data_bits) mod 2``
where G is the (8P x 8K) 0/1 lifting of the parity rows
(gf256.lift_to_bits).  Bytes are unpacked to 8 bit-planes, the matmul
runs on the MXU in bf16 with exact f32 accumulation (every dot is a sum
of <= 8*K <= 2048 zeros/ones, far below 2^24), and the result is
reduced mod 2 and repacked.  Decode is identical with G built from the
inverse of the surviving rows (inverted on host — O(k^3) on an
always-tiny matrix — and cached per erasure pattern).

This replaces the hand-written AVX2 GF kernels the reference leans on
(klauspost/reedsolomon, reference go.mod:10) with something the MXU is
*better* at: at N=128/f=42 an encode is a (672 x 352) @ (352 x L)
matmul — pure systolic-array work, vmappable across all N validators'
RBC instances at once (SURVEY.md §2.2).
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from cleisthenes_tpu.ops import gf256
from cleisthenes_tpu.ops.backend import ErasureCoder


def _unpack_bits(x: jnp.ndarray) -> jnp.ndarray:
    """(r, L) uint8 -> (8r, L) bf16 bit-planes, LSB-first per byte."""
    r, l = x.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (x[:, None, :] >> shifts[None, :, None]) & jnp.uint8(1)
    return bits.reshape(8 * r, l).astype(jnp.bfloat16)


def _pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """(8r, L) integer 0/1 -> (r, L) uint8."""
    r8, l = bits.shape
    b = bits.reshape(r8 // 8, 8, l).astype(jnp.uint32)
    weights = (jnp.uint32(1) << jnp.arange(8, dtype=jnp.uint32))[None, :, None]
    return (b * weights).sum(axis=1).astype(jnp.uint8)


def _gf_apply_bits(g_bits: jnp.ndarray, data: jnp.ndarray) -> jnp.ndarray:
    """Apply a lifted GF matrix to byte data: (8m,8k) x (k,L) -> (m,L)."""
    acc = jnp.dot(
        g_bits, _unpack_bits(data), preferred_element_type=jnp.float32
    )
    return _pack_bits(acc.astype(jnp.int32) & 1)


@jax.jit
def _encode_kernel(g_bits: jnp.ndarray, data: jnp.ndarray) -> jnp.ndarray:
    parity = _gf_apply_bits(g_bits, data)
    return jnp.concatenate([data, parity], axis=0)


@jax.jit
def _decode_kernel(g_bits: jnp.ndarray, shards: jnp.ndarray) -> jnp.ndarray:
    return _gf_apply_bits(g_bits, shards)


# Batched variants: one extra leading axis for the validator/instance
# dimension — all N RBC instances' codec work in a single dispatch.
_encode_kernel_batch = jax.jit(jax.vmap(_encode_kernel, in_axes=(None, 0)))
_decode_kernel_batch = jax.jit(jax.vmap(_decode_kernel, in_axes=(0, 0)))
# Shared-erasure-pattern decode: every instance lost the same shards
# (the common case — e.g. the same f laggards across all N RBCs), so
# one small matrix ships instead of a per-instance stack.
_decode_kernel_shared = jax.jit(jax.vmap(_decode_kernel, in_axes=(None, 0)))


@jax.jit
def _decode_recheck_kernel(g_dec, g_enc, shards):
    """RBC's delivery check in ONE program: interpolate the data
    shards, re-encode the full shard set, and hash the Merkle forest to
    its roots (docs/RBC-EN.md:37-39's decode + root recheck).  Fusing
    the chain keeps the intermediate (B, n, L) shard tensor on device
    and turns the hub's decode path from 3 dispatches into 1 — dispatch
    latency, not FLOPs, is the live-protocol cost under a remote TPU
    attachment (VERDICT round-2 item 2)."""
    from cleisthenes_tpu.ops.sha256_xla import build_forest

    data = jax.vmap(lambda s: _gf_apply_bits(g_dec, s))(shards)
    full = jax.vmap(
        lambda d: jnp.concatenate([d, _gf_apply_bits(g_enc, d)], axis=0)
    )(data)
    forest = build_forest(full)  # (B, 2p-1, 32); root is the last node
    return data, forest[:, -1]


class XlaErasureCoder(ErasureCoder):
    # A single instance's encode/decode below this byte count runs on
    # the host numpy path: under a remote TPU attachment one dispatch
    # round-trip (~30-100 ms) dwarfs a small GF matmul, and the
    # single-shot ops (one proposer's VAL encode) are exactly the small
    # case.  Batch waves always go to the device.
    HOST_FLOOR_BYTES = 1 << 16

    def __init__(self, n: int, k: int, mesh=None):
        super().__init__(n, k)
        self.matrix = gf256.systematic_rs_matrix(n, k)
        from cleisthenes_tpu.ops.rs_cpu import CpuErasureCoder

        self._host = CpuErasureCoder(n, k)
        self._g_enc = jnp.asarray(
            gf256.lift_to_bits(self.matrix[k:]), dtype=jnp.bfloat16
        )
        # parallel.mesh.CryptoMesh: batch ops shard (B, k, L) as
        # P('v', None, 'l') — the contraction is over the k axis, so
        # both the instance axis and the shard-length axis partition
        # with zero collectives (SURVEY.md §5.7's length sharding).
        self._mesh = mesh
        # Per-instance cache of lifted decode matrices by erasure
        # pattern (class-level lru_cache would pin instances alive).
        self._decode_bits = functools.lru_cache(maxsize=512)(
            self._decode_bits_impl
        )

    def _put_vl(self, data: np.ndarray):
        """Shard a (B, r, L) batch over the mesh, padding B to the 'v'
        dim and L to the 'l' dim; returns (device_array, b, l)."""
        v, l_dim = self._mesh.shape
        data, b = self._mesh.pad_rows(data, v)
        data, l = self._mesh.pad_cols(data, l_dim)
        return self._mesh.put_vl(jnp.asarray(data)), b, l

    def encode(self, data: np.ndarray) -> np.ndarray:
        data = np.ascontiguousarray(data, dtype=np.uint8)
        assert data.ndim == 2 and data.shape[0] == self.k, data.shape
        if self.n == self.k:
            return data.copy()
        if data.nbytes < self.HOST_FLOOR_BYTES:
            return self._host.encode(data)
        return np.asarray(_encode_kernel(self._g_enc, jnp.asarray(data)))

    def _decode_bits_impl(self, indices: tuple) -> jnp.ndarray:
        inv = gf256.gf_mat_inv(self.matrix[list(indices)])
        return jnp.asarray(gf256.lift_to_bits(inv), dtype=jnp.bfloat16)

    def _decode_impl(self, indices: tuple, shards: np.ndarray) -> np.ndarray:
        if shards.nbytes < self.HOST_FLOOR_BYTES:
            return self._host._decode_impl(indices, shards)
        return np.asarray(
            _decode_kernel(self._decode_bits(indices), jnp.asarray(shards))
        )

    def encode_batch(self, data: np.ndarray) -> np.ndarray:
        data = np.ascontiguousarray(data, dtype=np.uint8)
        assert data.ndim == 3 and data.shape[1] == self.k, data.shape
        if self.n == self.k:
            return data.copy()
        if self._mesh is None and data.nbytes < 4 * self.HOST_FLOOR_BYTES:
            return self._host.encode_batch(data)
        if self._mesh is None:
            return np.asarray(
                _encode_kernel_batch(self._g_enc, jnp.asarray(data))
            )
        dev, b, l = self._put_vl(data)
        out = _encode_kernel_batch(self._g_enc, dev)
        return np.asarray(out)[:b, :, :l]

    def decode_recheck_batch(self, indices: np.ndarray, shards: np.ndarray):
        """Fused decode + re-encode + Merkle roots, or None when the
        fusion doesn't apply (mesh-sharded runs and mixed erasure
        patterns use the separate batched kernels instead).

        Returns (data (B, k, L), roots (B, 32)).  The batch axis pads
        to a power of two (min 8) so each (bucket, k, L) shape compiles
        once."""
        if self._mesh is not None or self.n == self.k:
            return None
        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        if shards.nbytes < 4 * self.HOST_FLOOR_BYTES:
            return None  # tiny job: the host 3-step path wins
        patterns = [self._normalize_indices(ix) for ix in indices]
        if len(set(patterns)) != 1:
            return None
        g = self._decode_bits(patterns[0])
        b = shards.shape[0]
        bucket = 8
        while bucket < b:
            bucket <<= 1
        if bucket != b:
            shards = np.concatenate(
                [shards, np.repeat(shards[:1], bucket - b, axis=0)]
            )
        data, roots = _decode_recheck_kernel(
            g, self._g_enc, jnp.asarray(shards)
        )
        return np.asarray(data)[:b], np.asarray(roots)[:b]

    def decode_batch(
        self, indices: np.ndarray, shards: np.ndarray
    ) -> np.ndarray:
        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        if self._mesh is None and shards.nbytes < 4 * self.HOST_FLOOR_BYTES:
            return self._host.decode_batch(indices, shards)
        patterns = [self._normalize_indices(ix) for ix in indices]
        if len(set(patterns)) == 1:
            g = self._decode_bits(patterns[0])
            if self._mesh is None:
                return np.asarray(
                    _decode_kernel_shared(g, jnp.asarray(shards))
                )
            dev, b, l = self._put_vl(shards)
            return np.asarray(_decode_kernel_shared(g, dev))[:b, :, :l]
        g = jnp.stack([self._decode_bits(p) for p in patterns])
        if self._mesh is None:
            return np.asarray(_decode_kernel_batch(g, jnp.asarray(shards)))
        dev, b, l = self._put_vl(shards)
        v = self._mesh.shape[0]
        # the per-instance decode matrices shard batch-only: their
        # trailing axes are the contraction dims
        g_np, _ = self._mesh.pad_rows(np.asarray(g), v)
        g_dev = self._mesh.put_v(jnp.asarray(g_np))
        return np.asarray(_decode_kernel_batch(g_dev, dev))[:b, :, :l]


__all__ = ["XlaErasureCoder"]
