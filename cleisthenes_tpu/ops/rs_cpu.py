"""CPU reference Reed-Solomon codec (numpy table lookups).

The correctness anchor for the TPU codec, standing in for the
reference's klauspost/reedsolomon SIMD dependency (reference go.mod:10)
until the native C++ backend supersedes it for speed.
"""

from __future__ import annotations

import functools

import numpy as np

from cleisthenes_tpu.ops import gf256
from cleisthenes_tpu.ops.backend import ErasureCoder


class CpuErasureCoder(ErasureCoder):
    def __init__(self, n: int, k: int):
        super().__init__(n, k)
        self.matrix = gf256.systematic_rs_matrix(n, k)
        # Per-instance cache of decode matrices by erasure pattern
        # (class-level lru_cache would pin instances alive forever).
        self._decode_matrix = functools.lru_cache(maxsize=512)(
            self._decode_matrix_impl
        )

    def encode(self, data: np.ndarray) -> np.ndarray:
        data = np.ascontiguousarray(data, dtype=np.uint8)
        assert data.ndim == 2 and data.shape[0] == self.k, data.shape
        if self.n == self.k:
            return data.copy()
        parity = gf256.gf_matmul(self.matrix[self.k :], data)
        return np.concatenate([data, parity], axis=0)

    def _decode_matrix_impl(self, indices: tuple) -> np.ndarray:
        return gf256.gf_mat_inv(self.matrix[list(indices)])

    def _decode_impl(self, indices: tuple, shards: np.ndarray) -> np.ndarray:
        return gf256.gf_matmul(self._decode_matrix(indices), shards)
