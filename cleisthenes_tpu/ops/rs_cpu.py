"""CPU reference Reed-Solomon codec (numpy table lookups).

The correctness anchor for the TPU codec, standing in for the
reference's klauspost/reedsolomon SIMD dependency (reference go.mod:10)
until the native C++ backend supersedes it for speed.
"""

from __future__ import annotations

import functools
from typing import Sequence

import numpy as np

from cleisthenes_tpu.ops import gf256
from cleisthenes_tpu.ops.backend import ErasureCoder


class CpuErasureCoder(ErasureCoder):
    def __init__(self, n: int, k: int):
        super().__init__(n, k)
        self.matrix = gf256.systematic_rs_matrix(n, k)

    def encode(self, data: np.ndarray) -> np.ndarray:
        data = np.ascontiguousarray(data, dtype=np.uint8)
        assert data.ndim == 2 and data.shape[0] == self.k, data.shape
        if self.n == self.k:
            return data.copy()
        parity = gf256.gf_matmul(self.matrix[self.k :], data)
        return np.concatenate([data, parity], axis=0)

    @functools.lru_cache(maxsize=512)
    def _decode_matrix(self, indices: tuple) -> np.ndarray:
        return gf256.gf_mat_inv(self.matrix[list(indices)])

    def decode(self, indices: Sequence[int], shards: np.ndarray) -> np.ndarray:
        indices = tuple(int(i) for i in indices)
        if len(indices) != self.k or len(set(indices)) != self.k:
            raise ValueError(
                f"need exactly k={self.k} distinct shard indices, got {indices}"
            )
        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        assert shards.shape[0] == self.k, shards.shape
        if indices == tuple(range(self.k)):
            return shards.copy()
        return gf256.gf_matmul(self._decode_matrix(indices), shards)
