"""Native C++ Reed-Solomon codec (the 'cpp' backend).

Same math as rs_cpu (systematic Vandermonde over GF(2^8), poly 0x11D)
with the hot matmul running in the compiled kernel of
cleisthenes_tpu/native/gf256.cpp — the TPU-build equivalent of the
reference's klauspost/reedsolomon native SIMD path (reference
go.mod:10, rbc/rbc.go:98).  Falls back is handled by the caller
(ops.backend.make_erasure_coder raises if the toolchain is missing).
"""

from __future__ import annotations

import functools

import numpy as np

from cleisthenes_tpu.native.build import load_gf256
from cleisthenes_tpu.ops import gf256
from cleisthenes_tpu.ops.backend import ErasureCoder


class CppErasureCoder(ErasureCoder):
    def __init__(self, n: int, k: int):
        super().__init__(n, k)
        self._lib = load_gf256()
        if self._lib is None:
            raise RuntimeError(
                "native gf256 kernel unavailable (no C++ toolchain?)"
            )
        self.matrix = gf256.systematic_rs_matrix(n, k)
        self._parity = np.ascontiguousarray(self.matrix[k:])
        self._decode_matrix = functools.lru_cache(maxsize=512)(
            self._decode_matrix_impl
        )

    def _apply(self, mat: np.ndarray, data: np.ndarray) -> np.ndarray:
        m = mat.shape[0]
        out = np.empty((m, data.shape[1]), dtype=np.uint8)
        self._lib.gf256_matmul(
            mat.ctypes.data,
            data.ctypes.data,
            out.ctypes.data,
            m,
            mat.shape[1],
            data.shape[1],
        )
        return out

    def encode(self, data: np.ndarray) -> np.ndarray:
        data = np.ascontiguousarray(data, dtype=np.uint8)
        assert data.ndim == 2 and data.shape[0] == self.k, data.shape
        if self.n == self.k:
            return data.copy()
        parity = self._apply(self._parity, data)
        return np.concatenate([data, parity], axis=0)

    def encode_batch(self, data: np.ndarray) -> np.ndarray:
        data = np.ascontiguousarray(data, dtype=np.uint8)
        assert data.ndim == 3 and data.shape[1] == self.k, data.shape
        if self.n == self.k:
            return data.copy()
        b, _, length = data.shape
        m = self.n - self.k
        parity = np.empty((b, m, length), dtype=np.uint8)
        self._lib.gf256_matmul_batch(
            self._parity.ctypes.data,
            data.ctypes.data,
            parity.ctypes.data,
            b,
            m,
            self.k,
            length,
        )
        return np.concatenate([data, parity], axis=1)

    def _decode_matrix_impl(self, indices: tuple) -> np.ndarray:
        return np.ascontiguousarray(
            gf256.gf_mat_inv(self.matrix[list(indices)])
        )

    def _decode_impl(self, indices: tuple, shards: np.ndarray) -> np.ndarray:
        return self._apply(self._decode_matrix(indices), shards)


__all__ = ["CppErasureCoder"]
