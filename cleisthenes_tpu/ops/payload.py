"""Payload <-> shard-matrix conversion for RBC.

The reference's RBC splits a proposed batch into N pieces with N-2f
parity (docs/RBC-EN.md:28-31, rbc/rbc.go:98-100).  Here a byte payload
becomes a (k, L) uint8 matrix with a 4-byte length prefix and zero
padding; L is rounded up to a lane-friendly multiple so repeated epoch
sizes hit the same compiled TPU kernel shapes.
"""

from __future__ import annotations

import struct

import numpy as np

LANE_MULTIPLE = 128  # TPU lane width; also bounds jit retraces


def split_payload(payload: bytes, k: int, lane_multiple: int = LANE_MULTIPLE) -> np.ndarray:
    """bytes -> (k, L) uint8 data-shard matrix (length-prefixed, padded)."""
    framed = struct.pack(">I", len(payload)) + payload
    per_shard = -(-len(framed) // k)  # ceil
    per_shard = -(-per_shard // lane_multiple) * lane_multiple
    buf = np.zeros(k * per_shard, dtype=np.uint8)
    buf[: len(framed)] = np.frombuffer(framed, dtype=np.uint8)
    return buf.reshape(k, per_shard)


def join_payload(data_shards: np.ndarray) -> bytes:
    """(k, L) uint8 data-shard matrix -> original bytes."""
    flat = np.ascontiguousarray(data_shards, dtype=np.uint8).reshape(-1)
    if flat.size < 4:
        raise ValueError("shard matrix too small to hold length prefix")
    (length,) = struct.unpack(">I", flat[:4].tobytes())
    if length > flat.size - 4:
        raise ValueError(
            f"corrupt payload: declared length {length} exceeds capacity {flat.size - 4}"
        )
    return flat[4 : 4 + length].tobytes()
