"""Jitted GF(2^16) RS kernels: 16-bit-plane lifted matmuls.

Separate module so ops/rs16.py stays importable (and its CPU coder
usable) without touching JAX.  Structure mirrors ops/rs_xla.py's
8-bit kernels with uint16 symbols and 16 bit-planes per symbol.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

E = 16


def _unpack_bits16(x: jnp.ndarray) -> jnp.ndarray:
    """(r, S) uint16 -> (16r, S) bf16 bit-planes, LSB-first."""
    r, s = x.shape
    shifts = jnp.arange(E, dtype=jnp.uint16)
    bits = (x[:, None, :] >> shifts[None, :, None]) & jnp.uint16(1)
    return bits.reshape(E * r, s).astype(jnp.bfloat16)


def _pack_bits16(bits: jnp.ndarray) -> jnp.ndarray:
    """(16r, S) integer 0/1 -> (r, S) uint16."""
    r16, s = bits.shape
    b = bits.reshape(r16 // E, E, s).astype(jnp.uint32)
    weights = (jnp.uint32(1) << jnp.arange(E, dtype=jnp.uint32))[
        None, :, None
    ]
    return (b * weights).sum(axis=1).astype(jnp.uint16)


def _apply_bits16(g_bits: jnp.ndarray, syms: jnp.ndarray) -> jnp.ndarray:
    """Apply a lifted GF(2^16) matrix: (16m,16k) x (k,S) -> (m,S).

    Dots sum <= 16k ones — exact in bf16 multiply / f32 accumulate."""
    acc = jnp.dot(
        g_bits.astype(jnp.bfloat16),
        _unpack_bits16(syms),
        preferred_element_type=jnp.float32,
    )
    return _pack_bits16(acc.astype(jnp.int32) & 1)


@jax.jit
def _encode_kernel(g_bits, syms):
    parity = _apply_bits16(g_bits, syms)
    return jnp.concatenate([syms, parity], axis=0)


@jax.jit
def _decode_kernel(g_bits, syms):
    return _apply_bits16(g_bits, syms)


encode_kernel_batch = jax.jit(jax.vmap(_encode_kernel, in_axes=(None, 0)))
decode_kernel_shared = jax.jit(jax.vmap(_decode_kernel, in_axes=(None, 0)))

__all__ = ["encode_kernel_batch", "decode_kernel_shared"]
