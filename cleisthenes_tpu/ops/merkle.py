"""Merkle forest: batched tree build and branch verification.

RBC attaches to every VAL/ECHO a Merkle root h and branch b(j) proving
shard s(j) (reference rbc/request.go:9-13, docs/RBC-EN.md:31-39); after
interpolation the root is recomputed to catch corrupt shards
(docs/RBC-EN.md:37-38).  The network-wide cost is N^2 log N hashes per
epoch (docs/HONEYBADGER-EN.md:96) — all independent, so both the build
(one tree per validator's proposal) and the verify (N branches per
delivered instance) are batched onto the TPU via sha256_xla.

Convention: leaf digest = SHA256(0x00 || shard), interior node =
SHA256(0x01 || left || right) (domain separation against second-
preimage splices); leaf sets pad to the next power of two with a fixed
sentinel digest.
"""

from __future__ import annotations

import abc
import dataclasses
import hashlib
from typing import List, Sequence

import numpy as np

_LEAF_PREFIX = b"\x00"
_NODE_PREFIX = b"\x01"
_EMPTY_LEAF_DIGEST = hashlib.sha256(b"cleisthenes-tpu:empty-leaf").digest()


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


@dataclasses.dataclass
class MerkleTree:
    """A built tree: levels[0] is the (padded) leaf-digest row, levels[-1]
    is the single root digest.  All rows are (width, 32) uint8."""

    levels: List[np.ndarray]
    n_leaves: int

    @property
    def root(self) -> bytes:
        return self.levels[-1][0].tobytes()

    def branch(self, index: int) -> List[bytes]:
        """Sibling path for leaf ``index``, bottom-up
        (the b(j) of reference rbc/request.go:11)."""
        if not (0 <= index < self.n_leaves):
            raise IndexError(index)
        out = []
        for level in self.levels[:-1]:
            out.append(level[index ^ 1].tobytes())
            index >>= 1
        return out

    @property
    def depth(self) -> int:
        return len(self.levels) - 1


class MerkleBackend(abc.ABC):
    """Batched tree building + branch verification."""

    @abc.abstractmethod
    def _hash_batch(self, msgs: np.ndarray) -> np.ndarray:
        """(B, L) uint8 -> (B, 32) uint8."""

    # -- building ----------------------------------------------------

    def build(self, shards: np.ndarray) -> MerkleTree:
        """(N, L) uint8 shard matrix -> tree over N leaves."""
        return self.build_batch(shards[None])[0]

    def build_batch(self, shards: np.ndarray) -> List[MerkleTree]:
        """(B, N, L) -> B trees, all leaf hashing/level hashing batched."""
        b, n, l = shards.shape
        p = _next_pow2(n)
        prefixed = np.concatenate(
            [
                np.zeros((b * n, 1), dtype=np.uint8),
                shards.reshape(b * n, l),
            ],
            axis=1,
        )
        leaf_dig = self._hash_batch(prefixed).reshape(b, n, 32)
        if p != n:
            pad = np.broadcast_to(
                np.frombuffer(_EMPTY_LEAF_DIGEST, dtype=np.uint8), (b, p - n, 32)
            )
            leaf_dig = np.concatenate([leaf_dig, pad], axis=1)
        levels = [leaf_dig]
        width = p
        while width > 1:
            cur = levels[-1]  # (b, width, 32)
            pairs = cur.reshape(b, width // 2, 64)
            msgs = np.concatenate(
                [
                    np.ones((b * (width // 2), 1), dtype=np.uint8),
                    pairs.reshape(b * (width // 2), 64),
                ],
                axis=1,
            )
            levels.append(self._hash_batch(msgs).reshape(b, width // 2, 32))
            width //= 2
        return [
            MerkleTree([lvl[i] for lvl in levels], n_leaves=n) for i in range(b)
        ]

    # -- verification ------------------------------------------------

    def verify_branch(
        self, root: bytes, leaf: bytes, branch: Sequence[bytes], index: int
    ) -> bool:
        """One proof, pure hashlib: a scalar verify is a handful of
        SHA-256 calls — array assembly (let alone a device dispatch)
        costs more than the hashing.  Batch waves use verify_batch."""
        cur = hashlib.sha256(_LEAF_PREFIX + leaf).digest()
        idx = index
        for sib in branch:
            if idx & 1:
                cur = hashlib.sha256(_NODE_PREFIX + sib + cur).digest()
            else:
                cur = hashlib.sha256(_NODE_PREFIX + cur + sib).digest()
            idx >>= 1
        return cur == root

    def verify_batch(
        self,
        roots: np.ndarray,
        leaves: np.ndarray,
        branches: np.ndarray,
        indices: np.ndarray,
    ) -> np.ndarray:
        """Verify B branches at once.

        roots (B, 32), leaves (B, L) raw shard bytes, branches
        (B, D, 32) sibling paths bottom-up, indices (B,) leaf positions
        -> (B,) bool.  The whole thing is D+1 batched hash dispatches.
        """
        b, l = leaves.shape
        d = branches.shape[1]
        prefixed = np.concatenate(
            [np.zeros((b, 1), dtype=np.uint8), leaves], axis=1
        )
        cur = self._hash_batch(prefixed)  # (B, 32)
        idx = np.asarray(indices).copy()
        for lvl in range(d):
            sib = branches[:, lvl]
            bit = (idx & 1).astype(bool)[:, None]
            left = np.where(bit, sib, cur)
            right = np.where(bit, cur, sib)
            msgs = np.concatenate(
                [np.ones((b, 1), dtype=np.uint8), left, right], axis=1
            )
            cur = self._hash_batch(msgs)
            idx >>= 1
        return (cur == roots).all(axis=1)


class CpuMerkle(MerkleBackend):
    """Host backend: one native batched-SHA crossing per level
    (ops/hashrows; identical digests to the old hashlib loop)."""

    def _hash_batch(self, msgs: np.ndarray) -> np.ndarray:
        from cleisthenes_tpu.ops.hashrows import sha256_rows

        return sha256_rows(msgs)


class XlaMerkle(MerkleBackend):
    """Batched SHA-256 Merkle forest on TPU.

    ``build_batch`` and ``verify_batch`` are overridden with fully
    device-resident jitted kernels: every tree level's hashing is part
    of ONE XLA program (the base class would round-trip host<->device
    per level).  The batch axis is padded to the next power of two
    (min 8) so each (bucket, length) pair compiles exactly once.

    With a ``parallel.mesh.CryptoMesh``, the batch axis shards over
    EVERY mesh device flat (``P(('v','l'))``): hashing is sequential
    within a message but independent across the batch, so trees and
    branch proofs scatter across chips with zero collectives.
    """

    # Below this batch size the device round-trip costs more than the
    # hashes: small jobs run on host, batch waves run on device.
    # Host hashlib SHA-256 is ~0.7 us/hash; a relay dispatch is
    # ~40 ms round-trip, so the crossover sits near 8k branch proofs
    # (~7 hashes each) / 16k forest leaves (~2 hashes each).  An
    # N=16 live epoch's whole merkle load therefore stays native
    # (it is microseconds of hashing), while the N>=128 crypto-plane
    # waves (16k+ items) take the device path.
    HOST_FLOOR_VERIFY = 8192
    HOST_FLOOR_BUILD_LEAVES = 16384

    def __init__(self, mesh=None):
        self._mesh = mesh
        self._host = CpuMerkle()

    def _bucket(self, b: int) -> int:
        import math

        bucket = 8
        while bucket < b:
            bucket <<= 1
        if self._mesh is not None:
            # padded batch must divide across the flat device count;
            # lcm keeps the power-of-two compile-bucketing AND handles
            # non-power-of-two meshes (e.g. (3, 2))
            bucket = math.lcm(bucket, self._mesh.n_devices)
        return bucket

    def _put(self, x):
        import jax.numpy as jnp

        x = jnp.asarray(x)
        if self._mesh is None:
            return x
        return self._mesh.put_flat(x)[0]

    def _hash_batch(self, msgs: np.ndarray) -> np.ndarray:
        from cleisthenes_tpu.ops.sha256_xla import sha256_batch

        b = msgs.shape[0]
        if b < self.HOST_FLOOR_VERIFY:
            # also covers the base-class single-tree build(): a
            # 16-leaf tree is ~5 per-level dispatches on device vs
            # ~10 us of hashlib
            return self._host._hash_batch(msgs)
        bucket = self._bucket(b)
        if bucket != b:
            msgs = np.concatenate(
                [msgs, np.zeros((bucket - b, msgs.shape[1]), dtype=np.uint8)]
            )
        return np.asarray(sha256_batch(self._put(msgs)))[:b]

    def build_batch(self, shards: np.ndarray) -> List[MerkleTree]:
        from cleisthenes_tpu.ops.sha256_xla import build_forest

        b, n, _ = shards.shape
        if b * n < self.HOST_FLOOR_BUILD_LEAVES:
            return self._host.build_batch(shards)
        bucket = self._bucket(b)
        if bucket != b:
            shards = np.concatenate(
                [shards, np.zeros((bucket - b,) + shards.shape[1:], np.uint8)]
            )
        # (bucket, 2p-1, 32): the whole forest in one transfer
        forest = np.asarray(build_forest(self._put(shards)))
        p = _next_pow2(n)
        levels = []
        off, width = 0, p
        while width >= 1:
            levels.append(forest[:, off : off + width])
            off += width
            width //= 2
        return [
            MerkleTree([lvl[i] for lvl in levels], n_leaves=n)
            for i in range(b)
        ]

    def verify_batch(
        self,
        roots: np.ndarray,
        leaves: np.ndarray,
        branches: np.ndarray,
        indices: np.ndarray,
    ) -> np.ndarray:
        from cleisthenes_tpu.ops.sha256_xla import verify_branches

        b = leaves.shape[0]
        if b < self.HOST_FLOOR_VERIFY:
            return self._host.verify_batch(roots, leaves, branches, indices)
        bucket = self._bucket(b)

        def pad(a):
            if bucket == b:
                return a
            reps = np.repeat(a[:1], bucket - b, axis=0)
            return np.concatenate([a, reps])

        ok = verify_branches(
            self._put(pad(np.ascontiguousarray(roots, dtype=np.uint8))),
            self._put(pad(np.ascontiguousarray(leaves, dtype=np.uint8))),
            self._put(pad(np.ascontiguousarray(branches, dtype=np.uint8))),
            self._put(pad(np.asarray(indices, dtype=np.uint32))),
        )
        return np.asarray(ok)[:b]


def make_merkle(backend: str, mesh=None) -> MerkleBackend:
    if backend == "cpu":
        return CpuMerkle()
    if backend == "tpu":
        return XlaMerkle(mesh=mesh)
    raise ValueError(f"unknown merkle backend {backend!r}")


__all__ = [
    "MerkleTree",
    "MerkleBackend",
    "CpuMerkle",
    "XlaMerkle",
    "make_merkle",
]
